#!/bin/bash
# One-shot capture of every on-chip measurement owed since the round-4
# TPU outage (PARITY.md "Round-4 TPU availability record"). Run on a
# host with the live chip; each step is independent — failures don't
# block the rest. Commit the JSONs it produces.
set -x
cd "$(dirname "$0")"

# 0. Settle the BENCH_r04 (43,183) vs BENCH_r02 (49,976 img/s/chip)
#    regression: three back-to-back runs so the spread distinguishes
#    tunnel variance from a code regression (VERDICT r4 item 1).
for i in 1 2 3; do
  timeout 580 python bench.py > "BENCH_r05_run${i}.json" 2>/dev/null
done

# 1. Scatter-dispatch MoE A/B (dense dispatch einsums measured at ~25%
#    of step FLOPs — the scatter path skips them entirely).
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.moebench \
    --moe-dispatch scatter --out MOEBENCH_scatter.json
# 1b. Refresh the dense artifact on the same code for a clean A/B.
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.moebench \
    --out MOEBENCH.json

# 2. Sliding-window flash A/B (band skip => O(L*W) compute; tokens/s
#    should GROW as the window shrinks).
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --seq-len 4096 --batch 4 --remat dots --skip-ab --out WINBENCH_full.json
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --seq-len 4096 --batch 4 --remat dots --attn-window 512 --skip-ab \
    --out WINBENCH_w512.json

# 3. int8 KV-cache decode A/B, alone and composed with GQA.
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.genbench \
    --n-kv-heads 2 --kv-cache-quant int8 --out GENBENCH_kvq.json

# 4. Long-context training from the CLI at seq >= 2048 (VERDICT item 2).
timeout 580 python -m tensorflow_distributed_tpu.cli --model gpt_lm \
    --model-size small --seq-len 2048 --batch-size 8 --remat dots \
    --pos-emb rope --train-steps 50 --eval-every 0 --log-every 10 \
    --dataset synthetic 2>&1 | tail -5 > LONGCTX_r04.txt

# 5. Better unpipelined headline (49.4% MFU at batch 16 measured
#    pre-outage; record it as an artifact).
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --batch 16 --skip-ab --out LMBENCH_r04_b16.json

# 5b. Fused vocab-chunked CE A/B (ops/fused_ce.py): dense [B,L,V]
#     logits vs the chunked head+loss, same step otherwise.
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --batch 8 --skip-ab --out CEBENCH_dense.json
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --batch 8 --skip-ab --ce-chunk 8192 --out CEBENCH_fused.json
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --batch 8 --skip-ab --ce-chunk 8192 --ce-impl kernel \
    --out CEBENCH_kernel.json

# 5c. Stash-backward re-measure AFTER the weight-leaf hoist (the
#     19.9%-MFU number in PARITY predates it; matched shapes vs the
#     recompute run it lost to).
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --batch 32 --pipeline-microbatches 4 --pipeline-backward stash \
    --skip-ab --out STASHBENCH_hoisted.json

# 5d. Up the GPT-2 ladder: medium (355M) and large (774M) on the one
#     chip — what remat + fused CE exist for.
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --size medium --batch 8 --remat dots --ce-chunk 8192 --skip-ab \
    --out LMBENCH_r04_medium.json
timeout 580 python -m tensorflow_distributed_tpu.benchmarks.lm_perf \
    --size large --batch 4 --remat dots --ce-chunk 8192 --skip-ab \
    --out LMBENCH_r04_large.json

# 6. Ring local-compute block-size sweep: the recorded RINGBENCH showed
#    flash-partial ~parity with einsum at half-block 512 — find where
#    (if anywhere) the kernel pulls ahead, for the dispatch tuning the
#    parity result motivates.
for hb in 256 512 1024 2048; do
  timeout 580 python -m tensorflow_distributed_tpu.benchmarks.ringbench \
      --half-block "$hb" --out "RINGBENCH_hb${hb}.json"
done
