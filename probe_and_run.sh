#!/bin/bash
# TPU tunnel watcher (round-4 outage protocol, PARITY.md "Round-4 TPU
# availability record"): the failure mode is enumeration-works /
# compute-hangs, so the probe is a REAL computation with a readback.
# When a probe completes, run benchmarks_owed.sh once and exit.
# Probe attempts are logged for the outage record.
cd "$(dirname "$0")"
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
assert float(jax.jit(lambda a: (a @ a).sum())(x)) == 256.0 * 256 * 256
" >/dev/null 2>&1; then
    echo "$ts probe_ok (jit matmul + readback)" >> TPU_PROBES_r05.log
    bash benchmarks_owed.sh > owed_run.log 2>&1
    echo "$(date -u +%FT%TZ) owed_run_done rc=$?" >> TPU_PROBES_r05.log
    exit 0
  fi
  echo "$ts probe_fail (120s, no compute readback)" >> TPU_PROBES_r05.log
  sleep 600
done
