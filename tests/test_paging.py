"""Paged KV cache + radix prefix reuse (serve/paging).

Fast tier (jax-free): page-pool allocator invariants (no double free,
refcount round-trip, FIFO determinism), radix/session lookup + COW
preconditions, LRU eviction-under-pressure determinism, scheduler
wiring on a fake paged engine (admission deferral, retention routing,
session turn ordering), config validation, truncated-journal session
replay, report folding. Slow tier: real-engine dense-vs-paged token
identity across radix hits / copy-on-write / session re-attach,
quarantine shared-page survival, and the int8 / speculative
compositions.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.serve.paging.pool import (
    GARBAGE_PAGE, PagePool, PoolExhausted)
from tensorflow_distributed_tpu.serve.paging.radix import RadixCache
from tensorflow_distributed_tpu.serve.scheduler import Request, Scheduler


# --- page pool (pure host) ---------------------------------------------

def test_pool_alloc_release_roundtrip():
    pool = PagePool(num_pages=6, page_size=8)
    assert pool.capacity == 5 and pool.free_count == 5
    a = pool.alloc(3)
    assert len(a) == 3 and GARBAGE_PAGE not in a
    assert pool.pages_in_use == 3 and pool.peak_in_use == 3
    pool.retain(a[:1])                      # a second holder
    assert pool.release(a) == 2             # a[0] still referenced
    assert pool.pages_in_use == 1
    assert pool.release([a[0]]) == 1
    assert pool.free_count == 5 and pool.pages_in_use == 0
    assert pool.peak_in_use == 3            # high-water survives


def test_pool_double_free_and_exhaustion_raise():
    pool = PagePool(num_pages=4, page_size=8)
    a = pool.alloc(3)
    with pytest.raises(PoolExhausted, match="raise --serve.num-pages"):
        pool.alloc(1)
    pool.release(a)
    with pytest.raises(RuntimeError, match="double free"):
        pool.release([a[0]])
    with pytest.raises(RuntimeError, match="retain of unreferenced"):
        pool.retain([a[0]])
    # The write-off page is never allocatable and releasing it is a
    # tolerated no-op (tables pad with it).
    assert pool.release([GARBAGE_PAGE]) == 0


def test_pool_allocation_deterministic_fifo():
    def run():
        pool = PagePool(num_pages=8, page_size=4)
        a = pool.alloc(3)
        pool.release(a[1:2])
        b = pool.alloc(2)
        pool.release(a[:1] + b)
        return a, b, pool.alloc(4)

    assert run() == run()


# --- radix cache --------------------------------------------------------

def _pool_and_cache(num_pages=32, ps=4):
    pool = PagePool(num_pages, ps)
    return pool, RadixCache(pool)


def test_radix_insert_lookup_full_blocks():
    pool, rc = _pool_and_cache()
    toks = list(range(10))                 # 2 full blocks of 4 + tail
    pages = pool.alloc(3)
    rc.insert(toks, pages)
    # The tree holds refs on the 2 full-block pages only.
    assert pool.ref[pages[0]] == 2 and pool.ref[pages[1]] == 2
    assert pool.ref[pages[2]] == 1
    pool.release(pages)                    # the "slot" lets go
    assert pool.ref[pages[0]] == 1 and pool.ref[pages[2]] == 0
    got, m, src = rc.lookup("", toks, cap=9)
    assert src == "radix" and m == 8 and got == pages[:2]
    assert pool.ref[pages[0]] == 2         # caller owns a ref now
    # A diverging prompt matches only the shared leading block.
    other = toks[:4] + [99] * 6
    got2, m2, _ = rc.lookup("", other, cap=9)
    assert m2 == 4 and got2 == pages[:1]
    pool.release(got + got2)


def test_radix_cap_clamps_mid_page_for_cow():
    """A fully-cached prompt matches cap = plen - 1 tokens MID-page —
    the engine's copy-on-write precondition (the returned partial page
    is shared with the tree, refcount > 1)."""
    pool, rc = _pool_and_cache()
    toks = list(range(8))                  # exactly 2 blocks
    pages = pool.alloc(2)
    rc.insert(toks, pages)
    pool.release(pages)
    got, m, _ = rc.lookup("", toks, cap=7)
    assert m == 7 and len(got) == 2        # partial page 1 included
    assert pool.ref[got[1]] == 2           # shared -> COW must fire
    pool.release(got)


def test_radix_duplicate_insert_keeps_existing():
    pool, rc = _pool_and_cache()
    toks = list(range(8))
    first = pool.alloc(2)
    rc.insert(toks, first)
    dup = pool.alloc(2)
    rc.insert(toks, dup)                   # same blocks, new pages
    pool.release(first)
    pool.release(dup)
    assert pool.ref[dup[0]] == 0           # duplicate NOT adopted
    got, m, _ = rc.lookup("", toks + [9], cap=9)
    assert got == first and m == 8         # the original stays
    pool.release(got)


def test_session_store_match_transfer_and_divergence():
    pool, rc = _pool_and_cache(ps=4)
    conv = list(range(10))                 # 2.5 pages
    pages = pool.alloc(3)
    rc.session_store("s1", conv, pages)
    assert rc.sessions_live == 1
    pool.release(pages)                    # slot lets go; session holds
    assert pool.ref[pages[2]] == 1
    # The follow-up turn extends the conversation: the session's refs
    # TRANSFER to the caller and the entry is consumed.
    got, m, src = rc.lookup("s1", conv + [77, 78], cap=11)
    assert src == "session" and m == 10 and got == pages
    assert rc.sessions_live == 0
    assert pool.ref[pages[0]] == 1         # one ref: the caller's
    pool.release(got)
    # A diverged prompt drops the stale session and frees its pages.
    pages2 = pool.alloc(2)
    rc.session_store("s2", conv[:8], pages2)
    pool.release(pages2)
    got2, m2, _ = rc.lookup("s2", [99] * 12, cap=11)
    assert got2 == [] and m2 == 0 and rc.sessions_live == 0
    assert pool.ref[pages2[0]] == 0        # freed, not leaked


def test_eviction_under_pressure_deterministic():
    def run():
        pool, rc = _pool_and_cache(num_pages=16, ps=4)
        order = []
        for i in range(3):
            toks = [i * 100 + j for j in range(8)]
            pages = pool.alloc(2)
            rc.insert(toks, pages)
            pool.release(pages)
        pages = pool.alloc(2)
        rc.session_store("s", [7] * 8, pages)
        pool.release(pages)
        while rc.evict_one():
            order.append((pool.free_count, rc.cached_pages,
                          rc.sessions_live))
        return order

    a, b = run(), run()
    assert a == b and a                    # deterministic + non-empty
    assert a[-1][1] == 0 and a[-1][2] == 0  # fully drained


def test_evict_prefers_entries_that_free_pages():
    pool, rc = _pool_and_cache(num_pages=16, ps=4)
    held = pool.alloc(2)                   # "live slot" holds these
    rc.insert(list(range(8)), held)        # cached AND slot-held
    free_young = pool.alloc(2)
    rc.insert([50 + j for j in range(8)], free_young)
    pool.release(free_young)               # cache-only -> freeable
    # The slot-held entry is OLDER (inserted first) but evicting it
    # frees nothing — the freeing entry must win despite its age.
    before = pool.free_count
    assert rc.evict_one()
    assert pool.free_count == before + 1
    assert pool.ref[held[0]] == 2          # older entry untouched
    assert rc.evict_one()                  # the chain's first block
    assert rc.reclaimable_pages == 0
    assert pool.free_count == before + 2


# --- scheduler wiring (fake paged engine) ------------------------------

class _FakePagedEngine:
    """Host-only engine with the PAGED surface the scheduler keys on:
    ``paged``, ``can_admit``, ``release(tokens=, session=)``,
    kwargs-taking ``prefill``. Token stream rid*100 + step."""

    paged = True

    def __init__(self, num_slots=2, max_len=256, admit_ok=True):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (32, 64)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0
        self.admit_ok = admit_ok
        self.admit_checks = 0
        self.released = []                 # (rid, retained?, session)
        self.admitted = []                 # (rid, max_new, session)

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def can_admit(self, plen, max_new):
        self.admit_checks += 1
        return (self.admit_ok if isinstance(self.admit_ok, bool)
                else self.admit_ok(plen, max_new))

    def prefill(self, prompt, slot, max_new_tokens=0, session=""):
        rid = int(prompt[0])
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts.setdefault(rid, 0)
        self.prefills += 1
        self.admitted.append((rid, max_new_tokens, session))
        self.counts[rid] += 1
        return rid * 100 + self.counts[rid] - 1

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                out[s] = rid * 100 + self.counts[rid]
                self.counts[rid] += 1
        self.decode_steps += 1
        return out

    def release(self, slot, tokens=None, session=""):
        self.released.append((self.slot_rid.get(slot),
                              tokens is not None, session))
        self.active[slot] = False

    def free(self, slot):
        self.release(slot)

    def paging_stats(self):
        return {"pool_occupancy": 0.5, "prefix_hit_rate": 0.25,
                "prefix_hits": 1, "pages_peak": 7,
                "page_evictions": 2, "cow_copies": 1}


def test_scheduler_passes_admission_context_and_retains():
    eng = _FakePagedEngine()
    reqs = [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=4, session=f"conv{i}")
            for i in range(3)]
    done = Scheduler(eng, decode_priority=2).run(reqs)
    assert len(done) == 3
    # prefill saw the budget + session; finish retained with them.
    assert sorted(eng.admitted) == [(0, 4, "conv0"), (1, 4, "conv1"),
                                    (2, 4, "conv2")]
    assert sorted(eng.released) == [(0, True, "conv0"),
                                    (1, True, "conv1"),
                                    (2, True, "conv2")]
    # Summary folded the paging stats (router/Fleetbench feed).
    assert eng.admit_checks >= 3


def test_scheduler_summary_and_snapshot_carry_paging_stats():
    eng = _FakePagedEngine()
    sched = Scheduler(eng, decode_priority=2)
    sched.run([Request(rid=0, prompt=np.asarray([0], np.int32),
                       max_new_tokens=3)])
    assert sched.summary["prefix_hit_rate"] == 0.25
    assert sched.summary["page_evictions"] == 2
    snap = sched.metrics_snapshot()
    assert snap["pool_occupancy"] == 0.5 and snap["cow_copies"] == 1


def test_scheduler_defers_admission_under_pool_pressure():
    """can_admit False while slots are LIVE defers (decode continues,
    pages free as requests finish); False with an IDLE engine is a
    loud error, never a hang."""
    eng = _FakePagedEngine()
    # Pool "too tight for two": deny whenever a slot is live — each
    # admission must wait for the previous request to fully drain.
    eng.admit_ok = lambda plen, max_new: not eng.active.any()
    reqs = [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=3) for i in range(2)]
    done = Scheduler(eng, decode_priority=1).run(reqs)
    assert len(done) == 2                  # deferral, not loss
    # Serialized by the pool: rid 1's first token came after rid 0's
    # last (admissions never overlapped).
    assert eng.admitted == [(0, 3, ""), (1, 3, "")]
    eng2 = _FakePagedEngine(admit_ok=False)
    with pytest.raises(RuntimeError, match="raise --serve.num-pages"):
        Scheduler(eng2).run([Request(rid=0,
                                     prompt=np.asarray([0], np.int32),
                                     max_new_tokens=3)])


def test_scheduler_quarantine_releases_without_retention():
    class _Poisoning(_FakePagedEngine):
        def step(self):
            out = super().step()
            self._bad = [s for s in range(self.num_slots)
                         if self.active[s]
                         and self.slot_rid[s] == 1
                         and self.counts[1] == 2]
            return out

        def take_bad_slots(self):
            out = getattr(self, "_bad", [])
            self._bad = []
            return out

    eng = _Poisoning()
    reqs = [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=4, session=f"c{i}")
            for i in range(2)]
    done = Scheduler(eng, decode_priority=2, slot_retries=2).run(reqs)
    assert len(done) == 2
    # rid 1 was quarantined once: that release carried NO tokens (the
    # poisoned pages must never feed the prefix cache); the final
    # finishes retained.
    assert (1, False, "") in eng.released
    assert eng.released.count((1, True, "c1")) == 1
    by_rid = {c.rid: c for c in done}
    assert by_rid[1].retries == 1


def test_scheduler_session_turns_admit_in_order():
    """A session's turn j+1 never admits before turn j finishes (a
    client cannot send a follow-up before it has the reply) — even
    when both are queued with slots free."""
    eng = _FakePagedEngine(num_slots=2)
    reqs = [
        Request(rid=0, prompt=np.asarray([0], np.int32),
                max_new_tokens=6, session="conv"),
        Request(rid=1, prompt=np.asarray([1], np.int32),
                max_new_tokens=6, session="conv"),
        Request(rid=2, prompt=np.asarray([2], np.int32),
                max_new_tokens=6),
    ]
    done = Scheduler(eng, decode_priority=1).run(reqs)
    assert len(done) == 3
    admits = [rid for rid, _, _ in eng.admitted]
    # rid 2 (no session) may admit anytime; rid 1 strictly after rid 0
    # RELEASED (finished), not merely after it started.
    rel0 = eng.released.index((0, True, "conv"))
    adm1 = eng.admitted.index((1, 6, "conv"))
    assert admits.index(0) < admits.index(1)
    assert [r for r, _, _ in eng.released].index(0) is not None
    # turn 2's admission event happens after turn 1's release event:
    # reconstruct interleaving via counters — turn 1 ran its full
    # budget before turn 2's first token.
    assert eng.counts[0] >= 6
    assert rel0 is not None and adm1 is not None


# --- config surface -----------------------------------------------------

def test_paged_config_validation():
    from tensorflow_distributed_tpu.config import TrainConfig

    ok = TrainConfig(mode="serve", model="gpt_lm")
    ok.serve.paged = True
    ok.serve.page_size = 8
    ok.serve.num_pages = 64
    ok.serve.session_turns = 2
    ok.validate()
    for field, value, msg in [
            ("page_size", 8, "add --serve.paged"),
            ("num_pages", 64, "add --serve.paged"),
            ("radix", False, "add --serve.paged")]:
        bad = TrainConfig(mode="serve", model="gpt_lm")
        setattr(bad.serve, field, value)
        with pytest.raises(ValueError, match=msg):
            bad.validate()
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.serve.paged = True
    bad.serve.page_size = 0
    with pytest.raises(ValueError, match="page_size"):
        bad.validate()
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.serve.session_turns = 2
    bad.serve.requests = "reqs.jsonl"
    with pytest.raises(ValueError, match="session"):
        bad.validate()


# --- journal: sessions survive a kill ----------------------------------

def test_journal_session_roundtrip_and_truncated_replay(tmp_path):
    from tensorflow_distributed_tpu.serve import journal as jm

    path = str(tmp_path / "j.jsonl")
    j = jm.RequestJournal(path)
    j.admit(0, [5, 6], 4, -1, session="conv0")
    j.token(0, 50, 0.1)
    j.token(0, 51, 0.2)
    j.admit(1, [7], 4, -1)
    j.flush()
    j.close()
    # The admit record is self-describing (standalone reads keep the
    # conversation linkage).
    recs = [json.loads(ln) for ln in
            open(path).read().splitlines()]
    assert recs[0]["sess"] == "conv0" and "sess" not in recs[3]
    # Truncated tail (the SIGKILL lands mid-write): replay skips it.
    with open(path, "a") as f:
        f.write('{"e": "tok", "rid": 0, "t"')
    played = jm.replay(path)
    assert played[0]["tokens"] == [50, 51]
    reqs = [Request(rid=0, prompt=np.asarray([5, 6], np.int32),
                    max_new_tokens=4, session="conv0"),
            Request(rid=1, prompt=np.asarray([7], np.int32),
                    max_new_tokens=4, session="")]
    out = jm.apply_replay(reqs, played)
    cont = next(r for r in out if r.rid == 0)
    # The continuation keeps its session id (dataclasses.replace), so
    # the resumed leg re-links the conversation.
    assert cont.session == "conv0"
    assert list(cont.prompt) == [5, 6, 50, 51]
    assert cont.max_new_tokens == 2


# --- report folding -----------------------------------------------------

def test_report_folds_paging_fields(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    path = tmp_path / "m.jsonl"
    rows = [
        {"event": "prefix_hit", "slot": 0, "prompt_len": 40,
         "hit_tokens": 32, "tail_bucket": 16},
        {"event": "prefix_hit", "slot": 1, "prompt_len": 40,
         "hit_tokens": 24, "tail_bucket": 16},
        {"event": "page_evict", "evicted": 3, "reason": "pressure",
         "pages_free": 2, "pages_in_use": 20},
        {"event": "serve_summary", "requests": 4, "wall_s": 1.0,
         "tokens_per_sec": 10.0, "mean_slot_occupancy": 0.5,
         "prefix_hit_rate": 0.7, "prefix_hits": 2,
         "pool_occupancy": 0.8, "pages_peak": 21,
         "slot_pages_peak": 12, "page_evictions": 3,
         "cow_copies": 1, "sessions": 2},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    out = summarize(load_records(str(path)))
    assert out["serve_prefix_hit_events"] == 2
    assert out["serve_prefix_hit_tokens"] == 56
    assert out["serve_page_evict_events"] == 1
    assert out["serve_pages_evicted"] == 3
    assert out["serve_prefix_hit_rate"] == 0.7
    assert out["serve_pool_occupancy"] == 0.8
    assert out["serve_cow_copies"] == 1
    # Plain (dense) summaries stay shape-stable: no paging keys.
    plain = tmp_path / "p.jsonl"
    plain.write_text(json.dumps(
        {"event": "serve_summary", "requests": 1, "wall_s": 1.0,
         "tokens_per_sec": 5.0}) + "\n")
    out2 = summarize(load_records(str(plain)))
    assert not any(k.startswith("serve_prefix")
                   or k.startswith("serve_page") for k in out2)


# --- real engine (slow tier) -------------------------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)

    cfg = tiny_config(causal=True, max_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _decode(eng, prompt, n, session=""):
    slot = eng.free_slots()[0]
    if getattr(eng, "paged", False):
        first = eng.prefill(prompt, slot, max_new_tokens=n,
                            session=session)
    else:
        first = eng.prefill(prompt, slot)
    toks = [first]
    while len(toks) < n:
        toks.append(int(eng.step()[slot]))
    if getattr(eng, "paged", False):
        eng.release(slot, tokens=list(prompt) + toks, session=session)
    else:
        eng.free(slot)
    return toks


@pytest.mark.slow
def test_paged_prefix_hit_token_identity(tiny_lm):
    """THE e2e contract: radix hits, copy-on-write, and session
    re-attach all produce exactly the dense engine's greedy stream."""
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.paging.engine import (
        PagedSlotEngine)

    model, params = tiny_lm
    rng = np.random.default_rng(3)
    prefix = rng.integers(0, 64, 24).astype(np.int32)
    reqs = [np.concatenate([prefix,
                            rng.integers(0, 64, 4 + i).astype(
                                np.int32)]) for i in range(3)]
    aligned = rng.integers(0, 64, 32).astype(np.int32)  # COW trigger

    dense = SlotDecodeEngine(model, params, 2)
    paged = PagedSlotEngine(model, params, 2, page_size=8)
    ref = [_decode(dense, r, 6) for r in reqs]
    got = [_decode(paged, r, 6) for r in reqs]
    assert got == ref
    assert paged.prefix_hits >= 2          # later requests hit
    # Identical aligned prompt twice: full match capped at plen-1
    # lands mid-page on a SHARED page -> COW, identity preserved, and
    # the cached copy survives for the third pass.
    refA = _decode(dense, aligned, 6)
    assert _decode(paged, aligned, 6) == refA
    assert _decode(paged, aligned, 6) == refA
    assert _decode(paged, aligned, 6) == refA
    assert paged.cow_copies >= 1
    # Session re-attach: the follow-up turn extends the conversation
    # (partial tail page included) and matches the dense recompute.
    conv = list(reqs[0]) + ref[0]
    turn2 = np.asarray(conv + [9, 8, 7], np.int32)
    ref2 = _decode(dense, turn2, 5)
    p2 = PagedSlotEngine(model, params, 2, page_size=8)
    _decode(p2, reqs[0], 6, session="sess")
    assert _decode(p2, turn2, 5, session="sess") == ref2
    assert p2.prefix_hits == 1 and p2.radix.sessions_live == 1


@pytest.mark.slow
def test_can_admit_reserves_the_cow_page(tiny_lm):
    """Review finding: attaching cached pages makes them un-evictable,
    and a mid-page match then needs one MORE page for copy-on-write —
    can_admit must count it, or a tight pool passes the check and
    PoolExhausted crashes inside prefill instead of deferring."""
    from tensorflow_distributed_tpu.serve.paging.engine import (
        PagedSlotEngine)

    model, params = tiny_lm                # max_len 64 -> 4 pages of 16
    rng = np.random.default_rng(6)
    cached = rng.integers(0, 64, 32).astype(np.int32)   # 2 full blocks
    eng = PagedSlotEngine(model, params, 2, page_size=16, num_pages=6)
    _decode(eng, cached, 4)                # radix now holds 2 pages
    # Occupy: a live slot pins 2 pages -> 1 free, 2 reclaimable.
    eng.prefill(rng.integers(0, 64, 16).astype(np.int32), 0,
                max_new_tokens=16)
    assert eng.pool.free_count == 1
    # need = 3 (33 tokens) + 1 COW: 1 free + 2 reclaimable cannot
    # cover it — the old check said yes and prefill then exhausted.
    assert not eng.can_admit(32, 1)
    eng.free(0)                            # the live slot drains
    assert eng.can_admit(32, 1)
    out = _decode(eng, cached, 4)          # now admits, COW fires
    assert eng.cow_copies == 1
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    ref = _decode(SlotDecodeEngine(model, params, 2), cached, 4)
    assert out == ref


@pytest.mark.slow
def test_paged_quarantine_scrubs_private_spares_shared(tiny_lm):
    """slot_nan drill on a paged slot: only PRIVATE pages poison (the
    flag fires), the quarantine release scrubs them before they re-
    enter the free list, and the SHARED prefix pages keep serving
    correct tokens."""
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.paging.engine import (
        PagedSlotEngine)

    model, params = tiny_lm
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 64, 24).astype(np.int32)
    dense = SlotDecodeEngine(model, params, 2)
    ref = _decode(dense, prompt, 6)
    eng = PagedSlotEngine(model, params, 2, page_size=8)
    _decode(eng, prompt, 6)                # seeds the prefix cache
    slot = eng.free_slots()[0]
    eng.prefill(prompt, slot, max_new_tokens=6)
    assert eng.prefix_hits == 1            # shared pages attached
    eng.poison_slot(slot)
    eng.step()
    assert eng.take_bad_slots() == [slot]
    eng.free(slot)                         # quarantine: no retention
    # The shared pages survive — a fresh identical request still hits
    # AND still decodes the dense stream (nothing scrubbed them, no
    # NaN leaked through a recycled page).
    assert _decode(eng, prompt, 6) == ref
    assert eng.prefix_hits == 2
    # And the scrubbed pages are genuinely clean: fill the pool with
    # fresh admissions that reuse them.
    other = rng.integers(0, 64, 20).astype(np.int32)
    assert _decode(eng, other, 6) == _decode(dense, other, 6)


@pytest.mark.slow
def test_paged_composes_with_int8_and_speculation(tiny_lm):
    """kv_dtype=int8 and spec_tokens both ride the paged executables:
    int8-paged matches int8-dense bit-for-bit (same quantized math,
    relocated bytes), and paged speculation stays token-identical to
    plain paged decode."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.paging.engine import (
        PagedSlotEngine)

    model, params = tiny_lm
    q = type(model)(dc.replace(model.cfg, kv_cache_quant="int8"),
                    model.mesh)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 64, 12 + 3 * i).astype(np.int32)
               for i in range(3)]
    d8 = SlotDecodeEngine(q, params, 2)
    p8 = PagedSlotEngine(q, params, 2, page_size=8)
    for pr in prompts:
        assert _decode(p8, pr, 6) == _decode(d8, pr, 6)
    assert p8.page_bytes() < PagedSlotEngine(
        model, params, 2, page_size=8).page_bytes()
    # Speculation: k-gram self-draft over the paged verify program.
    from tensorflow_distributed_tpu.serve.speculate import SelfDraft

    plain = PagedSlotEngine(model, params, 2, page_size=8)
    ref = [_decode(plain, pr, 8) for pr in prompts]
    spec_eng = PagedSlotEngine(model, params, 2, page_size=8,
                               spec_tokens=2)
    sched = Scheduler(spec_eng, decode_priority=2,
                      speculator=SelfDraft(2, 2))
    done = sched.run([Request(rid=i, prompt=pr, max_new_tokens=8)
                      for i, pr in enumerate(prompts)])
    by_rid = {c.rid: c.tokens for c in done}
    assert [by_rid[i] for i in range(3)] == ref
    assert spec_eng.verify_steps > 0
