"""Regression ledger (observe/regress.py): manifest evaluation,
direction/tolerance semantics, the degraded-artifact drill, and the
clean pass over the committed set. Stdlib-only, jax-free."""

import json

import pytest

from tensorflow_distributed_tpu.observe import regress
from tensorflow_distributed_tpu.observe.regress import (
    Check, compare_artifact, compare_check, main, manifest_for,
    manifest_names, parse_artifact, render_table)


def test_parse_artifact_jsonl_and_json():
    jsonl = "\n".join([
        json.dumps({"metric": "a", "value": 1}),
        "not json",
        json.dumps({"metric": "a", "value": 2}),  # rerun: last wins
        json.dumps({"no_metric": True}),
    ])
    doc = parse_artifact(jsonl, "jsonl")
    assert doc == {"a": {"metric": "a", "value": 2}}
    doc = parse_artifact(json.dumps({"x": {"y": 3}}), "json")
    assert doc["x"]["y"] == 3


def _cmp(check, base, fresh):
    return compare_check(check, base, fresh)["verdict"]


def test_numeric_direction_and_band():
    c = Check("m.value", "higher", rtol=0.1)
    base = {"m": {"value": 100.0}}
    assert _cmp(c, base, {"m": {"value": 95.0}}) == "ok"      # in band
    assert _cmp(c, base, {"m": {"value": 85.0}}) == "regression"
    assert _cmp(c, base, {"m": {"value": 120.0}}) == "improved"
    c = Check("m.value", "lower", atol=0.5)
    base = {"m": {"value": 2.0}}
    assert _cmp(c, base, {"m": {"value": 2.4}}) == "ok"
    assert _cmp(c, base, {"m": {"value": 2.6}}) == "regression"
    assert _cmp(c, base, {"m": {"value": 1.0}}) == "improved"


def test_zero_baseline_uses_atol():
    # "must stay 0" counts: relative tolerance is useless at base 0.
    c = Check("m.value", "lower", rtol=0.5, atol=0.0)
    assert _cmp(c, {"m": {"value": 0}}, {"m": {"value": 1}}) \
        == "regression"
    assert _cmp(c, {"m": {"value": 0}}, {"m": {"value": 0}}) == "ok"


def test_truthy_semantics():
    c = Check("m.ok", "truthy")
    assert _cmp(c, {"m": {"ok": True}}, {"m": {"ok": True}}) == "ok"
    assert _cmp(c, {"m": {"ok": True}}, {"m": {"ok": False}}) \
        == "regression"
    # Baseline already failing -> skip, not a block on unrelated PRs.
    assert _cmp(c, {"m": {"ok": False}}, {"m": {"ok": False}}) \
        == "skip"


def test_equal_and_missing_semantics():
    c = Check("m.n", "equal")
    assert _cmp(c, {"m": {"n": 32}}, {"m": {"n": 32}}) == "ok"
    assert _cmp(c, {"m": {"n": 32}}, {"m": {"n": 31}}) == "regression"
    # Gate disappeared from the fresh artifact -> regression.
    assert _cmp(c, {"m": {"n": 32}}, {}) == "regression"
    # New metric (not in baseline) -> skip.
    assert _cmp(c, {}, {"m": {"n": 32}}) == "skip"


def test_manifest_covers_the_committed_artifacts():
    names = manifest_names()
    for required in ("GRADSYNC.json", "SERVEBENCH.json",
                     "SLOBENCH.json", "FIREBENCH.json",
                     "ELASTICBENCH.json", "PLANBENCH.json"):
        assert required in names
    assert any(n.startswith("BENCH_r") for n in names)
    assert manifest_for("BENCH_r03.json") is not None
    assert manifest_for("UNKNOWN.json") is None


def test_compare_artifact_explicit_paths(tmp_path):
    base = tmp_path / "base.json"
    fresh = tmp_path / "fresh.json"
    base.write_text(json.dumps(
        {"metric": "fire_goodput", "value": 0.9}) + "\n" + json.dumps(
        {"metric": "fire_checks", "goodput_ok": True,
         "lost_requests": 0, "token_identical": 32}) + "\n" + json.dumps(
        {"metric": "fire_tokens_per_sec", "value": 1800.0}))
    fresh.write_text(json.dumps(
        {"metric": "fire_goodput", "value": 0.5}) + "\n" + json.dumps(
        {"metric": "fire_checks", "goodput_ok": True,
         "lost_requests": 0, "token_identical": 32}) + "\n" + json.dumps(
        {"metric": "fire_tokens_per_sec", "value": 1801.0}))
    findings = compare_artifact("FIREBENCH.json",
                                fresh_path=str(fresh),
                                baseline_path=str(base))
    by_check = {f["check"]: f["verdict"] for f in findings}
    assert by_check["fire_goodput.value"] == "regression"
    assert by_check["fire_tokens_per_sec.value"] == "ok"
    assert by_check["fire_checks.goodput_ok"] == "ok"
    assert "REGRESSION" in render_table(findings)


def test_committed_set_passes_clean():
    # The t1 smoke contract: an untouched working tree vs HEAD has
    # zero regressions. Skip when git can't serve a baseline (e.g. a
    # tarball checkout).
    if regress.baseline_text("FIREBENCH.json") is None:
        pytest.skip("no git baseline available")
    findings = []
    for name in manifest_names():
        findings.extend(compare_artifact(name))
    bad = [f for f in findings if f["verdict"] == "regression"]
    assert not bad, bad


def test_cli_degraded_artifact_exits_nonzero(tmp_path, capsys):
    if regress.baseline_text("FIREBENCH.json") is None:
        pytest.skip("no git baseline available")
    from tensorflow_distributed_tpu.benchmarks.calibbench import (
        degraded_copy)

    degraded = degraded_copy("FIREBENCH.json", {"fire_goodput": 0.5})
    rc = main(["--artifact", "FIREBENCH.json", "--fresh", degraded])
    out = capsys.readouterr()
    assert rc == 1
    assert "REGRESSION" in out.out
    assert "fire_goodput.value" in out.out


def test_cli_list_prints_manifest(capsys):
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "FIREBENCH.json" in out
    assert "fire_goodput.value" in out


def test_cli_missing_fresh_artifact_is_regression(tmp_path, capsys):
    rc = main(["--artifact", "FIREBENCH.json",
               "--fresh", str(tmp_path / "nope.json")])
    assert rc == 1
