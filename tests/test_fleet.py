"""Fleet serving: router policy on fake replicas, controller
lifecycle on fake processes, the replica-side inbox feed, and one slow
supervised e2e (2-replica real fleet, SIGKILL mid-stream, zero lost).

The fast tier is jax-free by design: fleet/router.py and
fleet/controller.py are host policy driven by an explicit ``now``, so
every scenario (failover token identity, quarantine/rejoin, retry
budgets, shedding order, drain-before-stop, rolling swaps) runs on
fakes with a hand-advanced clock.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from tensorflow_distributed_tpu.fleet.controller import (
    ControllerConfig, FleetController, latest_ckpt_step)
from tensorflow_distributed_tpu.fleet.replica import (
    InboxFeed, ReplicaHandle, append_line)
from tensorflow_distributed_tpu.fleet.router import (
    Router, RouterConfig, SLO_CLASSES)


# --- the deterministic fake replica --------------------------------------

def _next_tok(context):
    """The fake "greedy decode": next token is a pure function of the
    FULL context — so a continuation (prompt + tokens so far) on a
    different replica produces exactly the tokens the dead one would
    have, like real greedy decode with shared weights."""
    return (sum(context) * 31 + 7) % 97


def _stream(prompt, n):
    ctx = list(prompt)
    out = []
    for _ in range(n):
        t = _next_tok(ctx)
        out.append(t)
        ctx.append(t)
    return out


class FakeReplica:
    """In-memory replica with the ReplicaHandle surface the router and
    controller read/write (name/epoch/send/read_snapshot/
    read_journal). ``tick()`` serves ``tok_per_tick`` tokens per live
    request and bumps the snapshot seq (unless frozen — the
    stale-snapshot drill)."""

    def __init__(self, name, tok_per_tick=2, max_len=4096):
        self.name = name
        self.epoch = 0
        self.tok_per_tick = tok_per_tick
        self.max_len = max_len
        self.live = {}        # rid -> {"ctx": [...], "left": n}
        self.journal = {}     # rid -> replay-shaped entry
        self.sent = []        # every inbox line, in order
        self.seq = 0
        self.frozen = False
        self.anomaly = {"anomalies": 0, "active": [],
                        "by_detector": {}}
        self.ckpt_step = 2
        self.queue_depth = 0  # extra load the snapshot reports
        self.ttft_p95 = {}    # class -> ms, for the score tiebreak

    # -- handle surface --------------------------------------------------

    def send(self, obj):
        self.sent.append(obj)
        if "cmd" in obj:
            if obj["cmd"] == "cancel":
                self.live.pop(obj.get("rid"), None)
            elif obj["cmd"] == "swap":
                self.ckpt_step = obj.get("_to", self.ckpt_step)
            return
        rid = obj["rid"]
        self.journal[rid] = {"req": None, "tokens": [], "done": False,
                             "reject": False, "last_s": 0.0}
        self.live[rid] = {"ctx": [int(t) for t in obj["prompt"]],
                          "left": int(obj["max_new"])}

    def read_snapshot(self):
        if self.seq == 0:
            return None
        snap = {"seq": self.seq, "wall_ts": 0.0, "pid": 1234,
                "queue_depth": self.queue_depth,
                "requests_live": len(self.live),
                "anomaly": dict(self.anomaly),
                "ckpt_step": self.ckpt_step,
                "num_slots": 2, "max_len": self.max_len}
        for cls, ms in self.ttft_p95.items():
            snap[f"ttft_ms_p95_{cls}"] = ms
        return snap

    def read_journal(self):
        return {rid: dict(e, tokens=list(e["tokens"]))
                for rid, e in self.journal.items()}

    # -- simulation ------------------------------------------------------

    def tick(self):
        for rid in list(self.live):
            st = self.live[rid]
            for _ in range(min(self.tok_per_tick, st["left"])):
                t = _next_tok(st["ctx"])
                st["ctx"].append(t)
                st["left"] -= 1
                self.journal[rid]["tokens"].append(t)
            if st["left"] == 0:
                self.journal[rid]["done"] = True
                del self.live[rid]
        if not self.frozen:
            self.seq += 1


def _gen(rid, n=1):
    """The wire/journal id of rid's n-th dispatch (router gen rids)."""
    return rid * 1024 + n


def _req(rid, arrival=0.0, slo="standard", max_new=6, plen=3):
    return {"rid": rid, "prompt": [rid + 1] * plen,
            "max_new": max_new, "eos": -1, "arrival_s": arrival,
            "slo": slo}


def _router(reps, emit=None, **cfg):
    r = Router(reps, RouterConfig(**cfg), emit=emit)
    r.begin(0.0)
    return r


def _spin(router, reps, t0, t1, dt=0.1):
    """Advance sim time: tick every replica, step the router."""
    t = t0
    while t < t1:
        for rep in reps:
            rep.tick()
        t = round(t + dt, 6)
        router.step(t)
    return t


def test_slo_class_parity_with_scheduler():
    from tensorflow_distributed_tpu.serve.scheduler import (
        SLO_CLASSES as sched_classes)
    assert tuple(SLO_CLASSES) == tuple(sched_classes)


def test_dispatch_least_loaded():
    a, b = FakeReplica("a"), FakeReplica("b")
    a.queue_depth = 3          # a is busier
    a.tick(), b.tick()         # first snapshots
    router = _router([a, b])
    router.submit([_req(0)])
    router.step(0.1)
    assert not b.live or _gen(0) in b.live
    assert [o for o in b.sent if "rid" in o]
    assert not [o for o in a.sent if "rid" in o]


def test_dispatch_class_p95_tiebreak():
    # Equal load; replica b has been slow for "high" — a wins.
    a, b = FakeReplica("a"), FakeReplica("b")
    a.ttft_p95 = {"high": 10.0}
    b.ttft_p95 = {"high": 500.0}
    a.tick(), b.tick()
    router = _router([a, b])
    router.submit([_req(0, slo="high")])
    router.step(0.1)
    assert [o for o in a.sent if "rid" in o]
    assert not [o for o in b.sent if "rid" in o]


def test_failover_redispatch_token_identity():
    a, b = FakeReplica("a", tok_per_tick=1), FakeReplica(
        "b", tok_per_tick=1)
    events = []
    router = _router([a, b], emit=lambda e, **f: events.append((e, f)))
    router.submit([_req(0, max_new=8)])
    a.tick(), b.tick()
    router.step(0.1)
    owner = a if a.live else b
    # A few tokens land, then the owner dies mid-request.
    t = _spin(router, [a, b], 0.1, 0.4)
    assert _gen(0) in owner.live
    served = len(owner.journal[_gen(0)]["tokens"])
    assert 0 < served < 8
    owner.frozen = True        # a dead process stops everything
    owner.live.clear()
    router.mark_dead(owner.name, t)
    other = b if owner is a else a
    t = _spin(router, [other], t, 2.0)
    tr = router.tracks[0]
    assert tr.state == "done"
    assert tr.retries == 1 and tr.redispatched
    # The assembled stream is exactly the uninterrupted one.
    assert tr.tokens == _stream([1, 1, 1], 8)
    # The continuation carried prompt + served tokens.
    cont = [o for o in other.sent if "rid" in o][-1]
    assert cont["prompt"] == [1, 1, 1] + tr.tokens[:served]
    assert cont["max_new"] == 8 - served
    kinds = [e for e, _ in events]
    assert "fleet_dispatch" in kinds
    assert ("fleet_replica",) and any(
        f.get("state") == "dead" for e, f in events
        if e == "fleet_replica")


def test_quarantine_on_anomaly_evacuates_and_rejoins():
    a, b = FakeReplica("a", tok_per_tick=1), FakeReplica(
        "b", tok_per_tick=1)
    events = []
    router = _router([a, b],
                     emit=lambda e, **f: events.append((e, f)),
                     anomaly_cooldown_s=60.0)
    router.submit([_req(0, max_new=12)])
    a.tick(), b.tick()
    router.step(0.1)
    owner = a if a.live else b
    other = b if owner is a else a
    t = _spin(router, [a, b], 0.1, 0.4)
    # The engine flags a slot: anomaly state rides the snapshot.
    owner.anomaly = {"anomalies": 1, "active": ["slot_nonfinite"],
                     "by_detector": {"slot_nonfinite": 1}}
    owner.tick()
    router.step(t + 0.1)
    assert router.reps[owner.name].health == "quarantined"
    # In-flight moved to the peer as a continuation; the old owner
    # got a cancel.
    assert any(o.get("cmd") == "cancel" for o in owner.sent)
    t = _spin(router, [a, b], t + 0.1, 1.2)
    assert (_gen(0, 2) in other.live
            or other.journal.get(_gen(0, 2), {}).get("done"))
    # New work never lands on the quarantined replica...
    router.submit([_req(1, arrival=0.0)])
    router.step(t + 0.1)
    assert _gen(1) not in owner.live
    # ...until the anomaly clears (hub horizon passed) — then REJOIN,
    # and the replica takes work again (no permanent capacity loss).
    owner.anomaly = {"anomalies": 1, "active": [],
                     "by_detector": {"slot_nonfinite": 1}}
    owner.tick()
    router.step(t + 0.2)
    assert router.reps[owner.name].health == "up"
    assert any(f.get("state") == "rejoined" for e, f in events
               if e == "fleet_replica")


def test_anomaly_cooldown_rejoin_does_not_oscillate():
    a, b = FakeReplica("a", tok_per_tick=1), FakeReplica("b")
    router = _router([a, b], anomaly_cooldown_s=1.0)
    router.submit([_req(0)])
    for rep in (a, b):
        rep.tick()
    router.step(0.1)
    a.anomaly = {"anomalies": 2, "active": ["slot_nonfinite"],
                 "by_detector": {"slot_nonfinite": 2}}
    t = _spin(router, [a, b], 0.1, 0.5)
    assert router.reps["a"].health == "quarantined"
    # The active entry never clears (idle replica, frozen step
    # clock) — the cooldown rejoins anyway...
    t = _spin(router, [a, b], t, t + 1.5)
    assert router.reps["a"].health == "up"
    # ...and the STALE active entry must not re-quarantine (count
    # unchanged). A NEW firing (count grows) must.
    t = _spin(router, [a, b], t, t + 0.5)
    assert router.reps["a"].health == "up"
    a.anomaly = {"anomalies": 3, "active": ["slot_nonfinite"],
                 "by_detector": {"slot_nonfinite": 3}}
    a.tick()
    router.step(t + 0.1)
    assert router.reps["a"].health == "quarantined"


def test_quarantine_on_stale_snapshot_and_rejoin():
    a, b = FakeReplica("a", tok_per_tick=1), FakeReplica(
        "b", tok_per_tick=1)
    router = _router([a, b], stale_s=0.5)
    router.submit([_req(0, max_new=20)])
    a.tick(), b.tick()
    router.step(0.1)
    owner = a if a.live else b
    other = b if owner is a else a
    t = _spin(router, [a, b], 0.1, 0.3)
    owner.frozen = True        # exports stop; the process still runs
    t = _spin(router, [a, b], t, t + 1.0)
    assert router.reps[owner.name].health == "quarantined"
    assert router.reps[owner.name].reason == "stale_snapshot"
    # In-flight re-dispatched; peer finishes the stream identically.
    t = _spin(router, [a, b], t, t + 3.0)
    assert router.tracks[0].state == "done"
    assert router.tracks[0].tokens == _stream([1, 1, 1], 20)
    assert other.journal[_gen(0, 2)]["done"]
    # Exports resume -> seq advances -> rejoin.
    owner.frozen = False
    owner.tick()
    router.step(t + 0.1)
    assert router.reps[owner.name].health == "up"


def test_retry_budget_exhaustion_sheds_loudly():
    # One replica that accepts work but never serves a token.
    a = FakeReplica("a", tok_per_tick=0)
    events = []
    router = _router([a], emit=lambda e, **f: events.append((e, f)),
                     dispatch_timeout_s=0.5, retry_budget=2,
                     backoff_base_s=0.1, backoff_max_s=0.2)
    router.submit([_req(0)])
    t = _spin(router, [a], 0.0, 5.0)
    tr = router.tracks[0]
    assert tr.state == "shed" and tr.shed_reason == "retry_budget"
    assert tr.retries == 3     # budget 2 exhausted on the 3rd
    assert not router.active()     # shed, never hang
    assert any(e == "fleet_shed" and f["reason"] == "retry_budget"
               for e, f in events)


def test_saturation_shed_order_lowest_class_first():
    a = FakeReplica("a")
    a.queue_depth = 99         # saturated forever
    a.tick()
    events = []
    router = _router([a], emit=lambda e, **f: events.append((e, f)),
                     queue_high=8, shed_wait_s=1.0)
    router.submit([_req(0, slo="high"), _req(1, slo="batch"),
                   _req(2, slo="standard")])
    t = 0.0
    while router.active() and t < 10.0:
        a.tick()
        t = round(t + 0.5, 6)
        router.step(t)
    sheds = [f for e, f in events if e == "fleet_shed"]
    assert [s["slo"] for s in sheds] == ["batch", "standard", "high"]
    assert all(s["reason"] == "saturated" for s in sheds)
    assert not router.active()


def test_dispatch_timeout_retries_with_capped_backoff():
    a = FakeReplica("a", tok_per_tick=0)   # wedged on the request
    b = FakeReplica("b", tok_per_tick=2)
    a.ttft_p95 = {}
    router = _router([a, b], dispatch_timeout_s=0.5,
                     backoff_base_s=0.4, backoff_max_s=1.0,
                     retry_budget=5)
    router.submit([_req(0, max_new=4)])
    a.tick(), b.tick()
    a.queue_depth = 0
    router.step(0.05)
    owner = a if a.live else b
    if owner is b:             # force the wedged replica as owner
        b.live.clear()
        router.reps["b"].inflight.clear()
        pytest.skip("dispatch landed on the healthy replica")
    # Past the timeout: cancelled on a, backoff scheduled.
    router.step(0.7)
    tr = router.tracks[0]
    assert tr.state == "waiting" and tr.retries == 1
    assert any(o.get("cmd") == "cancel" for o in a.sent)
    assert tr.next_t == pytest.approx(0.7 + 0.4)
    # Not re-dispatched before the backoff deadline...
    router.step(0.9)
    assert tr.state == "waiting"
    # ...after it, anywhere healthy (including b).
    _spin(router, [a, b], 1.2, 3.0)
    assert tr.state == "done"
    assert tr.tokens == _stream([1, 1, 1], 4)


def test_reject_in_journal_sheds():
    a = FakeReplica("a")
    a.tick()
    router = _router([a])
    router.submit([_req(0)])
    router.step(0.1)
    a.journal[_gen(0)]["reject"] = True
    a.live.pop(_gen(0), None)
    a.tick()
    router.step(0.2)
    assert router.tracks[0].state == "shed"
    assert router.tracks[0].shed_reason == "rejected"


def test_summary_shape_and_recovery_population():
    a, b = FakeReplica("a", tok_per_tick=1), FakeReplica(
        "b", tok_per_tick=1)
    router = _router([a, b])
    router.submit([_req(i, arrival=0.0, max_new=4)
                   for i in range(4)])
    a.tick(), b.tick()
    router.step(0.1)
    owner = a if a.live else b
    router.mark_dead(owner.name, 0.3)
    other = b if owner is a else a
    _spin(router, [other], 0.3, 3.0)
    s = router.summary()
    assert s["requests"] == 4 and s["requests_lost"] == 0
    assert s["requests_done"] == 4
    assert s["deaths"] == 1
    assert s["redispatches"] >= 1
    hist = s["dispatch_retry_hist"]
    assert sum(hist.values()) == 4 and "1" in hist
    assert s["recovery_requests"] >= 1
    assert "ttft_ms_p99_recovery" in s
    assert s["ttft_ms_p50"] >= 0


def test_session_turns_stick_to_one_replica_and_repin_on_death():
    a, b = FakeReplica("a", tok_per_tick=2), FakeReplica(
        "b", tok_per_tick=2)
    router = _router([a, b])
    router.submit([
        dict(_req(0, max_new=4), session="s1"),
        dict(_req(1, arrival=0.0, max_new=4), session="s1"),
        dict(_req(2, arrival=0.0, max_new=4)),   # fills the peer
    ])
    a.tick(), b.tick()
    _spin(router, [a, b], 0.0, 2.0)
    owner = {o.get("session"): n for n, rep in (("a", a), ("b", b))
             for o in rep.sent if "rid" in o and o.get("session")}
    # Both turns of s1 landed on the SAME replica despite
    # least-loaded balancing wanting to spread them.
    s1_owners = {n for n, rep in (("a", a), ("b", b))
                 for o in rep.sent
                 if "rid" in o and o.get("session") == "s1"}
    assert len(s1_owners) == 1
    assert owner["s1"] in s1_owners
    # A later turn re-pins when the owner dies.
    dead = a if "a" in s1_owners else b
    alive = b if dead is a else a
    router.mark_dead(dead.name, 2.0)
    router.submit([dict(_req(3, arrival=0.0, max_new=4),
                        session="s1")])
    _spin(router, [alive], 2.0, 4.0)
    assert any(o.get("session") == "s1" for o in alive.sent
               if "rid" in o)
    assert router.tracks[3].state == "done"


# --- replica-side: inbox feed + handle -----------------------------------

def test_inbox_feed_requests_commands_and_torn_tail(tmp_path):
    path = str(tmp_path / "inbox.jsonl")
    feed = InboxFeed(path, poll_s=0.0)
    assert feed.poll() == []                # absent file = quiet
    append_line(path, {"rid": 7, "prompt": [1, 2], "max_new": 3,
                       "slo": "high"})
    append_line(path, {"cmd": "drain"})
    # A torn tail (writer mid-append) stays unconsumed...
    with open(path, "a") as f:
        f.write('{"rid": 8, "prompt": [3')
    items = feed.poll()
    # ORDERED: the request line precedes the drain command.
    assert [getattr(i, "rid", None) for i in items] == [7, None]
    assert items[0].slo == "high" and items[0].max_new_tokens == 3
    assert items[1] == {"cmd": "drain"}
    # ...and is delivered once completed.
    with open(path, "a") as f:
        f.write(', 4], "max_new": 2}\n')
    items = feed.poll()
    assert [i.rid for i in items] == [8]
    assert list(items[0].prompt) == [3, 4]
    # Unknown SLO coerces; missing rid raises.
    append_line(path, {"rid": 9, "prompt": [1], "slo": "platinum"})
    assert feed.poll()[0].slo == "standard"
    append_line(path, {"prompt": [1]})
    with pytest.raises(ValueError, match="rid"):
        feed.poll()
    append_line(path, {"cmd": "explode"})
    with pytest.raises(ValueError, match="unknown command"):
        feed.poll()


def test_replica_handle_incremental_journal_tail(tmp_path):
    h = ReplicaHandle("r0", str(tmp_path / "r0"))
    h.begin_epoch(0)
    with open(h.journal, "w") as f:
        f.write(json.dumps({"e": "admit", "rid": 1, "prompt": [1],
                            "max_new": 4, "eos": -1}) + "\n")
        f.write(json.dumps({"e": "tok", "rid": 1, "t": 5,
                            "s": 0.1}) + "\n")
    assert h.read_journal()[1]["tokens"] == [5]
    # New lines accumulate; a torn tail waits for completion.
    with open(h.journal, "a") as f:
        f.write(json.dumps({"e": "tok", "rid": 1, "t": 6,
                            "s": 0.2}) + "\n")
        f.write('{"e": "tok", "rid": 1, "t":')
    assert h.read_journal()[1]["tokens"] == [5, 6]
    with open(h.journal, "a") as f:
        f.write(' 7, "s": 0.3}\n')
        f.write(json.dumps({"e": "done", "rid": 1}) + "\n")
    ent = h.read_journal()[1]
    assert ent["tokens"] == [5, 6, 7] and ent["done"]
    # The incremental accumulator matches a full replay, and an epoch
    # rollover resets it.
    from tensorflow_distributed_tpu.serve import journal as jmod
    assert h.read_journal()[1]["tokens"] == \
        jmod.replay(h.journal)[1]["tokens"]
    h.begin_epoch(1)
    assert h.read_journal() == {}


def test_replica_handle_epochs_and_tolerant_readers(tmp_path):
    h = ReplicaHandle("r0", str(tmp_path / "r0"))
    h.begin_epoch(0)
    assert "/e0/" in h.inbox
    assert h.read_snapshot() is None        # absent
    with open(h.snapshot, "w") as f:
        f.write("{torn")
    assert h.read_snapshot() is None        # torn
    with open(h.snapshot, "w") as f:
        json.dump({"seq": 3}, f)
    assert h.read_snapshot() == {"seq": 3}
    h.send({"rid": 1, "prompt": [1], "max_new": 1})
    assert os.path.exists(h.inbox)
    old_journal = h.journal
    with open(old_journal, "w") as f:
        f.write(json.dumps({"e": "admit", "rid": 1, "prompt": [1],
                            "max_new": 4, "eos": -1}) + "\n")
        f.write(json.dumps({"e": "tok", "rid": 1, "t": 5,
                            "s": 0.1}) + "\n")
    assert h.read_journal()[1]["tokens"] == [5]
    h.begin_epoch(1)
    assert "/e1/" in h.inbox
    assert h.read_journal() == {}           # fresh epoch, fresh files
    assert h.read_journal(epoch=0)[1]["tokens"] == [5]


# --- controller ----------------------------------------------------------

class FakeProc:
    def __init__(self):
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)

    def kill(self):
        self.signals.append(9)
        self.rc = -9


def _controller(tmp_path, n=2, ckpt_dir="", **cfg):
    handles = [ReplicaHandle(f"r{i}", str(tmp_path / f"r{i}"))
               for i in range(n)]
    procs = []

    def spawn(cmd):
        p = FakeProc()
        procs.append(p)
        return p

    deaths, restarts = [], []
    ctl = FleetController(
        handles, ["--mode", "serve"], ckpt_dir=ckpt_dir,
        cfg=ControllerConfig(backoff_base_s=0.5, backoff_max_s=2.0,
                             max_restarts=2, **cfg),
        spawn=spawn,
        on_death=lambda n_, t: deaths.append(n_),
        on_restart=lambda n_, t: restarts.append(n_))
    ctl.start(0.0)
    return ctl, handles, procs, deaths, restarts


def test_controller_restart_backoff_and_epoch_rotation(tmp_path):
    ctl, handles, procs, deaths, restarts = _controller(tmp_path)
    assert len(procs) == 2 and handles[0].epoch == 0
    procs[0].rc = -9                        # SIGKILL'd replica
    ctl.poll(1.0)
    assert deaths == ["r0"]
    ctl.poll(1.2)                           # inside backoff: no spawn
    assert len(procs) == 2
    ctl.poll(1.6)                           # past 0.5s backoff
    assert len(procs) == 3
    assert restarts == ["r0"]
    assert handles[0].epoch == 1            # fresh epoch directory
    assert os.path.isdir(handles[0].epoch_dir())
    # Second death: backoff doubles.
    procs[2].rc = 1
    ctl.poll(2.0)
    ctl.poll(2.5)
    assert len(procs) == 3
    ctl.poll(3.1)
    assert len(procs) == 4
    # Third death: budget (2) exhausted — stays down.
    procs[3].rc = 1
    ctl.poll(4.0)
    ctl.poll(99.0)
    assert len(procs) == 4
    assert ctl.members["r0"].gone


def test_controller_diverged_not_restarted(tmp_path):
    ctl, handles, procs, deaths, restarts = _controller(tmp_path)
    procs[1].rc = 2                         # SlotRetryExhausted
    ctl.poll(1.0)
    ctl.poll(50.0)
    assert len(procs) == 2 and ctl.members["r1"].gone
    assert deaths == ["r1"] and restarts == []


def test_controller_drain_before_stop(tmp_path):
    ctl, handles, procs, deaths, restarts = _controller(tmp_path)
    ctl.request_stop(5.0)
    for h in handles:
        with open(h.inbox) as f:
            lines = [json.loads(ln) for ln in f]
        assert {"cmd": "drain"} in lines
    # Replicas finish in-flight work and exit 0 by themselves: no
    # signal is ever sent.
    t = {"v": 0.0}

    def clock():
        t["v"] += 0.05
        if t["v"] > 1.0:
            for p in procs:
                p.rc = 0
        return t["v"]

    assert ctl.wait_stopped(clock=clock, sleep=lambda s: None)
    assert all(p.signals == [] for p in procs)
    # A drain exit during draining is not a death.
    ctl.poll(t["v"])
    assert deaths == []


def test_controller_drain_escalates_on_deadline(tmp_path):
    ctl, handles, procs, *_ = _controller(tmp_path,
                                          drain_timeout_s=1.0)
    ctl.request_stop(0.0)
    t = {"v": 0.0}

    def clock():
        t["v"] += 0.3
        return t["v"]

    assert not ctl.wait_stopped(clock=clock, sleep=lambda s: None)
    assert any(p.signals for p in procs)    # TERM (then KILL) sent


def _mk_step(ckpt_dir, step, marker="state.msgpack"):
    d = os.path.join(ckpt_dir, f"step_{step:08d}")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, marker), "w") as f:
        f.write("x")


def test_latest_ckpt_step_scanner_matches_checkpoint_layer(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    assert latest_ckpt_step(ckpt) is None
    _mk_step(ckpt, 2)
    _mk_step(ckpt, 6, marker="ORBAX_COMMITTED")
    _mk_step(ckpt, 8, marker="unrelated.file")   # incomplete: no marker
    os.makedirs(os.path.join(ckpt, "step_00000010.tmp"))
    os.makedirs(os.path.join(ckpt, "quarantined_step_00000004"))
    with open(os.path.join(ckpt, "step_00000012"), "w") as f:
        f.write("a stray file")
    assert latest_ckpt_step(ckpt) == 6
    # Contract parity with the checkpoint layer's own scan.
    from tensorflow_distributed_tpu.train.checkpoint import (
        available_steps)
    assert available_steps(ckpt) == [2, 6]


def test_controller_rolling_swap_one_replica_at_a_time(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    _mk_step(ckpt, 2)
    ctl, handles, procs, *_ = _controller(tmp_path, n=3,
                                          ckpt_dir=ckpt)
    # start() pinned the pre-existing step as already rolled.
    assert ctl.rolled_step == 2

    def snap(h, step):
        os.makedirs(h.epoch_dir(), exist_ok=True)
        with open(h.snapshot, "w") as f:
            json.dump({"seq": 1, "ckpt_step": step}, f)

    def swap_cmds(h):
        if not os.path.exists(h.inbox):
            return 0
        with open(h.inbox) as f:
            return sum(1 for ln in f
                       if json.loads(ln).get("cmd") == "swap")

    for h in handles:
        snap(h, 2)
    ctl.poll(1.0)
    assert all(swap_cmds(h) == 0 for h in handles)   # nothing new
    _mk_step(ckpt, 4)                               # trainer emitted
    ctl.poll(2.0)
    # ONE replica told to swap; the rest untouched (capacity >= N-1).
    assert [swap_cmds(h) for h in handles] == [1, 0, 0]
    ctl.poll(2.5)                                   # r0 not acked yet
    assert [swap_cmds(h) for h in handles] == [1, 0, 0]
    assert ctl.staleness_max == 2
    snap(handles[0], 4)                             # r0 acks
    ctl.poll(3.0)
    assert [swap_cmds(h) for h in handles] == [1, 1, 0]
    snap(handles[1], 4)
    ctl.poll(3.5)
    assert [swap_cmds(h) for h in handles] == [1, 1, 1]
    snap(handles[2], 4)
    ctl.poll(4.0)
    assert ctl.rolling_swaps == 1 and not ctl.swap_in_progress
    assert ctl.summary()["replica_swaps"] == {"r0": 1, "r1": 1,
                                              "r2": 1}
    # A dead replica is skipped (its restart restores the newest
    # checkpoint anyway) — the roll never stalls on it.
    procs[1].rc = -9
    ctl.poll(5.0)
    _mk_step(ckpt, 6)
    ctl.poll(5.1)
    snap(handles[0], 6)
    ctl.poll(5.2)
    ctl.poll(5.3)
    snap(handles[2], 6)
    ctl.poll(5.4)
    assert ctl.rolling_swaps == 2
    assert swap_cmds(handles[1]) == 1               # never re-told


def test_controller_swap_timeout_is_a_partial_roll(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    _mk_step(ckpt, 2)
    ctl, handles, procs, *_ = _controller(
        tmp_path, n=2, ckpt_dir=ckpt, swap_timeout_s=1.0)
    for h in handles:
        os.makedirs(h.epoch_dir(), exist_ok=True)
        with open(h.snapshot, "w") as f:
            json.dump({"seq": 1, "ckpt_step": 2}, f)
    _mk_step(ckpt, 4)
    ctl.poll(1.0)          # swap sent to r0
    ctl.poll(2.5)          # past the 1s ack timeout: r0 skipped
    with open(handles[1].snapshot, "w") as f:
        json.dump({"seq": 2, "ckpt_step": 4}, f)
    ctl.poll(3.0)          # r1 acks; the roll completes
    # A rollout with a timed-out replica is NOT a completed rolling
    # swap (the swaps_ok gate must not pass on a fleet that never
    # converged) — it is counted separately.
    assert ctl.rolling_swaps == 0
    assert ctl.partial_rolls == 1
    assert ctl.swap_timeouts == 1
    s = ctl.summary()
    assert s["rolling_swaps"] == 0 and s["partial_rolls"] == 1


# --- scheduler feed integration (fake engine, jax-free) ------------------

class _ScriptedFeed:
    """poll() pops scripted ORDERED item batches (Request objects
    interleaved with command dicts — the InboxFeed contract)."""

    def __init__(self, batches):
        self.batches = list(batches)

    def poll(self):
        return self.batches.pop(0) if self.batches else []


def _sched_requests(rids, max_new=4):
    from tensorflow_distributed_tpu.serve.scheduler import Request
    return [Request(rid=r, prompt=np.asarray([r], np.int32),
                    max_new_tokens=max_new) for r in rids]


class _Recorder:
    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append((event, fields))


def _fake_engine():
    import tests.test_serve as ts
    return ts._FakeEngine(num_slots=2)


def test_scheduler_feed_drain_and_snapshot_liveness():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    reg = _Recorder()
    feed = _ScriptedFeed([
        _sched_requests([1, 2]),
        [],
        _sched_requests([3]),
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(_fake_engine(), registry=reg, feed=feed)
    done = sched.run([])
    assert sorted(c.rid for c in done) == [1, 2, 3]
    assert sched.draining
    snap = sched.metrics_snapshot()
    # The liveness triplet + capacity facts (satellite: a poller can
    # tell a frozen file from a healthy idle replica).
    assert snap["seq"] >= 1 and snap["pid"] == os.getpid()
    assert snap["wall_ts"] > 0
    assert snap["num_slots"] == 2 and snap["max_len"] == 256
    assert "ckpt_step" not in snap          # no checkpoint armed
    snap2 = sched.metrics_snapshot()
    assert snap2["seq"] == snap["seq"] + 1  # monotonic


def test_scheduler_feed_rejects_unservable_into_journal(tmp_path):
    from tensorflow_distributed_tpu.serve import journal as jmod
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    reg = _Recorder()
    jpath = str(tmp_path / "j.jsonl")
    too_big = _sched_requests([9], max_new=500)     # cannot fit
    feed = _ScriptedFeed([
        too_big + _sched_requests([1]),
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(_fake_engine(), registry=reg, feed=feed,
                      journal=jmod.RequestJournal(jpath))
    done = sched.run([])
    assert [c.rid for c in done] == [1]
    assert jmod.replay(jpath)[9]["reject"]
    assert any(e == "serve_reject" and f["rid"] == 9
               for e, f in reg.events)


def test_scheduler_feed_redispatch_supersedes_stale_copy():
    # A stalled replica can read the original dispatch, its cancel,
    # AND the router's re-dispatched continuation in ONE poll batch —
    # the continuation must supersede the original (one admission,
    # one journal stream), never serve the rid twice.
    from tensorflow_distributed_tpu.serve.scheduler import (
        Request, Scheduler)
    reg = _Recorder()
    orig = _sched_requests([7], max_new=6)[0]
    cont = Request(rid=7, prompt=np.asarray([7, 700], np.int32),
                   max_new_tokens=5)
    feed = _ScriptedFeed([
        list(_sched_requests([1]))
        + [orig, {"cmd": "cancel", "rid": 7}, cont],
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(_fake_engine(), registry=reg, feed=feed)
    done = sched.run([])
    by_rid = {}
    for c in done:
        assert c.rid not in by_rid, "rid served twice"
        by_rid[c.rid] = c
    assert sorted(by_rid) == [1, 7]
    # The served copy is the CONTINUATION (its tighter budget).
    assert len(by_rid[7].tokens) == 5
    assert len([e for e, f in reg.events
                if e == "serve_request" and f["rid"] == 7]) == 1


def test_scheduler_feed_rejects_impossible_page_reservation():
    # A paged engine must journal-reject a dispatch whose reservation
    # can NEVER fit the pool (idle-engine admission would raise and
    # kill the replica — a replica never crashes on a bad dispatch).
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler

    class _PagedFake:
        def __init__(self, inner, capacity):
            self._inner = inner
            self.pool = type("P", (), {"capacity": capacity})()
            self.radix = None

        def pages_for(self, plen, max_new):
            return -(-(plen + max_new) // 4)       # page_size 4

        def __getattr__(self, name):
            return getattr(self._inner, name)

    reg = _Recorder()
    eng = _PagedFake(_fake_engine(), capacity=4)   # 3 usable pages
    feed = _ScriptedFeed([
        # 1 + 40 tokens -> 11 pages > 3 usable: impossible; rid 1
        # fits (3 usable pages hold its 2-page reservation).
        _sched_requests([9], max_new=40) + _sched_requests([1]),
        [{"cmd": "drain"}],
    ])
    done = Scheduler(eng, registry=reg, feed=feed).run([])
    assert [c.rid for c in done] == [1]
    assert any(e == "serve_reject" and f["rid"] == 9
               for e, f in reg.events)


def test_scheduler_feed_cancel_drops_live_without_completion():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    reg = _Recorder()
    feed = _ScriptedFeed([
        _sched_requests([1, 2], max_new=50),
        [],
        [{"cmd": "cancel", "rid": 1}],
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(_fake_engine(), registry=reg, feed=feed)
    done = sched.run([])
    assert [c.rid for c in done] == [2]
    assert any(e == "serve_cancel" and f["rid"] == 1
               and f["where"] == "live" for e, f in reg.events)


def test_scheduler_feed_swap_updates_served_ckpt_step():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    eng = _fake_engine()
    eng.swaps = 0

    def swap_params(p):
        eng.swaps += 1
    eng.swap_params = swap_params
    feed = _ScriptedFeed([
        _sched_requests([1]),
        [{"cmd": "swap"}],
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(eng, feed=feed, served_ckpt_step=2,
                      reload_fn=lambda: ({"w": 1}, 6))
    sched.run([])
    assert eng.swaps == 1
    assert sched.served_ckpt_step == 6
    assert sched.metrics_snapshot()["ckpt_step"] == 6


def test_scheduler_hold_export_freezes_snapshot_file(tmp_path):
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    path = str(tmp_path / "snap.json")
    feed = _ScriptedFeed([
        _sched_requests([1], max_new=10),
        [{"cmd": "hold_export", "secs": 3600.0}],
        [{"cmd": "drain"}],
    ])
    sched = Scheduler(_fake_engine(), feed=feed,
                      export_every=1e-9, export_path=path)
    sched.run([])
    # The command armed the hold...
    assert sched._export_hold_until > sched.clock()
    # ...which gates the cadence export (the snapshot file freezes —
    # the router's stale-snapshot drill) but NOT a forced one (the
    # run-end final still lands).
    seq0 = sched._snap_seq
    sched._maybe_export()
    assert sched._snap_seq == seq0            # held: no new snapshot
    sched._maybe_export(force=True)
    assert sched._snap_seq == seq0 + 1
    with open(path) as f:
        assert json.load(f)["seq"] == seq0 + 1


# --- paged auto-sizing (satellite: hbm_budget + slot_pages_peak) ---------

def test_auto_num_pages_arithmetic():
    from tensorflow_distributed_tpu.serve.paging.engine import (
        auto_num_pages)
    # No budget, no observation: serving + equal headroom.
    pool, lines = auto_num_pages(num_slots=2, need_pages=4,
                                 page_bytes=1000)
    assert pool == 1 + 8 + 8
    assert any("worst case" in ln for ln in lines)
    # An observed working set replaces the blind headroom.
    pool, lines = auto_num_pages(num_slots=2, need_pages=4,
                                 page_bytes=1000, observed_peak=3)
    assert pool == 1 + 8 + 3
    assert any("slot_pages_peak 3" in ln for ln in lines)
    # A budget caps the pool...
    pool, lines = auto_num_pages(num_slots=2, need_pages=4,
                                 page_bytes=1000,
                                 budget_bytes=12_000,
                                 reserved_bytes=2_000)
    assert pool == 10
    # ...but never below the floor (reservation + COW page).
    pool, _ = auto_num_pages(num_slots=2, need_pages=4,
                             page_bytes=1000, budget_bytes=3_000)
    assert pool == 2 + 8


def test_fleet_config_validation_matrix():
    from tensorflow_distributed_tpu.config import (
        ServeConfig, TrainConfig)

    def serve_cfg(**kw):
        return TrainConfig(mode="serve", model="gpt_lm", seq_len=64,
                           serve=ServeConfig(**kw))

    serve_cfg(inbox="/t/i", journal="/t/j").validate()
    with pytest.raises(ValueError, match="journal"):
        serve_cfg(inbox="/t/i").validate()
    with pytest.raises(ValueError, match="seq-len"):
        TrainConfig(mode="serve", model="gpt_lm",
                    serve=ServeConfig(inbox="/t/i",
                                      journal="/t/j")).validate()
    with pytest.raises(ValueError, match="mode"):
        TrainConfig(serve=ServeConfig(inbox="/t/i",
                                      journal="/t/j")).validate()
    with pytest.raises(ValueError, match="request file"):
        serve_cfg(inbox="/t/i", journal="/t/j",
                  requests="/t/r.jsonl").validate()
    with pytest.raises(ValueError, match="router owns"):
        serve_cfg(inbox="/t/i", journal="/t/j", trace="poisson",
                  arrival_rate=1.0).validate()
    with pytest.raises(ValueError, match="paged"):
        serve_cfg(hbm_budget_gb=1.0).validate()
    with pytest.raises(ValueError, match="drop one"):
        serve_cfg(paged=True, hbm_budget_gb=1.0,
                  num_pages=64).validate()
    serve_cfg(paged=True, hbm_budget_gb=1.0).validate()
    with pytest.raises(ValueError, match="stale_s"):
        RouterConfig(stale_s=0).validate()
    with pytest.raises(ValueError, match="max_restarts"):
        ControllerConfig(max_restarts=-1).validate()


# --- report folding ------------------------------------------------------

def test_report_folds_fleet_records():
    from tensorflow_distributed_tpu.observe.report import (
        render, summarize)
    records = [
        {"event": "fleet_dispatch", "rid": 0, "replica": "r0",
         "kind": "fresh", "retry": 0, "slo": "high", "t_s": 0.1},
        {"event": "fleet_dispatch", "rid": 0, "replica": "r1",
         "kind": "redispatch", "retry": 1, "slo": "high", "t_s": 0.5},
        {"event": "fleet_dispatch", "rid": 1, "replica": "r1",
         "kind": "fresh", "retry": 0, "slo": "batch", "t_s": 0.2},
        {"event": "fleet_replica", "replica": "r0",
         "state": "quarantined", "reason": "stale_snapshot",
         "t_s": 0.4},
        {"event": "fleet_replica", "replica": "r0",
         "state": "rejoined", "t_s": 1.0},
        {"event": "fleet_shed", "rid": 2, "slo": "batch",
         "reason": "saturated", "retries": 0, "t_s": 0.9},
        {"event": "fleet_swap", "replica": "r1", "ckpt_step": 4,
         "t_s": 0.8},
        {"event": "fleet_summary", "requests": 3, "requests_done": 2,
         "requests_shed": 1, "requests_lost": 0, "dispatches": 3,
         "redispatches": 1,
         "dispatch_retry_hist": {"0": 2, "1": 1},
         "quarantines": 1, "rejoins": 1, "deaths": 0, "restarts": 0,
         "rolling_swaps": 1, "staleness_max_steps": 2,
         "tokens_per_sec": 50.0, "wall_s": 2.0,
         "ttft_ms_p99_recovery": 120.0, "recovery_requests": 1,
         "shed_by_class": {"batch": 1}},
    ]
    out = summarize(records)
    fleet = out["fleet"]
    assert fleet["requests"] == 3 and fleet["requests_lost"] == 0
    assert fleet["dispatch_retry_hist"] == {"0": 2, "1": 1}
    assert fleet["staleness_max_steps"] == 2
    assert fleet["shed_events"] == 1
    assert fleet["replicas"]["r0"]["quarantined"] == 1
    assert fleet["replicas"]["r0"]["rejoined"] == 1
    assert fleet["replicas"]["r1"]["dispatches"] == 2
    assert fleet["replicas"]["r1"]["swaps"] == 1
    text = render(out)
    assert "Fleet" in text and "retry_hist" in text
    # Crashed-front-end path: no fleet_summary record — the histogram
    # re-derives from the dispatch stream.
    out2 = summarize([r for r in records
                      if r["event"] != "fleet_summary"])
    assert out2["fleet"]["dispatch_retry_hist"] == {"0": 1, "1": 1}
    # Plain reports stay shape-stable.
    assert "fleet" not in summarize([{"event": "step", "step": 1}])


# --- the real thing (slow) -----------------------------------------------

@pytest.mark.slow
def test_fleet_e2e_sigkill_zero_lost(tmp_path):
    """2-replica REAL fleet, SIGKILL one mid-stream: every request
    completes (re-dispatched as continuations), the dead replica
    restarts on a fresh epoch, and the streams match the fake-free
    greedy reference (the killed work re-derives identically)."""
    import subprocess
    import sys as _sys

    from tensorflow_distributed_tpu.fleet.controller import (
        ControllerConfig as CC)
    from tensorflow_distributed_tpu.fleet.router import (
        RouterConfig as RC)
    from tensorflow_distributed_tpu.fleet.run import (
        load_workload, run_fleet)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"
    ckpt = str(tmp_path / "ckpt")
    common = ["--model", "gpt_lm", "--model-size", "tiny",
              "--seq-len", "48", "--seed", "0",
              "--compute-dtype", "float32"]
    subprocess.run(
        [_sys.executable, "-m", "tensorflow_distributed_tpu.cli",
         *common, "--dataset", "synthetic", "--train-steps", "2",
         "--batch-size", "8", "--eval-every", "0", "--log-every",
         "0", "--checkpoint-dir", ckpt, "--checkpoint-every", "2"],
        env=env, check=True, capture_output=True, timeout=300)
    wl = str(tmp_path / "wl.jsonl")
    rng = np.random.default_rng(0)
    with open(wl, "w") as f:
        for i in range(10):
            plen = int(rng.integers(4, 12))
            f.write(json.dumps({
                "prompt": [int(t) for t in rng.integers(0, 64, plen)],
                "max_new_tokens": 32,
                "arrival_s": round(0.15 * i, 3)}) + "\n")

    def arm_kill(ctl, router):
        import threading
        import time as time_mod

        def hunt():
            # Journal-armed (fresh to one decode step): kill while a
            # request is mid-decode with budget left, so the death
            # reliably leaves in-flight work to re-dispatch.
            t_end = time_mod.monotonic() + 30
            while time_mod.monotonic() < t_end:
                h = ctl.members["r1"].handle
                jr = h.read_journal(epoch=h.epoch)  # stateless: the
                #   router owns the incremental tail cache
                if any(not e.get("done")
                       and 1 <= len(e.get("tokens", ())) <= 16
                       for e in jr.values()):
                    break
                time_mod.sleep(0.01)
            ctl.kill("r1")
        threading.Thread(target=hunt, daemon=True).start()

    summary = run_fleet(
        fleet_dir=str(tmp_path / "fleet"), replicas=2,
        base_args=["--mode", "serve", *common,
                   "--checkpoint-dir", ckpt,
                   "--serve.num-slots", "2",
                   "--serve.buckets", "48"],
        workload=load_workload(wl), ckpt_dir=ckpt, env=env,
        actions=[(0.2, arm_kill)],
        router_cfg=RC(dispatch_timeout_s=60.0),
        controller_cfg=CC(backoff_base_s=0.25),
        timeout_s=300.0,
        jsonl=str(tmp_path / "fleet.jsonl"))
    assert summary["requests_lost"] == 0
    assert summary["requests_done"] == 10
    assert summary["requests_shed"] == 0
    assert summary["deaths"] == 1 and summary["restarts"] == 1
    assert summary["redispatches"] >= 1
    # Every stream ran to its full budget (greedy, no EOS).
    assert all(len(t) == 32 for t in summary["tokens"].values())
    # The fleet JSONL folds into the report's Fleet section.
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    rep = summarize(load_records(str(tmp_path / "fleet.jsonl")))
    assert rep["fleet"]["requests_lost"] == 0
    assert rep["fleet"]["replicas"]["r1"]["exited"] >= 1
