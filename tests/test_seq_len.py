"""--seq-len / --synthetic-vocab: the long-context path is trainable
from the product surface (round-3 VERDICT weak #2 — ring attention,
RoPE theta, and remat existed but _make_lm_task pinned seq to 128).
"""

import jax
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.train.loop import _build_model_and_state, train
from tensorflow_distributed_tpu.train.tasks import make_task


def _cfg(**kw):
    kw.setdefault("model", "gpt_lm")
    kw.setdefault("model_size", "tiny")
    kw.setdefault("dataset", "synthetic")
    kw.setdefault("compute_dtype", "float32")
    kw.setdefault("dropout_rate", 0.0)
    return TrainConfig(**kw)


def test_seq_len_validation():
    _cfg(seq_len=256).validate()
    with pytest.raises(ValueError, match="seq_len"):
        _cfg(seq_len=1).validate()
    with pytest.raises(ValueError, match="no effect"):
        _cfg(model="mnist_cnn", model_size="", seq_len=256).validate()
    with pytest.raises(ValueError, match="divisible"):
        _cfg(seq_len=130, mesh=MeshConfig(seq=4)).validate()
    with pytest.raises(ValueError, match="synthetic_vocab"):
        _cfg(synthetic_vocab=-1).validate()
    with pytest.raises(ValueError, match="byte corpus"):
        _cfg(dataset="text", synthetic_vocab=32).validate()


def test_seq_len_reaches_model_and_data(devices8):
    """The knob lands in BOTH places: the model's max_len/vocab and the
    data stream's window."""
    cfg = _cfg(seq_len=256, synthetic_vocab=32,
               mesh=MeshConfig(data=4, seq=2))
    cfg.validate()
    mesh = make_mesh(cfg.mesh, devices8)
    task = make_task(cfg, mesh)
    assert task.sample_input.shape == (4, 256)  # data-axis-wide batch
    model, state = _build_model_and_state(cfg, mesh, task)
    assert model.cfg.max_len == 256
    assert model.cfg.vocab_size == 32
    batch = next(task.train_stream(0))
    assert batch["tokens"].shape[1] == 256
    assert int(batch["tokens"].max()) < 32


def test_cli_exposes_seq_len():
    from tensorflow_distributed_tpu.config import parse_args

    cfg = parse_args(["--model", "gpt_lm", "--seq-len", "512",
                      "--synthetic-vocab", "128", "--mesh.seq", "2"])
    assert cfg.seq_len == 512 and cfg.synthetic_vocab == 128


@pytest.mark.slow
def test_train_long_context_via_cli_path(devices8):
    """VERDICT r03 done-criterion: train() runs gpt_lm at seq >= 1024
    with mesh.seq > 1 (zigzag ring + RoPE + remat) end-to-end."""
    cfg = _cfg(seq_len=1024, pos_emb="rope", rope_theta=500000.0,
               remat="dots", batch_size=8, train_steps=2,
               eval_every=0, log_every=0, eval_batch_size=128,
               mesh=MeshConfig(data=2, seq=4))
    result = train(cfg)
    assert np.isfinite(result.final_metrics["loss"])
    assert int(jax.device_get(result.state.step)) == 2
