"""Serve observatory: per-request tracing, the live SLO burn-rate
monitor, and exportable rolling metrics (ISSUE 11).

Fast tier is jax-free: SLO grammar + burn-rate window math on the
deterministic decode-step clock, the ChromeTracer async primitives and
ServeTracer span trees (fake engines + fake clocks), the scheduler's
``metrics_snapshot()`` / export cadence / status line, report folding
(incl. the value-pinned recovery-window p99 — ISSUE satellite), the
per-slot verify fallback's scheduler accounting, and the
warmup-wall-exclusion audit. The slow tier pins the draft-model
warmup compile counter, the per-slot verify fallback's token identity
on the real engine, and a mode=serve e2e with the whole observatory
armed.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.observe.slo import (
    SLOMonitor, SLOTarget, parse_slo, parse_windows, percentile)
from tensorflow_distributed_tpu.observe.serve_trace import ServeTracer
from tensorflow_distributed_tpu.observe.trace import (
    ChromeTracer, load_trace, unbalanced_async)
from tensorflow_distributed_tpu.serve.scheduler import (
    Request, Scheduler)


# --- SLO grammar --------------------------------------------------------

def test_parse_slo_grammar():
    targets = parse_slo(
        "high:ttft_p95=100ms,tok_p50=30ms;standard:ttft_p95=0.5s;"
        "tok_p99=500us")
    assert [t.key for t in targets] == [
        "high:ttft_p95", "high:tok_p50", "standard:ttft_p95",
        "tok_p99"]
    assert targets[0].threshold_ms == 100.0
    assert targets[2].threshold_ms == 500.0      # 0.5s
    assert targets[3].threshold_ms == 0.5        # 500us
    assert targets[3].cls == ""                  # classless = all
    assert targets[0].budget == pytest.approx(0.05)
    assert targets[1].budget == pytest.approx(0.50)


@pytest.mark.parametrize("spec, match", [
    ("", "names no targets"),
    ("high:", "names no targets"),
    ("high:ttft=100ms", "not metric_pNN"),
    ("high:latency_p95=100ms", "unknown SLO metric"),
    ("ttft_p95=100", "unit suffix"),
    ("ttft_p0=100ms", "percentile"),
    ("ttft_p100=100ms", "percentile"),
    ("ttft_pxx=100ms", "not an integer"),
    ("ttft_p95=0ms", "must be > 0"),
    ("ttft_p95=100ms,ttft_p95=200ms", "declared twice"),
    ("high:ttft_p95", "not metric_pNN=value"),
])
def test_parse_slo_rejections(spec, match):
    with pytest.raises(ValueError, match=match):
        parse_slo(spec)


def test_parse_windows():
    assert parse_windows("60,600") == (60, 600)
    assert parse_windows(" 4 , 16 ") == (4, 16)
    for bad in ("60", "600,60", "0,10", "1,2,3"):
        with pytest.raises(ValueError):
            parse_windows(bad)


# --- burn-rate monitor (deterministic decode-step clock) ----------------

def _collect():
    events = []

    def emit(event, **fields):
        events.append({"event": event, **fields})

    return events, emit


def test_burn_rate_alert_fires_and_clears():
    """p95 target, windows 4/8, threshold 1: one violation in both
    windows burns 5x the budget -> alert; once both windows drain the
    violation, slo_ok. The whole trace is pinned — same inputs, same
    events, every run."""
    events, emit = _collect()
    mon = SLOMonitor(parse_slo("ttft_p95=100ms"), fast_window=4,
                     slow_window=8, burn_threshold=1.0, emit=emit)
    # Steps 1-2: compliant completions — no events.
    mon.observe("standard", 10.0, 1.0, step=1)
    assert mon.on_step(1) == []
    mon.observe("standard", 20.0, 1.0, step=2)
    assert mon.on_step(2) == []
    # Step 3: a violation. fast = 1/3 / 0.05 = 6.67x, slow the same ->
    # alert fires at step 3 exactly.
    mon.observe("standard", 500.0, 1.0, step=3)
    fired = mon.on_step(3)
    assert [e["event"] for e in fired] == ["slo_alert"]
    assert fired[0]["burn_fast"] == pytest.approx(1 / 3 / 0.05, rel=1e-3)
    assert fired[0]["budget_remaining"] == pytest.approx(
        1 - 1 / (0.05 * 3), abs=1e-3)
    assert mon.any_alerting()
    # Steps 4-7: quiet (still alerting, no transition). The violation
    # leaves the FAST window after step 3 + 4 -> slo_ok at step 7.
    cleared = []
    for s in range(4, 9):
        cleared += mon.on_step(s)
    assert [e["event"] for e in cleared] == ["slo_ok"]
    assert cleared[0]["step"] == 7
    assert not mon.any_alerting()
    assert events == fired + cleared          # emit mirrored returns
    assert mon.summary()["slo_alerts"] == 1


def test_budget_remaining_math():
    events, emit = _collect()
    mon = SLOMonitor(parse_slo("ttft_p95=100ms"), fast_window=2,
                     slow_window=20, emit=emit)
    for i in range(19):
        mon.observe("standard", 1.0, 1.0, step=1)
    mon.observe("standard", 999.0, 1.0, step=1)
    # 20 observed, 1 violation, budget 5% -> exactly spent.
    snap = mon.snapshot()["ttft_p95"]
    assert snap["budget_remaining"] == pytest.approx(0.0)
    mon.observe("standard", 999.0, 1.0, step=1)
    assert mon.snapshot()["ttft_p95"]["budget_remaining"] < 0


def test_monitor_class_filter_and_snapshot():
    mon = SLOMonitor(parse_slo("high:ttft_p95=100ms"), fast_window=2,
                     slow_window=4)
    mon.observe("standard", 9999.0, 1.0, step=1)   # wrong class
    mon.on_step(1)
    assert mon.snapshot()["high:ttft_p95"]["observed"] == 0
    mon.observe("high", 50.0, 1.0, step=2)
    mon.on_step(2)
    snap = mon.snapshot()["high:ttft_p95"]
    assert snap["observed"] == 1
    assert snap["window_value_ms"] == 50.0
    assert "high:ttft_p95" in mon.status_bits()


# --- tracer primitives --------------------------------------------------

def _tick_clock(step=0.001):
    t = [0.0]

    def clock():
        t[0] += step
        return t[0]

    return clock


def test_chrome_tracer_async_and_balance(tmp_path):
    path = str(tmp_path / "t.json")
    tr = ChromeTracer(path, clock=_tick_clock())
    tr.async_begin("request", 1, cat="serve", slo="high")
    tr.async_begin("queue", 1, cat="serve")
    tr.async_end("queue", 1, cat="serve")
    tr.async_begin("request", 2, cat="serve")
    tr.close()
    ev = load_trace(path)
    bs = [e for e in ev if e.get("ph") == "b"]
    assert {(e["name"], e["id"]) for e in bs} == {
        ("request", "1"), ("queue", "1"), ("request", "2")}
    stray = unbalanced_async(ev)
    assert [(e["name"], e["id"]) for e in stray] == [("request", "1"),
                                                     ("request", "2")]


def test_chrome_tracer_cap_preserves_async_balance(tmp_path):
    """The max_events cap must never unbalance async spans: an "e"
    whose "b" was recorded is appended even past the cap; an "e"
    whose "b" was dropped is dropped with it (no stray ends)."""
    path = str(tmp_path / "t.json")
    tr = ChromeTracer(path, clock=_tick_clock(), max_events=3)
    tr.async_begin("a", 1, cat="serve")
    tr.async_begin("b", 2, cat="serve")
    tr.instant("filler")                  # buffer now at the cap
    tr.async_begin("c", 3, cat="serve")   # dropped
    tr.async_end("c", 3, cat="serve")     # dropped with its begin
    tr.async_end("b", 2, cat="serve")     # forced past the cap
    tr.async_end("a", 1, cat="serve")     # forced past the cap
    tr.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    assert not any(e.get("name") == "c" for e in ev)
    assert tr.dropped >= 2                # c's begin + end accounted


def test_chrome_tracer_preload_offsets_clock(tmp_path):
    tr = ChromeTracer(str(tmp_path / "t.json"), clock=_tick_clock())
    tr.preload([{"ph": "X", "name": "old", "ts": 500.0, "dur": 100.0}])
    tr.instant("new")
    tr.close()
    ev = load_trace(str(tmp_path / "t.json"))
    new = [e for e in ev if e.get("name") == "new"][0]
    assert new["ts"] > 600.0              # after the preloaded span


def test_serve_tracer_request_tree(tmp_path):
    path = str(tmp_path / "serve.json")
    tr = ServeTracer(path, clock=_tick_clock())
    tr.request_queued(7, slo="high", prompt_len=5, tenant="t0")
    with tr.prefill(7, bucket=16, slot=0):
        pass
    tr.request_done(7, "eos", 12, 34.5)
    tr.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    names = [e["name"] for e in ev if e.get("ph") == "b"]
    assert names == ["request", "queue", "prefill", "decode"]


def test_serve_tracer_evict_reopens_queue(tmp_path):
    path = str(tmp_path / "serve.json")
    tr = ServeTracer(path, clock=_tick_clock())
    tr.request_queued(1)
    with tr.prefill(1, bucket=16, slot=0):
        pass
    tr.request_evicted(1, "quarantine")
    with tr.prefill(1, bucket=32, slot=1):
        pass
    tr.request_done(1, "length", 8, 10.0)
    tr.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    queues = [e for e in ev if e.get("name") == "queue"
              and e.get("ph") == "b"]
    assert len(queues) == 2               # original + post-eviction


def test_serve_tracer_resume_closes_dead_spans(tmp_path):
    """A killed leg leaves open spans in the flushed file; the resumed
    tracer closes them at the resume instant and continues the
    timeline — one balanced file across the restart."""
    path = str(tmp_path / "serve.json")
    dead = ServeTracer(path, clock=_tick_clock())
    dead.request_queued(1)
    with dead.prefill(1, bucket=16, slot=0):
        pass                               # decode left open = in flight
    dead.flush()                           # what a SIGKILL leaves behind
    assert unbalanced_async(load_trace(path))
    alive = ServeTracer(path, clock=_tick_clock(), resume=True)
    alive.request_queued(2)
    with alive.prefill(2, bucket=16, slot=0):
        pass
    alive.request_done(2, "eos", 4, 9.0)
    alive.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    assert any(e.get("name") == "journal_resume" for e in ev)
    death_ends = [e for e in ev if e.get("ph") == "e"
                  and (e.get("args") or {}).get("process_death")]
    assert {e["name"] for e in death_ends} == {"request", "decode"}


def test_serve_tracer_close_balances_open_requests(tmp_path):
    path = str(tmp_path / "serve.json")
    tr = ServeTracer(path, clock=_tick_clock())
    tr.request_queued(3)
    tr.close()
    assert not unbalanced_async(load_trace(path))


# --- fake engines (jax-free; mirror tests/test_serve_slo.py) ------------

class _FakeEngine:
    """Deterministic stream: token = rid * 100 + count; continuation-
    aware (rid rides prompt[0], emitted count = len(prompt) - 1)."""

    def __init__(self, num_slots=1, max_len=256):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (64, 128)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = len(prompt) - 1
        self.prefills += 1
        return rid * 100 + self.counts[rid]

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                self.counts[rid] += 1
                out[s] = rid * 100 + self.counts[rid]
        self.decode_steps += 1
        return out

    def free(self, slot):
        self.active[slot] = False


class _QuarantineOnceEngine(_FakeEngine):
    """Flags slot 0 bad exactly once, on the first decode step."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._fired = False

    def take_bad_slots(self):
        if not self._fired and self.decode_steps >= 1:
            self._fired = True
            return [0]
        return []


class _FallbackFakeEngine(_FakeEngine):
    """Speculative surface implementing the per-slot fallback
    contract: REQUEST 1 never has verify headroom (wherever it sits,
    verify_fallback_slots names its slot), so each verify dispatch
    must retire k+1 tokens for request 0's slot and exactly 1 for
    request 1's, with the scheduler excluding the latter from accept
    accounting."""

    def __init__(self, spec_tokens=3, **kw):
        super().__init__(num_slots=2, **kw)
        self.spec_tokens = spec_tokens
        self.verify_steps = 0
        self.seen_tails = []
        self.last_verify_fallback = []

    def verify_fallback_slots(self):
        return [s for s in range(self.num_slots)
                if self.active[s] and self.slot_rid.get(s) == 1]

    def verify_step(self, props, tails=None):
        k = self.spec_tokens
        assert np.asarray(props).shape == (2, k)
        fb = [s for s in (tails or {})]
        self.seen_tails.append(dict(tails or {}))
        toks = np.zeros((2, k + 1), np.int32)
        acc = np.zeros((2,), np.int32)
        for s in range(2):
            if not self.active[s]:
                continue
            rid = self.slot_rid[s]
            n = 1 if s in fb else k + 1
            for j in range(n):
                self.counts[rid] += 1
                toks[s, j] = rid * 100 + self.counts[rid]
            acc[s] = n
        self.decode_steps += 1
        self.verify_steps += 1
        self.last_verify_fallback = fb
        return toks, acc


class _NullSpec:
    needs_histories = True

    def __init__(self, num_slots, k):
        self.num_slots, self.k = num_slots, k

    def propose(self, histories):
        return np.zeros((self.num_slots, self.k), np.int32)

    def observe_admit(self, slot, prompt, first_tok):
        pass

    def observe_free(self, slot):
        pass

    def sync_from(self, engine):
        pass


class _FakeRegistry:
    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        self.records.append({"event": event, **fields})


def _reqs(n, max_new=6, slo=None):
    return [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=max_new,
                    slo=(slo[i] if slo else "standard"))
            for i in range(n)]


def _expected(rid, max_new, plen=1):
    return [rid * 100 + (plen - 1) + j for j in range(max_new)]


# --- scheduler wiring ----------------------------------------------------

def test_scheduler_traces_requests_fake_engine(tmp_path):
    path = str(tmp_path / "serve.json")
    tr = ServeTracer(path, clock=_tick_clock())
    sched = Scheduler(_FakeEngine(num_slots=2), decode_priority=2,
                      tracer=tr, clock=_tick_clock())
    done = sched.run(_reqs(4))
    assert len(done) == 4
    tr.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    reqs = [e for e in ev if e.get("ph") == "b"
            and e["name"] == "request"]
    assert {e["id"] for e in reqs} == {"0", "1", "2", "3"}
    assert {e["name"] for e in ev if e.get("ph") == "C"} >= {
        "slots", "queue", "tokens_per_s"}


def test_scheduler_quarantine_traced_and_balanced(tmp_path):
    path = str(tmp_path / "serve.json")
    tr = ServeTracer(path, clock=_tick_clock())
    reg = _FakeRegistry()
    sched = Scheduler(_QuarantineOnceEngine(num_slots=1),
                      decode_priority=2, tracer=tr, registry=reg,
                      clock=_tick_clock())
    done = sched.run(_reqs(1, max_new=5))
    tr.close()
    assert done[0].tokens == _expected(0, 5)     # identity through it
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    assert any(e.get("name") == "slot_quarantine"
               and e.get("ph") == "i" for e in ev)
    # The request's track shows serve -> evict -> requeue -> serve.
    assert len([e for e in ev if e.get("ph") == "b"
                and e["name"] == "queue"]) == 2


def test_metrics_snapshot_fields_and_pinned_percentiles():
    reg = _FakeRegistry()
    sched = Scheduler(_FakeEngine(num_slots=2), decode_priority=2,
                      registry=reg, clock=_tick_clock(),
                      policy="slo",
                      slo_monitor=SLOMonitor(
                          parse_slo("ttft_p95=10000ms"),
                          fast_window=4, slow_window=8,
                          emit=reg.emit))
    slos = ["high", "standard", "standard", "batch"]
    done = sched.run(_reqs(4, slo=slos))
    snap = sched.metrics_snapshot()
    assert snap["requests_done"] == 4
    assert snap["requests_live"] == 0 and snap["queue_depth"] == 0
    assert snap["decoded_tokens"] == sum(len(c.tokens) for c in done)
    assert snap["decode_steps"] == sched.summary["decode_steps"]
    # Per-class p95 pinned to the report's nearest-rank formula over
    # the same completions.
    for cls in ("high", "standard", "batch"):
        vals = sorted(1e3 * c.ttft_s for c in done if c.slo == cls)
        assert snap[f"ttft_ms_p95_{cls}"] == round(
            percentile(vals, 95), 3)
    assert snap["slo"]["ttft_p95"]["observed"] == 4
    assert snap["slo"]["ttft_p95"]["alerting"] is False


def test_export_cadence_atomic_file_and_records(tmp_path):
    path = str(tmp_path / "snap.json")
    reg = _FakeRegistry()
    sched = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                      registry=reg, clock=_tick_clock(0.01),
                      export_every=0.05, export_path=path)
    sched.run(_reqs(3, max_new=8))
    snaps = [r for r in reg.records
             if r["event"] == "metrics_snapshot"]
    assert len(snaps) >= 2                # cadence + forced final
    final = json.load(open(path))
    # The file is the LAST emitted snapshot, atomically replaced.
    assert final == {k: v for k, v in snaps[-1].items()
                     if k != "event"}
    assert final["requests_done"] == 3    # forced final covers all


def test_export_final_only_with_path(tmp_path):
    path = str(tmp_path / "snap.json")
    reg = _FakeRegistry()
    sched = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                      registry=reg, clock=_tick_clock(),
                      export_every=0.0, export_path=path)
    sched.run(_reqs(2))
    snaps = [r for r in reg.records
             if r["event"] == "metrics_snapshot"]
    assert len(snaps) == 1                # only the forced final
    assert json.load(open(path))["requests_done"] == 2


def test_slo_events_flow_through_scheduler():
    reg = _FakeRegistry()
    mon = SLOMonitor(parse_slo("ttft_p95=0.000001ms"), fast_window=2,
                     slow_window=4, emit=reg.emit)
    sched = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                      registry=reg, clock=_tick_clock(),
                      slo_monitor=mon)
    sched.run(_reqs(3))
    events = [r["event"] for r in reg.records]
    assert "slo_alert" in events
    summary = sched.summary
    assert summary["slo_alerts"] >= 1
    assert summary["slo_budget_remaining_min"] < 0
    assert summary["slo_targets"] == "ttft_p95"
    # A generous target on the same workload stays quiet.
    reg2 = _FakeRegistry()
    sched2 = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                       registry=reg2, clock=_tick_clock(),
                       slo_monitor=SLOMonitor(
                           parse_slo("ttft_p95=1e9ms"), fast_window=2,
                           slow_window=4, emit=reg2.emit))
    sched2.run(_reqs(3))
    assert not any(r["event"] == "slo_alert" for r in reg2.records)
    assert sched2.summary["slo_alerts"] == 0


def test_status_line_cadence_and_content():
    lines = []
    sched = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                      clock=_tick_clock(),
                      slo_monitor=SLOMonitor(
                          parse_slo("ttft_p95=100ms"), fast_window=2,
                          slow_window=4),
                      status_fn=lines.append, status_every=4)
    sched.run(_reqs(3, max_new=8))
    steps = sched.summary["decode_steps"]
    assert len(lines) == steps // 4
    assert "occ=" in lines[0] and "queue=" in lines[0]
    assert "ttft_p95" in lines[0]


def test_summary_wall_excludes_prerun_clock():
    """ISSUE satellite: serve_summary tokens/s is computed over the
    SERVING wall only — clock time spent before run() (warmup,
    compiles, restore) must not leak into wall_s."""
    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    sched = Scheduler(_FakeEngine(num_slots=1), decode_priority=2,
                      clock=clock)
    t[0] += 1000.0                       # "warmup" before run()
    sched.run(_reqs(2, max_new=8))
    assert sched.summary["wall_s"] < 1.0
    assert sched.summary["tokens_per_sec"] > 0


def test_spec_fallback_scheduler_accounting():
    """Per-slot verify fallback (ISSUE satellite), scheduler side: the
    fallback slot retires exactly 1 token per dispatch, gets its
    history tail passed through, is EXCLUDED from accept accounting,
    and the streams stay identical to the plain run."""
    eng = _FallbackFakeEngine(spec_tokens=3)
    sched = Scheduler(eng, decode_priority=2,
                      speculator=_NullSpec(2, 3))
    done = {c.rid: c for c in sched.run(_reqs(2, max_new=7))}
    assert done[0].tokens == _expected(0, 7)
    assert done[1].tokens == _expected(1, 7)
    s = sched.summary
    assert s["verify_steps"] == eng.verify_steps > 0
    assert s["spec_fallback_slots"] > 0
    # Only the speculating slot counts toward proposals; the fake
    # accepts everything there, so accept_rate stays exactly 1.0 —
    # a fallback slot folded into the denominator would deflate it.
    assert s["accept_rate"] == 1.0
    # Tails were supplied for exactly the fallback slot and carry its
    # history stream (request 1's tokens are all >= 100).
    mixed = [t for t in eng.seen_tails if t]
    assert mixed
    for t in mixed:
        assert len(t) == 1
        (tail,) = t.values()
        assert tail[-1] >= 100


def test_fallback_engine_contract_matches_real_engine_guard():
    """verify_fallback_slots None (can_verify-only fakes) keeps the
    whole-batch fallback path: the scheduler must not call
    verify_step at all."""
    eng = _FakeEngine(num_slots=1)     # no verify surface
    sched = Scheduler(eng, decode_priority=2,
                      speculator=_NullSpec(1, 3))
    done = sched.run(_reqs(1, max_new=5))
    assert done[0].tokens == _expected(0, 5)
    assert "verify_steps" in sched.summary
    assert sched.summary["verify_steps"] == 0


# --- report folding ------------------------------------------------------

def _write_jsonl(path, recs):
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))


def test_report_folds_slo_and_snapshots(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    recs = [
        {"event": "slo_alert", "target": "high:ttft_p95",
         "burn_fast": 14.4, "burn_slow": 2.0,
         "budget_remaining": 0.61, "step": 40},
        {"event": "slo_ok", "target": "high:ttft_p95",
         "burn_fast": 0.2, "burn_slow": 0.9,
         "budget_remaining": 0.57, "step": 90},
        {"event": "metrics_snapshot", "t_s": 1.0, "decode_steps": 50,
         "requests_done": 4, "queue_depth": 1, "tokens_per_sec": 99.0,
         "ttft_ms_p95_high": 12.0},
        {"event": "metrics_snapshot", "t_s": 2.0, "decode_steps": 100,
         "requests_done": 9, "queue_depth": 0, "tokens_per_sec": 120.0,
         "ttft_ms_p95_high": 15.5},
        {"event": "serve_summary", "tokens_per_sec": 120.0,
         "slo_alerts": 1, "slo_budget_remaining_min": 0.57,
         "slo_targets": "high:ttft_p95"},
    ]
    path = tmp_path / "m.jsonl"
    _write_jsonl(path, recs)
    out = summarize(load_records(str(path)))
    assert out["slo"]["high:ttft_p95"] == {
        "alerts": 1, "clears": 1, "worst_burn_fast": 14.4,
        "budget_remaining": 0.57}
    assert out["snapshots"] == 2
    assert out["snapshot_last"]["requests_done"] == 9
    assert out["snapshot_last"]["ttft_ms_p95_high"] == 15.5
    assert out["serve_slo_alerts"] == 1
    from tensorflow_distributed_tpu.observe.report import render
    text = render(out)
    assert "SLO" in text and "Snapshot (final)" in text


def test_report_plain_serve_shape_unchanged(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    recs = [{"event": "serve_request", "rid": 0, "ttft_ms": 5.0,
             "tok_ms": 1.0, "slo": "standard"},
            {"event": "serve_summary", "tokens_per_sec": 10.0}]
    path = tmp_path / "m.jsonl"
    _write_jsonl(path, recs)
    out = summarize(load_records(str(path)))
    assert "slo" not in out and "snapshots" not in out
    assert not any(k.startswith("serve_slo") for k in out)


def test_report_recovery_window_p99_value_pinned(tmp_path):
    """ISSUE satellite: a synthetic JSONL with KNOWN recovery windows
    reproduces the exact nearest-rank p99-during-recovery value, not
    just its presence."""
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    recovery_ttfts = [10.0, 20.0, 30.0, 40.0, 50.0,
                      60.0, 70.0, 80.0, 90.0, 1000.0]
    recs = [{"event": "serve_request", "rid": i, "ttft_ms": t,
             "tok_ms": 1.0, "recovery_window": True}
            for i, t in enumerate(recovery_ttfts)]
    # Plenty of fast non-recovery requests that must NOT dilute the
    # recovery population.
    recs += [{"event": "serve_request", "rid": 100 + i,
              "ttft_ms": 1.0, "tok_ms": 1.0,
              "recovery_window": False} for i in range(30)]
    path = tmp_path / "m.jsonl"
    _write_jsonl(path, recs)
    out = summarize(load_records(str(path)))
    assert out["serve_recovery_requests"] == 10
    # Nearest-rank p99 over 10 sorted values: index round(.99*9) = 9.
    assert out["serve_ttft_ms_p99_recovery"] == 1000.0
    # And the overall p99 covers all 40: index round(.99*39) = 39 of
    # the merged sorted list -> the same 1000.0 outlier; p50 differs.
    assert out["serve_ttft_ms_p99"] == 1000.0
    assert out["serve_ttft_ms_p50"] == 1.0


# --- config plumbing -----------------------------------------------------

def _cfg(**kw):
    from tensorflow_distributed_tpu.config import TrainConfig
    cfg = TrainConfig(mode="serve", model="gpt_lm",
                      model_size="tiny")
    for k, v in kw.items():
        obj, _, field = k.rpartition(".")
        setattr(cfg.observe if obj == "observe" else cfg, field, v)
    return cfg


def test_config_serve_observatory_knobs_valid():
    cfg = _cfg(**{"observe.slo": "high:ttft_p95=100ms,tok_p50=30ms",
                  "observe.slo_windows": "30,300",
                  "observe.export_every": 2.0,
                  "observe.export_path": "/tmp/x.json"})
    cfg.validate()


@pytest.mark.parametrize("kw, match", [
    ({"observe.slo": "gold:ttft_p95=1ms"}, "unknown class"),
    ({"observe.slo": "ttft_p95=1"}, "unit suffix"),
    ({"observe.slo_windows": "600,60"}, "fast < slow"),
    ({"observe.slo_burn": 0.0}, "slo_burn"),
    ({"observe.slo_status_every": -1}, "slo_status_every"),
    ({"observe.export_every": -1.0}, "export_every"),
])
def test_config_serve_observatory_rejections(kw, match):
    with pytest.raises(ValueError, match=match):
        _cfg(**kw).validate()


def test_config_slo_and_export_are_serve_only():
    from tensorflow_distributed_tpu.config import TrainConfig
    cfg = TrainConfig()
    cfg.observe.slo = "ttft_p95=100ms"
    with pytest.raises(ValueError, match="mode=serve"):
        cfg.validate()
    cfg2 = TrainConfig()
    cfg2.observe.export_every = 1.0
    with pytest.raises(ValueError, match="mode=serve"):
        cfg2.validate()


# --- real engine (slow tier) --------------------------------------------

def _tiny_serving_model(max_len=96, **overrides):
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.transformer import gpt_lm

    model = gpt_lm(None, size="tiny", max_len=max_len,
                   dropout_rate=0.0, **overrides)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.mark.slow
def test_draft_warmup_no_compiles_during_serving():
    """ISSUE satellite: engine.warmup(speculator) also dispatches the
    draft mirror's prefill/insert/scan — the serving loop then runs
    with ZERO compiled-program cache misses (the first speculative
    round pays compute, not compile)."""
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.generate import (
        compile_cache_stats)
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.speculate import (
        DraftSpeculator)

    model, params = _tiny_serving_model()
    draft = gpt_lm(None, size="tiny", n_layers=1, max_len=96,
                   dropout_rate=0.0)
    dparams = draft.init(jax.random.key(1),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    buckets = default_buckets(16)
    K = 3
    eng = SlotDecodeEngine(model, params, 2, buckets=buckets,
                           spec_tokens=K)
    drafter = DraftSpeculator(draft, dparams, 2, buckets, K)
    eng.warmup(drafter)
    before = compile_cache_stats()["misses"]
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32)
               for n in rng.integers(4, 14, size=4)]
    sched = Scheduler(eng, decode_priority=3, speculator=drafter)
    done = sched.run([Request(rid=i, prompt=p, max_new_tokens=10)
                      for i, p in enumerate(prompts)])
    assert len(done) == 4
    assert sched.summary["verify_steps"] > 0
    assert compile_cache_stats()["misses"] == before


@pytest.mark.slow
def test_per_slot_verify_fallback_token_identity_real():
    """ISSUE satellite: one headroom-starved slot takes the plain path
    INSIDE the verify dispatch while the other slot keeps speculating
    — tokens identical to the non-speculative run, and the mixed
    dispatches really happened (spec_fallback_slots > 0)."""
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.speculate import SelfDraft

    K = 4
    model, params = _tiny_serving_model(max_len=32)
    rng = np.random.default_rng(7)
    # Request 0 ends at pos 32 = max_len: its final decode rounds lack
    # pos + K + 1 headroom. Request 1 stays shallow throughout.
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=20).astype(np.int32),
               rng.integers(0, model.cfg.vocab_size,
                            size=4).astype(np.int32)]
    buckets = default_buckets(32, cap=32)

    def run(spec_tokens):
        eng = SlotDecodeEngine(model, params, 2, buckets=buckets,
                               spec_tokens=spec_tokens)
        spec = (SelfDraft(2, spec_tokens) if spec_tokens else None)
        sched = Scheduler(eng, decode_priority=3, speculator=spec)
        reqs = [Request(rid=0, prompt=prompts[0], max_new_tokens=12),
                Request(rid=1, prompt=prompts[1], max_new_tokens=12)]
        return {c.rid: c.tokens for c in sched.run(reqs)}, sched

    ref, _ = run(0)
    out, sched = run(K)
    assert out[0] == ref[0] and out[1] == ref[1]
    assert sched.summary["verify_steps"] > 0
    assert sched.summary["spec_fallback_slots"] > 0


@pytest.mark.slow
def test_serve_run_observatory_e2e(tmp_path):
    """mode=serve with the full observatory armed: balanced trace,
    slo_alert fires on an absurd target, snapshots exported, report
    folds all of it."""
    from tensorflow_distributed_tpu.config import TrainConfig
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    from tensorflow_distributed_tpu.serve.run import serve_run

    cfg = TrainConfig(mode="serve", model="gpt_lm", model_size="tiny",
                      seed=11)
    cfg.serve.num_requests = 5
    cfg.serve.num_slots = 2
    cfg.serve.max_new_tokens = 8
    cfg.observe.metrics_jsonl = str(tmp_path / "m.jsonl")
    cfg.observe.trace = str(tmp_path / "serve.trace.json")
    cfg.observe.slo = "ttft_p95=0.0001ms"
    cfg.observe.slo_windows = "4,16"
    cfg.observe.export_every = 0.001
    cfg.observe.export_path = str(tmp_path / "snap.json")
    cfg.validate()
    summary = serve_run(cfg)
    assert summary["requests"] == 5
    assert summary["slo_alerts"] >= 1
    ev = load_trace(cfg.observe.trace)
    assert not unbalanced_async(ev)
    assert any(e.get("ph") == "C" for e in ev)
    snap = json.load(open(cfg.observe.export_path))
    out = summarize(load_records(cfg.observe.metrics_jsonl))
    assert out["snapshots"] >= 1
    # Final snapshot agrees with the report's per-class p95 exactly
    # (same nearest-rank formula over the same completions).
    assert (snap["ttft_ms_p95_standard"]
            == out["serve_ttft_ms_p95_standard"]
            if "serve_ttft_ms_p95_standard" in out
            else snap["requests_done"] == 5)
    assert out["serve_slo_alerts"] == summary["slo_alerts"]
