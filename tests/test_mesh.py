"""Mesh construction + sharding rule tests."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflow_distributed_tpu.config import MeshConfig
from tensorflow_distributed_tpu.parallel import mesh as meshlib
from tensorflow_distributed_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_batch)


def test_make_mesh_all_data(devices8):
    m = meshlib.make_mesh(MeshConfig(data=-1), devices8)
    assert m.shape == {"data": 8, "pipe": 1, "seq": 1, "model": 1, "expert": 1}


def test_make_mesh_2d(devices8):
    m = meshlib.make_mesh(MeshConfig(data=4, model=2), devices8)
    assert m.shape == {"data": 4, "pipe": 1, "seq": 1, "model": 2, "expert": 1}


def test_make_mesh_seq(devices8):
    m = meshlib.make_mesh(MeshConfig(data=2, seq=4), devices8)
    assert m.shape == {"data": 2, "pipe": 1, "seq": 4, "model": 1, "expert": 1}


def test_make_mesh_expert_axis(devices8):
    m = meshlib.make_mesh(MeshConfig(data=2, expert=4), devices8)
    assert m.shape == {"data": 2, "pipe": 1, "seq": 1, "model": 1,
                       "expert": 4}


def test_make_mesh_rejects_indivisible(devices8):
    with pytest.raises(ValueError):
        meshlib.make_mesh(MeshConfig(data=3, model=3), devices8)


def test_single_device_mesh_is_same_code_path(devices8):
    m = meshlib.single_device_mesh(devices8[0])
    assert m.shape == {"data": 1, "pipe": 1, "seq": 1, "model": 1, "expert": 1}


def test_batch_sharding_splits_leading_axis(mesh8):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    arr = jax.device_put(x, batch_sharding(mesh8, 2))
    assert arr.sharding.spec == P("data", None)
    # Each device holds exactly one row.
    assert arr.addressable_shards[0].data.shape == (1, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_shard_batch_pytree(mesh8):
    imgs = np.zeros((16, 28, 28, 1), np.float32)
    labels = np.zeros((16,), np.int32)
    simgs, slabels = shard_batch(mesh8, (imgs, labels))
    assert simgs.shape == (16, 28, 28, 1)
    assert simgs.addressable_shards[0].data.shape == (2, 28, 28, 1)
    assert slabels.addressable_shards[0].data.shape == (2,)


def test_replicated_places_full_copy_everywhere(mesh8):
    x = np.arange(6, dtype=np.float32)
    arr = jax.device_put(x, replicated(mesh8))
    assert all(s.data.shape == (6,) for s in arr.addressable_shards)


def test_is_chief_single_host():
    assert meshlib.is_chief()


def _fake_procs(monkeypatch, count, index):
    monkeypatch.setattr(jax, "process_count", lambda: count)
    monkeypatch.setattr(jax, "process_index", lambda: index)


def test_process_batch_role_layouts(devices8, monkeypatch):
    """Pure-function enumeration of the multi-host batch-role math
    (parallel.mesh.process_batch_role) — garbage here means silently
    wrong global batches, so every branch gets a unit case."""
    from tensorflow_distributed_tpu.parallel.mesh import process_batch_role

    # data axis spans the processes: disjoint per-process slices.
    m = meshlib.make_mesh(MeshConfig(data=8), devices8)
    _fake_procs(monkeypatch, 2, 1)
    assert process_batch_role(m) == (2, 1)

    # data=2 x seq=4, 2 procs: each proc owns one whole data coord.
    m = meshlib.make_mesh(MeshConfig(data=2, seq=4), devices8)
    _fake_procs(monkeypatch, 2, 1)
    assert process_batch_role(m) == (2, 1)

    # seq spans the processes (data=1): both procs share data coord 0
    # and must supply IDENTICAL rows.
    m = meshlib.make_mesh(MeshConfig(data=1, seq=8), devices8)
    for p in range(2):
        _fake_procs(monkeypatch, 2, p)
        assert process_batch_role(m) == (1, 0)

    # Mixed: data=2 x seq=2 x model=2 over 4 procs — procs pair up per
    # data coordinate.
    m = meshlib.make_mesh(MeshConfig(data=2, seq=2, model=2), devices8)
    for p in range(4):
        _fake_procs(monkeypatch, 4, p)
        assert process_batch_role(m) == (2, p // 2)

    # Straddle: a process crossing a data-shard boundary is rejected.
    m = meshlib.make_mesh(MeshConfig(data=3, seq=2), devices8[:6])
    _fake_procs(monkeypatch, 2, 0)
    with pytest.raises(ValueError, match="straddle"):
        process_batch_role(m)


def test_process_axis_range_layouts(devices8, monkeypatch):
    from tensorflow_distributed_tpu.parallel.mesh import process_axis_range

    # seq spans 2 procs: each gets its half of the sequence dim.
    m = meshlib.make_mesh(MeshConfig(data=1, seq=8), devices8)
    _fake_procs(monkeypatch, 2, 0)
    assert process_axis_range(m, "seq", 128) == (0, 64)
    _fake_procs(monkeypatch, 2, 1)
    assert process_axis_range(m, "seq", 128) == (64, 128)

    # data spans procs, seq inside each: every proc sees the full seq.
    m = meshlib.make_mesh(MeshConfig(data=2, seq=4), devices8)
    _fake_procs(monkeypatch, 2, 1)
    assert process_axis_range(m, "seq", 128) == (0, 128)

    # Inner model axis: seq coordinate alternates across 4 procs.
    m = meshlib.make_mesh(MeshConfig(data=2, seq=2, model=2), devices8)
    for p, want in [(0, (0, 64)), (1, (64, 128)),
                    (2, (0, 64)), (3, (64, 128))]:
        _fake_procs(monkeypatch, 4, p)
        assert process_axis_range(m, "seq", 128) == want

    # Wrapped non-contiguous coverage is rejected, not mis-sliced.
    m = meshlib.make_mesh(MeshConfig(data=1, pipe=2, seq=3), devices8[:6])
    _fake_procs(monkeypatch, 3, 1)
    with pytest.raises(ValueError, match="wrapped"):
        process_axis_range(m, "seq", 12)

    # Size-1 axis or single process: identity.
    m = meshlib.make_mesh(MeshConfig(data=8), devices8)
    _fake_procs(monkeypatch, 2, 1)
    assert process_axis_range(m, "seq", 128) == (0, 128)
