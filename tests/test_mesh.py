"""Mesh construction + sharding rule tests."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflow_distributed_tpu.config import MeshConfig
from tensorflow_distributed_tpu.parallel import mesh as meshlib
from tensorflow_distributed_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_batch)


def test_make_mesh_all_data(devices8):
    m = meshlib.make_mesh(MeshConfig(data=-1), devices8)
    assert m.shape == {"data": 8, "pipe": 1, "seq": 1, "model": 1, "expert": 1}


def test_make_mesh_2d(devices8):
    m = meshlib.make_mesh(MeshConfig(data=4, model=2), devices8)
    assert m.shape == {"data": 4, "pipe": 1, "seq": 1, "model": 2, "expert": 1}


def test_make_mesh_seq(devices8):
    m = meshlib.make_mesh(MeshConfig(data=2, seq=4), devices8)
    assert m.shape == {"data": 2, "pipe": 1, "seq": 4, "model": 1, "expert": 1}


def test_make_mesh_expert_axis(devices8):
    m = meshlib.make_mesh(MeshConfig(data=2, expert=4), devices8)
    assert m.shape == {"data": 2, "pipe": 1, "seq": 1, "model": 1,
                       "expert": 4}


def test_make_mesh_rejects_indivisible(devices8):
    with pytest.raises(ValueError):
        meshlib.make_mesh(MeshConfig(data=3, model=3), devices8)


def test_single_device_mesh_is_same_code_path(devices8):
    m = meshlib.single_device_mesh(devices8[0])
    assert m.shape == {"data": 1, "pipe": 1, "seq": 1, "model": 1, "expert": 1}


def test_batch_sharding_splits_leading_axis(mesh8):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    arr = jax.device_put(x, batch_sharding(mesh8, 2))
    assert arr.sharding.spec == P("data", None)
    # Each device holds exactly one row.
    assert arr.addressable_shards[0].data.shape == (1, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_shard_batch_pytree(mesh8):
    imgs = np.zeros((16, 28, 28, 1), np.float32)
    labels = np.zeros((16,), np.int32)
    simgs, slabels = shard_batch(mesh8, (imgs, labels))
    assert simgs.shape == (16, 28, 28, 1)
    assert simgs.addressable_shards[0].data.shape == (2, 28, 28, 1)
    assert slabels.addressable_shards[0].data.shape == (2,)


def test_replicated_places_full_copy_everywhere(mesh8):
    x = np.arange(6, dtype=np.float32)
    arr = jax.device_put(x, replicated(mesh8))
    assert all(s.data.shape == (6,) for s in arr.addressable_shards)


def test_is_chief_single_host():
    assert meshlib.is_chief()
