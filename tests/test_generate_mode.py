"""--mode generate: the CLI surface over models/generate.py.

Train a few steps to a checkpoint, then restore-and-continue a prompt
through the same entrypoint — ids for synthetic-stream models, a real
string round-tripped through the corpus tokenizer for dataset=text.
"""

import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.train.loop import generate_only, train


def _train_ckpt(tmp_path, **overrides):
    kw = dict(
        model="gpt_lm", model_size="tiny", dataset="synthetic",
        batch_size=16, train_steps=4, eval_every=0, log_every=0,
        eval_batch_size=16, compute_dtype="float32",
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=4,
        mesh=MeshConfig(data=8))
    kw.update(overrides)
    cfg = TrainConfig(**kw)
    train(cfg)
    return cfg


def test_generate_from_checkpoint_ids(tmp_path):
    import dataclasses

    cfg = _train_ckpt(tmp_path)
    gen = dataclasses.replace(cfg, mode="generate", prompt="1,2,3,4",
                              max_new_tokens=6)
    rec = generate_only(gen)
    assert len(rec["new_tokens"]) == 6
    assert all(0 <= t < 64 for t in rec["new_tokens"])
    assert "text" not in rec  # no tokenizer for synthetic streams

    # Beam search through the same surface: the best beam of
    # num_beams=1 is exactly the greedy continuation.
    beam = dataclasses.replace(gen, num_beams=2)
    rec_b = generate_only(beam)
    assert len(rec_b["new_tokens"]) == 6
    assert "beam_score" in rec_b

    # Sampling path runs end to end.
    hot = dataclasses.replace(gen, gen_temperature=0.8, gen_top_k=8)
    assert len(generate_only(hot)["new_tokens"]) == 6


def test_generate_text_round_trip(tmp_path):
    """dataset=text: the prompt is a STRING through the training
    tokenizer; the continuation decodes back to text."""
    import dataclasses

    from tests.test_text_lm import _write_corpus

    p = _write_corpus(tmp_path / "corpus.txt")
    cfg = _train_ckpt(tmp_path, dataset="text", data_dir=str(p),
                      seq_len=32, batch_size=8, eval_batch_size=8)
    gen = dataclasses.replace(cfg, mode="generate", prompt="a0:abc",
                              max_new_tokens=5)
    rec = generate_only(gen)
    assert len(rec["new_tokens"]) == 5
    assert isinstance(rec["text"], str)

    from tensorflow_distributed_tpu.data.lm import text_codec
    enc, dec, vocab = text_codec(str(p), "byte")
    assert vocab == 256
    assert dec(enc("a0:abc")) == "a0:abc"


def test_generate_from_moe_checkpoint(tmp_path):
    """moe_lm generates too: the router's moe_aux sows are no-ops when
    the collection isn't mutable, so the decode path is clean."""
    import dataclasses

    cfg = _train_ckpt(tmp_path, model="moe_lm",
                      mesh=MeshConfig(data=4, expert=2))
    gen = dataclasses.replace(cfg, mode="generate", prompt="5,6,7",
                              max_new_tokens=4)
    rec = generate_only(gen)
    assert len(rec["new_tokens"]) == 4


def test_generate_mode_validation():
    base = dict(model="gpt_lm", model_size="tiny", mode="generate",
                checkpoint_dir="/tmp/x", prompt="1,2")
    TrainConfig(**base).validate()
    with pytest.raises(ValueError, match="prompt"):
        TrainConfig(**{**base, "prompt": ""}).validate()
    with pytest.raises(ValueError, match="checkpoint_dir"):
        TrainConfig(**{**base, "checkpoint_dir": ""}).validate()
    with pytest.raises(ValueError, match="causal"):
        TrainConfig(**{**base, "model": "bert_mlm"}).validate()
    with pytest.raises(ValueError, match="mesh.seq"):
        TrainConfig(**base, mesh=MeshConfig(seq=2)).validate()
    with pytest.raises(ValueError, match="pick one"):
        TrainConfig(**{**base, "num_beams": 2,
                       "gen_temperature": 0.5}).validate()
    with pytest.raises(ValueError, match="pick one"):
        TrainConfig(**{**base, "num_beams": 2,
                       "gen_top_k": 50}).validate()
    with pytest.raises(ValueError, match="inverted"):
        TrainConfig(**{**base, "gen_temperature": -0.5}).validate()


def test_generate_out_of_vocab_prompt_rejected(tmp_path):
    """Out-of-range ids must error, not be clamped by the embedding
    gather into a silently different prompt."""
    import dataclasses

    cfg = _train_ckpt(tmp_path)
    gen = dataclasses.replace(cfg, mode="generate", prompt="100,2",
                              max_new_tokens=4)
    with pytest.raises(ValueError, match="vocabulary"):
        generate_only(gen)


def test_generate_string_prompt_without_text_dataset_rejected(tmp_path):
    import dataclasses

    cfg = _train_ckpt(tmp_path)
    gen = dataclasses.replace(cfg, mode="generate", prompt="hello",
                              max_new_tokens=4)
    with pytest.raises(ValueError, match="comma-separated"):
        generate_only(gen)
