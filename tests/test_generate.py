"""KV-cache decoding: cache-vs-full-forward parity + end-to-end
generation quality on the learnable stride data."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.models.generate import generate
from tensorflow_distributed_tpu.models.transformer import CausalLM, tiny_config


def _model():
    return CausalLM(tiny_config(causal=True, compute_dtype=jnp.float32))


def test_decode_logits_match_full_forward():
    """Teacher-forced decode through the cache must reproduce the
    ordinary causal forward logits position by position."""
    model = _model()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 12)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)          # [B, L, V]

    # Prefill 5 tokens, then feed the rest one at a time.
    logits5, state = model.apply({"params": params}, tokens[:, :5],
                                 decode=True,
                                 positions=jnp.arange(5)[None, :],
                                 mutable=["cache"])
    np.testing.assert_allclose(logits5, full[:, :5], atol=1e-4, rtol=1e-3)
    cache = state["cache"]
    for t in range(5, 12):
        step_logits, state = model.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, positions=jnp.full((1, 1), t), mutable=["cache"])
        cache = state["cache"]
        np.testing.assert_allclose(step_logits[:, 0], full[:, t],
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"position {t}")


def test_generate_shapes_and_determinism():
    model = _model()
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    params = model.init(jax.random.key(1), prompt)["params"]
    out1 = generate(model, params, prompt, 8)
    out2 = generate(model, params, prompt, 8)
    assert out1.shape == (1, 8)
    np.testing.assert_array_equal(out1, out2)  # greedy => deterministic
    sampled = generate(model, params, prompt, 8, temperature=1.0,
                       key=jax.random.key(2))
    assert sampled.shape == (1, 8)


def test_filter_logits_top_k_and_top_p():
    from tensorflow_distributed_tpu.models.generate import _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.07, 0.03]]))
    # top-k=2 keeps exactly the two largest.
    k2 = np.asarray(_filter_logits(logits, top_k=2, top_p=1.0))
    assert np.isfinite(k2[0, :2]).all() and np.isinf(k2[0, 2:]).all()
    # top-p=0.6: 0.5 alone misses p, 0.5+0.25 crosses it -> keep 2.
    p6 = np.asarray(_filter_logits(logits, top_k=0, top_p=0.6))
    assert np.isfinite(p6[0, :2]).all() and np.isinf(p6[0, 2:]).all()
    # top-p tiny still keeps the argmax (never an empty nucleus).
    p0 = np.asarray(_filter_logits(logits, top_k=0, top_p=1e-6))
    assert np.isfinite(p0[0, 0]) and np.isinf(p0[0, 1:]).all()
    # k=0 / p=1 are no-ops.
    np.testing.assert_array_equal(
        np.asarray(_filter_logits(logits, top_k=0, top_p=1.0)),
        np.asarray(logits))


def test_generate_top_k_restricts_support():
    """With top_k=1, sampling at any temperature IS greedy decoding."""
    model = _model()
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    params = model.init(jax.random.key(1), prompt)["params"]
    greedy = generate(model, params, prompt, 8)
    k1 = generate(model, params, prompt, 8, temperature=1.7, top_k=1,
                  key=jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, 4, temperature=1.0, top_p=0.0,
                 key=jax.random.key(0))
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, 4, temperature=1.0, top_k=-1,
                 key=jax.random.key(0))


@pytest.mark.slow
def test_trained_model_continues_pattern(devices8):
    """Train tiny GPT on stride progressions, then generate: the greedy
    continuation must mostly follow x_{t+1} = x_t + stride."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="gpt_lm", model_size="tiny",
                      dataset="synthetic", batch_size=64, train_steps=120,
                      eval_every=0, log_every=0, eval_batch_size=64,
                      compute_dtype="float32", learning_rate=3e-3,
                      mesh=MeshConfig(data=8))
    result = train(cfg)
    model = CausalLM(tiny_config(causal=True, compute_dtype=jnp.float32))

    # Short-horizon accuracy over several prompts: free-running
    # generation compounds errors in a 25k-param model, so judge the
    # first 4 continuations, averaged over strides/starts.
    P, N = 16, 4
    prompts, wants = [], []
    for stride in (1, 2, 3, 4):
        for start in (5, 20):
            prompts.append((start + stride * np.arange(P)) % 64)
            wants.append((start + stride * (np.arange(N) + P)) % 64)
    prompt = np.stack(prompts).astype(np.int32)
    out = np.asarray(generate(model, jax.device_get(result.state.params),
                              jnp.asarray(prompt), N))
    acc = float(np.mean(out == np.stack(wants).astype(np.int32)))
    assert acc >= 0.5, (out.tolist(), acc)


def test_generate_sharded_prompt_matches_single_device(devices8):
    """Decode under mesh.data > 1 (VERDICT r03 item 8): the same
    prompt, sharded over a data=4 mesh, must greedy-decode to exactly
    the single-device tokens — generation is jit + GSPMD like the
    train step, so batch sharding is a layout, not math. (GENBENCH.json
    records the on-chip decode throughput this path delivers.)"""
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import (
        make_mesh, single_device_mesh)
    from tensorflow_distributed_tpu.train.state import create_train_state
    import optax

    prompt_np = np.random.default_rng(3).integers(0, 64, size=(4, 6))
    outs = {}
    for name, mesh in (("dp4", make_mesh(MeshConfig(data=4),
                                         devices8[:4])),
                       ("single", single_device_mesh(devices8[0]))):
        model = gpt_lm(mesh, size="tiny", compute_dtype=jnp.float32,
                       dropout_rate=0.0)
        state = create_train_state(model, optax.sgd(1e-2),
                                   np.zeros((2, 8), np.int32), mesh, 0)
        with mesh:
            prompt = jax.device_put(
                jnp.asarray(prompt_np, jnp.int32),
                jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("data", None)))
            outs[name] = np.asarray(
                generate(model, state.params, prompt, 8))
    np.testing.assert_array_equal(outs["dp4"], outs["single"])


def test_int8_kv_cache_decode_close_to_full_forward():
    """kv_cache_quant="int8": teacher-forced decode through the
    quantized cache tracks the (unquantized) training forward within
    per-(token, head) absmax int8 error — the scale-adjusted dots are
    exact given the quantized values, so ALL error is the ~0.4%
    rounding of k/v themselves. Also pins the GQA branch (narrow AND
    thin cache, the composed decode-bandwidth story) and that
    generation runs deterministically end to end."""

    for kw in ({}, {"n_kv_heads": 2}):
        model_q = CausalLM(tiny_config(causal=True, compute_dtype=jnp.float32,
                                       kv_cache_quant="int8", **kw))
        tokens = jnp.asarray(
            np.random.default_rng(5).integers(0, 64, size=(2, 10)),
            jnp.int32)
        params = model_q.init(jax.random.key(0), tokens)["params"]
        full = model_q.apply({"params": params}, tokens)

        logits, state = model_q.apply(
            {"params": params}, tokens[:, :4], decode=True,
            positions=jnp.arange(4)[None, :], mutable=["cache"])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, :4]),
                                   atol=0.05, rtol=0.05)
        cache = state["cache"]
        for t in range(4, 10):
            step_logits, state = model_q.apply(
                {"params": params, "cache": cache}, tokens[:, t:t + 1],
                decode=True, positions=jnp.full((1, 1), t),
                mutable=["cache"])
            cache = state["cache"]
            np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                       np.asarray(full[:, t]),
                                       atol=0.05, rtol=0.05,
                                       err_msg=f"position {t} kw={kw}")

        out1 = generate(model_q, params, tokens[:, :4], 6)
        out2 = generate(model_q, params, tokens[:, :4], 6)
        assert out1.shape == (2, 6)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_kv_cache_quant_validation():
    from tensorflow_distributed_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="kv_cache_quant"):
        TrainConfig(model="gpt_lm", kv_cache_quant="fp4",
                    batch_size=32).validate()


def test_beam_search_k1_is_greedy_and_beams_ordered():
    """num_beams=1 must reproduce greedy decoding token for token; at
    K=4 the returned beams are sorted best-first and the top beam's
    raw score can only match or beat the greedy path's log-prob."""
    from tensorflow_distributed_tpu.models.generate import beam_search

    model = _model()
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 64, size=(3, 5)), jnp.int32)
    params = model.init(jax.random.key(0), jnp.zeros((2, 16),
                                                     jnp.int32))["params"]
    greedy = generate(model, params, prompt, 6)
    seq1, sc1 = beam_search(model, params, prompt, 6, num_beams=1,
                            length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(seq1[:, 0]),
                                  np.asarray(greedy))

    seq4, sc4 = beam_search(model, params, prompt, 6, num_beams=4,
                            length_penalty=0.0)
    assert seq4.shape == (3, 4, 6) and sc4.shape == (3, 4)
    sc = np.asarray(sc4)
    assert (np.diff(sc, axis=1) <= 1e-6).all()        # sorted desc
    # With length_penalty=0 the scores are raw sums of log-probs; the
    # best beam cannot be worse than the greedy path it contains in
    # its search space.
    np.testing.assert_array_compare(
        lambda a, b: a >= b - 1e-5, sc[:, 0], np.asarray(sc1[:, 0]))
    # Determinism.
    seq4b, _ = beam_search(model, params, prompt, 6, num_beams=4,
                           length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(seq4), np.asarray(seq4b))


def test_beam_search_eos_freezes_beams():
    """A beam that emits eos_id freezes: it pads with eos at no score
    cost and keeps competing on its frozen score."""
    from tensorflow_distributed_tpu.models.generate import beam_search

    model = _model()
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    params = model.init(jax.random.key(1), jnp.zeros((2, 16),
                                                     jnp.int32))["params"]
    seq, _ = beam_search(model, params, prompt, 8, num_beams=4, eos_id=5)
    s = np.asarray(seq[0])
    for beam in s:
        hits = np.where(beam == 5)[0]
        if hits.size:                                  # eos fired =>
            assert (beam[hits[0]:] == 5).all()         # eos-padded tail

    with pytest.raises(ValueError, match="eos_id"):
        beam_search(model, params, prompt, 4, eos_id=999)
    with pytest.raises(ValueError, match="num_beams"):
        beam_search(model, params, prompt, 4, num_beams=0)


def test_beam_search_composes_with_quant_window_gqa():
    """Beam search through the int8-quantized, windowed, grouped cache:
    the per-step cache gather must reindex EVERY cache leaf (int8
    values AND their scale arrays) and the prefill tile must replicate
    them; deterministic, sorted output pins the composition."""
    from tensorflow_distributed_tpu.models.generate import beam_search

    model = CausalLM(tiny_config(
        causal=True, n_kv_heads=2, attn_window=6, kv_cache_quant="int8",
        pos_emb="rope", max_len=32, compute_dtype=jnp.float32))
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    params = model.init(jax.random.key(0),
                        jnp.zeros((2, 16), jnp.int32))["params"]
    s1, sc = beam_search(model, params, prompt, 8, num_beams=3)
    s2, _ = beam_search(model, params, prompt, 8, num_beams=3)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert s1.shape == (1, 3, 8)
    assert (np.diff(np.asarray(sc), axis=1) <= 1e-6).all()
