"""Byte-level text corpus path: dataset='text' trains a char-level GPT
on a local file — the real-corpus story with zero egress."""

import jax
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.data.lm import text_clm


def _write_corpus(path, n=400):
    """Deterministic, learnable byte patterns: repeated key:value lines
    whose value is a rotation of the key."""
    lines = [f"{'abcdefghij'[i % 10]}{i % 10}:" + "abcdefghij"[i % 10:]
             + "abcdefghij"[:i % 10] + "\n" for i in range(n)]
    path.write_text("".join(lines))
    return path


def test_text_clm_shapes_and_split(tmp_path):
    p = _write_corpus(tmp_path / "corpus.txt")
    train, val = text_clm(str(p), seq_len=32, seed=0)
    assert train.vocab_size == 256
    assert train.tokens.shape[1] == 32
    # Targets are the byte stream shifted one.
    np.testing.assert_array_equal(train.tokens[:, 1:], train.targets[:, :-1])
    assert train.tokens.min() >= 0 and train.tokens.max() < 256
    assert len(val) >= 1 and len(train) > len(val)
    # Deterministic per seed.
    t2, _ = text_clm(str(p), seq_len=32, seed=0)
    np.testing.assert_array_equal(train.tokens, t2.tokens)


def test_text_clm_too_small_raises(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_text("abc")
    with pytest.raises(ValueError, match="windows"):
        text_clm(str(p), seq_len=32)


def test_small_corpus_fails_at_task_creation(tmp_path):
    """A corpus with too few windows must fail BEFORE training, not in
    the final eval after the budget is spent."""
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.tasks import make_task

    p = _write_corpus(tmp_path / "small.txt", n=40)  # ~600 bytes
    cfg = TrainConfig(model="gpt_lm", model_size="tiny", dataset="text",
                      data_dir=str(p), batch_size=32,
                      mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="too small"):
        make_task(cfg, make_mesh(cfg.mesh))


def test_unknown_lm_dataset_rejected(tmp_path):
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.tasks import make_task

    cfg = TrainConfig(model="gpt_lm", model_size="tiny", dataset="txt",
                      mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="unknown dataset"):
        make_task(cfg, make_mesh(cfg.mesh))


def test_text_requires_causal_family(tmp_path):
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.tasks import make_task

    p = _write_corpus(tmp_path / "corpus.txt")
    cfg = TrainConfig(model="bert_mlm", model_size="tiny", dataset="text",
                      data_dir=str(p), mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="causal"):
        make_task(cfg, make_mesh(cfg.mesh))


@pytest.mark.slow
def test_byte_gpt_trains_on_text(tmp_path):
    """End to end through train(): a char-level GPT on the corpus file
    learns the line structure (loss drops well below the ~5.5-nat
    uniform-byte floor)."""
    from tensorflow_distributed_tpu.train.loop import train

    p = _write_corpus(tmp_path / "corpus.txt", n=2000)
    cfg = TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="text",
        data_dir=str(p), batch_size=32, train_steps=120,
        eval_every=120, log_every=0, eval_batch_size=64,
        compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0)
    result = train(cfg)
    assert int(jax.device_get(result.state.step)) == 120
    assert result.final_metrics["loss"] < 2.2  # uniform bytes ~ 5.55


def test_bpe_tokenizer_roundtrip_and_windows(tmp_path):
    """text_tokenizer='bpe': the corpus-trained byte-level BPE is
    lossless (decode(encode(x)) == x), caches next to the file, packs
    more text per window than bytes, and the dataset's vocab tracks
    what the trainer actually emitted (tiny corpora train fewer merges
    than requested — the model vocab must follow)."""
    from tensorflow_distributed_tpu.data.lm import (
        _encode_corpus, train_or_load_bpe)

    import glob

    p = _write_corpus(tmp_path / "corpus.txt", n=1200)
    tok = train_or_load_bpe(str(p), 300)
    assert glob.glob(str(tmp_path / "corpus.txt.bpe300.*.json"))
    text = p.read_text()
    ids = _encode_corpus(str(p), tok)
    assert tok.decode(list(ids)) == text          # lossless
    assert len(ids) < len(text.encode())          # compresses vs bytes

    train_b, _ = text_clm(str(p), seq_len=32, tokenizer="byte")
    train_s, _ = text_clm(str(p), seq_len=32, tokenizer="bpe",
                          bpe_vocab_size=300)
    assert len(train_s) < len(train_b)            # fewer, denser windows
    assert train_s.vocab_size <= 300
    assert train_s.tokens.dtype == np.uint16
    b = train_s.batch(np.arange(2))
    np.testing.assert_array_equal(b["tokens"][:, 1:],
                                  b["targets"][:, :-1])

    with pytest.raises(ValueError, match="tokenizer"):
        text_clm(str(p), seq_len=32, tokenizer="wordpiece")
    with pytest.raises(ValueError, match="bpe_vocab_size"):
        text_clm(str(p), seq_len=32, tokenizer="bpe",
                 bpe_vocab_size=100000)
    with pytest.raises(ValueError, match="text_tokenizer"):
        TrainConfig(model="gpt_lm", dataset="text",
                    text_tokenizer="wordpiece", batch_size=32).validate()

    # Content-hash-keyed cache: editing the corpus must retrain (new
    # cache file), not silently reuse a vocab whose alphabet may not
    # cover the new text.
    p.write_text(text + "zzz new content\n")
    train_or_load_bpe(str(p), 300)
    assert len(glob.glob(str(tmp_path / "corpus.txt.bpe300.*.json"))) == 2


@pytest.mark.slow
def test_bpe_gpt_trains_on_text(tmp_path):
    """End to end through train() with --text-tokenizer bpe: the model
    embedding is sized from the TRAINED vocab (task.vocab_size) and
    the subword GPT learns the line structure."""
    from tensorflow_distributed_tpu.train.loop import train

    p = _write_corpus(tmp_path / "corpus.txt", n=4000)
    cfg = TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="text",
        data_dir=str(p), text_tokenizer="bpe", bpe_vocab_size=300,
        batch_size=32, train_steps=120, eval_every=120, log_every=0,
        eval_batch_size=64, compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0)
    result = train(cfg)
    assert int(jax.device_get(result.state.step)) == 120
    # Subword units are higher-entropy than bytes; the structure is
    # still learnable far below uniform over the ~300-token vocab.
    assert result.final_metrics["loss"] < 3.0
