"""Multi-step runner: K scanned steps == K sequential dispatches."""

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.multistep import (
    make_multi_step, stacked_batch_shardings)
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step


def _setup(mesh8):
    import optax

    from tensorflow_distributed_tpu.models.cnn import MnistCNN

    model = MnistCNN(compute_dtype=jnp.float32, dropout_rate=0.0)
    state = create_train_state(model, optax.sgd(0.1),
                               np.zeros((2, 28, 28, 1), np.float32),
                               mesh8, seed=0)
    rng = np.random.default_rng(0)
    K, B = 4, 32
    xs = rng.normal(size=(K, B, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=(K, B)).astype(np.int32)
    return state, (xs, ys)


def test_multi_step_matches_sequential(mesh8):
    state, (xs, ys) = _setup(mesh8)
    step1 = make_train_step(mesh8, donate=False)
    s_seq = state
    for k in range(4):
        batch = shard_batch(mesh8, (xs[k], ys[k]))
        s_seq, m_seq = step1(s_seq, batch)

    step_k = make_multi_step(mesh8)
    stacked = tuple(
        jax.device_put(h, s) for h, s in zip(
            (xs, ys), jax.tree_util.tree_leaves(
                stacked_batch_shardings(mesh8))))
    s_k, m_k = step_k(state, stacked)

    assert int(jax.device_get(s_k.step)) == 4
    np.testing.assert_allclose(float(m_k["loss"]), float(m_seq["loss"]),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-6, rtol=2e-5),
        s_seq.params, s_k.params)


def test_multi_step_preprocess(mesh8):
    state, (xs, ys) = _setup(mesh8)
    u8 = np.clip(np.rint(xs * 255.0), 0, 255).astype(np.uint8)
    step_k = make_multi_step(
        mesh8, preprocess=lambda b: (b[0].astype(jnp.float32) / 255.0,
                                     b[1]))
    stacked = tuple(
        jax.device_put(h, s) for h, s in zip(
            (u8, ys), jax.tree_util.tree_leaves(
                stacked_batch_shardings(mesh8))))
    s_k, m_k = step_k(state, stacked)
    assert np.isfinite(float(jax.device_get(m_k["loss"])))
    assert int(jax.device_get(s_k.step)) == 4
