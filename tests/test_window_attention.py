"""Sliding-window attention: model-level semantics.

Kernel-level window correctness (vs the dense masked oracle, fwd +
all three bwd kernels, band predicates and clamp index maps) lives in
test_flash_attention.py; here the window rides the full model: the
training forward and the KV-cache decode path must implement the SAME
(pos - W, pos] band, or generation silently diverges from training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.transformer import (
    CausalLM, tiny_config)


def _model(window, **kw):
    return CausalLM(tiny_config(causal=True, attn_window=window,
                                compute_dtype=jnp.float32, **kw))


@pytest.mark.parametrize("n_kv_heads", [0, 1])
def test_window_decode_logits_match_full_forward(n_kv_heads):
    """Teacher-forced decode through the windowed cache reproduces the
    windowed training forward position by position — including
    positions beyond the window, where the cache mask must HIDE
    entries the plain causal mask would show. n_kv_heads=1 exercises
    the separate grouped (narrow-cache) decode branch."""
    W = 5
    kw = {"n_kv_heads": n_kv_heads} if n_kv_heads else {}
    model = _model(W, **kw)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 12)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)          # [B, L, V]

    logits4, state = model.apply({"params": params}, tokens[:, :4],
                                 decode=True,
                                 positions=jnp.arange(4)[None, :],
                                 mutable=["cache"])
    np.testing.assert_allclose(logits4, full[:, :4], atol=1e-4,
                               rtol=1e-3)
    cache = state["cache"]
    for t in range(4, 12):
        step_logits, state = model.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, positions=jnp.full((1, 1), t),
            mutable=["cache"])
        cache = state["cache"]
        np.testing.assert_allclose(step_logits[:, 0], full[:, t],
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"position {t}")


def test_window_changes_the_function():
    """A window strictly smaller than the sequence must CHANGE the
    logits vs full causal (same params) — guards against the window
    being silently dropped anywhere in the stack."""
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 12)), jnp.int32)
    windowed = _model(4)
    plain = _model(0)
    params = plain.init(jax.random.key(0), tokens)["params"]
    lw = windowed.apply({"params": params}, tokens)
    lp = plain.apply({"params": params}, tokens)
    assert float(jnp.max(jnp.abs(lw - lp))) > 1e-3
    # ...and a window >= L is exactly full causal.
    same = _model(12).apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(same), np.asarray(lp),
                               rtol=1e-6, atol=1e-6)


def test_window_trains_end_to_end(devices8):
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="gpt_lm", model_size="tiny",
                      dataset="synthetic", batch_size=32,
                      train_steps=30, eval_every=0, log_every=0,
                      eval_batch_size=32, compute_dtype="float32",
                      learning_rate=3e-3, dropout_rate=0.0,
                      attn_window=8, seq_len=32,
                      mesh=MeshConfig(data=8))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.3, result.final_metrics


def test_window_config_validation():
    with pytest.raises(ValueError, match="causal LM family"):
        TrainConfig(model="bert_mlm", attn_window=8,
                    batch_size=32).validate()
    with pytest.raises(ValueError, match="mesh.seq"):
        TrainConfig(model="gpt_lm", attn_window=8, batch_size=32,
                    mesh=MeshConfig(data=1, seq=2)).validate()
    with pytest.raises(ValueError, match="attn_window"):
        TrainConfig(model="gpt_lm", attn_window=-1,
                    batch_size=32).validate()
    # Model-level wall: ring attention is not windowed.
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(MeshConfig(data=4, seq=2))
    model = CausalLM(tiny_config(causal=True, attn_window=4,
                                 compute_dtype=jnp.float32), mesh)
    tokens = jnp.zeros((4, 16), jnp.int32)
    with pytest.raises(ValueError, match="not"):
        model.init(jax.random.key(0), tokens)
