"""Checkpoint/resume tests (SURVEY.md N7 replacement — including the
cross-run resume the reference structurally could not do)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step


def _state(mesh):
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    return create_train_state(model, optax.adam(1e-3),
                              jnp.zeros((2, 28, 28, 1)), mesh, seed=0)


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def test_roundtrip_bitexact(tmp_path, mesh8):
    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    state, _ = step(state, shard_batch(mesh8, _batch()))
    path = ckpt.save(str(tmp_path), state)
    assert os.path.isdir(path)

    template = _state(mesh8)  # fresh init, different values
    restored = ckpt.restore(str(tmp_path), template)
    assert int(jax.device_get(restored.step)) == 1
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(state.params), jax.device_get(restored.params))
    # Optimizer slots (Adam m/v — the reference's ps-resident slots,
    # SURVEY.md N12) restore too.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(state.opt_state), jax.device_get(restored.opt_state))


def test_async_save_matches_sync(tmp_path, mesh8):
    """background=True produces byte-identical checkpoints; saves
    queued while training continues don't block or corrupt — the
    reference Supervisor's background saver behavior."""
    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    state, _ = step(state, shard_batch(mesh8, _batch()))

    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    ckpt.save(sync_dir, state)
    ckpt.save(async_dir, state, background=True)
    # Keep training while the writer drains — the snapshot was taken
    # at submit time, so the write must reflect step 1, not step 2.
    state2, _ = step(state, shard_batch(mesh8, _batch(seed=1)))
    ckpt.save(async_dir, state2, background=True)
    ckpt.wait()
    assert ckpt.available_steps(async_dir) == [1, 2]

    a = (tmp_path / "sync" / "step_00000001" / "state.msgpack").read_bytes()
    b = (tmp_path / "async" / "step_00000001" / "state.msgpack").read_bytes()
    assert a == b

    restored = ckpt.restore(async_dir, _state(mesh8), step=1)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)),
        jax.device_get(state.params), jax.device_get(restored.params))


def test_async_save_surfaces_writer_errors(tmp_path, mesh8):
    state = _state(mesh8)
    bad = str(tmp_path / "file-not-dir")
    (tmp_path / "file-not-dir").write_text("occupied")
    ckpt.save(bad, state, background=True)
    import pytest as _pytest
    with _pytest.raises(OSError):
        ckpt.wait()
    ckpt.wait()  # queue is drained; second wait is a clean no-op


def test_resume_continues_identically(tmp_path, mesh8):
    """train 4 steps == train 2, checkpoint, restore, train 2 more."""
    step = make_train_step(mesh8, donate=False)
    batches = [shard_batch(mesh8, _batch(seed=i)) for i in range(4)]

    s_full = _state(mesh8)
    for b in batches:
        s_full, _ = step(s_full, b)

    s_a = _state(mesh8)
    for b in batches[:2]:
        s_a, _ = step(s_a, b)
    ckpt.save(str(tmp_path), s_a)
    s_b = ckpt.restore(str(tmp_path), _state(mesh8))
    for b in batches[2:]:
        s_b, _ = step(s_b, b)

    assert int(jax.device_get(s_b.step)) == 4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(s_full.params), jax.device_get(s_b.params))


def test_restore_across_mesh_shapes(tmp_path, mesh8, mesh1):
    """Save on 8 devices, restore on 1 — the mesh-agnostic restore the
    Supervisor never had."""
    s8 = _state(mesh8)
    step8 = make_train_step(mesh8, donate=False)
    s8, _ = step8(s8, shard_batch(mesh8, _batch()))
    ckpt.save(str(tmp_path), s8)

    s1 = ckpt.restore(str(tmp_path), _state(mesh1))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(s8.params), jax.device_get(s1.params))


def test_keep_prunes_old(tmp_path, mesh8):
    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    b = shard_batch(mesh8, _batch())
    for _ in range(5):
        state, _ = step(state, b)
        ckpt.save(str(tmp_path), state, keep=2)
    assert ckpt.available_steps(str(tmp_path)) == [4, 5]


def test_restore_missing_raises(tmp_path, mesh8):
    with pytest.raises(FileNotFoundError):
        ckpt.restore(str(tmp_path / "nope"), _state(mesh8))


def test_restore_explicit_missing_step_lists_available(tmp_path, mesh8):
    """An explicit step that isn't on disk must die with the steps
    that ARE, not an opaque open() traceback."""
    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    b = shard_batch(mesh8, _batch())
    for _ in range(2):
        state, _ = step(state, b)
        ckpt.save(str(tmp_path), state)
    with pytest.raises(FileNotFoundError,
                       match=r"available steps: \[1, 2\]"):
        ckpt.restore(str(tmp_path), _state(mesh8), step=7)
    with pytest.raises(FileNotFoundError, match="empty or absent"):
        ckpt.restore(str(tmp_path / "empty"), _state(mesh8))
    with pytest.raises(FileNotFoundError,
                       match=r"available steps: \[1, 2\]"):
        ckpt.restore_averaged(str(tmp_path), _state(mesh8), step=7)


def test_available_steps_ignores_garbage(tmp_path, mesh8):
    """Crashed/partial/foreign entries must never surface as resume
    targets: tmp staging dirs, quarantined dirs, stray files named
    like steps, step dirs without a state file, non-step entries."""
    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    state, _ = step(state, shard_batch(mesh8, _batch()))
    ckpt.save(str(tmp_path), state)

    # A crashed mid-write staging dir WITH a complete-looking payload.
    tmp_dir = tmp_path / "step_00000005.tmp"
    tmp_dir.mkdir()
    (tmp_dir / "state.msgpack").write_bytes(b"partial")
    # A quarantined dir from a previous integrity failure.
    qdir = tmp_path / "quarantined_step_00000004"
    qdir.mkdir()
    (qdir / "state.msgpack").write_bytes(b"bad")
    # A stray FILE named exactly like a step dir.
    (tmp_path / "step_00000007").write_text("not a dir")
    # An empty step dir (no state file, no orbax marker).
    (tmp_path / "step_00000009").mkdir()
    # Foreign debris.
    (tmp_path / "notes.txt").write_text("hi")

    assert ckpt.available_steps(str(tmp_path)) == [1]
    assert ckpt.latest_step(str(tmp_path)) == 1
    restored = ckpt.restore(str(tmp_path), _state(mesh8))
    assert int(jax.device_get(restored.step)) == 1


def test_restore_pre_ema_checkpoint(tmp_path, mesh8):
    """A checkpoint written before TrainState grew the ema field (no
    "ema" key in the serialized dict) must still restore — absence
    means "EMA off", not a from_state_dict missing-field error."""
    from flax import serialization

    import json

    state = _state(mesh8)
    path = ckpt.save(str(tmp_path), state)
    fname = os.path.join(path, "state.msgpack")
    with open(fname, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    raw.pop("ema", None)  # simulate the pre-EMA on-disk layout
    with open(fname, "wb") as f:
        f.write(serialization.msgpack_serialize(raw))
    # Pre-EMA checkpoints predate the integrity manifest too — strip
    # the checksum so the simulation is the real old layout (restore
    # skips verification when no sha256 is recorded).
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest.pop("sha256", None)
    with open(mpath, "w") as f:
        json.dump(manifest, f)

    restored = ckpt.restore(str(tmp_path), _state(mesh8))
    assert restored.ema is None
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(state.params), jax.device_get(restored.params))
