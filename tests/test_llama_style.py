"""SwiGLU MLP + RMSNorm knobs, and the full Llama-style composition
(RoPE + GQA + SwiGLU + RMSNorm + tied embeddings) end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.models.transformer import (
    CausalLM, tiny_config)


def _tokens(b=2, l=12, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 64, size=(b, l)), jnp.int32)


def test_swiglu_param_tree():
    import flax.linen as nn

    p = nn.meta.unbox(CausalLM(tiny_config(
        causal=True, mlp_variant="swiglu",
        compute_dtype=jnp.float32)).init(
        jax.random.key(0), _tokens())["params"])
    mlp = p["layer_0"]["mlp"]
    assert set(mlp) == {"gate", "up", "down"}
    assert mlp["gate"]["kernel"].shape == (32, 64)


def test_rmsnorm_param_tree():
    p = CausalLM(tiny_config(causal=True, norm="rmsnorm",
                             compute_dtype=jnp.float32)).init(
        jax.random.key(0), _tokens())["params"]
    # RMSNorm is scale-only: no bias in any norm.
    for ln in ("ln1", "ln2"):
        assert set(p["layer_0"][ln]) == {"scale"}
    assert set(p["ln_f"]) == {"scale"}


def test_unknown_variants_raise():
    with pytest.raises(ValueError, match="mlp_variant"):
        CausalLM(tiny_config(causal=True, mlp_variant="relu2")).init(
            jax.random.key(0), _tokens())
    with pytest.raises(ValueError, match="norm"):
        CausalLM(tiny_config(causal=True, norm="batchnorm")).init(
            jax.random.key(0), _tokens())


def _stack_roundtrip(extra_cfg, toks, atol, rtol):
    """Shared skeleton for the full-composition tests: init, forward-
    vs-decode parity at the given tolerance, grad finiteness; returns
    (model, params) for composition-specific follow-ups."""
    model = CausalLM(tiny_config(
        causal=True, pos_emb="rope", n_kv_heads=2, mlp_variant="swiglu",
        norm="rmsnorm", tie_embeddings=True, max_len=64,
        compute_dtype=jnp.float32, **extra_cfg))
    params = model.init(jax.random.key(0), toks)["params"]
    assert "lm_head" not in params and "pos_emb" not in params

    full = model.apply({"params": params}, toks)
    logits, _ = model.apply({"params": params}, toks, decode=True,
                            positions=jnp.arange(toks.shape[1])[None, :],
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=atol, rtol=rtol)

    loss, grads = jax.value_and_grad(
        lambda p: jnp.mean(model.apply({"params": p}, toks) ** 2))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree_util.tree_leaves(grads))
    return model, params


def test_llama_style_stack_trains_decodes_generates():
    """The full modern composition in one model: rotary positions,
    grouped KV heads, gated MLP, RMSNorm, tied output projection —
    trains, cache-decodes at parity, and generates."""
    from tensorflow_distributed_tpu.models.generate import generate

    model, params = _stack_roundtrip({}, _tokens(l=16), 1e-4, 1e-3)
    out = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 5,
                   temperature=0.7, top_p=0.9, key=jax.random.key(1))
    assert out.shape == (1, 5)


def test_modern_knobs_on_bidirectional_family():
    """pos_emb/GQA/SwiGLU/RMSNorm are family-wide: the encoder (MLM)
    model composes them too — forward, loss, and grads stay finite."""
    from tensorflow_distributed_tpu.models.transformer import BertMLM
    from tensorflow_distributed_tpu.ops.losses import (
        masked_softmax_cross_entropy)

    model = BertMLM(tiny_config(pos_emb="rope", n_kv_heads=2,
                                mlp_variant="swiglu", norm="rmsnorm",
                                compute_dtype=jnp.float32))
    toks = _tokens(l=16)
    variables = model.init(jax.random.key(0), toks)
    assert "pos_emb" not in variables["params"]

    def loss(p):
        logits = model.apply({"params": p}, toks)
        assert logits.shape == (*toks.shape, 64)
        return masked_softmax_cross_entropy(
            logits, toks, jnp.ones(toks.shape, jnp.float32))

    val, grads = jax.value_and_grad(loss)(variables["params"])
    assert np.isfinite(float(val))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree_util.tree_leaves(grads))


@pytest.mark.slow
def test_llama_knobs_through_pipeline(devices8):
    """SwiGLU + RMSNorm ride the shared Block into the 1F1B pipeline."""
    import optax
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    # model=2 exercises the _TP_SUFFIX entries for the swiglu gate —
    # its kernel must shard over the model axis like up/down.
    mesh = make_mesh(MeshConfig(data=1, model=2, pipe=4), devices8)
    model = pipelined_lm(mesh, num_microbatches=4, mlp_variant="swiglu",
                         norm="rmsnorm", max_len=16, use_flash=False)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    gate = state.params["blocks"]["mlp"]["gate"]["kernel"]
    assert "model" in jax.tree_util.tree_leaves(tuple(gate.sharding.spec))
    step = make_1f1b_train_step(model, mesh, donate=False)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64, seed=0)
    batch = shard_batch(mesh, next(LmBatcher(ds, 8, 0).forever(0)),
                        seq_axis=1)
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_mistral_style_stack_trains_decodes_generates():
    """The round-4 composition on top of the Llama stack: sliding-
    window attention + int8 KV cache — decode tracks the windowed
    forward within quantization error (including past the window
    horizon), and generates deterministically."""
    from tensorflow_distributed_tpu.models.generate import generate

    model, params = _stack_roundtrip(
        dict(attn_window=6, kv_cache_quant="int8"), _tokens(l=16, seed=2),
        0.05, 0.05)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out1 = generate(model, params, prompt, 8)
    out2 = generate(model, params, prompt, 8)
    assert out1.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
