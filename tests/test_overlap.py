"""Overlap-aware gradient sync (parallel/overlap.py + its wiring).

Four layers, mirroring the PR:

1. bucket partitioner units — size bound respected, deterministic
   order, dtype keying, and the block-layout round trip bit-identical;
2. overlap-vs-serial step identity on a mesh>1 CPU run: params, Adam
   slots, EMA, and a ``skip_nonfinite``-skipped step all BIT-equal,
   with the per-module health vitals agreeing across formulations;
3. census golden drift gate for the new ``*_train_overlap`` programs
   (trace-only — no compiles);
4. config validation (overlap rejected where the data axis is 1, the
   family is pipelined, the partition isn't zero1, ...) and the
   planner's overlap strategy (enumeration constraints, cli_args
   mapping, roofline overlap discount) — jax-free where the planner
   tier is.
"""

import dataclasses

import numpy as np
import pytest

from tensorflow_distributed_tpu.analysis.planner.candidates import (
    Candidate, ModelFacts, enumerate_candidates)
from tensorflow_distributed_tpu.analysis.planner.score import (
    Hardware, roofline_ms)
from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig

# --- bucket planning (import-light: plan_buckets flattens shapes) -------


def _fake_tree(shapes, dtype="float32"):
    return {f"leaf_{i:02d}": np.zeros(s, dtype=dtype)
            for i, s in enumerate(shapes)}


def test_plan_buckets_size_bound_and_determinism():
    from tensorflow_distributed_tpu.parallel.overlap import plan_buckets

    tree = _fake_tree([(64, 64)] * 6)  # 16 KiB leaves
    plan = plan_buckets(tree, 2, bucket_bytes=40 * 1024,
                        fsdp_min_size=256)
    assert plan.n_leaves == 6
    for bucket in plan.scatter:
        assert sum(lp.nbytes for lp in bucket) <= 40 * 1024
    # Deterministic: same inputs, same plan; leaves keep flatten order.
    again = plan_buckets(tree, 2, bucket_bytes=40 * 1024,
                         fsdp_min_size=256)
    assert plan == again
    order = [lp.index for b in plan.scatter for lp in b]
    assert order == sorted(order)


def test_plan_buckets_oversize_leaf_gets_own_bucket():
    from tensorflow_distributed_tpu.parallel.overlap import plan_buckets

    tree = _fake_tree([(16, 16), (512, 512), (16, 16)])
    plan = plan_buckets(tree, 2, bucket_bytes=8 * 1024,
                        fsdp_min_size=64)
    big = [b for b in plan.scatter if any(lp.shape == (512, 512)
                                          for lp in b)]
    assert len(big) == 1 and len(big[0]) == 1  # alone, over the bound


def test_plan_buckets_dtype_keyed_and_small_leaves_replicated():
    from tensorflow_distributed_tpu.parallel.overlap import plan_buckets

    tree = {"a": np.zeros((64, 64), np.float32),
            "b": np.zeros((64, 64), np.float16),
            "c": np.zeros((64, 64), np.float32),
            "tiny": np.zeros((8,), np.float32),
            "odd": np.zeros((63, 3), np.float32)}  # no dim % 2 == 0
    plan = plan_buckets(tree, 2, bucket_bytes=1 << 20, fsdp_min_size=64)
    for bucket in plan.scatter:
        assert len({lp.dtype for lp in bucket}) == 1
    rep_paths = {lp.path for b in plan.replicated for lp in b}
    assert ("tiny",) in rep_paths      # under fsdp_min_size
    assert ("odd",) in rep_paths       # no divisible dim
    assert all(("a",) != p for p in rep_paths)


def test_comm_bytes_estimate_scales_with_axis():
    from tensorflow_distributed_tpu.parallel.overlap import (
        comm_bytes_per_step, plan_buckets)

    tree = _fake_tree([(64, 64)] * 4)
    total = sum(x.nbytes for x in tree.values())
    p2 = plan_buckets(tree, 2, fsdp_min_size=64)
    p4 = plan_buckets(tree, 4, fsdp_min_size=64)
    assert comm_bytes_per_step(p2) == pytest.approx(2 * total * 1 / 2)
    assert comm_bytes_per_step(p4) == pytest.approx(2 * total * 3 / 4)
    p1 = plan_buckets(tree, 1, fsdp_min_size=64)
    assert comm_bytes_per_step(p1) == 0.0


def test_block_layout_round_trip_bit_identical():
    """leaf -> rows -> per-device flats -> blocks -> gathered rows ->
    leaf reconstructs every value bit-for-bit, for scatter dims 0/1/2."""
    import jax
    from tensorflow_distributed_tpu.parallel.overlap import (
        LeafPlan, _block_to_flat, _flat_to_block, _leaf_to_rows,
        _rows_to_leaf)

    rng = np.random.default_rng(0)
    n = 4
    for shape, dim in [((8, 5), 0), ((5, 8), 1), ((3, 4, 6), 1),
                       ((2, 3, 8), 2)]:
        lp = LeafPlan(index=0, path=("x",), shape=shape,
                      dtype="float32", scatter_dim=dim)
        x = rng.normal(size=shape).astype(np.float32)
        rows = np.asarray(_leaf_to_rows(jax.numpy.asarray(x), dim, n))
        assert rows.shape == (n, x.size // n)
        blocks = [np.asarray(_flat_to_block(
            jax.numpy.asarray(rows[i]), lp, n)) for i in range(n)]
        # Each block is the device's slice along the scatter dim.
        blk = shape[dim] // n
        for i, b in enumerate(blocks):
            sl = [slice(None)] * len(shape)
            sl[dim] = slice(i * blk, (i + 1) * blk)
            np.testing.assert_array_equal(b, x[tuple(sl)])
        flats = np.stack([np.asarray(_block_to_flat(
            jax.numpy.asarray(b), lp)) for b in blocks])
        np.testing.assert_array_equal(flats, rows)
        back = np.asarray(_rows_to_leaf(jax.numpy.asarray(rows), lp, n))
        np.testing.assert_array_equal(back, x)


# --- the identity run (compiles; shares one tiny-gpt setup) ------------

_SEQ, _BATCH, _BUCKET, _MIN = 16, 8, 8192, 256


@pytest.fixture(scope="module")
def overlap_setup(devices8):
    """data=2 mesh, mesh-less tiny gpt, loss/shardings/data — shared
    by every compiling test in this module."""
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings)

    mesh = make_mesh(MeshConfig(data=2), devices8[:2])
    model = transformer.gpt_lm(mesh=None, size="tiny",
                               tp_partitioning=False, dropout_rate=0.0,
                               compute_dtype=jnp.bfloat16, max_len=_SEQ)
    sh = mlm_batch_shardings(mesh)
    ds = synthetic_clm(n=64, seq_len=_SEQ, vocab_size=64)

    def put(i, poison=False):
        b = ds.batch((np.arange(_BATCH) + i * _BATCH)
                     % ds.tokens.shape[0])
        if poison:
            b = dict(b)
            b["mask"] = np.asarray(b["mask"]) * np.nan
        return {k: jax.device_put(np.asarray(v), sh[k])
                for k, v in b.items()}

    return {"mesh": mesh, "model": model, "loss": make_mlm_loss(),
            "sh": sh, "put": put}


def _build(setup, sync, **kw):
    import jax
    import optax

    from tensorflow_distributed_tpu.parallel.overlap import (
        make_explicit_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    overlap = sync == "overlap"
    state = create_train_state(
        setup["model"], optax.adam(1e-3),
        np.zeros((2, _SEQ), np.int32), setup["mesh"], seed=0,
        opt_fsdp=overlap, fsdp_min_size=_MIN, ema=True)
    params_out = (jax.tree_util.tree_map(lambda a: a.sharding,
                                         state.params)
                  if overlap else None)
    step = make_explicit_train_step(
        setup["mesh"], state, loss=setup["loss"],
        batch_shardings=setup["sh"], grad_sync=sync,
        bucket_bytes=_BUCKET, fsdp_min_size=_MIN, donate=False,
        ema_decay=0.999, params_out_shardings=params_out, **kw)
    return state, step


def _bit_equal(a, b):
    import jax

    fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(fa, fb))


def test_overlap_matches_serial_bit_identical(overlap_setup):
    """THE identity gate: 3 steps (the middle one NaN-poisoned and
    skipped on device) leave params, Adam slots, and EMA bit-equal
    across the serial-psum and bucketed-overlap formulations — and the
    skipped step really discarded the update on both sides."""
    from tensorflow_distributed_tpu.parallel.overlap import plan_buckets

    ss, serial = _build(overlap_setup, "serial", skip_nonfinite=True,
                        grad_norm_metric=True, health_every=2)
    so, over = _build(overlap_setup, "overlap", skip_nonfinite=True,
                      grad_norm_metric=True, health_every=2)
    plan = plan_buckets(ss.params, 2, bucket_bytes=_BUCKET,
                        fsdp_min_size=_MIN)
    assert len(plan.scatter) > 1  # the bucketed schedule is exercised

    pre_skip = None
    for i in range(3):
        poison = i == 1
        if poison:
            pre_skip = so.params
        ss, ms = serial(ss, overlap_setup["put"](i, poison=poison))
        so, mo = over(so, overlap_setup["put"](i, poison=poison))
        assert float(ms["skipped_nonfinite"]) == float(
            mo["skipped_nonfinite"]) == (1.0 if poison else 0.0)
        if poison:
            assert _bit_equal(so.params, pre_skip)  # update discarded
        if i != 1:
            np.testing.assert_allclose(float(ms["grad_norm"]),
                                       float(mo["grad_norm"]),
                                       rtol=1e-5)
        # Per-module health vitals agree across formulations on the
        # cadence step (psum-reconstructed norms vs full-tree norms:
        # same values modulo summation order).
        if float(ms.get("health_emit", 0.0)) > 0:
            for k in ms:
                if k.startswith("health/"):
                    np.testing.assert_allclose(
                        float(ms[k]), float(mo[k]), rtol=1e-4,
                        err_msg=k)
    assert int(so.step) == 3
    assert _bit_equal(ss.params, so.params)
    assert _bit_equal(ss.opt_state, so.opt_state)
    assert _bit_equal(ss.ema, so.ema)


def test_clip_tree_matches_optax_semantics():
    """_clip_tree fed optax's own global_norm reproduces
    optax.clip_by_global_norm BIT-EXACTLY, on both sides of the
    trigger — the explicit step's clip is the chain clip with the
    norm made pluggable (so the shard_map paths can psum-reconstruct
    it), not a reimplementation with different rounding."""
    import jax
    import optax

    from tensorflow_distributed_tpu.parallel.overlap import _clip_tree

    tree = _fake_tree([(8, 12), (5,), (3, 4, 2)])
    tree = jax.tree_util.tree_map(
        lambda x: jax.numpy.asarray(x - np.mean(x)), tree)
    for max_norm in (0.05, 1e6):   # clipping / not clipping
        clip = optax.clip_by_global_norm(max_norm)
        ref, _ = clip.update(tree, clip.init(tree))
        got = _clip_tree(tree, optax.global_norm(tree), max_norm)
        assert _bit_equal(ref, got), f"max_norm={max_norm}"


def test_overlap_matches_serial_bit_identical_with_clip(overlap_setup):
    """The grad-clip composition gate (ROADMAP item 2's follow-up):
    with clipping ACTIVE on every step (clip << observed grad norms),
    serial+clip and overlap+clip stay bit-equal — both modes scale by
    the same psum-reconstructed global-norm scalar — and the clip
    demonstrably changed the trajectory vs the unclipped run."""
    ss, serial = _build(overlap_setup, "serial", grad_clip_norm=0.05,
                        grad_norm_metric=True)
    so, over = _build(overlap_setup, "overlap", grad_clip_norm=0.05,
                      grad_norm_metric=True)
    su, unclipped = _build(overlap_setup, "serial",
                           grad_norm_metric=True)
    for i in range(3):
        ss, ms = serial(ss, overlap_setup["put"](i))
        so, mo = over(so, overlap_setup["put"](i))
        su, _ = unclipped(su, overlap_setup["put"](i))
        # The pre-clip norm is the reported metric, identical across
        # formulations (same reconstruction), and far above the bound
        # (the clip genuinely fires every step).
        assert float(ms["grad_norm"]) == float(mo["grad_norm"])
        assert float(ms["grad_norm"]) > 0.05
    assert _bit_equal(ss.params, so.params)
    assert _bit_equal(ss.opt_state, so.opt_state)
    assert _bit_equal(ss.ema, so.ema)
    assert not _bit_equal(ss.params, su.params)  # clip changed things


def test_overlap_slots_stay_sharded(overlap_setup):
    """The point of ZeRO-1 composition: after an overlap step the
    Adam mirrors keep their data-sharded layout (never gathered), and
    the params keep the replicated layout the constraint pins."""
    import jax

    from tensorflow_distributed_tpu.analysis import runtime as graftcheck

    so, over = _build(overlap_setup, "overlap")
    declared = graftcheck.sharding_tree(so.opt_state)
    so, _ = over(so, overlap_setup["put"](0))
    graftcheck.assert_sharding_contract(so.opt_state, declared,
                                        what="opt_state")
    after = jax.tree_util.tree_map(lambda a: a.sharding, so.opt_state)
    sharded = [s for s in jax.tree_util.tree_leaves(after)
               if "data" in str(s.spec)]
    assert sharded  # some slot really lives sharded
    for p in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda a: a.sharding, so.params)):
        assert "data" not in str(p.spec)


def test_multistep_overlap_matches_single_steps(overlap_setup):
    """K=2 stacked dispatch of the overlap step == 2 single steps
    (scan-wrapped program; allclose — cross-program elementwise
    rounding is not pinned, the bit gate lives in the identity test)."""
    import jax

    from tensorflow_distributed_tpu.train.multistep import (
        make_multi_step, stacked_batch_shardings)

    s_single, single = _build(overlap_setup, "overlap")
    s_multi, _ = _build(overlap_setup, "overlap")
    multi = make_multi_step(
        overlap_setup["mesh"], loss=overlap_setup["loss"],
        batch_shardings=overlap_setup["sh"], grad_sync="overlap",
        state_template=s_multi, grad_sync_bucket_bytes=_BUCKET,
        grad_sync_min_size=_MIN)
    b0, b1 = overlap_setup["put"](0), overlap_setup["put"](1)
    stacked = jax.tree_util.tree_map(
        lambda a, b, s: jax.device_put(
            np.stack([np.asarray(a), np.asarray(b)]), s),
        b0, b1, stacked_batch_shardings(overlap_setup["mesh"],
                                        overlap_setup["sh"]))
    s_multi, m = multi(s_multi, stacked)
    for b in (b0, b1):
        s_single, ms = single(s_single, b)
    assert int(s_multi.step) == 2
    np.testing.assert_allclose(float(m["loss"]), float(ms["loss"]),
                               rtol=1e-6)
    for x, y in zip(jax.tree_util.tree_leaves(s_single.params),
                    jax.tree_util.tree_leaves(s_multi.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)


def test_builder_rejections(overlap_setup, devices8):
    import optax

    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.overlap import (
        make_explicit_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh1 = make_mesh(MeshConfig(data=1), devices8[:1])
    state = create_train_state(overlap_setup["model"],
                               optax.adam(1e-3),
                               np.zeros((2, _SEQ), np.int32), mesh1)
    with pytest.raises(ValueError, match="data"):
        make_explicit_train_step(mesh1, state, grad_sync="overlap")
    with pytest.raises(ValueError, match="unknown grad_sync"):
        make_explicit_train_step(mesh1, state, grad_sync="banana")
    mesh_tp = make_mesh(MeshConfig(data=2, model=2), devices8[:4])
    with pytest.raises(ValueError, match="pure data"):
        make_explicit_train_step(mesh_tp, state, grad_sync="overlap")
    from tensorflow_distributed_tpu.train.step import make_train_step
    with pytest.raises(ValueError, match="state_template"):
        make_train_step(overlap_setup["mesh"], grad_sync="overlap")
    with pytest.raises(ValueError, match="accum_steps"):
        make_train_step(overlap_setup["mesh"], grad_sync="overlap",
                        state_template=state, accum_steps=2)


# --- census drift gate (trace-only) ------------------------------------


def test_overlap_census_matches_golden():
    """The new ``*_train_overlap`` programs trace to exactly the
    committed collective counts — a reduce-scatter or all-gather
    gained/lost per bucket fails here, not in an ICI profile later."""
    from tensorflow_distributed_tpu.analysis import jaxprcheck

    current = jaxprcheck.census(["gpt_train_overlap"])
    drift = jaxprcheck.diff_censuses(jaxprcheck.load_golden(), current,
                                     required=["gpt_train_overlap"])
    assert drift == [], drift


# --- config validation --------------------------------------------------


def _cfg(**kw):
    defaults = dict(model="gpt_lm", model_size="tiny",
                    dataset="synthetic", grad_sync="overlap",
                    param_partition="zero1",
                    mesh=MeshConfig(data=2), batch_size=16)
    defaults.update(kw)
    return TrainConfig(**defaults)


def test_config_overlap_valid():
    _cfg().validate()
    _cfg(grad_sync="serial", param_partition="replicated").validate()
    # grad_clip_norm COMPOSES since the psum-reconstructed pre-scale
    # landed (the old validate-time rejection is lifted).
    _cfg(grad_clip_norm=1.0).validate()
    _cfg(grad_sync="serial", param_partition="replicated",
         grad_clip_norm=1.0).validate()


@pytest.mark.parametrize("kw,match", [
    (dict(mesh=MeshConfig(data=1)), "nothing to synchronize"),
    (dict(mesh=MeshConfig(data=2, model=2)), "pure data"),
    (dict(model="pipelined_lm"), "pipeline"),
    (dict(param_partition="replicated"), "zero1"),
    (dict(param_partition="fsdp"), "zero1"),
    (dict(grad_sync="serial"), "replicated"),
    (dict(optimizer="adafactor"), "ELEMENTWISE"),
    (dict(grad_accum_steps=2, batch_size=16), "microbatch"),
    (dict(ce_chunk=8), "ce_chunk"),
    (dict(mode="serve"), "mode"),
    (dict(grad_sync="banana"), "unknown grad_sync"),
])
def test_config_overlap_rejections(kw, match):
    with pytest.raises(ValueError, match=match):
        _cfg(**kw).validate()


def test_config_bucket_knob_needs_overlap():
    with pytest.raises(ValueError, match="grad_sync_bucket_mb"):
        TrainConfig(grad_sync_bucket_mb=8.0).validate()
    # An explicitly-passed DEFAULT value is just as ignored without
    # overlap — the sentinel (None = unset) catches it too.
    with pytest.raises(ValueError, match="grad_sync_bucket_mb"):
        TrainConfig(grad_sync_bucket_mb=4.0).validate()
    _cfg(grad_sync_bucket_mb=8.0).validate()
    _cfg(grad_sync_bucket_mb=4.0).validate()


@pytest.mark.parametrize("kw", [
    dict(optimizer="adafactor"),
    dict(grad_accum_steps=2),
    dict(param_sync_every=2),
    dict(ce_chunk=8),
    dict(shard_vocab=True),
])
def test_overlap_conflict_single_source_of_truth(kw):
    # overlap_grad_sync_conflict (what --plan auto consults) must be
    # EXACTLY the message validate raises for the same knob — the
    # planner and the launch guard can never disagree about whether
    # overlap fits a config.
    cfg = _cfg(**kw)
    msg = cfg.overlap_grad_sync_conflict()
    assert msg
    with pytest.raises(ValueError) as ei:
        cfg.validate()
    assert str(ei.value) == msg
    assert _cfg().overlap_grad_sync_conflict() is None


def test_config_plan_auto_owns_grad_sync():
    # serial + replicated + default mesh passes every grad_sync rule,
    # so the plan-auto ownership guard is what fires.
    with pytest.raises(ValueError, match="plan auto owns the "
                                         "grad-sync"):
        TrainConfig(model="gpt_lm", plan="auto",
                    grad_sync="serial").validate()
    # overlap + plan auto dies earlier (plan auto pins replicated,
    # overlap demands zero1) — still rejected, different guard.
    with pytest.raises(ValueError):
        TrainConfig(model="gpt_lm", plan="auto",
                    grad_sync="overlap").validate()


# --- planner strategy (jax-free like the planner unit tier) -------------


def _stub_infeasible(axes, devices, batch):
    product = 1
    for v in axes.values():
        product *= v
    if product != devices:
        return "product"
    if batch % axes.get("data", 1):
        return "batch"
    return None


def test_planner_enumerates_overlap_pure_data_only():
    facts = ModelFacts(family="gpt", n_heads=4, n_layers=2)
    feasible, pruned = enumerate_candidates(
        facts, devices=4, batch=16, infeasible=_stub_infeasible)
    strategies = {(c.strategy, tuple(sorted(c.mesh.items())))
                  for c in feasible}
    assert ("overlap", (("data", 4), ("expert", 1), ("model", 1),
                        ("pipe", 1), ("seq", 1))) in strategies
    # overlap never appears on a tensor-carrying or data=1 shape
    for c in feasible:
        if c.partition == "overlap":
            assert c.mesh["model"] == 1 and c.mesh["data"] > 1
    reasons = [p.reason for p in pruned
               if p.candidate.partition == "overlap"]
    assert any("pure data" in r for r in reasons)
    pipe_facts = ModelFacts(family="pipelined", n_heads=4, n_layers=4)
    feas_p, pruned_p = enumerate_candidates(
        pipe_facts, devices=4, batch=16, infeasible=_stub_infeasible)
    assert not any(c.partition == "overlap" for c in feas_p)


def test_planner_prunes_overlap_on_knob_conflict():
    facts = ModelFacts(family="gpt", n_heads=4, n_layers=2)
    feasible, pruned = enumerate_candidates(
        facts, devices=4, batch=16, infeasible=_stub_infeasible,
        overlap_conflict="optimizer 'adafactor' is not elementwise")
    assert not any(c.partition == "overlap" for c in feasible)
    reasons = [p.reason for p in pruned
               if p.candidate.partition == "overlap"
               and p.candidate.mesh["data"] == 4]
    assert reasons and "adafactor" in reasons[0]


def test_apply_auto_threads_overlap_conflict(monkeypatch):
    # apply_auto must hand the run's knob conflicts to the enumeration
    # so --plan auto never picks an overlap layout the post-plan
    # re-validate would reject (e.g. --optimizer adafactor).
    from tensorflow_distributed_tpu.analysis.planner import plan as plan_lib
    from tensorflow_distributed_tpu.parallel import mesh as mesh_lib
    seen = {}

    def fake_make_plan(*args, **kw):
        seen.update(kw)
        return {"family": "gpt", "size": "tiny", "devices": 2,
                "batch_size": 16, "candidates": [], "pruned": [],
                "chosen": {"mesh": {"data": 2}, "partition": "zero1",
                           "strategy": "zero1", "step_ms": 1.0,
                           "peak_hbm_bytes": 1}}

    monkeypatch.setattr(plan_lib, "make_plan", fake_make_plan)
    monkeypatch.setattr(mesh_lib, "alive_devices", lambda: [0, 0])
    monkeypatch.setattr(mesh_lib, "is_chief", lambda: False)
    cfg = TrainConfig(model="gpt_lm", model_size="tiny",
                      dataset="synthetic", batch_size=16, plan="auto",
                      optimizer="adafactor")
    plan_lib.apply_auto(cfg)
    assert seen["overlap_conflict"] == cfg.overlap_grad_sync_conflict()
    assert "adafactor" in seen["overlap_conflict"]


def test_planner_overlap_cli_args_and_strategy():
    cand = Candidate.make({"data": 4}, "overlap")
    assert cand.strategy == "overlap"
    args = cand.cli_args()
    assert args[args.index("--param-partition") + 1] == "zero1"
    assert args[args.index("--grad-sync") + 1] == "overlap"


def test_roofline_overlap_discount():
    hw = Hardware(platform="cpu", device_kind="x", peak_flops=1e12,
                  hbm_bw=1e11, ici_bw=1e10)
    costs = {"flops": 2e9, "bytes_accessed": 1e8}  # 2 ms compute, 1 ms mem
    serial = roofline_ms(costs, 3e7, hw)            # 3 ms collective
    over = roofline_ms(costs, 3e7, hw, overlap=True)
    assert serial["step_ms"] == pytest.approx(2.0 + 3.0)
    assert over["step_ms"] == pytest.approx(3.0)    # max, not sum
    small = roofline_ms(costs, 1e7, hw, overlap=True)
    assert small["step_ms"] == pytest.approx(2.0)   # fully hidden


def test_min_latency_probe_helper():
    from tensorflow_distributed_tpu.parallel.collectives import (
        min_latency)

    seen = iter([0.5, 0.2, 0.9])
    assert min_latency(lambda: next(seen), iters=3) == 0.2
    with pytest.raises(ValueError):
        min_latency(lambda: 0.0, iters=0)
