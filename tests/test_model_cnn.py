"""Model-layer tests: exact reference shapes + loss math golden numbers."""

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.ops.losses import accuracy, softmax_cross_entropy


def _init(model, batch=2):
    x = jnp.zeros((batch, 28, 28, 1), jnp.float32)
    return model.init(jax.random.key(0), x, train=False), x


def test_parameter_shapes_match_reference():
    """Exact parity with the reference weight dicts
    (mnist_python_m.py:185-196): wc1 [5,5,1,32], wc2 [5,5,32,64],
    wd1 [3136,1024], out [1024,10] + matching biases."""
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, _ = _init(model)
    p = variables["params"]
    assert p["conv1"]["kernel"].shape == (5, 5, 1, 32)
    assert p["conv1"]["bias"].shape == (32,)
    assert p["conv2"]["kernel"].shape == (5, 5, 32, 64)
    assert p["conv2"]["bias"].shape == (64,)
    assert p["fc1"]["kernel"].shape == (3136, 1024)
    assert p["fc1"]["bias"].shape == (1024,)
    assert p["out"]["kernel"].shape == (1024, 10)
    assert p["out"]["bias"].shape == (10,)
    total = sum(x.size for x in jax.tree_util.tree_leaves(p))
    # 832 + 51264 + 3212288 + 10250 (conv+bias, fc+bias) — the reference
    # model's exact parameter count.
    assert total == 3_274_634


def test_forward_shapes_and_dtype():
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, x = _init(model, batch=4)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_accepts_flat_784_input():
    """The reference's placeholder was [None, 784]
    (mnist_python_m.py:198)."""
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, _ = _init(model)
    flat = jnp.zeros((3, 784), jnp.float32)
    assert model.apply(variables, flat, train=False).shape == (3, 10)


def test_dropout_only_active_in_train_mode():
    model = MnistCNN(compute_dtype=jnp.float32, dropout_rate=0.5)
    variables, x = _init(model, batch=8)
    e1 = model.apply(variables, x + 1.0, train=False)
    e2 = model.apply(variables, x + 1.0, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    t1 = model.apply(variables, x + 1.0, train=True,
                     rngs={"dropout": jax.random.key(1)})
    t2 = model.apply(variables, x + 1.0, train=True,
                     rngs={"dropout": jax.random.key(2)})
    assert not np.array_equal(np.asarray(t1), np.asarray(t2))


def test_reference_init_scheme_is_wild():
    """reference init = normal stddev 1.0 (mnist_python_m.py:185-196);
    improved = He. Their weight scales must differ by orders of
    magnitude on the big fc1 matrix."""
    ref = MnistCNN(init_scheme="reference", compute_dtype=jnp.float32)
    imp = MnistCNN(init_scheme="improved", compute_dtype=jnp.float32)
    pr, _ = _init(ref)
    pi, _ = _init(imp)
    sr = float(jnp.std(pr["params"]["fc1"]["kernel"]))
    si = float(jnp.std(pi["params"]["fc1"]["kernel"]))
    assert 0.9 < sr < 1.1          # stddev ~1.0
    assert si < 0.05               # He: sqrt(2/3136) ~ 0.025


def test_softmax_xent_golden():
    """Hand-computed golden numbers for the loss
    (reference: tf.nn.softmax_cross_entropy_with_logits mean,
    mnist_python_m.py:205)."""
    logits = jnp.array([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.array([0, 1])
    # per-row: log(exp(2)+exp(0)) - 2 = log(1+exp(-2)) = 0.126928...
    got = float(softmax_cross_entropy(logits, labels))
    np.testing.assert_allclose(got, 0.12692805, rtol=1e-6)
    # Uniform logits -> log(num_classes).
    u = jnp.zeros((5, 10))
    np.testing.assert_allclose(
        float(softmax_cross_entropy(u, jnp.zeros(5, jnp.int32))),
        np.log(10.0), rtol=1e-6)


def test_accuracy_golden():
    logits = jnp.array([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0], [0.0, 1.0]])
    labels = jnp.array([0, 1, 1, 1])
    assert float(accuracy(logits, labels)) == 0.75
