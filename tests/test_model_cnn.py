"""Model-layer tests: exact reference shapes + loss math golden numbers."""

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.ops.losses import accuracy, softmax_cross_entropy


def _init(model, batch=2):
    x = jnp.zeros((batch, 28, 28, 1), jnp.float32)
    return model.init(jax.random.key(0), x, train=False), x


def test_parameter_shapes_match_reference():
    """Exact parity with the reference weight dicts
    (mnist_python_m.py:185-196): wc1 [5,5,1,32], wc2 [5,5,32,64],
    wd1 [3136,1024], out [1024,10] + matching biases."""
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, _ = _init(model)
    p = variables["params"]
    assert p["conv1"]["kernel"].shape == (5, 5, 1, 32)
    assert p["conv1"]["bias"].shape == (32,)
    assert p["conv2"]["kernel"].shape == (5, 5, 32, 64)
    assert p["conv2"]["bias"].shape == (64,)
    assert p["fc1"]["kernel"].shape == (3136, 1024)
    assert p["fc1"]["bias"].shape == (1024,)
    assert p["out"]["kernel"].shape == (1024, 10)
    assert p["out"]["bias"].shape == (10,)
    total = sum(x.size for x in jax.tree_util.tree_leaves(p))
    # 832 + 51264 + 3212288 + 10250 (conv+bias, fc+bias) — the reference
    # model's exact parameter count.
    assert total == 3_274_634


def test_forward_shapes_and_dtype():
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, x = _init(model, batch=4)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_accepts_flat_784_input():
    """The reference's placeholder was [None, 784]
    (mnist_python_m.py:198)."""
    model = MnistCNN(compute_dtype=jnp.float32)
    variables, _ = _init(model)
    flat = jnp.zeros((3, 784), jnp.float32)
    assert model.apply(variables, flat, train=False).shape == (3, 10)


def test_dropout_only_active_in_train_mode():
    model = MnistCNN(compute_dtype=jnp.float32, dropout_rate=0.5)
    variables, x = _init(model, batch=8)
    e1 = model.apply(variables, x + 1.0, train=False)
    e2 = model.apply(variables, x + 1.0, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    t1 = model.apply(variables, x + 1.0, train=True,
                     rngs={"dropout": jax.random.key(1)})
    t2 = model.apply(variables, x + 1.0, train=True,
                     rngs={"dropout": jax.random.key(2)})
    assert not np.array_equal(np.asarray(t1), np.asarray(t2))


def test_reference_init_trains_materially_worse():
    """TRAINING-OUTCOME faithful-vs-improved comparison (VERDICT r02
    weak #7): same data, same fixed step budget —
    init_scheme="reference" with the reference's Adam lr 0.01
    (mnist_python_m.py:185-196,208) lands materially below "improved".
    The reference's own performance table is exactly such a
    fixed-budget curve (40 steps -> 90%, performance:2). On real MNIST
    the bad init also caps the ceiling at 95.75% (performance:6); the
    synthetic glyph set is easy enough that even stddev-1.0 init
    eventually recovers (measured: 0.996 by step 80 of batch 64), so
    the fixed-budget comparison is the honest, deterministic form of
    the outcome gap here. Measured (fixed seeds, CPU, batch 32 x 32
    steps): reference 0.605 vs improved 0.828."""
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.mnist import synthetic_mnist
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import (
        make_eval_step, make_train_step)

    mesh = make_mesh(MeshConfig(data=8))
    train_ds, val_ds, _ = synthetic_mnist(n_train=4096, n_test=512,
                                          validation_size=256, seed=0)
    # lr rides in the optimizer STATE (inject_hyperparams), so one
    # compiled step serves both schemes — the graphs are identical,
    # only initial params and lr differ.
    tx = optax.inject_hyperparams(optax.adam)(learning_rate=1e-3)
    sample = np.zeros((2, 28, 28, 1), np.float32)
    step = make_train_step(mesh, donate=False)
    eval_step = make_eval_step(mesh)
    val_batch = shard_batch(mesh, (val_ds.images, val_ds.labels))

    accs = {}
    for scheme, lr in (("reference", 0.01), ("improved", 1e-3)):
        model = MnistCNN(init_scheme=scheme, compute_dtype=jnp.float32)
        state = create_train_state(model, tx, sample, mesh)
        state.opt_state.hyperparams["learning_rate"] = jnp.asarray(lr)
        for i in range(32):
            lo = (i * 32) % 2048
            b = shard_batch(mesh, (train_ds.images[lo:lo + 32],
                                   train_ds.labels[lo:lo + 32]))
            state, metrics = step(state, b)
            # Block each step: unbounded async dispatch of 8-device
            # SPMD programs aborts XLA:CPU's collective rendezvous on
            # oversubscribed hosts (see train/loop.py's inflight deque).
            jax.block_until_ready(metrics)
        accs[scheme] = float(
            jax.device_get(eval_step(state, val_batch)["accuracy"]))
    # "Materially below" at the fixed budget: the stddev-1.0 init +
    # lr 0.01 combination saturates activations and thrashes Adam.
    # Everything above is seed-fixed, so the 22-point measured gap is
    # deterministic; the margins leave slack for backend math drift.
    assert accs["improved"] >= accs["reference"] + 0.10, accs
    assert accs["improved"] >= 0.80, accs
    assert accs["reference"] <= 0.70, accs


def test_reference_init_scheme_is_wild():
    """reference init = normal stddev 1.0 (mnist_python_m.py:185-196);
    improved = He. Their weight scales must differ by orders of
    magnitude on the big fc1 matrix."""
    ref = MnistCNN(init_scheme="reference", compute_dtype=jnp.float32)
    imp = MnistCNN(init_scheme="improved", compute_dtype=jnp.float32)
    pr, _ = _init(ref)
    pi, _ = _init(imp)
    sr = float(jnp.std(pr["params"]["fc1"]["kernel"]))
    si = float(jnp.std(pi["params"]["fc1"]["kernel"]))
    assert 0.9 < sr < 1.1          # stddev ~1.0
    assert si < 0.05               # He: sqrt(2/3136) ~ 0.025


def test_softmax_xent_golden():
    """Hand-computed golden numbers for the loss
    (reference: tf.nn.softmax_cross_entropy_with_logits mean,
    mnist_python_m.py:205)."""
    logits = jnp.array([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.array([0, 1])
    # per-row: log(exp(2)+exp(0)) - 2 = log(1+exp(-2)) = 0.126928...
    got = float(softmax_cross_entropy(logits, labels))
    np.testing.assert_allclose(got, 0.12692805, rtol=1e-6)
    # Uniform logits -> log(num_classes).
    u = jnp.zeros((5, 10))
    np.testing.assert_allclose(
        float(softmax_cross_entropy(u, jnp.zeros(5, jnp.int32))),
        np.log(10.0), rtol=1e-6)


def test_accuracy_golden():
    logits = jnp.array([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0], [0.0, 1.0]])
    labels = jnp.array([0, 1, 1, 1])
    assert float(accuracy(logits, labels)) == 0.75
