"""End-to-end loop + CLI tests: the accuracy-bar integration test the
reference performed by hand (SURVEY.md §4 "accuracy-as-test")."""

import jax
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.train.loop import train
from tests.conftest import FIXTURE_DIR


def _cfg(**kw):
    base = dict(dataset="synthetic", batch_size=128, train_steps=40,
                eval_every=0, log_every=0, eval_batch_size=128,
                compute_dtype="float32", mesh=MeshConfig(data=8))
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.slow
def test_train_reaches_accuracy_bar():
    """The integration bar: the loop must reach high accuracy on the
    synthetic digits within a small budget (the analog of the
    reference's 95.75%-at-120-steps ceiling, performance:6 — which our
    'improved' init scheme beats by design)."""
    result = train(_cfg(train_steps=60))
    assert result.final_metrics["accuracy"] >= 0.97
    assert int(jax.device_get(result.state.step)) == 60
    assert result.images_per_sec > 0


def test_train_on_fixture_real_bytes_reaches_bar():
    """DEFAULT-TIER accuracy bar on REAL idx bytes (VERDICT r03 item
    4): train end-to-end on the committed fixture — real on-disk
    idx1/idx3 files through the full parser/batcher/loop path, not
    synthetic arrays handed past it — and demand a fixture-appropriate
    accuracy. The recorded artifact from this exact path is
    ACCURACY_r04.md (100% at step 75, batch 64)."""
    from tensorflow_distributed_tpu.data import load_dataset

    # Guard the guard: load_dataset falls back to synthetic digits on
    # missing files (which would also pass the bar) — prove the
    # fixture actually loads as real mnist before training on it.
    train_ds, _, _ = load_dataset("mnist", FIXTURE_DIR,
                                  validation_size=64)
    assert train_ds.name == "mnist", train_ds.name
    cfg = _cfg(dataset="mnist", data_dir=FIXTURE_DIR,
               validation_size=64, batch_size=64, train_steps=50,
               eval_every=0, eval_batch_size=64, learning_rate=2e-3)
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.95, result.final_metrics


@pytest.mark.slow
def test_train_resume_roundtrip(tmp_path):
    cfg = _cfg(train_steps=10, checkpoint_dir=str(tmp_path),
               checkpoint_every=5)
    r1 = train(cfg)
    cfg2 = _cfg(train_steps=14, checkpoint_dir=str(tmp_path),
                checkpoint_every=5, resume=True)
    r2 = train(cfg2)
    assert int(jax.device_get(r2.state.step)) == 14


def test_train_resume_roundtrip_async_checkpoints(tmp_path):
    """checkpoint_async=True: cadence saves overlap training, the loop
    flushes the writer on exit, and resume lands on the same step.

    Runs in a SUBPROCESS: concurrent device_put (prefetch thread) +
    dispatch + the background writer thread intermittently SIGSEGVs
    the XLA:CPU runtime on the CI container — reproducible on the
    untouched seed tree — and an in-process crash aborts the whole
    pytest run. Isolation turns a host-runtime crash into a plain
    failure; one retry absorbs the known flake (a real regression in
    the checkpoint logic fails both attempts deterministically).
    """
    import subprocess
    import sys

    script = """
import jax
from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.loop import train

def cfg(**kw):
    base = dict(dataset="synthetic", batch_size=128, train_steps=40,
                eval_every=0, log_every=0, eval_batch_size=128,
                compute_dtype="float32", mesh=MeshConfig(data=8))
    base.update(kw)
    return TrainConfig(**base)

d = %r
train(cfg(train_steps=10, checkpoint_dir=d, checkpoint_every=5,
          checkpoint_async=True))
assert ckpt.latest_step(d) == 10  # flushed before return
r2 = train(cfg(train_steps=14, checkpoint_dir=d, checkpoint_every=5,
               checkpoint_async=True, resume=True))
assert int(jax.device_get(r2.state.step)) == 14
print("ASYNC_RESUME_OK")
""" % str(tmp_path)
    for attempt in (1, 2):
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True,
                              timeout=300)
        if proc.returncode == 0:
            assert "ASYNC_RESUME_OK" in proc.stdout
            return
        if proc.returncode >= 0:  # real assertion/exception: no retry
            break
    raise AssertionError(
        f"async resume subprocess failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr[-2000:]}")


def test_eval_only_mode(tmp_path):
    """mode=eval restores the checkpoint and reproduces the training
    run's final validation metrics without a single training step.
    (Cross-mesh-shape restore itself is pinned in
    test_checkpoint.test_restore_across_mesh_shapes.)"""
    from tensorflow_distributed_tpu.train.loop import evaluate_only

    cfg = _cfg(train_steps=10, checkpoint_dir=str(tmp_path),
               checkpoint_every=0, eval_every=10)
    r = train(cfg)

    m8 = evaluate_only(_cfg(mode="eval", checkpoint_dir=str(tmp_path)))
    for k, v in r.final_metrics.items():
        np.testing.assert_allclose(m8[k], v, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="mode=eval"):
        _cfg(mode="eval").validate()


def test_grad_norm_metric_opt_in():
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    import jax.numpy as jnp
    import numpy as np
    import optax

    mesh = make_mesh(MeshConfig(data=8))
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    state = create_train_state(model, optax.adam(1e-3),
                               jnp.zeros((2, 28, 28, 1), jnp.float32), mesh)
    batch = shard_batch(mesh, (
        np.random.default_rng(0).normal(size=(32, 28, 28, 1)).astype(
            np.float32),
        np.random.default_rng(0).integers(0, 10, size=(32,)).astype(
            np.int32)))
    _, m_off = make_train_step(mesh, donate=False)(state, batch)
    assert "grad_norm" not in m_off  # default dicts stay stable
    _, m_on = make_train_step(mesh, donate=False,
                              grad_norm_metric=True)(state, batch)
    gn = float(m_on["grad_norm"])
    assert np.isfinite(gn) and gn > 0


def test_halt_on_nonfinite_raises():
    cfg = _cfg(train_steps=20, log_every=1, halt_on_nonfinite=True,
               learning_rate=1e38)
    with pytest.raises(FloatingPointError, match="non-finite"):
        train(cfg)


def test_performance_table_emitted():
    result = train(_cfg(train_steps=10, eval_every=5))
    table = result.logger.performance_table(1e-3)
    lines = table.splitlines()
    assert lines[0].startswith("Steps,")
    assert len(lines) >= 3  # header + 2 eval rows


@pytest.mark.slow
def test_cli_main_runs():
    from tensorflow_distributed_tpu.cli import main
    rc = main(["--dataset", "synthetic", "--train-steps", "5",
               "--batch-size", "64", "--eval-every", "0",
               "--log-every", "0", "--eval-batch-size", "64",
               "--compute-dtype", "float32"])
    assert rc == 0


def _load_graft_entry():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_graft_entry_single():
    mod = _load_graft_entry()
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


@pytest.mark.slow
def test_graft_entry_multichip():
    _load_graft_entry().dryrun_multichip(8)


def test_first_step_hits_log_and_checkpoint_cadence(tmp_path):
    """The warm-up compile step is still step 1: with log_every=1 and
    checkpoint_every=1 it must be logged and checkpointed."""
    cfg = _cfg(train_steps=3, log_every=1, checkpoint_dir=str(tmp_path),
               checkpoint_every=1)
    result = train(cfg)
    logged_steps = [r.step for r in result.logger.records]
    assert 1 in logged_steps
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    assert 1 in ckpt.available_steps(str(tmp_path))


@pytest.mark.slow
def test_resume_continues_sample_stream():
    """A resumed run must consume the same batches an uninterrupted run
    would have (data-stream fast-forward on resume)."""
    from tensorflow_distributed_tpu.data.mnist import Dataset, ShardedBatcher
    import numpy as np
    ds = Dataset(np.zeros((64, 1, 1, 1), np.float32),
                 np.arange(64, dtype=np.int32))
    b = ShardedBatcher(ds, 16, seed=1)
    stream = b.forever()
    full = [next(stream)[1] for _ in range(10)]
    resumed = b.forever(start_step=6)
    tail = [next(resumed)[1] for _ in range(4)]
    for a, c in zip(full[6:], tail):
        np.testing.assert_array_equal(a, c)
