"""Execute the multi-host path: 2 real processes over jax.distributed.

The reference's headline feature is multi-process training coordinated
over gRPC (mnist_python_m.py:146-161); its only "fake backend" was
pointing ps_hosts/worker_hosts at localhost and launching 3 local
processes (SURVEY.md §4). This is the same trick for the TPU-native
build: 2 local processes, each owning 4 virtual CPU devices, form one
8-device jax.distributed cluster and run the FULL train() loop —
bootstrap, process-disjoint data, make_array_from_process_local_data,
chief-only checkpointing — then the result is checked for exact parity
with a single-process 8-device run of the same config.

Parity holds because the sample stream is identical by construction
(ShardedBatcher: same seeded permutation everywhere, processes take
disjoint contiguous slices of the SAME global batch) and SPMD
collectives are deterministic.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # real 2-process cluster, 540 s budget

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_cluster(tmp, ckpt_dir, tag, extra_env=None):
    """Run one 2-process cluster of multihost_worker.py to completion;
    returns (results, logs)."""
    port = _free_port()
    procs, outs = [], []
    for p in range(2):
        out = tmp / f"result_{tag}_{p}.json"
        outs.append(out)
        env = {
            # Minimal, explicit env: no axon sitecustomize, no inherited
            # JAX/XLA flags from the pytest process.
            "PATH": os.environ["PATH"],
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "TPU_NUM_PROCESSES": "2",
            "TPU_PROCESS_ID": str(p),
            "MH_CKPT_DIR": str(ckpt_dir),
            "JAX_COMPILATION_CACHE_DIR":
                os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
            **(extra_env or {}),
        }
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "multihost_worker.py"),
             str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    logs = []
    for proc in procs:
        try:
            stdout, _ = proc.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        logs.append(stdout)
    for rc, log in zip([p.returncode for p in procs], logs):
        assert rc == 0, f"worker failed (rc={rc}):\n{log[-3000:]}"
    return [json.loads(out.read_text()) for out in outs], logs


@pytest.fixture(scope="module")
def multihost_results(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("multihost")
    ckpt_dir = tmp / "ckpt"
    results, logs = _launch_cluster(tmp, ckpt_dir, "main")
    return results, ckpt_dir, logs


def test_cluster_shape(multihost_results):
    results, _, _ = multihost_results
    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 8
        assert r["local_devices"] == 4
        assert r["step"] == 6


def test_processes_agree(multihost_results):
    """SPMD: both processes hold bit-identical replicated params."""
    results, _, _ = multihost_results
    a, b = results
    assert a["params_checksum"] == b["params_checksum"]
    assert a["final_metrics"] == b["final_metrics"]


def test_chief_only_checkpoint(multihost_results):
    """Exactly the chief wrote the checkpoint (reference: the chief ran
    the Supervisor's saver, mnist_python_m.py:238-253)."""
    results, ckpt_dir, _ = multihost_results
    assert ckpt_dir.exists() and any(ckpt_dir.iterdir())


def test_chief_only_logging(multihost_results):
    """Process 1's stdout has no metric rows (MetricLogger is
    chief-gated), process 0's does."""
    _, _, logs = multihost_results
    assert '"event": "done"' in logs[0]
    assert '"event": "done"' not in logs[1]


def test_ring_attention_across_processes(multihost_results):
    """The zigzag causal ring with its seq axis spanning BOTH
    processes: ppermutes cross the process boundary (the DCN analog of
    the reference's cross-VM gRPC traffic), and the result matches a
    single-process 8-device run of the same config exactly."""
    results, _, _ = multihost_results
    a, b = results
    assert a["lm_params_checksum"] == b["lm_params_checksum"]
    assert a["lm_final_metrics"] == b["lm_final_metrics"]

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="synthetic",
        batch_size=16, train_steps=4, eval_every=0, log_every=0,
        eval_batch_size=32, compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=1, seq=8), seed=0)
    single = train(cfg)
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue  # derived as exp(loss): comparing loss covers
            # it without the ~4x relative-error amplification
        np.testing.assert_allclose(a["lm_final_metrics"][k], v,
                                   rtol=1e-4, atol=1e-5)


def test_crash_and_resume_across_processes(tmp_path_factory):
    """Failure recovery at the whole-job fault model (SURVEY.md §5:
    the reference's Supervisor re-attached a restarted worker from its
    checkpoint): a 2-process cluster trains to step 5 with durable
    checkpoints and dies; a FRESH cluster restarts with --resume and
    finishes to step 10, landing exactly where an uninterrupted run
    lands (same sample stream: the resume fast-forward is tested
    single-process in test_loop_cli; this pins it across processes
    with chief-only checkpoint writes)."""
    tmp = tmp_path_factory.mktemp("multihost_crash")
    ckpt_dir = tmp / "ckpt"
    _launch_cluster(tmp, ckpt_dir, "crash",
                    extra_env={"MH_PHASE": "crash"})
    assert ckpt_dir.exists() and any(ckpt_dir.iterdir()), \
        "no checkpoint written before crash"
    resumed, _ = _launch_cluster(tmp, ckpt_dir, "resume",
                                 extra_env={"MH_PHASE": "resume"})
    assert all(r["step"] == 10 for r in resumed)
    assert resumed[0]["params_checksum"] == resumed[1]["params_checksum"]

    # Uninterrupted oracle: the same 10 steps in one process.
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=10, eval_every=0, log_every=0, eval_batch_size=128,
        compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0)
    single = train(cfg)
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue  # derived as exp(loss): comparing loss covers
            # it without the ~4x relative-error amplification
        np.testing.assert_allclose(resumed[0]["final_metrics"][k], v,
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_across_processes(tmp_path_factory):
    """FSDP with params/Adam slots sharded ACROSS the process boundary
    (param_partition="fsdp", data axis spanning both processes): the
    checkpoint save does a collective allgather fetch, the resume
    restore re-places shards per process, and the final state matches
    an uninterrupted single-process FSDP run exactly."""
    tmp = tmp_path_factory.mktemp("multihost_fsdp")
    ckpt_dir = tmp / "ckpt"
    results, _ = _launch_cluster(tmp, ckpt_dir, "fsdp",
                                 extra_env={"MH_PHASE": "fsdp"})
    assert all(r["step"] == 8 for r in results)
    assert results[0]["params_checksum"] == results[1]["params_checksum"]

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=8, eval_every=0, log_every=0, eval_batch_size=128,
        param_partition="fsdp", compute_dtype="float32",
        dropout_rate=0.0, mesh=MeshConfig(data=8), seed=0)
    single = train(cfg)
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue  # derived as exp(loss): comparing loss covers
            # it without the ~4x relative-error amplification
        np.testing.assert_allclose(results[0]["final_metrics"][k], v,
                                   rtol=1e-4, atol=1e-5)


def test_orbax_across_processes(tmp_path_factory):
    """The orbax backend in a REAL 2-process cluster with FSDP params
    spanning the boundary: each process writes/restores its own shards
    (no allgather — unverifiable single-process), the chief's commit
    marker publishes completeness, resume works, and the final state
    matches an uninterrupted single-process FSDP run exactly."""
    tmp = tmp_path_factory.mktemp("multihost_orbax")
    ckpt_dir = tmp / "ckpt"
    results, _ = _launch_cluster(tmp, ckpt_dir, "orbax",
                                 extra_env={"MH_PHASE": "orbax"})
    assert all(r["step"] == 8 for r in results)
    assert results[0]["params_checksum"] == results[1]["params_checksum"]
    # The on-disk layout really is orbax (marker present).
    steps = sorted(p.name for p in ckpt_dir.iterdir())
    assert (ckpt_dir / steps[-1] / "ORBAX_COMMITTED").exists()

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    single = train(TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=8, eval_every=0, log_every=0, eval_batch_size=128,
        param_partition="fsdp", compute_dtype="float32",
        dropout_rate=0.0, mesh=MeshConfig(data=8), seed=0))
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue
        np.testing.assert_allclose(results[0]["final_metrics"][k], v,
                                   rtol=1e-4, atol=1e-5)


def test_local_sgd_across_processes(tmp_path_factory):
    """Local SGD with the 8 replicas spanning a REAL process boundary:
    the stacked step [8] is data-sharded across processes (host_step's
    index-before-device_get), the stacked checkpoint is written via
    the collective fetch and restored via per-process shard placement,
    and the final state matches an uninterrupted single-process run
    EXACTLY (replica identity = data-axis index, process-layout
    independent)."""
    tmp = tmp_path_factory.mktemp("multihost_lsgd")
    ckpt_dir = tmp / "ckpt"
    results, _ = _launch_cluster(tmp, ckpt_dir, "local_sgd",
                                 extra_env={"MH_PHASE": "local_sgd"})
    assert all(r["step"] == 6 for r in results)
    assert results[0]["params_checksum"] == results[1]["params_checksum"]

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    # UNINTERRUPTED oracle — no checkpointing at all, straight to step
    # 6: the cluster's crash-at-3-and-resume sequence must land exactly
    # here, which pins the stacked save/restore itself (a process-
    # layout-independent restore defect cannot hide in a replayed
    # interruption).
    single = train(TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=6, eval_every=0, log_every=0, eval_batch_size=128,
        param_sync_every=2, compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0))
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue  # derived as exp(loss): comparing loss covers
            # it without the ~4x relative-error amplification
        np.testing.assert_allclose(results[0]["final_metrics"][k], v,
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_and_expert_axes_across_processes(tmp_path_factory):
    """The pipe axis (1F1B activation/cotangent ppermutes every tick)
    and the expert axis (MoE dispatch/combine all_to_alls) spanning
    BOTH processes — the deepest cross-process collectives the
    framework emits — match single-process 8-device oracles exactly."""
    tmp = tmp_path_factory.mktemp("multihost_xaxes")
    results, _ = _launch_cluster(tmp, tmp / "ckpt", "xaxes",
                                 extra_env={"MH_PHASE": "xaxes"})
    a, b = results
    assert a == b  # SPMD: both processes computed identical results

    # The oracle runs THE SAME scenario definition the workers ran
    # (multihost_worker.run_xaxes_scenarios) — single process, plain
    # device_get fetch.
    import importlib.util

    import jax

    spec = importlib.util.spec_from_file_location(
        "multihost_worker",
        os.path.join(REPO, "tests", "multihost_worker.py"))
    worker_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(worker_mod)
    oracle = worker_mod.run_xaxes_scenarios(jax.device_get)
    for key, got in a.items():
        np.testing.assert_allclose(got, oracle[key], rtol=1e-4,
                                   err_msg=key)


def test_r5_compositions_across_processes(tmp_path_factory):
    """Round-5 compositions with their new collectives spanning the
    process boundary: ring-inside-the-pipeline (pipe hops cross DCN
    while the nested ring runs per-process) and ZeRO-1 x 1F1B (slot
    shards + the restore-layout allgather cross processes). Must match
    the single-process oracle running THE SAME scenario definition."""
    tmp = tmp_path_factory.mktemp("multihost_r5")
    results, _ = _launch_cluster(tmp, tmp / "ckpt", "r5",
                                 extra_env={"MH_PHASE": "r5"})
    a, b = results
    assert a == b  # SPMD: both processes computed identical results

    import importlib.util

    import jax

    spec = importlib.util.spec_from_file_location(
        "multihost_worker",
        os.path.join(REPO, "tests", "multihost_worker.py"))
    worker_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(worker_mod)
    oracle = worker_mod.run_r5_scenarios(jax.device_get)
    for key, got in a.items():
        np.testing.assert_allclose(got, oracle[key], rtol=1e-4,
                                   err_msg=key)


def test_fused_ce_kernel_across_processes(tmp_path_factory):
    """The fused-CE Pallas path with its loss reductions spanning the
    process boundary: the dispatcher's shard_map psums ce/correct/mask
    over (data, seq), and here those axes cross processes. Must match
    the single-process oracle running THE SAME scenario definition."""
    tmp = tmp_path_factory.mktemp("multihost_fusedce")
    results, _ = _launch_cluster(tmp, tmp / "ckpt", "fusedce",
                                 extra_env={"MH_PHASE": "fusedce"})
    a, b = results
    assert a == b  # SPMD: both processes computed identical results

    import importlib.util

    import jax

    spec = importlib.util.spec_from_file_location(
        "multihost_worker",
        os.path.join(REPO, "tests", "multihost_worker.py"))
    worker_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(worker_mod)
    oracle = worker_mod.run_fusedce_scenario(jax.device_get)
    for key, got in a.items():
        np.testing.assert_allclose(got, oracle[key], rtol=1e-4,
                                   err_msg=key)


def test_parity_with_single_process(multihost_results):
    """2-process x 4-device == 1-process x 8-device, same config: the
    N-vs-1 equivalence of SURVEY.md §7 extended across process
    boundaries. Loss/accuracy match to float tolerance."""
    results, _, _ = multihost_results

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=6, eval_every=0, log_every=0, eval_batch_size=128,
        compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0)
    single = train(cfg)

    multi = results[0]["final_metrics"]
    for k, v in single.final_metrics.items():
        if k == "perplexity":
            continue  # derived as exp(loss): comparing loss covers
            # it without the ~4x relative-error amplification
        np.testing.assert_allclose(multi[k], v, rtol=1e-4, atol=1e-5)
