"""Force an 8-device virtual CPU platform before JAX initializes.

This is the JAX analog of the reference's in-process-server trick
(SURVEY.md §4): the reference could exercise its full gRPC ps/worker
path on one machine by pointing ps_hosts/worker_hosts at localhost;
we exercise the full SPMD psum path on one machine with
--xla_force_host_platform_device_count=8.

Note: this environment's sitecustomize registers a TPU-ish backend at
interpreter start, so setting env vars alone is not enough — we must
also flip jax_platforms before the backend is first used.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from tensorflow_distributed_tpu.utils.compilecache import (  # noqa: E402
    enable_persistent_cache)

# CPU test compiles of 8-device SPMD programs are the suite's wall-clock;
# cache them across runs.
enable_persistent_cache()


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices8):
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    return make_mesh(MeshConfig(data=8), devices8)


@pytest.fixture(scope="session")
def mesh1(devices8):
    from tensorflow_distributed_tpu.parallel.mesh import single_device_mesh
    return single_device_mesh(devices8[0])


@pytest.fixture(scope="session")
def tiny_data():
    from tensorflow_distributed_tpu.data.mnist import synthetic_mnist
    return synthetic_mnist(n_train=2048, n_test=512, validation_size=256, seed=0)


# Committed real-idx fixture (shared by test_data / test_loop_cli).
FIXTURE_DIR = __file__.rsplit("/", 1)[0] + "/fixtures/mnist"
