"""ResNet family + CIFAR data + BatchNorm (mutable collections) tests.

The reference has no ResNet; these guard the scale-out configs
(BASELINE.json: ResNet-20/CIFAR-10, ResNet-50/ImageNet) and the
batch_stats plumbing through TrainState.extra.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.data.cifar import (
    parse_cifar_batch, synthetic_cifar10, synthetic_imagenet)
from tensorflow_distributed_tpu.data.mnist import load_dataset
from tensorflow_distributed_tpu.models.resnet import resnet20, resnet50
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state, param_count
from tensorflow_distributed_tpu.train.step import make_eval_step, make_train_step


def _cifar_batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.uniform(size=(n, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def test_cifar_bin_parse_roundtrip():
    rng = np.random.default_rng(0)
    n = 7
    labels = rng.integers(0, 10, size=n).astype(np.uint8)
    images = rng.integers(0, 256, size=(n, 3, 32, 32)).astype(np.uint8)
    raw = b"".join(bytes([labels[i]]) + images[i].tobytes() for i in range(n))
    imgs, labs = parse_cifar_batch(raw)
    assert imgs.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(labs, labels.astype(np.int32))
    # HWC pixel (y,x,c) == CHW plane value
    np.testing.assert_array_equal(imgs[3, 5, 9], images[3, :, 5, 9])


def test_cifar_bin_parse_rejects_bad_size():
    with pytest.raises(ValueError):
        parse_cifar_batch(b"\x00" * 100)


def test_synthetic_cifar_shapes_and_dispatch():
    train, val, test = synthetic_cifar10(n_train=256, n_test=64,
                                         validation_size=32)
    assert train.images.shape == (224, 32, 32, 3)
    assert val.images.shape[0] == 32 and test.images.shape[0] == 64
    # load_dataset falls back to synthetic when .bin files are absent
    tr2, _, _ = load_dataset("cifar10", "/nonexistent-dir", seed=0)
    assert tr2.images.shape[1:] == (32, 32, 3)
    # Direct small-N call (load_dataset's default-size imagenet twin
    # allocates ~1.2 GB of random pixels — too heavy for the fast tier).
    tr3, _, _ = synthetic_imagenet(n_train=16, n_test=8,
                                   validation_size=8)
    assert tr3.images.shape[1:] == (224, 224, 3)


def test_imagenet_synthetic_dispatch(monkeypatch):
    """The load_dataset("imagenet_synthetic") registry branch, with the
    generator shrunk so the fast tier doesn't pay the 1.2 GB default."""
    from tensorflow_distributed_tpu.data import cifar

    real = cifar.synthetic_imagenet
    small = lambda seed=0: real(  # noqa: E731
        n_train=16, n_test=8, validation_size=8, seed=seed)
    monkeypatch.setattr(cifar, "synthetic_imagenet", small)
    tr, _, _ = load_dataset("imagenet_synthetic", "", seed=0)
    assert tr.images.shape[1:] == (224, 224, 3)


def test_resnet20_shapes_params_and_stats(mesh1):
    model = resnet20(compute_dtype=jnp.float32)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 32, 32, 3), np.float32), mesh1)
    n = param_count(state.params)
    assert 250_000 < n < 300_000, n  # ResNet-20 is ~0.27M params
    assert "batch_stats" in state.extra
    images, _ = _cifar_batch(4)
    logits = model.apply({"params": state.params, **state.extra},
                         jnp.asarray(images), train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_resnet50_abstract_shapes():
    # eval_shape only — no 25M-param allocation in CI
    model = resnet50(compute_dtype=jnp.bfloat16)
    abstract = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 224, 224, 3)), train=False),
        jax.random.key(0))
    n = sum(int(np.prod(x.shape))
            for x in jax.tree_util.tree_leaves(abstract["params"]))
    assert 25_000_000 < n < 26_000_000, n
    out = jax.eval_shape(
        lambda v, x: model.apply(v, x, train=False),
        abstract, jnp.zeros((2, 224, 224, 3)))
    assert out.shape == (2, 1000)


@pytest.mark.slow
def test_resnet20_train_step_updates_stats_8dev(mesh8):
    model = resnet20(compute_dtype=jnp.float32)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 32, 32, 3), np.float32), mesh8)
    step = make_train_step(mesh8, donate=False)
    before = jax.device_get(state.extra["batch_stats"])
    batch = shard_batch(mesh8, _cifar_batch(16))
    state2, metrics = step(state, batch)
    assert int(jax.device_get(state2.step)) == 1
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    after = jax.device_get(state2.extra["batch_stats"])
    changed = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(a, b), before, after)
    assert any(jax.tree_util.tree_leaves(changed))
    # eval path consumes running stats without mutating
    ev = make_eval_step(mesh8)
    m = ev(state2, batch)
    assert np.isfinite(float(jax.device_get(m["loss"])))


@pytest.mark.slow
def test_resnet20_bn_parity_8dev_vs_1dev(mesh8, mesh1):
    """Global-batch BN inside jit: the 8-device step must produce the
    same loss and the same updated batch_stats as the 1-device step on
    the identical global batch (sync-BN semantics by construction)."""
    model = resnet20(compute_dtype=jnp.float32)
    batch = _cifar_batch(16)
    outs = []
    for mesh in (mesh8, mesh1):
        state = create_train_state(model, optax.adam(1e-3),
                                   np.zeros((2, 32, 32, 3), np.float32), mesh)
        step = make_train_step(mesh, donate=False)
        state2, metrics = step(state, shard_batch(mesh, batch))
        outs.append((float(jax.device_get(metrics["loss"])),
                     jax.device_get(state2.extra["batch_stats"])))
    l8, s8 = outs[0]
    l1, s1 = outs[1]
    assert np.isclose(l8, l1, rtol=1e-4), (l8, l1)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        s8, s1)
