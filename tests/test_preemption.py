"""Preemption notice -> durable checkpoint -> clean exit -> resume.

The reference lost everything since the last periodic checkpoint when
a worker was killed (Supervisor re-attach, mnist_python_m.py:245-253);
acting on the SIGTERM eviction notice loses nothing.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_guard_flag_and_handler_restore():
    from tensorflow_distributed_tpu.train.preemption import PreemptionGuard

    prev = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard()
    assert signal.getsignal(signal.SIGTERM) != prev
    assert not guard.should_stop(0)
    os.kill(os.getpid(), signal.SIGTERM)
    # Delivery is synchronous for self-signals on the main thread.
    assert guard.should_stop(1)
    assert guard.fired == 1
    guard.close()
    assert signal.getsignal(signal.SIGTERM) == prev


def test_guard_close_without_should_stop_restores_everything():
    """close() must restore the previous SIGTERM disposition and leave
    the process-global goodput state untouched even when a notice
    arrived but ``should_stop`` never consumed it (the loop raised, or
    the run finished first) — and a later SIGTERM must not feed the
    dead guard's flag or charge drain to a later run's counter."""
    from tensorflow_distributed_tpu.observe import goodput
    from tensorflow_distributed_tpu.train.preemption import PreemptionGuard

    counter = goodput.GoodputCounter()
    goodput.set_active(counter)
    prev = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard()
    try:
        os.kill(os.getpid(), signal.SIGTERM)  # notice, never consumed
        assert guard._flag.is_set()
        guard.close()
        # Handlers restored despite the un-consumed notice...
        assert signal.getsignal(signal.SIGTERM) == prev
        # ...the installed goodput global is exactly as we left it
        # (the guard neither uninstalled nor swapped it)...
        assert goodput.get_active() is counter
        # ...no drain was charged (only should_stop charges it)...
        assert "drain" not in counter.overhead
        # ...and the un-consumed notice state was dropped, so a
        # should_stop on the closed guard doesn't fire stale.
        assert not guard.should_stop(0)
        guard.close()  # idempotent
    finally:
        goodput.set_active(None)
        signal.signal(signal.SIGTERM, prev)


def test_guard_disabled_installs_nothing():
    from tensorflow_distributed_tpu.train.preemption import PreemptionGuard

    prev = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard(enabled=False)
    assert signal.getsignal(signal.SIGTERM) == prev
    assert not guard.should_stop(0)
    guard.close()


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path):
    """Full story at the process level: SIGTERM mid-run -> 'preempted'
    event, durable checkpoint, exit 0; --resume finishes the budget."""
    ckpt_dir = str(tmp_path / "ckpt")
    env = {
        "PATH": os.environ["PATH"],
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
        "PYTHONUNBUFFERED": "1",
    }
    args = [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
            "--dataset", "synthetic", "--mesh.data", "8",
            "--train-steps", "2000", "--eval-every", "0",
            "--log-every", "1", "--eval-batch-size", "64",
            "--batch-size", "64", "--compute-dtype", "float32",
            "--checkpoint-dir", ckpt_dir,
            # Cadence far beyond the horizon: the checkpoint that
            # exists afterwards can only be the preemption save.
            "--checkpoint-every", "100000"]
    proc = subprocess.Popen(args, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    # Wait until steps are flowing (first step line), then preempt.
    deadline = time.time() + 300
    saw_step = False
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if "[step " in line:
            saw_step = True
            break
    assert saw_step, "".join(lines)[-2000:]
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=240)
    lines.append(out)
    log = "".join(lines)
    assert proc.returncode == 0, log[-2000:]
    assert '"event": "preempted"' in log

    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    saved = ckpt.latest_step(ckpt_dir)
    assert saved is not None and 0 < saved < 2000

    # Resume to a small total; must pick up from the preemption save.
    args2 = [a for a in args]
    args2[args2.index("--train-steps") + 1] = str(saved + 3)
    args2 += ["--resume", "true"]
    out2 = subprocess.run(args2, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=300)
    assert out2.returncode == 0, out2.stdout[-2000:]
    assert f'"resumed", "step": {saved}' in out2.stdout
    assert ckpt.latest_step(ckpt_dir) == saved + 3
