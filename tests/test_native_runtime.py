"""Native C++ host runtime: build, IDX parse, gather, prefetcher."""

import gzip
import struct

import numpy as np
import pytest

from tensorflow_distributed_tpu.native import runtime

pytestmark = pytest.mark.skipif(not runtime.available(),
                                reason="no C++ toolchain")


def _write_idx_u8(path, arr):
    """Write IDX in the MNIST wire format (big-endian dims, u8 data)."""
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def _write_idx_i32(path, arr):
    with open(path, "wb") as f:  # uncompressed on purpose
        f.write(struct.pack(">BBBB", 0, 0, 0x0C, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(">i4").tobytes())


def test_idx_read_u8_gzip(tmp_path):
    arr = np.arange(3 * 4 * 5, dtype=np.uint8).reshape(3, 4, 5)
    p = str(tmp_path / "t.idx.gz")
    _write_idx_u8(p, arr)
    got = runtime.idx_read(p)
    np.testing.assert_array_equal(got, arr)


def test_idx_read_i32_endianness(tmp_path):
    arr = np.array([[1, -2, 300000], [7, 8, 9]], np.int32)
    p = str(tmp_path / "t32.idx")
    _write_idx_i32(p, arr)
    got = runtime.idx_read(p)
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, arr)


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 256, size=(100, 28, 28), dtype=np.uint8)
    idx = rng.integers(0, 100, size=64)
    got = runtime.gather_u8_f32(src, idx, 1.0 / 255.0)
    np.testing.assert_allclose(got, src[idx].astype(np.float32) / 255.0)


def test_prefetcher_epoch_coverage():
    n, batch = 64, 16
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, size=(n, 4), dtype=np.uint8)
    labels = np.arange(n, dtype=np.int32)  # label == index
    pf = runtime.NativePrefetcher(images, labels, batch, seed=7,
                                  scale=1.0)
    try:
        seen = []
        for _ in range(n // batch):  # one epoch
            x, y = next(pf)
            seen.extend(y.tolist())
            # Batch contents must be the gathered rows for those labels.
            np.testing.assert_allclose(x, images[y].astype(np.float32))
        assert sorted(seen) == list(range(n))  # exact epoch, shuffled
        assert seen != list(range(n))          # ...and actually shuffled
    finally:
        pf.close()


def test_prefetcher_rejects_bad_batch():
    images = np.zeros((4, 2), np.uint8)
    labels = np.zeros((4,), np.int32)
    with pytest.raises(ValueError):
        runtime.NativePrefetcher(images, labels, batch=8)
