"""Ring attention == dense attention, on real sharded meshes.

The correctness bar for the sequence-parallel path: rotating K,V blocks
around the "seq" ring with streaming-softmax merging must reproduce
exact dense attention to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.ring_attention import (
    full_attention, ring_attention)


def _qkv(b=2, l=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def test_full_attention_matches_naive_softmax():
    q, k, v = _qkv(b=1, l=8, h=2, d=4)
    out = full_attention(q, k, v)
    # Naive oracle.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(4.0)
    w = jax.nn.softmax(s, axis=-1)
    oracle = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=2, seq=4, model=1),
    MeshConfig(data=1, seq=8, model=1),
    MeshConfig(data=2, seq=2, model=2),
])
def test_ring_equals_dense(devices8, mesh_cfg):
    mesh = make_mesh(mesh_cfg, devices8)
    q, k, v = _qkv(b=2, l=32, h=4, d=8)
    dense = full_attention(q, k, v)
    ring = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_ring_seq1_degenerates_to_dense(mesh8):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh8)  # mesh8 has seq=1
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(full_attention(q, k, v)),
                               rtol=1e-6)


def test_ring_rejects_mask(devices8):
    mesh = make_mesh(MeshConfig(data=2, seq=4), devices8)
    q, k, v = _qkv()
    with pytest.raises(NotImplementedError):
        ring_attention(q, k, v, mesh, mask=jnp.zeros((2, 32, 32)))


def test_ring_long_sequence_streams(devices8):
    """Longer-than-VMEM-ish shape sanity: L=512 over 8-way seq."""
    mesh = make_mesh(MeshConfig(data=1, seq=8), devices8)
    q, k, v = _qkv(b=1, l=512, h=2, d=8, seed=3)
    ring = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    dense = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)

# --- causal: the zigzag (load-balanced) and naive schedules ------------


def _causal_oracle(q, k, v):
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        causal_bias)
    return full_attention(q, k, v, causal_bias(q.shape[1], k.shape[1]))


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=2, seq=4, model=1),
    MeshConfig(data=1, seq=8, model=1),
    MeshConfig(data=2, seq=2, model=2),
])
@pytest.mark.parametrize("schedule", ["zigzag", "naive"])
def test_ring_causal_equals_dense(devices8, mesh_cfg, schedule):
    mesh = make_mesh(mesh_cfg, devices8)
    q, k, v = _qkv(b=2, l=32, h=4, d=8, seed=1)
    dense = _causal_oracle(q, k, v)
    ring = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True, schedule=schedule))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_ring_causal_zigzag_grads_match_dense(devices8):
    """AD through the zigzag conversion permutes + where-selected
    accumulator folds must equal dense-causal gradients."""
    mesh = make_mesh(MeshConfig(data=1, seq=4), devices8[:4])
    q, k, v = _qkv(b=1, l=32, h=2, d=8, seed=2)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, schedule="zigzag")
        return jnp.sum(o * o)

    def loss_dense(q, k, v):
        o = _causal_oracle(q, k, v)
        return jnp.sum(o * o)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ring_causal_odd_block_falls_back(devices8):
    """Local block length 5 (odd) can't split into zigzag halves; the
    dispatcher silently uses the naive schedule and stays exact."""
    mesh = make_mesh(MeshConfig(data=1, seq=4), devices8[:4])
    q, k, v = _qkv(b=1, l=20, h=2, d=8, seed=4)
    ring = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring),
                               np.asarray(_causal_oracle(q, k, v)),
                               rtol=2e-5, atol=2e-6)


def test_ring_bad_schedule_raises(devices8):
    mesh = make_mesh(MeshConfig(data=1, seq=4), devices8[:4])
    q, k, v = _qkv(b=1, l=16, h=2, d=8)
    with pytest.raises(ValueError, match="schedule"):
        ring_attention(q, k, v, mesh, causal=True, schedule="spiral")


@pytest.mark.slow
def test_ring_zigzag_flash_partial_path(devices8, monkeypatch):
    """The zigzag schedule's local compute on the Pallas partial-softmax
    kernel (TFD_FLASH_INTERPRET forces it off-TPU): forward AND
    gradients must match the dense causal oracle — this is the exact
    code path the TPU runs for seq-sharded long context."""
    monkeypatch.setenv("TFD_FLASH_INTERPRET", "1")
    mesh = make_mesh(MeshConfig(data=1, seq=4), devices8[:4])
    # nh = 256/(2*4) = 32 >= 8 and D = 8: supported() admits the kernel.
    q, k, v = _qkv(b=1, l=256, h=2, d=8, seed=5)
    dense = _causal_oracle(q, k, v)
    ring = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True, schedule="zigzag"))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, schedule="zigzag")
        return jnp.sum(o * o)

    def loss_dense(q, k, v):
        return jnp.sum(_causal_oracle(q, k, v) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
