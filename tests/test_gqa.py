"""Grouped-query / multi-query attention: smaller KV projections and
decode caches, exact MHA equivalence when groups collapse."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.models.transformer import (
    CausalLM, tiny_config)


def _model(**overrides):
    return CausalLM(tiny_config(causal=True, compute_dtype=jnp.float32,
                                **overrides))


def _tokens(b=2, l=12, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 64, size=(b, l)), jnp.int32)


def test_gqa_param_tree_and_size():
    toks = _tokens()
    mha = _model().init(jax.random.key(0), toks)["params"]
    gqa = _model(n_kv_heads=2).init(jax.random.key(0), toks)["params"]
    mqa = _model(n_kv_heads=1).init(jax.random.key(0), toks)["params"]

    a0 = mha["layer_0"]["attn"]
    assert "qkv" in a0  # MHA keeps the fused (pre-GQA) tree
    g0, m0 = gqa["layer_0"]["attn"], mqa["layer_0"]["attn"]
    assert set(g0) == {"q", "kv", "out"}
    # tiny: d=32, h=4, dh=8. kv kernel [32, 2, nk, 8] shrinks with nk.
    assert g0["kv"]["kernel"].shape == (32, 2, 2, 8)
    assert m0["kv"]["kernel"].shape == (32, 2, 1, 8)
    n = lambda p: sum(x.size for x in jax.tree_util.tree_leaves(p))  # noqa
    assert n(m0) < n(g0) < n(a0)


def test_gqa_decode_cache_is_small_and_exact():
    """The decode cache stores n_kv heads; teacher-forced cache decode
    still reproduces the full forward exactly."""
    model = _model(n_kv_heads=1, max_len=128)
    toks = _tokens()
    params = model.init(jax.random.key(0), toks)["params"]
    full = model.apply({"params": params}, toks)

    logits5, state = model.apply({"params": params}, toks[:, :5],
                                 decode=True,
                                 positions=jnp.arange(5)[None, :],
                                 mutable=["cache"])
    assert state["cache"]["layer_0"]["attn"]["key"].shape == (2, 128, 1, 8)
    np.testing.assert_allclose(logits5, full[:, :5], atol=1e-4, rtol=1e-3)
    cache = state["cache"]
    for t in range(5, 12):
        step_logits, state = model.apply(
            {"params": params, "cache": cache}, toks[:, t:t + 1],
            decode=True, positions=jnp.full((1, 1), t), mutable=["cache"])
        cache = state["cache"]
        np.testing.assert_allclose(step_logits[:, 0], full[:, t],
                                   atol=1e-4, rtol=1e-3)


def test_gqa_equals_mha_when_kv_heads_match_by_construction():
    """n_kv_heads == n_heads goes through the fused path (identical to
    a no-GQA model, bit for bit)."""
    toks = _tokens()
    a = _model()
    b = _model(n_kv_heads=4)
    pa = a.init(jax.random.key(0), toks)
    pb = b.init(jax.random.key(0), toks)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)), pa, pb)
    np.testing.assert_array_equal(np.asarray(a.apply(pa, toks)),
                                  np.asarray(b.apply(pb, toks)))


def test_gqa_trains_with_rope_and_generates():
    from tensorflow_distributed_tpu.models.generate import generate

    model = _model(n_kv_heads=2, pos_emb="rope", max_len=32)
    toks = _tokens(l=16)
    params = model.init(jax.random.key(0), toks)["params"]
    loss, grads = jax.value_and_grad(
        lambda p: jnp.mean(model.apply({"params": p}, toks) ** 2))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree_util.tree_leaves(grads))
    out = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 5)
    assert out.shape == (1, 5)


def test_gqa_rejects_indivisible_heads():
    with pytest.raises(ValueError, match="divisible"):
        _model(n_kv_heads=3).init(jax.random.key(0), _tokens())
    # 0 is TrainConfig's MHA sentinel — must mean MHA, not crash.
    p = _model(n_kv_heads=0).init(jax.random.key(0), _tokens())["params"]
    assert "qkv" in p["layer_0"]["attn"]


@pytest.mark.slow
def test_gqa_through_the_pipeline(devices8):
    """GQA lives in SelfAttention, which the pipelined Block shares —
    a 1F1B step with grouped KV heads runs and stays finite."""
    import numpy as np
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state
    import optax

    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model = pipelined_lm(mesh, num_microbatches=4, n_kv_heads=2,
                         max_len=16, use_flash=False)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    step = make_1f1b_train_step(model, mesh, donate=False)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64, seed=0)
    batch = shard_batch(mesh, next(LmBatcher(ds, 8, 0).forever(0)),
                        seq_axis=1)
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
