"""Local SGD (param_sync_every > 1): the runnable async-family mode.

Reference counterpart: sync_replicas=False (mnist_python_m.py:208,
247-253, SURVEY N6) — replicas training on diverged parameters between
sync points. The SPMD-native expression is periodic parameter
averaging; its defining algebra is pinned here:
  - H=1 + SGD == synchronous data parallelism EXACTLY,
  - replicas diverge between syncs and re-agree at sync steps,
  - the full loop trains to the accuracy bar with H > 1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.local_sgd import (
    averaged_view, make_local_sgd_train_step, stack_state)
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step


def _setup(mesh, tx):
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    state = create_train_state(model, tx,
                               jnp.zeros((2, 28, 28, 1), jnp.float32),
                               mesh)
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, (
        rng.normal(size=(32, 28, 28, 1)).astype(np.float32),
        rng.integers(0, 10, size=(32,)).astype(np.int32)))
    return state, batch


def test_h1_sgd_equals_sync_dp(mesh8):
    """avg(p - lr*g_r) == p - lr*avg(g_r): local SGD at H=1 with plain
    SGD is EXACTLY the synchronous psum step."""
    state, batch = _setup(mesh8, optax.sgd(1e-2))
    s_sync, m_sync = make_train_step(mesh8, donate=False)(state, batch)

    s_l, m_l = make_local_sgd_train_step(mesh8, sync_every=1,
                                         donate=False)(
        stack_state(state, mesh8), batch)
    av = averaged_view(s_l)
    np.testing.assert_allclose(float(m_l["loss"]), float(m_sync["loss"]),
                               rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6), s_sync.params,
        av.params)
    assert int(jax.device_get(av.step)) == 1


def test_replicas_diverge_then_resync(mesh8):
    """Between syncs the 8 replicas hold genuinely different params
    (they saw different batch rows); at the H-th step the pmean makes
    them bit-identical again."""
    state, batch = _setup(mesh8, optax.sgd(1e-2))
    step = make_local_sgd_train_step(mesh8, sync_every=4, donate=False)
    s = stack_state(state, mesh8)
    spreads = []
    for _ in range(4):
        s, _ = step(s, batch)
        leaf = np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(s.params)[0]))
        spreads.append(float(np.max(np.abs(leaf - leaf[:1]))))
    assert all(sp > 0 for sp in spreads[:3]), spreads
    assert spreads[3] == 0.0, spreads


def test_stack_state_rejects_extra_and_ema(mesh8):
    state, _ = _setup(mesh8, optax.sgd(1e-2))
    bad = state.replace(extra={"batch_stats": {"x": jnp.zeros(3)}})
    with pytest.raises(ValueError, match="extra state"):
        stack_state(bad, mesh8)
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    with_ema = create_train_state(
        model, optax.sgd(1e-2), jnp.zeros((2, 28, 28, 1), jnp.float32),
        mesh8, ema=True)
    with pytest.raises(ValueError, match="ema"):
        stack_state(with_ema, mesh8)


def test_config_validation():
    ok = TrainConfig(param_sync_every=4, batch_size=32)
    ok.validate()
    for kw, msg in [
        (dict(param_sync_every=0), "param_sync_every"),
        (dict(param_sync_every=4, mesh=MeshConfig(data=4, model=2)),
         "pure"),
        (dict(param_sync_every=4, param_partition="fsdp"), "replicated"),
        (dict(param_sync_every=4, grad_accum_steps=2), "grad_accum"),
        (dict(param_sync_every=4, ema_decay=0.9), "ema"),
        (dict(param_sync_every=4, model="resnet20"), "extra state"),
    ]:
        with pytest.raises(ValueError, match=msg):
            TrainConfig(batch_size=32, **kw).validate()


def test_sync_flip_across_resume_is_a_clear_error(tmp_path, mesh8):
    """A checkpoint saved with one param_sync_every cannot silently
    load into the other layout: restore's shape check names the knob
    instead of failing opaquely inside the shard_map (or training on
    garbage slices)."""
    from tensorflow_distributed_tpu.train import checkpoint as ckpt

    state, _ = _setup(mesh8, optax.sgd(1e-2))
    ckpt.save(str(tmp_path), state)  # plain (unstacked) checkpoint
    stacked_tmpl = stack_state(state, mesh8)
    with pytest.raises(ValueError, match="param-sync-every"):
        ckpt.restore(str(tmp_path), stacked_tmpl)


@pytest.mark.slow
def test_local_sgd_trains_and_resumes(tmp_path):
    """The full loop: H=4 local SGD reaches the synthetic-digit bar,
    checkpoints persist the replica STACK (divergence survives resume),
    and mode=eval reproduces the averaged-view metrics."""
    from tensorflow_distributed_tpu.train.loop import evaluate_only, train

    cfg = TrainConfig(dataset="synthetic", batch_size=128,
                      train_steps=60, eval_every=0, log_every=0,
                      eval_batch_size=128, compute_dtype="float32",
                      param_sync_every=4, checkpoint_dir=str(tmp_path),
                      checkpoint_every=30, mesh=MeshConfig(data=8))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.9, result.final_metrics
    assert int(jax.device_get(result.state.step)) == 60

    cfg2 = TrainConfig(dataset="synthetic", batch_size=128,
                       train_steps=64, eval_every=0, log_every=0,
                       eval_batch_size=128, compute_dtype="float32",
                       param_sync_every=4, checkpoint_dir=str(tmp_path),
                       checkpoint_every=30, resume=True,
                       mesh=MeshConfig(data=8))
    r2 = train(cfg2)
    assert int(jax.device_get(r2.state.step)) == 64

    m = evaluate_only(TrainConfig(
        mode="eval", dataset="synthetic", batch_size=128,
        eval_batch_size=128, compute_dtype="float32",
        param_sync_every=4, checkpoint_dir=str(tmp_path),
        mesh=MeshConfig(data=8)))
    np.testing.assert_allclose(m["accuracy"],
                               r2.final_metrics["accuracy"], rtol=1e-5)

    # The cross-mesh half of the capability: the stacked checkpoint
    # averages ON HOST into a template on a DIFFERENT mesh (1 device
    # vs 8 training replicas) — the restore path mode=eval rides.
    from tensorflow_distributed_tpu.parallel.mesh import (
        single_device_mesh)
    from tensorflow_distributed_tpu.train import checkpoint as ckpt

    from tensorflow_distributed_tpu.train.optim import make_optimizer

    mesh1 = single_device_mesh(jax.devices()[0])
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    tmpl = create_train_state(model, make_optimizer(cfg2),
                              jnp.zeros((2, 28, 28, 1), jnp.float32),
                              mesh1)
    restored = ckpt.restore_averaged(str(tmp_path), tmpl)
    want = averaged_view(r2.state) if r2.state.step.ndim else r2.state
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6),
        jax.device_get(restored.params), jax.device_get(want.params))
