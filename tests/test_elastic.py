"""Elastic restarts: checkpoint resharding + degrade-and-continue.

Covers the mesh/sharding manifest written beside every checkpoint,
``restore_resharded`` (bitwise round trips across mesh shapes and
layouts), the ``MeshMismatchError`` diagnosis, the supervisor's
``--elastic`` mesh picking (pure, jax-free units), the ``device_loss``
fault grammar, and — slow tier — the supervised
device_loss -> shrink -> continue e2e the ELASTICBENCH artifact pins.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.resilience import supervisor as sup
from tensorflow_distributed_tpu.resilience.faults import parse_fault_plan
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.state import TrainState, create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(mesh, fsdp=False, ema=True):
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    return create_train_state(model, optax.adam(1e-3),
                              jnp.zeros((2, 28, 28, 1)), mesh, seed=0,
                              fsdp=fsdp, ema=ema)


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)),
        jax.device_get(a), jax.device_get(b))


# --- supervisor elastic units (pure, jax-free) --------------------------

def test_pick_elastic_mesh_units():
    axes = {"data": 4, "model": 1, "seq": 1, "pipe": 1, "expert": 1}
    # Shrink: data absorbs the resize.
    assert sup.pick_elastic_mesh(axes, 2, 64)["data"] == 2
    # Grow: fill the returned capacity.
    assert sup.pick_elastic_mesh(axes, 8, 64)["data"] == 8
    # Global batch must stay an integer per-device share: 6 alive but
    # 64 % 6 != 0 -> 4.
    assert sup.pick_elastic_mesh(axes, 6, 64)["data"] == 4
    # Non-data axes are preserved exactly (semantic parallelism).
    tp = {"data": 2, "model": 2, "seq": 1, "pipe": 1, "expert": 1}
    got = sup.pick_elastic_mesh(tp, 4, 64)
    assert got == tp
    assert sup.pick_elastic_mesh(tp, 2, 64) == {**tp, "data": 1}
    # Fewer devices than the non-data product: nothing to degrade to.
    assert sup.pick_elastic_mesh(tp, 1, 64) is None
    assert sup.pick_elastic_mesh(axes, 0, 64) is None


def test_rewrite_mesh_args_both_spellings_and_append():
    mesh = {"data": 2, "model": 1, "seq": 1, "pipe": 1, "expert": 1}
    assert sup.rewrite_mesh_args(["--mesh.data", "4", "--x", "y"],
                                 mesh) == ["--mesh.data", "2",
                                           "--x", "y"]
    assert sup.rewrite_mesh_args(["--mesh.data=4"],
                                 {**mesh, "data": 8}) == [
        "--mesh.data=8"]
    # Absent flag: the chosen width is appended EXPLICITLY (a
    # default -1 child must not re-fill to whatever is visible).
    assert sup.rewrite_mesh_args(["--train-steps", "5"], mesh) == [
        "--train-steps", "5", "--mesh.data", "2"]
    # Non-data axes only appear when != 1.
    out = sup.rewrite_mesh_args([], {**mesh, "model": 2})
    assert "--mesh.model" in out and "--mesh.seq" not in out


def test_plan_elastic_masks_dead_chips_and_remainder():
    # 8 visible, 6 declared lost -> mesh data=2 and the child must
    # hide 6 devices so its visible set exactly equals the mesh.
    mesh, child_mask = sup.plan_elastic(
        ["--mesh.data", "4", "--batch-size", "64"], total=8, masked=6)
    assert mesh["data"] == 2 and child_mask == 6
    # 6 alive of 8 with batch 64: data=4 and the unusable remainder
    # (2 alive chips the mesh can't shape around) is masked too.
    mesh, child_mask = sup.plan_elastic(
        ["--mesh.data", "4", "--batch-size", "64"], total=8, masked=2)
    assert mesh["data"] == 4 and child_mask == 4
    assert sup.plan_elastic(["--mesh.model", "4"], total=8,
                            masked=6) is None


def test_read_mask_absent_and_garbage(tmp_path):
    assert sup._read_mask(None) == 0
    assert sup._read_mask(str(tmp_path / "nope")) == 0
    bad = tmp_path / "DEVICE_MASK"
    bad.write_text("not json")
    assert sup._read_mask(str(bad)) == 0
    bad.write_text(json.dumps({"lost": 3}))
    assert sup._read_mask(str(bad)) == 3


def test_build_leg_args_unchanged_without_elastic():
    """Non-elastic behavior pinned: restarted train legs only gain
    --resume; no mesh flag is ever touched."""
    args = ["--mesh.data", "8", "--checkpoint-dir", "/tmp/c"]
    assert sup.build_leg_args(args, 0) == args
    assert sup.build_leg_args(args, 1) == args + ["--resume", "true"]


def test_supervisor_elastic_stops_when_no_mesh_fits(tmp_path,
                                                    monkeypatch):
    """Survivors below the non-data product: the supervisor refuses to
    launch a doomed leg and stops (in-process main with a stubbed
    probe — jax-free)."""
    mask = tmp_path / "DEVICE_MASK"
    mask.write_text(json.dumps({"lost": 7}))
    monkeypatch.setenv("TFD_DEVICE_MASK_FILE", str(mask))
    monkeypatch.setattr(sup, "_probe_devices", lambda: 8)
    rc = sup.main(["--elastic", "--", "--mesh.model", "2",
                   "--checkpoint-dir", str(tmp_path / "ckpt")])
    assert rc == 1


# --- fault grammar / config ---------------------------------------------

def test_device_loss_grammar_and_phase():
    plan = parse_fault_plan("device_loss@13:2")
    assert ("device_loss", 13) in plan._by_step
    with pytest.raises(ValueError, match="positive int"):
        parse_fault_plan("device_loss@13:0")
    with pytest.raises(ValueError, match="positive int"):
        parse_fault_plan("device_loss@13:1.5")
    # Train-phase only: a serve run must reject it at config time.
    cfg = TrainConfig(mode="serve", model="gpt_lm",
                      checkpoint_dir="/tmp/x")
    cfg.resilience.fault_plan = "device_loss@5"
    with pytest.raises(ValueError, match="train-phase only"):
        cfg.validate()
    # And it needs a checkpoint dir (mask file + resume target).
    cfg2 = TrainConfig()
    cfg2.resilience.fault_plan = "device_loss@5"
    with pytest.raises(ValueError, match="device-mask"):
        cfg2.validate()


def test_device_loss_first_leg_only(tmp_path, monkeypatch):
    """A resumed leg (bind(start_step > 0)) never re-fires the drill —
    the restart IS the recovery under test."""
    from tensorflow_distributed_tpu.resilience import faults
    killed = []
    monkeypatch.setattr(faults.os, "kill",
                        lambda *a: killed.append(a))
    monkeypatch.setenv("TFD_DEVICE_MASK_FILE",
                       str(tmp_path / "DEVICE_MASK"))
    plan = parse_fault_plan("device_loss@5:2")
    plan.bind(4)
    plan.maybe_device_loss(5, str(tmp_path))
    assert not killed and not (tmp_path / "DEVICE_MASK").exists()
    plan2 = parse_fault_plan("device_loss@5:2")
    plan2.bind(0)
    plan2.maybe_device_loss(5, str(tmp_path))
    assert killed
    assert json.loads(
        (tmp_path / "DEVICE_MASK").read_text())["lost"] == 2


# --- mesh manifest + resharded restore ----------------------------------

def test_mesh_manifest_written_and_listed(tmp_path, mesh8):
    state = _state(mesh8, ema=False)
    ckpt.save(str(tmp_path), state)
    man = ckpt.read_mesh_manifest(str(tmp_path), 0)
    assert man["mesh"]["data"] == 8
    assert man["process_count"] == 1
    assert any("kernel" in k for k in man["specs"])
    assert ckpt.steps_with_mesh(str(tmp_path)) == [(0, man["mesh"])]
    # Operator-facing errors carry the written topology.
    with pytest.raises(FileNotFoundError,
                       match=r"available steps: \[0\] \(written on "
                             r"mesh data=8\)"):
        ckpt.restore(str(tmp_path), _state(mesh8, ema=False), step=7)


@pytest.mark.parametrize("src,dst,fsdp", [
    (1, 2, False), (2, 4, False), (4, 8, False),
    (8, 2, True), (2, 8, True),
])
def test_reshard_roundtrip_matrix(tmp_path, devices8, src, dst, fsdp):
    """Save on mesh A, restore_resharded onto mesh B: gathered params,
    optimizer state AND the EMA come back bit-identical, and the
    restored layout satisfies the template's sharding contract
    (restore_resharded asserts it)."""
    mesh_a = make_mesh(MeshConfig(data=src), devices8[:src])
    mesh_b = make_mesh(MeshConfig(data=dst), devices8[:dst])
    s_a = _state(mesh_a, fsdp=fsdp)
    step = make_train_step(mesh_a, donate=False, ema_decay=0.99)
    s_a, _ = step(s_a, shard_batch(mesh_a, _batch()))
    ckpt.save(str(tmp_path), s_a)

    s_b, info = ckpt.restore_resharded(str(tmp_path),
                                       _state(mesh_b, fsdp=fsdp))
    assert info["resharded"] and info["step"] == 1
    assert info["from_mesh"]["data"] == src
    assert info["to_mesh"]["data"] == dst
    assert info["seconds"] >= 0
    _assert_trees_equal(s_a.params, s_b.params)
    _assert_trees_equal(s_a.opt_state, s_b.opt_state)
    _assert_trees_equal(s_a.ema, s_b.ema)


def test_reshard_roundtrip_tensor_layout(tmp_path, devices8):
    """A tensor-sharded leaf (P(None, 'model')) written on a
    data=2,model=2 mesh round-trips bitwise onto a pure-data mesh —
    the layouts come from the TEMPLATE, the values from the bytes."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh_tp = make_mesh(MeshConfig(data=2, model=2), devices8[:4])
    mesh_dp = make_mesh(MeshConfig(data=2), devices8[:2])
    w = np.arange(64, dtype=np.float32).reshape(8, 8)

    def tp_state(mesh, spec):
        return TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32),
                                NamedSharding(mesh, P())),
            params={"w": jax.device_put(w, NamedSharding(mesh, spec))},
            opt_state=(), apply_fn=None, tx=None)

    ckpt.save(str(tmp_path), tp_state(mesh_tp, P(None, "model")))
    man = ckpt.read_mesh_manifest(str(tmp_path), 0)
    assert man["mesh"] == {"data": 2, "pipe": 1, "seq": 1, "model": 2,
                           "expert": 1}
    assert "model" in man["specs"]["params/w"]
    restored, info = ckpt.restore_resharded(
        str(tmp_path), tp_state(mesh_dp, P("data", None)))
    assert info["resharded"]
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.params["w"])), w)


def test_restore_resharded_same_mesh_is_plain(tmp_path, mesh8):
    state = _state(mesh8, ema=False)
    ckpt.save(str(tmp_path), state)
    restored, info = ckpt.restore_resharded(str(tmp_path),
                                            _state(mesh8, ema=False))
    assert not info["resharded"]
    _assert_trees_equal(state.params, restored.params)


def test_mesh_mismatch_error_names_both_meshes(tmp_path, mesh8, mesh1,
                                               monkeypatch):
    """An opaque runtime failure during a CROSS-mesh placement is
    re-raised as MeshMismatchError naming written vs requested mesh
    and pointing at restore_resharded; the SAME-mesh failure stays
    itself (not a mesh problem)."""
    state = _state(mesh8, ema=False)
    ckpt.save(str(tmp_path), state)
    tmpl1, tmpl8 = _state(mesh1, ema=False), _state(mesh8, ema=False)

    def boom(*a, **k):
        raise RuntimeError("XLA placement exploded")

    monkeypatch.setattr(jax, "device_put", boom)
    with pytest.raises(ckpt.MeshMismatchError) as ei:
        ckpt.restore(str(tmp_path), tmpl1)
    msg = str(ei.value)
    assert "data=8" in msg and "single-device" in msg
    assert "restore_resharded" in msg
    with pytest.raises(RuntimeError, match="XLA placement exploded"):
        ckpt.restore(str(tmp_path), tmpl8)


def test_quarantine_event_carries_written_mesh(tmp_path, mesh8,
                                               monkeypatch):
    events = []
    monkeypatch.setattr(
        ckpt, "emit_event",
        lambda event, **f: events.append({"event": event, **f}))
    state = _state(mesh8, ema=False)
    step = make_train_step(mesh8, donate=False)
    for _ in range(2):
        state, _ = step(state, shard_batch(mesh8, _batch()))
        ckpt.save(str(tmp_path), state)
    blob = os.path.join(str(tmp_path), "step_00000002",
                        "state.msgpack")
    with open(blob, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    restored = ckpt.restore(str(tmp_path), _state(mesh8, ema=False))
    assert int(jax.device_get(restored.step)) == 1
    quar = [e for e in events if e.get("kind") == "quarantine"]
    assert quar and quar[0]["mesh"] == "data=8"


# --- report folding (jax-free inputs) -----------------------------------

def test_report_folds_mesh_changes():
    from tensorflow_distributed_tpu.observe.report import (
        render, summarize)
    mesh8 = {"data": 8, "model": 1, "seq": 1, "pipe": 1, "expert": 1}
    mesh4 = {**mesh8, "data": 4}
    recs = [
        {"event": "recovery", "kind": "mesh_change", "leg": 1,
         "from_mesh": mesh8, "to_mesh": mesh4, "alive": 4},
        {"event": "recovery", "kind": "reshard_restore", "step": 4,
         "from_mesh": mesh8, "to_mesh": mesh4, "resharded": True,
         "seconds": 0.21},
        {"event": "recovery", "kind": "restart", "leg": 1, "rc": -9},
    ]
    out = summarize(recs)
    assert out["mesh_changes"] == 1
    assert out["mesh_change_path"] == "data=8 -> data=4"
    assert out["reshard_seconds_total"] == 0.21
    assert out["recovery_counts"]["mesh_change"] == 1
    text = render(out)
    assert "mesh_changes" in text and "data=8 -> data=4" in text
    assert "reshard_seconds_total" in text
    # The loop-only flavor (manual --resume onto a new mesh): the
    # reshard events alone still fold.
    out2 = summarize(recs[1:2])
    assert out2["mesh_changes"] == 1
    assert out2["reshard_seconds_total"] == 0.21


# --- supervised e2e (slow) ----------------------------------------------

def _child_env():
    return {
        "PATH": os.environ["PATH"],
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
        "PYTHONUNBUFFERED": "1",
    }


@pytest.mark.slow
def test_supervisor_elastic_device_loss_shrinks_and_continues(tmp_path):
    """The acceptance scenario: device_loss@5:4 on a mesh-8 run under
    --elastic ends in a CONVERGING run on mesh 4 (exit 0), resumed at
    the last pre-kill checkpoint with the resize recorded — not a
    crash loop."""
    ckpt_dir = str(tmp_path / "ckpt")
    jsonl = str(tmp_path / "m.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--elastic", "--max-restarts", "3", "--backoff-base-s", "0.2",
         "--", "--dataset", "synthetic", "--mesh.data", "8",
         "--batch-size", "64", "--train-steps", "8",
         "--eval-every", "0", "--log-every", "0",
         "--eval-batch-size", "64", "--compute-dtype", "float32",
         "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "2",
         "--observe.metrics-jsonl", jsonl,
         "--resilience.fault-plan", "device_loss@5:4"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=500)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert '"kind": "mesh_change"' in proc.stdout
    assert "--mesh.data 4" in proc.stdout  # the rewritten leg

    with open(jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    rec = [r for r in recs if r.get("event") == "recovery"]
    assert any(r.get("fault") == "device_loss" and r.get("lost") == 4
               for r in rec)
    reshard = [r for r in rec if r.get("kind") == "reshard_restore"]
    assert reshard and reshard[0]["from_mesh"]["data"] == 8 \
        and reshard[0]["to_mesh"]["data"] == 4
    resumed = [r for r in recs if r.get("event") == "resumed"]
    # Kill at dispatch of 5, cadence save at 4: zero lost steps.
    assert resumed and resumed[-1]["step"] == 4
    assert resumed[-1]["per_device_batch"] == 16
    assert [r.get("steps") for r in recs
            if r.get("event") == "summary"] == [8]
    # The run's goodput ledger charged the resize window.
    summary = [r for r in recs if r.get("event") == "summary"][-1]
    assert summary.get("reshard_seconds", 0) > 0
