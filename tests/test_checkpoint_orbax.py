"""Orbax checkpoint backend: sharded saves, auto-detected restores.

The native backend allgathers cross-process-sharded leaves to the
chief's host before writing (documented in train/checkpoint.py as fine
for this framework's sizes, with orbax named as the scale path). This
pins that path: every process writes its own shards (no allgather),
restore reads shards directly into the template's shardings, and
--resume auto-detects which backend wrote the checkpoint.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.state import create_train_state


def _state(mesh, fsdp=False, seed=0):
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    return create_train_state(model, optax.adam(1e-3),
                              jnp.zeros((2, 28, 28, 1), jnp.float32),
                              mesh, seed, fsdp=fsdp)


@pytest.mark.parametrize("fsdp", [False, True])
def test_orbax_roundtrip_matches_native(tmp_path, mesh8, fsdp):
    """Same state through both backends: identical restored values,
    including FSDP-sharded params (orbax reads shards straight into
    the sharded template — the allgather-free path)."""
    state = _state(mesh8, fsdp=fsdp)
    state = state.replace(step=jnp.asarray(7, jnp.int32))
    ckpt.save(str(tmp_path / "native"), state)
    ckpt.save(str(tmp_path / "orbax"), state, backend="orbax")
    assert ckpt.latest_step(str(tmp_path / "orbax")) == 7

    tmpl = _state(mesh8, fsdp=fsdp, seed=1)
    r_native = ckpt.restore(str(tmp_path / "native"), tmpl)
    r_orbax = ckpt.restore(str(tmp_path / "orbax"), tmpl)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        jax.device_get(ckpt._fetch_host(r_native.params)),
        jax.device_get(ckpt._fetch_host(r_orbax.params)))
    if fsdp:
        # The restored leaves keep the template's FSDP shardings.
        leaf = jax.tree_util.tree_leaves(r_orbax.params)[0]
        assert leaf.sharding == jax.tree_util.tree_leaves(
            tmpl.params)[0].sharding


def test_orbax_end_to_end_resume_and_prune(tmp_path):
    """The full loop on the orbax backend: cadence saves, keep-N
    pruning, resume (auto-detected format), exact parity with an
    uninterrupted run."""
    from tensorflow_distributed_tpu.train.loop import train

    base = dict(dataset="synthetic", batch_size=64, eval_every=0,
                log_every=0, eval_batch_size=128,
                compute_dtype="float32", dropout_rate=0.0,
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                checkpoint_backend="orbax", keep_checkpoints=2,
                mesh=MeshConfig(data=8), seed=0)
    train(TrainConfig(**base, train_steps=6))
    steps = ckpt.available_steps(str(tmp_path / "ck"))
    assert steps == [4, 6]  # keep-N pruned 2

    r = train(TrainConfig(**base, train_steps=8, resume=True))
    assert int(jax.device_get(r.state.step)) == 8

    single = train(TrainConfig(
        dataset="synthetic", batch_size=64, train_steps=8, eval_every=0,
        log_every=0, eval_batch_size=128, compute_dtype="float32",
        dropout_rate=0.0, mesh=MeshConfig(data=8), seed=0))
    for k, v in single.final_metrics.items():
        np.testing.assert_allclose(r.final_metrics[k], v, rtol=1e-4,
                                   atol=1e-5)


def test_orbax_validation_walls():
    with pytest.raises(ValueError, match="checkpoint_backend"):
        TrainConfig(checkpoint_backend="s3", batch_size=32).validate()
    # The r4 wall is gone: local SGD composes with the orbax backend
    # (restore_averaged auto-detects the OCDBT layout — VERDICT r4
    # item 7).
    TrainConfig(checkpoint_backend="orbax", param_sync_every=2,
                batch_size=32, mesh=MeshConfig(data=8)).validate()


def test_orbax_local_sgd_restore_averaged(tmp_path, mesh8):
    """Local SGD's replica-stacked state round-trips through the orbax
    backend AND restore_averaged reads the OCDBT layout into a PLAIN
    template (the two r4 marquee features no longer exclude each
    other). The averaged restore must equal averaged_view of the live
    state."""
    from tensorflow_distributed_tpu.train.local_sgd import (
        averaged_view, stack_state)

    state = _state(mesh8)
    stacked = stack_state(state, mesh8)
    # Make replicas visibly distinct so the mean is a real check.
    stacked = stacked.replace(params=jax.tree_util.tree_map(
        lambda p: p + jnp.arange(p.shape[0], dtype=p.dtype).reshape(
            (-1,) + (1,) * (p.ndim - 1)), stacked.params))
    ckpt.save(str(tmp_path), stacked, backend="orbax")

    tmpl = _state(mesh8, seed=1)
    restored = ckpt.restore_averaged(str(tmp_path), tmpl)
    want = averaged_view(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6),
        jax.device_get(restored.params), jax.device_get(want.params))
    # Template shardings won: the restored state lives plain.
    assert jax.tree_util.tree_leaves(restored.params)[0].shape == \
        jax.tree_util.tree_leaves(tmpl.params)[0].shape


def test_unmarked_orbax_dir_never_shadows_previous(tmp_path, mesh8):
    """Crash-mid-save atomicity: an orbax step dir WITHOUT the commit
    marker (what a crash leaves behind — the marker lands only after
    orbax confirms the write) is invisible to available_steps, so
    --resume falls back to the intact previous checkpoint instead of
    failing on debris; pruning is deferred to the same marker phase,
    so a failed save can never have deleted the last good one."""
    import os

    state = _state(mesh8)
    ckpt.save(str(tmp_path), state.replace(step=jnp.asarray(3)),
              backend="orbax")
    assert ckpt.latest_step(str(tmp_path)) == 3
    # Simulate the crash: a step-5 dir exists but the commit marker
    # does not (strip it after a real save to get realistic debris).
    ckpt.save(str(tmp_path), state.replace(step=jnp.asarray(5)),
              backend="orbax")
    os.remove(str(tmp_path / "step_00000005" / "ORBAX_COMMITTED"))
    assert ckpt.latest_step(str(tmp_path)) == 3
    restored = ckpt.restore(str(tmp_path), _state(mesh8, seed=1))
    assert int(jax.device_get(restored.step)) == 3


def test_orbax_ema_toggle_across_restore(tmp_path, mesh8):
    """The EMA on/off flip across an orbax save/restore mirrors the
    native contract: newly-enabled EMA seeds from the restored params;
    newly-disabled EMA drops the saved average."""
    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)

    def mk(ema, seed=0):
        return create_train_state(model, optax.adam(1e-3),
                                  jnp.zeros((2, 28, 28, 1), jnp.float32),
                                  mesh8, seed, ema=ema)

    ckpt.save(str(tmp_path / "no_ema"), mk(False), backend="orbax")
    on = ckpt.restore(str(tmp_path / "no_ema"), mk(True, seed=1))
    assert on.ema is not None
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(on.ema), jax.device_get(on.params))

    ckpt.save(str(tmp_path / "with_ema"), mk(True), backend="orbax")
    off = ckpt.restore(str(tmp_path / "with_ema"), mk(False, seed=1))
    assert off.ema is None
