"""The observe/ subsystem: registry + sinks, step-time breakdown on a
fake clock, MFU accounting for known configs, Chrome-trace validity,
goodput ledger, the report tool, and the CPU-only end-to-end run the
acceptance criteria name. All tier-1 fast."""

import io
import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.observe.goodput import GoodputCounter
from tensorflow_distributed_tpu.observe.mfu import (
    ThroughputAccountant, attn_flops_per_token_fwd, flops_per_item,
    flops_per_token, matmul_params)
from tensorflow_distributed_tpu.observe.registry import (
    CsvSink, JsonlSink, MetricsRegistry, StdoutSink, config_hash)
from tensorflow_distributed_tpu.observe.steptime import (
    StepTimeBreakdown, percentile)
from tensorflow_distributed_tpu.observe.trace import ChromeTracer, load_trace


class FakeClock:
    """Deterministic clock: advance() by hand, call like time.*()."""

    def __init__(self, start=100.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


# --- registry + sinks ----------------------------------------------------

def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)], tags={"process_index": 0})
    emitted = [
        reg.emit("start", model="gpt_lm", params=25408),
        reg.emit("step", step=10, loss=3.25, mfu=0.41),
        reg.emit("summary", goodput=0.97),
    ]
    reg.close()
    read = [json.loads(line) for line in open(path)]
    assert read == emitted
    assert all(r["process_index"] == 0 for r in read)
    assert read[1]["loss"] == 3.25


def test_registry_chief_only_and_ring_buffer(tmp_path):
    path = str(tmp_path / "quiet.jsonl")
    reg = MetricsRegistry([JsonlSink(path)], enabled=False,
                          max_records=5)
    for i in range(12):
        reg.emit("step", step=i)
    reg.close()
    # Non-chief: no sink output, but the bounded buffer still fills.
    assert not (tmp_path / "quiet.jsonl").exists()
    assert len(reg.records) == 5
    assert reg.records[0]["step"] == 7  # oldest rows dropped first


def test_csv_sink_union_header(tmp_path):
    path = str(tmp_path / "m.csv")
    sink = CsvSink(path)
    reg = MetricsRegistry([sink])
    reg.emit("start", model="x")            # filtered out (not a step)
    reg.emit("step", step=1, loss=2.0)
    reg.emit("step", step=2, loss=1.5, mfu=0.4)  # late column
    reg.close()
    rows = list(open(path))
    header = rows[0].strip().split(",")
    assert "mfu" in header and "loss" in header
    assert len(rows) == 3  # header + 2 step rows, start dropped


def test_stdout_sink_step_format():
    buf = io.StringIO()
    reg = MetricsRegistry([StdoutSink(buf)])
    reg.emit("step", step=7, loss=1.25)
    reg.emit("done", steps=7)
    out = buf.getvalue().splitlines()
    assert out[0].startswith("[step      7] t=")
    assert "loss=1.25" in out[0]
    assert json.loads(out[1])["event"] == "done"


def test_config_hash_stable_and_order_free():
    assert config_hash({"a": 1, "b": 2}) == config_hash({"b": 2, "a": 1})
    assert config_hash({"a": 1}) != config_hash({"a": 2})


def test_jsonl_sink_replaces_previous_run(tmp_path):
    """Reruns replace (the repo-wide artifact rule): a second run's
    first emit truncates the previous run's file so observe.report
    never aggregates across runs."""
    path = str(tmp_path / "m.jsonl")
    r1 = MetricsRegistry([JsonlSink(path)])
    r1.emit("step", step=1)
    r1.emit("step", step=2)
    r1.close()
    r2 = MetricsRegistry([JsonlSink(path)])
    r2.emit("step", step=99)
    r2.close()
    rows = [json.loads(line) for line in open(path)]
    assert [r["step"] for r in rows] == [99]


# --- step-time breakdown -------------------------------------------------

def test_steptime_breakdown_fake_clock():
    clk = FakeClock()
    st = StepTimeBreakdown(window=10, clock=clk)
    for _ in range(4):
        st.data_start()
        clk.advance(0.010)   # data wait
        st.data_end()
        clk.advance(0.002)   # dispatch
        st.dispatch_end()
        clk.advance(0.030)   # device
        st.device_end()
        clk.advance(0.001)   # cadence host work
        rec = st.step_end()
    assert rec["data"] == pytest.approx(0.010)
    assert rec["dispatch"] == pytest.approx(0.002)
    assert rec["device"] == pytest.approx(0.030)
    assert rec["host"] == pytest.approx(0.001)
    assert rec["total"] == pytest.approx(0.043)
    s = st.summary()
    assert s["data_ms"] == pytest.approx(10.0)
    assert s["step_ms_p50"] == pytest.approx(43.0)
    assert s["step_ms_p95"] == pytest.approx(43.0)
    assert st.steps == 4


def test_steptime_missing_phases_count_zero():
    clk = FakeClock()
    st = StepTimeBreakdown(clock=clk)
    st.data_start()
    clk.advance(0.005)
    st.data_end()
    clk.advance(0.001)
    rec = st.step_end()  # no dispatch/device marks
    assert rec["dispatch"] == 0.0 and rec["device"] == 0.0
    assert rec["total"] == pytest.approx(0.006)


def test_percentile_nearest_rank():
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert percentile(vals, 50) == 3.0
    assert percentile(vals, 95) == 5.0
    assert percentile(vals, 0) == 1.0
    with pytest.raises(ValueError):
        percentile([], 50)


# --- MFU accounting ------------------------------------------------------

def test_matmul_params_skips_embeddings_and_scales_moe():
    params = {
        "layer_0": {"mlp": {"w": np.zeros((32, 64))}},           # 2048
        "tok_emb": {"embedding": np.zeros((64, 32))},            # skipped
        "moe_mlp": {"wi": np.zeros((4, 32, 64))},                # 8192
        "bias": {"b": np.zeros((64,))},                          # ndim 1
    }
    assert matmul_params(params) == 2048 + 8192  # no MoE hints: full
    # top_k=2 of 4 experts -> half the expert weights per token.
    assert matmul_params(params, moe_experts=4, moe_top_k=2) == (
        2048 + 8192 / 2)


def test_flops_per_token_known_tiny_config():
    from tensorflow_distributed_tpu.models.transformer import tiny_config

    cfg = tiny_config(causal=True, max_len=32)  # d_model=32, n_layers=2
    params = {"w": np.zeros((32, 64))}  # N = 2048
    # attention fwd/token: 4 * d_model * n_layers * (L/2) = 4*32*2*16
    assert attn_flops_per_token_fwd(cfg) == 4096.0
    assert flops_per_token(params, cfg) == 3.0 * (2.0 * 2048 + 4096)
    # seq_len override shrinks the attended length.
    assert attn_flops_per_token_fwd(cfg, seq_len=16) == 2048.0


def test_flops_per_item_families():
    flops, unit = flops_per_item("mnist_cnn")
    assert unit == "image"
    # conv1 + conv2 + dense1 + dense2 MACs, x2 per MAC, x3 train.
    assert flops == 3.0 * 2.0 * (5*5*1*32*28*28 + 5*5*32*64*14*14
                                 + 3136*1024 + 1024*10)
    none_flops, unit = flops_per_item("resnet20")
    assert none_flops is None and unit == "image"  # honest: no estimate


def test_throughput_accountant_rates():
    acc = ThroughputAccountant(flops_per_item=1e9, unit="token",
                               peak_flops_total=1e12)
    r = acc.rates(items=1000, seconds=2.0)
    assert r["tokens_per_sec"] == 500.0
    assert r["model_tflops"] == pytest.approx(0.5)
    assert r["mfu"] == pytest.approx(0.5)
    assert acc.rates(0, 1.0) == {}  # empty window -> no rates
    # No peak -> throughput + tflops only, no invented MFU.
    r2 = ThroughputAccountant(flops_per_item=1e9, unit="token").rates(
        1000, 2.0)
    assert "mfu" not in r2 and r2["model_tflops"] == pytest.approx(0.5)


def test_note_step_fn_enables_hw_mfu():
    """A step function advertising observe_hw_recompute (the 1F1B
    recompute schedule, train.pipeline_step) switches the accountant to
    also report hw-MFU; ordinary steps don't."""
    from tensorflow_distributed_tpu.models.transformer import tiny_config
    from tensorflow_distributed_tpu.observe.hub import Observatory

    cfg = tiny_config(causal=True, max_len=32)
    params = {"blocks": {"w": np.zeros((32, 64))},
              "tok_emb": {"embedding": np.zeros((64, 32))}}
    obs = Observatory(accountant=ThroughputAccountant(
        flops_per_item=1.0, unit="token", peak_flops_total=1e12))
    obs.seq_len = 32

    def plain_step(state, batch):
        return state, {}

    obs.note_step_fn(plain_step, params=params, model_cfg=cfg)
    assert obs.accountant.hw_flops_per_item is None
    plain_step.observe_hw_recompute = True
    obs.note_step_fn(plain_step, params=params, model_cfg=cfg)
    # model 3x-fwd + one extra block forward (2N_blocks + attn).
    assert obs.accountant.hw_flops_per_item == (
        3.0 * (2.0 * 2048 + 4096) + 2.0 * 2048 + 4096)
    obs.close()


# --- Chrome trace --------------------------------------------------------

def test_chrome_trace_valid_and_complete(tmp_path):
    path = str(tmp_path / "trace.json")
    clk = FakeClock()
    tr = ChromeTracer(path, pid=3, process_name="test", clock=clk)
    with tr.span("data"):
        clk.advance(0.002)
    with tr.span("dispatch", step=4):
        clk.advance(0.001)
    tr.instant("preempted", step=9)
    tr.counter("mfu", mfu=0.41)
    tr.close()
    events = load_trace(path)  # json.loads validity via the loader
    assert all("ph" in e and "name" in e for e in events)
    spans = [e for e in events if e["ph"] == "X"]
    assert {s["name"] for s in spans} == {"data", "dispatch"}
    assert all("ts" in s and s["dur"] > 0 for s in spans)
    assert spans[0]["dur"] == pytest.approx(2000.0)  # microseconds
    assert [e for e in events if e["ph"] == "i"][0]["args"]["step"] == 9
    assert [e for e in events if e["ph"] == "C"][0]["args"]["mfu"] == 0.41


def test_chrome_trace_caps_events(tmp_path):
    """Host memory stays bounded on long traced runs: past max_events
    new events drop (counted) and the written file carries a marker."""
    path = str(tmp_path / "trace.json")
    tr = ChromeTracer(path, max_events=5, clock=FakeClock())
    for i in range(12):
        tr.instant(f"e{i}")
    tr.close()
    assert tr.dropped == 7
    events = load_trace(path)
    assert len(events) == 6  # 5 kept + the dropped-events marker
    assert "dropped" in events[-1]["name"]


def test_chrome_trace_disabled_writes_nothing(tmp_path):
    tr = ChromeTracer("", enabled=True)
    with tr.span("x"):
        pass
    tr.close()
    assert not list(tmp_path.iterdir())


# --- goodput -------------------------------------------------------------

def test_goodput_outermost_category_wins():
    clk = FakeClock()
    c = GoodputCounter(clock=clk)
    with c.account("drain"):
        with c.account("checkpoint"):  # nested: suppressed
            clk.advance(3.0)
        clk.advance(1.0)
    with c.account("eval"):
        clk.advance(2.0)
    clk.advance(4.0)  # productive time
    s = c.summary()
    assert c.overhead == {"drain": pytest.approx(4.0),
                          "eval": pytest.approx(2.0)}
    assert "checkpoint_seconds" not in s
    assert s["total_seconds"] == pytest.approx(10.0)
    assert s["productive_seconds"] == pytest.approx(4.0)
    assert s["goodput"] == pytest.approx(0.4)


def test_goodput_charged_includes_in_flight_block():
    """charged() counts the elapsed part of an open outermost block —
    what lets preemption drain accounting bracket a window exactly
    even when the SIGTERM lands mid-eval."""
    clk = FakeClock()
    c = GoodputCounter(clock=clk)
    with c.account("eval"):
        clk.advance(30.0)
        snap = c.charged()        # mid-block: 30s in flight
        assert snap == pytest.approx(30.0)
        clk.advance(30.0)
    assert c.charged() == pytest.approx(60.0)
    # Window [snap, now] overhead = difference of snapshots.
    assert c.charged() - snap == pytest.approx(30.0)


def test_goodput_module_hooks_are_noop_without_active():
    from tensorflow_distributed_tpu.observe import goodput

    assert goodput.get_active() is None
    with goodput.account("checkpoint"):
        pass  # must not raise
    goodput.add("restore", 1.0)  # must not raise


def test_checkpoint_save_charges_goodput(tmp_path):
    """train.checkpoint's save/wait/restore are accounted on the active
    counter (the tentpole's preemption/checkpoint hook)."""
    import optax

    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.observe import goodput
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh = make_mesh(MeshConfig())  # data = all local devices
    state = create_train_state(
        MnistCNN(), optax.adam(1e-3),
        np.zeros((2, 28, 28, 1), np.float32), mesh)
    counter = GoodputCounter()
    goodput.set_active(counter)
    try:
        ckpt.save(str(tmp_path), state)
        ckpt.restore(str(tmp_path), state)
    finally:
        goodput.set_active(None)
    assert counter.overhead["checkpoint"] > 0
    assert counter.overhead["restore"] > 0


# --- satellites ----------------------------------------------------------

def test_timer_exit_without_enter_is_safe():
    from tensorflow_distributed_tpu.utils.logging import Timer

    t = Timer()
    t.__exit__(None, None, None)  # regression: used to TypeError
    assert t.elapsed == 0.0


def test_metric_logger_ring_buffer_cap():
    from tensorflow_distributed_tpu.utils.logging import MetricLogger

    logger = MetricLogger(enabled=False, max_records=5)
    for i in range(12):
        logger.log(i, loss=float(i))
    assert len(logger.records) == 5
    assert logger.records[0].step == 7


def test_metric_logger_shim_emits_through_registry():
    buf = io.StringIO()
    from tensorflow_distributed_tpu.utils.logging import MetricLogger

    logger = MetricLogger(enabled=True, stream=buf)
    logger.log(3, loss=2.5)
    logger.log_json({"event": "done", "steps": 3})
    lines = buf.getvalue().splitlines()
    assert lines[0].startswith("[step      3]") and "loss=2.5" in lines[0]
    assert '"event": "done"' in lines[1]


# --- report tool ---------------------------------------------------------

def test_report_summarizes_jsonl(tmp_path, capsys):
    from tensorflow_distributed_tpu.observe import report

    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry([JsonlSink(path)])
    reg.emit("start", model="gpt_lm")
    reg.emit("step", step=10, loss=3.0, step_ms_p50=21.0,
             step_ms_p95=30.0, tokens_per_sec=9000.0, mfu=0.41)
    reg.emit("step", step=20, loss=2.5, step_ms_p50=20.0,
             step_ms_p95=29.0, tokens_per_sec=11000.0, mfu=0.43)
    reg.emit("summary", goodput=0.93, checkpoint_seconds=1.5)
    reg.close()

    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "step_ms_p50" in out and "goodput" in out

    s = report.summarize(report.load_records(path))
    assert s["step_records"] == 2 and s["last_step"] == 20
    assert s["step_ms_p50"] == 20.0      # freshest rolling window
    assert s["mean_mfu"] == pytest.approx(0.42)
    assert s["mean_tokens_per_sec"] == pytest.approx(10000.0)
    assert s["goodput"] == 0.93
    assert s["first_loss"] == 3.0 and s["last_loss"] == 2.5


def test_report_bad_lines_skipped_missing_file_exits_nonzero(
        tmp_path, capsys):
    """Malformed lines are counted-and-skipped with a stderr note —
    crash-time metrics are exactly when the report matters (the old
    behavior raised and reported nothing). A MISSING file is still a
    hard error."""
    from tensorflow_distributed_tpu.observe import report

    bad = tmp_path / "bad.jsonl"
    bad.write_text("{not json\n")
    assert report.main([str(bad)]) == 0
    assert "skipped 1 malformed line(s)" in capsys.readouterr().err
    assert report.main([str(tmp_path / "missing.jsonl")]) == 1


# --- config surface ------------------------------------------------------

def test_observe_config_validation():
    from tensorflow_distributed_tpu.config import ObserveConfig, TrainConfig

    with pytest.raises(ValueError, match="observe.window"):
        TrainConfig(observe=ObserveConfig(window=0)).validate()
    with pytest.raises(ValueError, match="max_records"):
        TrainConfig(observe=ObserveConfig(max_records=0)).validate()
    with pytest.raises(ValueError, match="peak_tflops"):
        TrainConfig(observe=ObserveConfig(peak_tflops=-1)).validate()


def test_observe_cli_flags():
    from tensorflow_distributed_tpu.config import parse_args

    cfg = parse_args(["--observe.metrics-jsonl", "/tmp/m.jsonl",
                      "--observe.trace", "/tmp/t.json",
                      "--observe.peak-tflops", "275"])
    assert cfg.observe.metrics_jsonl == "/tmp/m.jsonl"
    assert cfg.observe.trace == "/tmp/t.json"
    assert cfg.observe.peak_tflops == 275.0


# --- multi-stream report + device-time section (ISSUE 12) ----------------

def _write_stream(path, host, steps):
    import json as _json

    with open(path, "w") as f:
        for i, ms in enumerate(steps, 1):
            f.write(_json.dumps({"event": "step", "t": i * 1.0,
                                 "process_index": host, "step": i,
                                 "loss": 3.0 - 0.1 * i,
                                 "step_ms_p50": ms}) + "\n")


def test_report_merges_multiple_host_streams(tmp_path, capsys):
    """Satellite: report.main accepts multiple JSONL paths; records
    merge into one summary and a per-host section appears exactly when
    more than one host tag is present."""
    from tensorflow_distributed_tpu.observe import report

    a = str(tmp_path / "h0.jsonl")
    b = str(tmp_path / "h1.jsonl")
    _write_stream(a, 0, [10.0, 11.0])
    _write_stream(b, 1, [20.0, 21.0, 22.0])
    assert report.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "Hosts" in out
    records = report.load_records(a) + report.load_records(b)
    s = report.summarize(records)
    assert s["step_records"] == 5
    assert set(s["hosts"]) == {"0", "1"}
    assert s["hosts"]["0"]["step_records"] == 2
    assert s["hosts"]["1"]["step_ms_p50"] == 22.0
    # One stream alone: no Hosts section (shape-stable plain reports).
    assert "hosts" not in report.summarize(report.load_records(a))


def test_report_device_time_section(tmp_path, capsys):
    """device_time records fold into a "Device time" section: latest
    record per program, measured beside predicted, null parses counted
    but not rendered as rows."""
    import json as _json

    from tensorflow_distributed_tpu.observe import report

    path = str(tmp_path / "m.jsonl")
    recs = [
        {"event": "device_time", "program": "train_step",
         "module": "jit_train_step", "device_ms": 90.0,
         "device_ms_per_call": 30.0, "calls": 3,
         "predicted_ms_per_call": 25.0, "collective_ms": 4.0,
         "exposed_collective_ms": 1.5, "coarse": True},
        {"event": "device_time", "program": None, "module": None,
         "device_ms": None, "reason": "no trace"},
        {"event": "step", "step": 1, "loss": 1.0,
         "comm_exposed_ms_est": 2.1},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(_json.dumps(r) + "\n")
    s = report.summarize(report.load_records(path))
    assert len(s["device_time"]) == 1
    entry = s["device_time"][0]
    assert entry["program"] == "train_step"
    assert entry["device_ms_per_call"] == 30.0
    assert s["device_time_null_records"] == 1
    assert s["comm_exposed_ms_est"] == 2.1
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "Device time" in out
    assert "measured=30.0ms/call" in out
    assert "predicted=25.0ms" in out
    assert "[coarse]" in out


def test_report_plan_drift_folds_into_plan_section(tmp_path):
    import json as _json

    from tensorflow_distributed_tpu.observe import report

    path = str(tmp_path / "m.jsonl")
    recs = [
        {"event": "plan", "family": "gpt", "mesh": {"data": 8},
         "strategy": "data", "partition": "replicated",
         "predicted_step_ms": 2.5, "candidates": 3, "feasible": 3,
         "infeasible": 0, "calibration_id": "cpu-abc123"},
        {"event": "plan_drift", "predicted_step_ms": 2.5,
         "measured_step_ms_p50": 20.0, "drift_ratio": 8.0,
         "calibration_id": "cpu-abc123"},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(_json.dumps(r) + "\n")
    s = report.summarize(report.load_records(path))
    assert s["plan"]["drift_ratio"] == 8.0
    assert s["plan"]["measured_step_ms_p50"] == 20.0
    assert s["plan"]["calibration_id"] == "cpu-abc123"
    assert "drift" in report.render(s)
