"""Autopilot suite: the online controller on fake clocks and engines.

Fast tier (jax-free, per the repo's tier rules — observe/autopilot.py
is pure stdlib and the scheduler runs against host-only fakes): ctor +
config validation matrices, confirm-count hysteresis (a noisy-but-
healthy stream never acts), per-knob cooldown rate limiting, the four
loops' trigger/actuate/back-off paths, pins, the streaming metrics
tail, run-end advisory recommendations, and the scheduler integration
— tune commands through the control path, token identity across
actuations, the rolling accept_rate_window, tune_actions in snapshot
and summary. The real-engine live-recompile path (set_spec_k mid-run)
is pinned by benchmarks/tunebench.py and the committed TUNEBENCH.json.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.observe.autopilot import (
    ACCEPT_HI, ACCEPT_LO, KNOBS, POOL_HI, POOL_LO, Autopilot)
from tensorflow_distributed_tpu.serve.scheduler import (
    Request, Scheduler)


def _ap(**kw):
    recs = []
    ap = Autopilot(emit=lambda event, **f: recs.append(
        {"event": event, **f}), **kw)
    return ap, recs


def _alert(burn=3.0):
    return {"slo": {"ttft_p95": {"alerting": True, "burn_fast": burn}}}


def _calm():
    return {"slo": {"ttft_p95": {"alerting": False, "burn_fast": 0.0}}}


# --- ctor + config validation -------------------------------------------

@pytest.mark.parametrize("kw,match", [
    (dict(every=0), "every"),
    (dict(confirm=0), "confirm"),
    (dict(cooldown=-1), "cooldown"),
    (dict(drift_tol=0.0), "drift_tol"),
    (dict(pins=("decode_priority", "nope")), "unknown autopilot pin"),
    (dict(k_ladder=()), "k_ladder"),
    (dict(k_ladder=(0, 2)), "k_ladder"),
])
def test_ctor_rejections(kw, match):
    with pytest.raises(ValueError, match=match):
        Autopilot(**kw)


def _observe_cfg(**kw):
    from tensorflow_distributed_tpu.config import TrainConfig

    cfg = TrainConfig(mode="serve", model="gpt_lm")
    for k, v in kw.items():
        setattr(cfg.observe, k, v)
    return cfg


def test_observe_autopilot_config_valid():
    _observe_cfg(autopilot=True).validate()
    _observe_cfg(autopilot=True, autopilot_every=5,
                 autopilot_confirm=1, autopilot_cooldown=0,
                 autopilot_pin="spec_k,buckets",
                 autopilot_calibration="c.json").validate()


@pytest.mark.parametrize("kw,match", [
    (dict(autopilot=True, autopilot_every=0), "autopilot_every"),
    (dict(autopilot=True, autopilot_confirm=0), "autopilot_confirm"),
    (dict(autopilot=True, autopilot_cooldown=-1),
     "autopilot_cooldown"),
    (dict(autopilot=True, autopilot_drift_tol=0.0),
     "autopilot_drift_tol"),
    (dict(autopilot=True, autopilot_pin="gold"), "unknown knob"),
    # Every autopilot_* knob is inert without the master switch.
    (dict(autopilot_every=5), "no effect without"),
    (dict(autopilot_pin="spec_k"), "no effect without"),
    (dict(autopilot_calibration="c.json"), "no effect without"),
])
def test_observe_autopilot_config_rejections(kw, match):
    with pytest.raises(ValueError, match=match):
        _observe_cfg(**kw).validate()


# --- loop 4: admission (SLO burn -> decode_priority AIMD) ---------------

def test_admission_tighten_halves_then_relaxes_additively():
    ap, recs = _ap(every=1, confirm=2, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    assert ap.evaluate(1, _alert()) == []          # confirm 1/2
    cmds = ap.evaluate(2, _alert())                # sustained -> halve
    assert cmds == [
        {"cmd": "tune", "knob": "decode_priority", "value": 4}]
    tune = [r for r in recs if r["event"] == "tune"][-1]
    assert tune["loop"] == "admission"
    assert tune["action"] == "tighten"
    assert tune["prev"] == 8 and tune["value"] == 4
    assert tune["signal"] == "slo_burn_fast"
    assert tune["observed"] == 3.0 and tune["threshold"] == 1.0
    assert tune["applied"] is True
    assert tune["evidence"]["alerting"] == ["ttft_p95"]
    # Calm: additive relax back toward the configured baseline — the
    # knob that burned is re-approached one step at a time, not
    # snapped back.
    assert ap.evaluate(3, _calm()) == []
    assert ap.evaluate(4, _calm()) == [
        {"cmd": "tune", "knob": "decode_priority", "value": 5}]
    relax = [r for r in recs if r["event"] == "tune"][-1]
    assert relax["action"] == "relax"
    # At the baseline the relax trigger itself goes quiet.
    for step in range(5, 12):
        ap.evaluate(step, _calm())
    values = [r["value"] for r in recs if r["event"] == "tune"]
    assert values == [4, 5, 6, 7, 8]
    assert ap.evaluate(20, _calm()) == []


def test_admission_floor_at_one():
    ap, _ = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=2)
    assert ap.evaluate(1, _alert())[0]["value"] == 1
    assert ap.evaluate(2, _alert()) == []          # dp == 1: floor


def test_hysteresis_noisy_but_healthy_never_acts():
    # Alternating alert/calm (and pool occupancy wobbling around the
    # deadband) never satisfies a confirm count of 2 — zero decisions.
    ap, recs = _ap(every=1, confirm=2, cooldown=0)
    ap.bind_scheduler(num_slots=4, spec_k=2, has_spec=True,
                      decode_priority=8)
    for step in range(1, 41):
        snap = _alert() if step % 2 else _calm()
        snap["pool_occupancy"] = 0.95 if step % 2 else 0.70
        snap["accept_rate_window"] = 0.9 if step % 2 else 0.5
        assert ap.evaluate(step, snap) == []
    assert ap.actions == 0
    assert not [r for r in recs if r["event"] == "tune"]


def test_cooldown_rate_limit_counts_suppressed():
    ap, _ = _ap(every=1, confirm=1, cooldown=100)
    ap.bind_scheduler(num_slots=4, decode_priority=32)
    assert ap.evaluate(10, _alert())[0]["value"] == 16
    # Still alerting inside the cooldown window: triggered but held.
    assert ap.evaluate(20, _alert()) == []
    assert ap.evaluate(60, _alert()) == []
    assert ap.suppressed == 2
    assert ap.evaluate(110, _alert())[0]["value"] == 8


# --- loop 2: capacity (pool occupancy <-> slot cap) ---------------------

def test_capacity_shrink_and_grow_deadband():
    ap, recs = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    assert ap.evaluate(1, {"pool_occupancy": POOL_HI}) == [
        {"cmd": "tune", "knob": "slot_cap", "value": 3}]
    assert ap.slot_cap == 3
    # Inside the deadband: quiet in both directions.
    assert ap.evaluate(2, {"pool_occupancy": 0.75}) == []
    # Headroom: grow back toward the allocated num_slots, capped.
    assert ap.evaluate(3, {"pool_occupancy": POOL_LO})[0]["value"] == 4
    assert ap.evaluate(4, {"pool_occupancy": 0.2}) == []
    tune = [r for r in recs if r["event"] == "tune"][0]
    assert tune["loop"] == "capacity"
    assert tune["signal"] == "pool_occupancy"


def test_capacity_needs_pool_signal_and_slots():
    ap, _ = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=1, decode_priority=8)
    assert ap.evaluate(1, {"pool_occupancy": 0.99}) == []  # 1 slot
    ap2, _ = _ap(every=1, confirm=1, cooldown=0)
    ap2.bind_scheduler(num_slots=4, decode_priority=8)
    assert ap2.evaluate(1, {}) == []          # unpaged: no signal


# --- loop 3: speculation (accept rate -> k ladder) ----------------------

def test_speculation_walks_ladder_both_ways():
    ap, _ = _ap(every=1, confirm=1, cooldown=0, k_ladder=(1, 2, 4))
    ap.bind_scheduler(num_slots=4, spec_k=2, has_spec=True,
                      decode_priority=8)
    assert ap.evaluate(1, {"accept_rate_window": ACCEPT_HI})[0] == {
        "cmd": "tune", "knob": "spec_k", "value": 4}
    assert ap.evaluate(2, {"accept_rate_window": 0.99}) == []  # top
    assert ap.evaluate(3, {"accept_rate_window": ACCEPT_LO})[
        0]["value"] == 2
    assert ap.evaluate(4, {"accept_rate_window": 0.1})[0]["value"] == 1
    assert ap.evaluate(5, {"accept_rate_window": 0.1}) == []  # bottom
    # Mid-band: quiet.
    assert ap.evaluate(6, {"accept_rate_window": 0.5}) == []


def test_speculation_off_ladder_anchor_and_fallback_rate():
    ap, _ = _ap(every=1, confirm=1, cooldown=0, k_ladder=(1, 2, 4))
    ap.bind_scheduler(num_slots=4, spec_k=3, has_spec=True,
                      decode_priority=8)
    # k=3 anchors to the rung below (2) and deepens to 4; the
    # cumulative accept_rate is the fallback when no window exists.
    assert ap.evaluate(1, {"accept_rate": 0.9})[0]["value"] == 4


def test_speculation_inert_without_spec():
    ap, _ = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, spec_k=0, has_spec=False,
                      decode_priority=8)
    assert ap.evaluate(1, {"accept_rate_window": 0.99}) == []


# --- loop 1: calibration refit ------------------------------------------

def _feed_drifting_join(ap, ratio=2.0, programs=("a", "b")):
    for i, prog in enumerate(programs):
        ap.observe_record("compile", {
            "program": prog, "flops": 1e9 * (i + 1),
            "bytes_accessed": 1e6 * (i + 1)})
        ap.observe_record("device_time", {
            "program": prog, "device_ms_per_call": ratio * (i + 1),
            "predicted_ms_per_call": 1.0 * (i + 1)})


def test_calibration_refit_writes_profile(tmp_path):
    path = str(tmp_path / "calib.json")
    replans = []
    ap, recs = _ap(every=1, confirm=1, cooldown=0, drift_tol=0.25,
                   calibration_path=path)
    ap.replan = replans.append
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    _feed_drifting_join(ap, ratio=2.0)
    assert ap.evaluate(1, {}) == []     # a refit is a file write, not
    tune = [r for r in recs if r["event"] == "tune"]  # a sched cmd
    assert len(tune) == 1
    assert tune[0]["loop"] == "calibration"
    assert tune[0]["signal"] == "drift_ratio"
    assert tune[0]["observed"] == 2.0
    assert tune[0]["applied"] is True
    assert tune[0]["evidence"]["source"] == "device_time"
    profile = json.load(open(path))
    assert profile["calibration_id"] == tune[0]["value"]
    assert replans and replans[0]["calibration_id"] == tune[
        0]["value"]
    # Evidence-gated back-off: no NEW measurements -> no second refit.
    ap.evaluate(2, {})
    ap.evaluate(3, {})
    assert len([r for r in recs if r["event"] == "tune"]) == 1
    # New drift evidence re-arms the loop.
    ap.observe_record("device_time", {
        "program": "a", "device_ms_per_call": 3.0,
        "predicted_ms_per_call": 1.0})
    ap.evaluate(4, {})
    assert len([r for r in recs if r["event"] == "tune"]) == 2


def test_calibration_prefers_plan_drift_record():
    ap, recs = _ap(every=1, confirm=1, cooldown=0, drift_tol=0.25)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    _feed_drifting_join(ap, ratio=1.1)  # join alone: inside tolerance
    ap.observe_record("plan_drift", {
        "drift_ratio": 1.8, "predicted_step_ms": 10.0,
        "measured_step_ms_p50": 18.0, "calibration_id": "old"})
    ap.evaluate(1, {})
    tune = [r for r in recs if r["event"] == "tune"]
    assert len(tune) == 1
    assert tune[0]["evidence"]["source"] == "plan_drift"
    assert tune[0]["prev"] == "old"
    assert tune[0]["applied"] is False   # no calibration_path: advisory


def test_calibration_quiet_inside_tolerance():
    ap, recs = _ap(every=1, confirm=1, cooldown=0, drift_tol=0.25)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    _feed_drifting_join(ap, ratio=1.1)
    ap.evaluate(1, {})
    assert not [r for r in recs if r["event"] == "tune"]


# --- cross-loop rules ----------------------------------------------------

def test_one_applied_action_per_tick_protection_order():
    ap, _ = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=2)
    snap = {**_alert(), "pool_occupancy": 0.99}
    # Admission (SLO protection) outranks capacity on the same tick.
    assert ap.evaluate(1, snap) == [
        {"cmd": "tune", "knob": "decode_priority", "value": 1}]
    # dp at floor: capacity gets the next tick.
    assert ap.evaluate(2, snap) == [
        {"cmd": "tune", "knob": "slot_cap", "value": 3}]


def test_pins_never_actuate():
    ap, recs = _ap(every=1, confirm=1, cooldown=0, drift_tol=0.25,
                   pins=KNOBS)
    ap.bind_scheduler(num_slots=4, spec_k=2, has_spec=True,
                      decode_priority=8)
    _feed_drifting_join(ap, ratio=2.0)
    snap = {**_alert(), "pool_occupancy": 0.99,
            "accept_rate_window": 0.99, "slot_pages_peak": 9}
    for step in range(1, 10):
        assert ap.evaluate(step, snap) == []
    ap.bind_paging(num_pages=100, recommend=lambda peak: (200, []))
    ap.bind_buckets((16, 32))
    ap.observe_prompt(100)
    ap.emit_summary(10, snap)
    assert ap.actions == 0 and ap.advisories == 0
    assert not [r for r in recs if r["event"] == "tune"]
    assert [r for r in recs if r["event"] == "tune_summary"][
        0]["quiet"] is True


def test_maybe_step_cadence_only_builds_snapshot_on_ticks():
    ap, _ = _ap(every=10, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    calls = []

    def snap_fn():
        calls.append(1)
        return _calm()

    for step in range(1, 31):
        ap.maybe_step(step, snap_fn)
    assert len(calls) == 3 and ap.evals == 3


# --- streaming tail ------------------------------------------------------

def test_tail_reads_incrementally_and_skips_torn_lines(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    ap, _ = _ap(every=1, confirm=1, cooldown=0, metrics_path=path)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    with open(path, "w") as f:
        f.write(json.dumps({"event": "compile", "program": "a",
                            "flops": 1.0, "bytes_accessed": 1.0})
                + "\n")
        f.write('{"event": "device_time", "program": "a"')  # torn
    ap.evaluate(1, {})
    assert "a" in ap._costs and not ap._measured
    with open(path, "a") as f:                   # the write completes
        f.write(', "device_ms_per_call": 2.0}\n')
    ap.evaluate(2, {})
    assert ap._measured["a"]["device_ms_per_call"] == 2.0
    # Missing file: silently quiet (the run may not export JSONL).
    ap2, _ = _ap(metrics_path=str(tmp_path / "nope.jsonl"))
    ap2.bind_scheduler(num_slots=4, decode_priority=8)
    ap2.evaluate(1, {})


# --- run-end advisories --------------------------------------------------

def test_num_pages_and_bucket_recommendations():
    ap, recs = _ap(every=1, confirm=1, cooldown=0)
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    ap.bind_paging(num_pages=100,
                   recommend=lambda peak: (160, [f"peak={peak}"]))
    ap.bind_buckets((16, 32))
    for n in [6] * 2 + [100] * 30:
        ap.observe_prompt(n)
    ap.emit_summary(50, {"slot_pages_peak": 40})
    tunes = {r["knob"]: r for r in recs if r["event"] == "tune"}
    assert tunes["num_pages"]["value"] == 160
    assert tunes["num_pages"]["applied"] is False
    assert tunes["num_pages"]["evidence"]["rationale"] == ["peak=40"]
    assert tunes["buckets"]["value"] == 128      # pow2 cover of p99
    assert tunes["buckets"]["applied"] is False
    summary = [r for r in recs if r["event"] == "tune_summary"][0]
    assert summary["actions"] == 0
    assert summary["advisories"] == 2
    assert summary["quiet"] is True              # advisories != actions


def test_num_pages_recommendation_inside_band_is_quiet():
    ap, recs = _ap()
    ap.bind_scheduler(num_slots=4, decode_priority=8)
    ap.bind_paging(num_pages=100, recommend=lambda peak: (110, []))
    ap.emit_summary(50, {"slot_pages_peak": 40})
    assert not [r for r in recs if r["event"] == "tune"]


# --- scheduler integration (host-only fake engine) ----------------------

class _FakeEngine:
    """Deterministic host engine: token = rid * 1000 + count, so the
    stream is a pure function of (rid, emitted-count) and identity
    across actuations is exact."""

    def __init__(self, num_slots=2, max_len=256):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (64, 128)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots)
                if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])
        self.prefills += 1
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = len(prompt) - 1
        return rid * 1000 + self.counts[rid]

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                self.counts[rid] += 1
                out[s] = rid * 1000 + self.counts[rid]
        return out

    def free(self, slot):
        self.active[slot] = False


class _FakeSpecEngine(_FakeEngine):
    """Speculative surface over the same stream; ``set_spec_k`` is the
    live-retune actuator the scheduler drives."""

    def __init__(self, num_slots=2, max_len=256, spec_tokens=2):
        super().__init__(num_slots, max_len)
        self.spec_tokens = spec_tokens
        self.set_k_calls = []

    def can_verify(self):
        return True

    def verify_step(self, props):
        k = self.spec_tokens
        toks = np.zeros((self.num_slots, k + 1), np.int32)
        acc = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            rid = self.slot_rid[s]
            for j in range(k + 1):               # full accept + bonus
                self.counts[rid] += 1
                toks[s, j] = rid * 1000 + self.counts[rid]
            acc[s] = k + 1
        return toks, acc

    def set_spec_k(self, k):
        self.set_k_calls.append(k)
        self.spec_tokens = k


class _FakeSpeculator:
    def __init__(self, num_slots, k):
        self.num_slots, self.k = num_slots, k

    def propose(self, histories):
        return np.zeros((self.num_slots, self.k), np.int32)

    def observe_admit(self, slot, prompt, first):
        pass

    def observe_free(self, slot):
        pass

    def sync_from(self, engine):
        pass

    def set_k(self, k):
        self.k = k


def _reqs(n=4, max_new=24):
    return [Request(rid=i, prompt=np.array([i], np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def _tokens(comps):
    return {c.rid: list(c.tokens) for c in comps}


def test_scheduler_routes_tune_and_keeps_identity():
    from tensorflow_distributed_tpu.observe.slo import (
        SLOMonitor, parse_slo)

    ref = _tokens(Scheduler(_FakeEngine(), decode_priority=16).run(
        _reqs()))
    recs = []
    ap = Autopilot(emit=lambda event, **f: recs.append(
        {"event": event, **f}), every=5, confirm=1, cooldown=0)
    # An impossible TTFT target: every completion violates, the burn
    # alert fires, and the autopilot must walk decode_priority down
    # THROUGH the live control-command path.
    mon = SLOMonitor(parse_slo("ttft_p95=0.000001ms"), fast_window=4,
                     slow_window=8)
    sched = Scheduler(_FakeEngine(), decode_priority=16,
                      slo_monitor=mon, autopilot=ap)
    comps = sched.run(_reqs())
    assert _tokens(comps) == ref                 # identity across
    assert sched.decode_priority < 16            # every actuation
    tunes = [r for r in recs if r["event"] == "tune"]
    assert tunes and all(r["knob"] == "decode_priority"
                         for r in tunes)
    assert sched.summary["tune_actions"] == len(tunes) == ap.actions
    assert sched.metrics_snapshot()["tune_actions"] == len(tunes)
    summaries = [r for r in recs if r["event"] == "tune_summary"]
    assert len(summaries) == 1
    assert summaries[0]["actions"] == len(tunes)
    assert summaries[0]["quiet"] is False


def test_scheduler_quiet_without_alerts():
    recs = []
    ap = Autopilot(emit=lambda event, **f: recs.append(
        {"event": event, **f}), every=5, confirm=1, cooldown=0)
    sched = Scheduler(_FakeEngine(), decode_priority=4, autopilot=ap)
    sched.run(_reqs())
    assert sched.summary["tune_actions"] == 0
    assert [r for r in recs if r["event"] == "tune_summary"][
        0]["quiet"] is True


def test_scheduler_spec_retune_through_engine():
    eng = _FakeSpecEngine(spec_tokens=2)
    spec = _FakeSpeculator(2, 2)
    ap = Autopilot(every=5, confirm=1, cooldown=0, k_ladder=(1, 2, 4))
    sched = Scheduler(eng, decode_priority=4, speculator=spec,
                      autopilot=ap)
    comps = sched.run(_reqs(n=2, max_new=40))
    # Full-accept stream: the window rate is 1.0 and the controller
    # deepens k through engine.set_spec_k + speculator.set_k.
    assert eng.set_k_calls == [4]
    assert eng.spec_tokens == 4 and spec.k == 4
    assert sched.summary["tune_actions"] == 1
    ref = _tokens(Scheduler(_FakeEngine(), decode_priority=4).run(
        _reqs(n=2, max_new=40)))
    assert _tokens(comps) == ref                 # identity across the
    #                                              mid-stream retune


def test_snapshot_windowed_fields_beside_cumulative():
    eng = _FakeSpecEngine(spec_tokens=3)
    sched = Scheduler(eng, decode_priority=4,
                      speculator=_FakeSpeculator(2, 3))
    sched.run(_reqs(n=2, max_new=30))
    snap = sched.metrics_snapshot()
    assert snap["accept_rate"] == 1.0            # lifetime-cumulative
    assert snap["accept_rate_window"] == 1.0     # rolling window
    assert snap["spec_tokens"] == 3
    assert snap["tokens_per_sec_window"] >= 0.0
    assert "tune_actions" not in snap            # no autopilot armed
    assert "tune_actions" not in sched.summary


def test_apply_tune_clamps_and_ignores_unknown():
    sched = Scheduler(_FakeEngine(num_slots=4), decode_priority=8)
    sched._apply_tune({"cmd": "tune", "knob": "decode_priority",
                       "value": 0})
    assert sched.decode_priority == 1
    sched._apply_tune({"cmd": "tune", "knob": "slot_cap", "value": 99})
    assert sched._slot_cap == 4                  # clamped to num_slots
    sched._apply_tune({"cmd": "tune", "knob": "slot_cap", "value": 0})
    assert sched._slot_cap == 1                  # floor: can't wedge
    sched._apply_tune({"cmd": "tune", "knob": "warp_factor",
                       "value": 9})              # unknown: ignored,
    assert sched._tunes == 3                     # not counted
    # spec_k without an engine that can retune: ignored, not counted.
    sched._apply_tune({"cmd": "tune", "knob": "spec_k", "value": 4})
    assert sched._tunes == 3


def test_report_folds_tune_records():
    from tensorflow_distributed_tpu.observe.report import summarize

    recs = [
        {"event": "tune", "step": 10, "loop": "admission",
         "knob": "decode_priority", "action": "tighten", "value": 4,
         "prev": 8, "signal": "slo_burn_fast", "observed": 2.0,
         "threshold": 1.0, "applied": True, "evidence": {}},
        {"event": "tune_summary", "step": 50, "evals": 5, "actions": 1,
         "advisories": 0, "suppressed": 1,
         "by_knob": {"decode_priority": 1}, "quiet": False},
        {"event": "serve_summary", "requests": 4, "decode_steps": 50,
         "decoded_tokens": 96, "wall_s": 1.0, "tokens_per_sec": 96.0,
         "tune_actions": 1},
        {"event": "metrics_snapshot", "t_s": 1.0, "decode_steps": 50,
         "requests_done": 4, "queue_depth": 0, "slot_occupancy": 0.5,
         "tokens_per_sec": 96.0, "accept_rate_window": 0.5,
         "tune_actions": 1},
    ]
    summary = summarize(recs)
    assert summary["serve_tune_actions"] == 1
    assert summary["tune"]["actions"] == 1
    assert summary["tune"]["quiet"] is False
    assert summary["tune"]["decisions_by_loop"] == {"admission": 1}
    assert summary["snapshot_last"]["accept_rate_window"] == 0.5
    assert summary["snapshot_last"]["tune_actions"] == 1
