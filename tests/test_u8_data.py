"""u8-backed data path: quantization, native gather parity, stream
identity with the float batcher."""

import numpy as np

from tensorflow_distributed_tpu.data.mnist import ShardedBatcher, synthetic_mnist
from tensorflow_distributed_tpu.data.u8 import U8Dataset, U8ShardedBatcher


def test_from_float_roundtrip(tiny_data):
    train, _, _ = tiny_data
    u8 = U8Dataset.from_float(train)
    assert u8.images.dtype == np.uint8
    # Quantization error bounded by half a level.
    back = u8.images.astype(np.float32) * u8.scale
    assert float(np.max(np.abs(back - train.images))) <= 0.5 / 255.0 + 1e-6


def test_gather_parity_with_numpy(tiny_data):
    train, _, _ = tiny_data
    u8 = U8Dataset.from_float(train)
    idx = np.random.default_rng(0).integers(0, len(u8), size=64)
    x, y = u8.gather(idx)
    np.testing.assert_allclose(
        x, u8.images[idx].astype(np.float32) * u8.scale, atol=1e-7)
    np.testing.assert_array_equal(y, train.labels[idx])


def test_stream_identical_to_float_batcher(tiny_data):
    """Same Batcher permutation => same sample order, u8 or float."""
    train, _, _ = tiny_data
    f = ShardedBatcher(train, global_batch=128, seed=3)
    u = U8ShardedBatcher(U8Dataset.from_float(train), global_batch=128,
                         seed=3)
    fi, ui = f.forever(), u.forever()
    for _ in range(5):
        (fx, fy), (ux, uy) = next(fi), next(ui)
        np.testing.assert_array_equal(fy, uy)
        assert float(np.max(np.abs(fx - ux))) <= 0.5 / 255.0 + 1e-6


def test_sharded_streams_partition(tiny_data):
    train, _, _ = tiny_data
    whole = U8ShardedBatcher(U8Dataset.from_float(train), 128, seed=1)
    parts = [U8ShardedBatcher(U8Dataset.from_float(train), 128, seed=1,
                              num_processes=4, process_index=p)
             for p in range(4)]
    w = next(whole.forever())
    ps = [next(p.forever()) for p in parts]
    np.testing.assert_array_equal(w[1], np.concatenate([p[1] for p in ps]))
