"""Fleet observatory: the router's FleetTracer, clock-offset
estimation, cross-replica trace stitching on canned fake traces
(skewed clocks, torn files, dead legs), the latency decomposition,
fleet-level SLO plumbing, the control-plane snapshot, the fleetview
screen, and one slow supervised e2e (real 2-replica fleet, SIGKILL
mid-stream, merged trace balanced).

The fast tier is jax-free: every stitcher scenario runs on hand-built
Chrome-trace JSON with explicit clock anchors, so skew, tears and
process death are exact, not raced.
"""

from __future__ import annotations

import json
import os

import pytest

from tensorflow_distributed_tpu.observe.fleet_trace import (
    FleetTracer, decompose, estimate_offset, gen_to_rid, stitch)
from tensorflow_distributed_tpu.observe.trace import (
    load_trace, unbalanced_async)


# --- FleetTracer (router-side spans) --------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _events_by(events, ph=None, name=None, cat=None):
    return [e for e in events
            if (ph is None or e.get("ph") == ph)
            and (name is None or e.get("name") == name)
            and (cat is None or e.get("cat") == cat)]


def test_fleet_tracer_request_lifecycle_balanced(tmp_path):
    path = str(tmp_path / "router_trace.json")
    clock = _Clock()
    ft = FleetTracer(path, clock=clock)
    ft.request_queued(0, slo="high", prompt_len=7)
    clock.t = 0.010
    ft.dispatch(0, 1, "r0", retry=0)
    clock.t = 0.025
    ft.first_token(0, 1, "r0")
    clock.t = 0.100
    ft.request_done(0, finish="done", tokens=32, ttft_ms=15.0,
                    retries=0)
    ft.counters(waiting=2, inflight=1)
    ft.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    # The anchor the stitcher needs, and the named process row.
    assert _events_by(ev, ph="M", name="clock_sync")
    names = {e["args"]["name"] for e in
             _events_by(ev, ph="M", name="process_name")}
    assert "tfd-router" in names
    # request + client_queue keyed by rid, dispatch by the WIRE id.
    req = _events_by(ev, ph="b", name="request", cat="fleet")
    assert [e["id"] for e in req] == ["0"]
    assert req[0]["args"] == {"slo": "high", "prompt_len": 7}
    disp = _events_by(ev, ph="b", name="dispatch", cat="fleet")
    assert [e["id"] for e in disp] == ["1"]
    assert disp[0]["args"]["replica"] == "r0"
    # client_queue closed AT dispatch, not at done.
    qe = _events_by(ev, ph="e", name="client_queue")[0]
    assert qe["ts"] == pytest.approx(10_000, abs=1)
    assert _events_by(ev, ph="i", name="first_token")
    done = _events_by(ev, ph="e", name="request")[0]
    assert done["args"]["finish"] == "done"
    assert done["args"]["tokens"] == 32
    assert _events_by(ev, ph="C", name="waiting")


def test_fleet_tracer_leg_failed_reopens_queue_and_marks(tmp_path):
    path = str(tmp_path / "router_trace.json")
    clock = _Clock()
    ft = FleetTracer(path, clock=clock)
    ft.request_queued(3)
    clock.t = 0.01
    ft.dispatch(3, 3073, "r1")
    clock.t = 0.05
    ft.leg_failed(3, 3073, "r1", why="replica_death")
    clock.t = 0.08
    ft.dispatch(3, 3074, "r0", retry=1)
    clock.t = 0.20
    ft.request_done(3, finish="done", tokens=8, retries=1)
    ft.close()
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    # The stitcher's dead-leg hook: a redispatch instant carrying the
    # failed generation id.
    redisp = _events_by(ev, ph="i", name="redispatch")
    assert [e["args"]["gen"] for e in redisp] == [3073]
    # Both dispatch legs present; the failed one says so.
    ends = {e["id"]: e for e in
            _events_by(ev, ph="e", name="dispatch")}
    assert ends["3073"]["args"]["failed"] is True
    assert ends["3074"]["args"]["finish"] == "done"
    # client_queue opened twice (arrival + back-at-router).
    assert len(_events_by(ev, ph="b", name="client_queue")) == 2


def test_fleet_tracer_shed_and_close_balance(tmp_path):
    path = str(tmp_path / "router_trace.json")
    ft = FleetTracer(path, clock=_Clock())
    ft.request_queued(0)
    ft.shed(0, reason="saturated")
    ft.request_queued(1)
    ft.dispatch(1, 1025, "r0")
    ft.replica_event("replica_death", "r0", pid=123)
    ft.close()                       # rid 1 still open: closed here
    ev = load_trace(path)
    assert not unbalanced_async(ev)
    assert _events_by(ev, ph="i", name="shed")
    assert _events_by(ev, ph="i", name="replica_death")
    end = _events_by(ev, ph="e", name="dispatch")[0]
    assert end["args"]["finish"] == "open_at_close"


# --- clock-offset estimation ----------------------------------------------

def test_estimate_offset_median_and_empty():
    assert estimate_offset([]) == 0.0
    # Odd count: the middle delta.
    assert estimate_offset([(10.0, 10.3), (20.0, 20.1),
                            (30.0, 30.2)]) == pytest.approx(0.2)
    # Even count: mean of the two middles.
    assert estimate_offset([(0.0, 0.1), (1.0, 1.3)]) \
        == pytest.approx(0.2)
    # One wild poll-lagged sample doesn't move the median.
    samples = [(float(i), float(i) + 0.05) for i in range(9)]
    samples.append((100.0, 109.0))
    assert estimate_offset(samples) == pytest.approx(0.05)


def test_gen_to_rid_inverts_router_wire_ids():
    assert gen_to_rid(1025) == 1
    assert gen_to_rid(3074) == 3
    assert gen_to_rid(0) == 0


# --- the stitcher on canned traces ----------------------------------------

def _trace_file(path, name, wall_ts, events):
    """A minimal ChromeTracer-shaped file with an explicit anchor."""
    pre = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": name}},
        {"ph": "M", "name": "clock_sync", "pid": 0, "tid": 0,
         "args": {"wall_ts": wall_ts}},
    ]
    with open(path, "w") as f:
        json.dump({"traceEvents": pre + events,
                   "displayTimeUnit": "ms"}, f)


def _b(name, id, ts, cat="serve", **args):
    ev = {"ph": "b", "name": name, "cat": cat, "pid": 0, "tid": 0,
          "id": str(id), "ts": float(ts)}
    if args:
        ev["args"] = args
    return ev


def _e(name, id, ts, cat="serve", **args):
    ev = dict(_b(name, id, ts, cat=cat, **args))
    ev["ph"] = "e"
    return ev


def _i(name, ts, cat="fleet", **args):
    return {"ph": "i", "name": name, "cat": cat, "pid": 0, "tid": 0,
            "ts": float(ts), "s": "p", "args": args}


def test_stitch_skewed_clocks_one_ordered_timeline(tmp_path):
    # Router starts at wall 1000.0; r0's tracer started 0.5s later
    # but its clock reads 0.2s FAST (offset -0.2 corrects it).
    router = str(tmp_path / "router.json")
    rep = str(tmp_path / "r0.json")
    out = str(tmp_path / "merged.json")
    _trace_file(router, "tfd-router", 1000.0, [
        _b("request", 0, 0.0, cat="fleet"),
        _b("dispatch", 1, 100.0, cat="fleet", rid=0, replica="r0"),
        _e("dispatch", 1, 900_000.0, cat="fleet", finish="done"),
        _e("request", 0, 900_100.0, cat="fleet", finish="done"),
    ])
    _trace_file(rep, "tfd-serve[r0]", 1000.7, [
        _b("request", 1, 0.0),
        _e("request", 1, 500_000.0, finish="length"),
    ])
    stats = stitch(router, [("r0/e0", rep, -0.2)], out)
    assert stats == {"sources": 2, "skipped": 0,
                     "events": stats["events"],
                     "closed_at_death": 0, "balanced": True}
    merged = load_trace(out)
    assert not unbalanced_async(merged)
    # Per-source process rows renamed fleet:<name>.
    rows = sorted(e["args"]["name"] for e in merged
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name")
    assert rows == ["fleet:r0/e0", "fleet:router"]
    # r0's corrected start is 1000.7 - 0.2 = 1000.5 -> its events are
    # shifted +0.5s onto the router's axis: the replica request begins
    # AFTER the dispatch, inside it.
    by = {(e.get("cat"), e.get("ph"), e.get("name")): e
          for e in merged if e.get("ph") in ("b", "e")}
    rep_b = by[("serve", "b", "request")]
    assert rep_b["ts"] == pytest.approx(500_000.0, abs=1)
    assert by[("fleet", "b", "dispatch")]["ts"] < rep_b["ts"] \
        < by[("fleet", "e", "dispatch")]["ts"]
    # Distinct pids per source (Perfetto track separation).
    assert len({e["pid"] for e in merged}) == 2


def test_stitch_torn_replica_file_skipped_with_marker(tmp_path):
    router = str(tmp_path / "router.json")
    torn = str(tmp_path / "torn.json")
    out = str(tmp_path / "merged.json")
    _trace_file(router, "tfd-router", 1000.0, [
        _b("request", 0, 0.0, cat="fleet"),
        _e("request", 0, 1000.0, cat="fleet"),
    ])
    with open(torn, "w") as f:
        f.write('{"traceEvents": [{"ph": "b", "na')   # SIGKILL mid-write
    stats = stitch(router, [("r1/e0", torn, 0.0),
                            ("r2/e0", str(tmp_path / "absent.json"),
                             0.0)], out)
    assert stats["sources"] == 1 and stats["skipped"] == 2
    assert stats["balanced"]
    merged = load_trace(out)
    markers = {e["name"] for e in merged if e.get("ph") == "i"}
    assert "trace_skipped:r1/e0" in markers
    assert "trace_skipped:r2/e0" in markers


def test_stitch_closes_dead_leg_at_redispatch_instant(tmp_path):
    # r1 was SIGKILLed mid-decode: its durable trace has open request/
    # decode spans for gen 1025. The router's redispatch instant for
    # that generation is the fleet-level end of the leg.
    router = str(tmp_path / "router.json")
    rep = str(tmp_path / "r1.json")
    out = str(tmp_path / "merged.json")
    _trace_file(router, "tfd-router", 1000.0, [
        _b("request", 1, 0.0, cat="fleet"),
        _b("dispatch", 1025, 50.0, cat="fleet", rid=1, replica="r1"),
        _e("dispatch", 1025, 300_000.0, cat="fleet", failed=True),
        _i("redispatch", 300_000.0, rid=1, gen=1025,
           replica="r1", why="replica_death"),
        _b("dispatch", 1026, 300_100.0, cat="fleet", rid=1,
           replica="r0", retry=1),
        _e("dispatch", 1026, 700_000.0, cat="fleet", finish="done"),
        _e("request", 1, 700_050.0, cat="fleet", finish="done"),
        # A second request SHED with no redispatch: its dead leg falls
        # back to the router-side request end.
        _b("request", 2, 0.0, cat="fleet"),
        _e("request", 2, 800_000.0, cat="fleet", finish="shed:x"),
    ])
    _trace_file(rep, "tfd-serve[r1]", 1000.0, [
        _b("request", 1025, 60.0),
        _b("decode", 1025, 2_000.0),
        _b("request", 2049, 70.0),
    ])
    stats = stitch(router, [("r1/e0", rep, 0.0)], out)
    assert stats["closed_at_death"] == 3
    assert stats["balanced"]
    merged = load_trace(out)
    assert not unbalanced_async(merged)
    deaths = [e for e in merged if e.get("ph") == "e"
              and (e.get("args") or {}).get("process_death")]
    by_id = {}
    for e in deaths:
        by_id.setdefault(e["id"], []).append(float(e["ts"]))
    # gen-1025 spans close exactly at the redispatch instant...
    assert by_id["1025"] == [pytest.approx(300_000.0, abs=1)] * 2
    # ...the shed request's at its router request end.
    assert by_id["2049"] == [pytest.approx(800_000.0, abs=1)]


def test_stitch_no_readable_source_raises(tmp_path):
    with pytest.raises(ValueError, match="no readable trace"):
        stitch(str(tmp_path / "nope.json"), [],
               str(tmp_path / "out.json"))


# --- latency decomposition ------------------------------------------------

def test_decompose_components_sum_to_e2e():
    ev = [
        _b("request", 0, 0.0, cat="fleet"),
        _b("client_queue", 0, 0.0, cat="fleet"),
        _e("client_queue", 0, 5_000.0, cat="fleet"),
        _b("dispatch", 1, 5_000.0, cat="fleet"),
        _b("request", 1, 15_000.0),            # inbox lag 10ms
        _b("queue", 1, 15_000.0),
        _e("queue", 1, 17_000.0),              # replica queue 2ms
        _b("prefill", 1, 17_000.0),
        _e("prefill", 1, 25_000.0),            # prefill 8ms
        _b("decode", 1, 25_000.0),
        _e("decode", 1, 85_000.0),             # decode 60ms
        _e("request", 1, 85_500.0),
        _e("dispatch", 1, 99_000.0, cat="fleet"),  # absorb 13.5ms
        _e("request", 0, 100_000.0, cat="fleet"),
    ]
    rows = decompose(ev)
    assert len(rows) == 1
    r = rows[0]
    assert r["rid"] == 0 and r["gens"] == [1]
    assert r["e2e_ms"] == pytest.approx(100.0)
    assert r["router_queue_ms"] == pytest.approx(5.0)
    assert r["inbox_lag_ms"] == pytest.approx(10.0)
    assert r["replica_queue_ms"] == pytest.approx(2.0)
    assert r["prefill_ms"] == pytest.approx(8.0)
    assert r["decode_ms"] == pytest.approx(60.0)
    assert r["absorb_ms"] == pytest.approx(13.5)
    # Residual = e2e - sum(parts): the 1.5ms of unattributed gap.
    assert r["residual_ms"] == pytest.approx(1.5)


def test_decompose_failover_spans_both_generations():
    ev = [
        _b("request", 2, 0.0, cat="fleet"),
        _b("dispatch", 2049, 1_000.0, cat="fleet"),
        _b("request", 2049, 2_000.0),
        _b("decode", 2049, 3_000.0),
        _e("decode", 2049, 30_000.0, process_death=True),
        _e("request", 2049, 30_000.0, process_death=True),
        _e("dispatch", 2049, 30_000.0, cat="fleet", failed=True),
        _b("dispatch", 2050, 31_000.0, cat="fleet"),
        _b("request", 2050, 33_000.0),
        _b("decode", 2050, 33_500.0),
        _e("decode", 2050, 60_000.0),
        _e("request", 2050, 60_100.0),
        _e("dispatch", 2050, 61_000.0, cat="fleet"),
        _e("request", 2, 61_500.0, cat="fleet"),
    ]
    r = decompose(ev)[0]
    assert r["gens"] == [2049, 2050]
    # Decode accumulates across BOTH legs (27 + 26.5 ms).
    assert r["decode_ms"] == pytest.approx(53.5)
    # Inbox lag and absorb likewise per leg.
    assert r["inbox_lag_ms"] == pytest.approx(1.0 + 2.0)
    assert r["absorb_ms"] == pytest.approx(0.0 + 0.9)


# --- fleet SLO plumbing ---------------------------------------------------

def test_slo_monitor_event_prefix_namespaces_records():
    from tensorflow_distributed_tpu.observe.slo import (
        SLOMonitor, parse_slo)
    emitted = []
    mon = SLOMonitor(parse_slo("ttft_p95=10ms"), fast_window=4,
                     slow_window=8,
                     emit=lambda e, **f: emitted.append((e, f)),
                     event_prefix="fleet_")
    for i in range(6):
        mon.observe("standard", ttft_ms=500.0, tok_ms=1.0, step=i)
        mon.on_step(i)
    kinds = [e for e, _ in emitted]
    assert "fleet_slo_alert" in kinds and "slo_alert" not in kinds
    assert mon.summary()["slo_alerts"] >= 1


def test_fleet_obs_config_validation():
    from tensorflow_distributed_tpu.fleet.run import FleetObsConfig
    FleetObsConfig().validate()
    FleetObsConfig(trace=True, slo="ttft_p95=100ms",
                   export_path="/t/s.json",
                   export_every=0.5).validate()
    with pytest.raises(ValueError, match="export_path"):
        FleetObsConfig(export_every=1.0).validate()
    with pytest.raises(ValueError, match="slo_burn"):
        FleetObsConfig(slo="ttft_p95=1ms", slo_burn=0).validate()
    with pytest.raises(ValueError, match="fleet.slo"):
        FleetObsConfig(slo_windows="5,10").validate()
    with pytest.raises(ValueError, match="export_every"):
        FleetObsConfig(export_path="/t/s.json",
                       export_every=-1).validate()


# --- inbox-poll lag (the decomposition's replica-side anchor) -------------

def test_inbox_feed_lag_stats_from_enq_ts(tmp_path):
    from tensorflow_distributed_tpu.fleet.replica import (
        InboxFeed, append_line)
    import time as time_mod
    path = str(tmp_path / "inbox.jsonl")
    feed = InboxFeed(path, poll_s=0.0)
    assert feed.lag_stats() == {}           # nothing stamped yet
    now = time_mod.time()
    append_line(path, {"rid": 1, "prompt": [1], "max_new": 2,
                       "enq_ts": now - 0.05})
    append_line(path, {"rid": 2, "prompt": [1], "max_new": 2})
    assert len(feed.poll()) == 2
    stats = feed.lag_stats()
    assert stats["inbox_poll_lag_ms"] >= 50.0
    assert stats["inbox_poll_lag_ms_p95"] >= stats["inbox_poll_lag_ms"]


def test_scheduler_snapshot_carries_inbox_poll_lag():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler
    import tests.test_fleet as tf
    import tests.test_serve as ts

    class _LagFeed(tf._ScriptedFeed if hasattr(tf, "_ScriptedFeed")
                   else object):
        def __init__(self):
            self.batches = [[{"cmd": "drain"}]]

        def poll(self):
            return self.batches.pop(0) if self.batches else []

        def lag_stats(self):
            return {"inbox_poll_lag_ms": 7.5,
                    "inbox_poll_lag_ms_p95": 12.0}

    sched = Scheduler(ts._FakeEngine(num_slots=2), feed=_LagFeed())
    sched.run([])
    snap = sched.metrics_snapshot()
    assert snap["inbox_poll_lag_ms"] == 7.5
    assert snap["inbox_poll_lag_ms_p95"] == 12.0


# --- control-plane snapshot == report (per-class e2e TTFT) ----------------

def test_router_fleet_snapshot_matches_summary_per_class():
    import tests.test_fleet as tf
    a = tf.FakeReplica("a", tok_per_tick=2)
    b = tf.FakeReplica("b", tok_per_tick=2)
    a.tick(), b.tick()
    router = tf._router([a, b])
    router.submit([tf._req(0, slo="high"), tf._req(1, slo="high"),
                   tf._req(2, slo="batch")])
    tf._spin(router, [a, b], 0.0, 3.0)
    summ = router.summary()
    snap = router.fleet_snapshot(3.0)
    keys = [k for k in summ if k.startswith(("ttft_ms_p95_",
                                             "ttft_ms_p50_"))]
    assert any(k.endswith("_high") for k in keys)
    for k in keys:
        # EXACT equality: same population, same nearest-rank
        # percentile, same rounding — the snapshot==report contract.
        assert snap[k] == summ[k], k
    assert snap["requests_done"] == 3
    assert set(snap["replicas"]) == {"a", "b"}
    for rep in snap["replicas"].values():
        assert rep["health"] == "up"


def test_router_emits_fleet_request_records():
    import tests.test_fleet as tf
    a = tf.FakeReplica("a", tok_per_tick=2)
    a.tick()
    events = []
    router = tf._router([a], emit=lambda e, **f: events.append((e, f)))
    router.submit([tf._req(0, slo="batch", max_new=4)])
    tf._spin(router, [a], 0.0, 2.0)
    recs = [f for e, f in events if e == "fleet_request"]
    assert len(recs) == 1
    r = recs[0]
    assert r["slo"] == "batch" and r["retries"] == 0
    assert not r["redispatched"]
    assert r["ttft_ms"] >= 0 and r["e2e_ms"] >= r["ttft_ms"]
    assert r["tokens"] == 4 and "tok_ms" in r


# --- fleetview + report folding -------------------------------------------

def _seed_fleet_dir(tmp_path):
    d = str(tmp_path / "fleet")
    os.makedirs(d)
    with open(os.path.join(d, "fleet_snapshot.json"), "w") as f:
        json.dump({"t_s": 9.5, "step": 42, "requests": 10,
                   "requests_done": 9, "requests_shed": 1,
                   "waiting": 0, "inflight": 0, "slots": 4,
                   "slots_live": 0, "queue_depth": 0,
                   "quarantined": [], "deaths": 1,
                   "ttft_ms_p95_high": 12.5, "ttft_ms_p50_high": 8.0,
                   "slo_alerting": True,
                   "slo_budget_remaining_min": -0.5,
                   "slo": {"high:ttft_p95": {
                       "alerting": True, "alerts": 1,
                       "burn_fast": 2.0, "burn_slow": 1.5,
                       "budget_remaining": -0.5}},
                   "replicas": {"r0": {"health": "up", "epoch": 0,
                                       "load": 0, "inflight": 0,
                                       "done": 9, "reason": "",
                                       "stale_s": 0.1}}}, f)
    records = [
        {"event": "fleet_summary", "requests": 10, "requests_done": 9,
         "requests_shed": 1, "redispatches": 1, "deaths": 1,
         "tokens_per_sec": 55.0, "ttft_ms_p95_high": 12.5},
        {"event": "fleet_slo_alert", "target": "high:ttft_p95",
         "burn_fast": 2.0, "burn_slow": 1.5, "budget_remaining": -0.5,
         "t_s": 4.0},
        {"event": "fleet_slo_ok", "target": "high:ttft_p95",
         "burn_fast": 0.1, "burn_slow": 0.9, "budget_remaining": 0.2,
         "t_s": 8.0},
        {"event": "fleet_replica", "replica": "r1", "state": "dead",
         "t_s": 3.0},
        {"event": "fleet_decomp", "rid": 0, "e2e_ms": 100.0,
         "router_queue_ms": 5.0, "inbox_lag_ms": 10.0,
         "replica_queue_ms": 2.0, "prefill_ms": 8.0,
         "decode_ms": 60.0, "absorb_ms": 13.5, "residual_ms": 1.5},
        {"event": "fleet_snapshot", "t_s": 9.5},
    ]
    with open(os.path.join(d, "fleet.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    with open(os.path.join(d, "fleet_trace.json"), "w") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
             "args": {"name": "fleet:router"}},
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "fleet:r0/e0"}},
            _b("request", 0, 0.0, cat="fleet"),
            _e("request", 0, 1000.0, cat="fleet",
               process_death=True),
        ]}, f)
    return d, records


def test_fleetview_renders_all_sections(tmp_path):
    from tensorflow_distributed_tpu.observe import fleetview
    d, _ = _seed_fleet_dir(tmp_path)
    view = fleetview.render(d)
    assert "fleet observatory" in view
    assert "ALERTING" in view
    assert "high: p95=12.5ms" in view
    assert "1 alert(s), 1 all-clear(s)" in view
    assert "incident t=3s r1: dead" in view
    assert "absorb 13.5" in view
    assert "stitched trace" in view and "balanced" in view
    assert "1 span(s) closed at process death" in view
    assert "fleet:r0/e0" in view
    # Empty dir: every section degrades, none crashes.
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    view2 = fleetview.render(empty)
    assert "(no snapshot" in view2 and "(no fleet.jsonl)" in view2
    assert "(no fleet_trace.json" in view2


def test_fleetview_cli_main(tmp_path, capsys):
    from tensorflow_distributed_tpu.observe import fleetview
    d, _ = _seed_fleet_dir(tmp_path)
    assert fleetview.main([d]) == 0
    assert "fleet observatory" in capsys.readouterr().out
    assert fleetview.main([str(tmp_path / "nope")]) == 2


def test_report_folds_decomposition_and_fleet_slo(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        render, summarize)
    _, records = _seed_fleet_dir(tmp_path)
    out = summarize(records)
    fl = out["fleet"]
    dec = fl["decomposition"]
    assert dec["requests"] == 1
    assert dec["absorb_ms_mean"] == pytest.approx(13.5)
    assert dec["residual_frac_mean"] == pytest.approx(0.015)
    assert fl["slo"]["alerts"] == 1
    assert fl["slo"]["budget_remaining_min"] == pytest.approx(-0.5)
    assert fl["snapshots"] == 1
    text = render(out)
    assert "absorb 13.5" in text and "frac=0.015" in text


# --- the real thing (slow) -----------------------------------------------

@pytest.mark.slow
def test_fleet_obs_e2e_sigkill_merged_trace_balanced(tmp_path):
    """Real 2-replica fleet with the full observatory armed, SIGKILL
    one replica mid-stream: the stitched trace is balanced with the
    dead leg closed at process death, the decomposition covers every
    request, and the exported snapshot agrees with the report."""
    import subprocess
    import sys as _sys

    import numpy as np

    from tensorflow_distributed_tpu.fleet.controller import (
        ControllerConfig as CC)
    from tensorflow_distributed_tpu.fleet.router import (
        RouterConfig as RC)
    from tensorflow_distributed_tpu.fleet.run import (
        FleetObsConfig, load_workload, run_fleet)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"
    ckpt = str(tmp_path / "ckpt")
    common = ["--model", "gpt_lm", "--model-size", "tiny",
              "--seq-len", "48", "--seed", "0",
              "--compute-dtype", "float32"]
    subprocess.run(
        [_sys.executable, "-m", "tensorflow_distributed_tpu.cli",
         *common, "--dataset", "synthetic", "--train-steps", "2",
         "--batch-size", "8", "--eval-every", "0", "--log-every",
         "0", "--checkpoint-dir", ckpt, "--checkpoint-every", "2"],
        env=env, check=True, capture_output=True, timeout=300)
    wl = str(tmp_path / "wl.jsonl")
    rng = np.random.default_rng(0)
    with open(wl, "w") as f:
        for i in range(8):
            plen = int(rng.integers(4, 12))
            f.write(json.dumps({
                "prompt": [int(t) for t in rng.integers(0, 64, plen)],
                "max_new_tokens": 24,
                "arrival_s": round(0.15 * i, 3)}) + "\n")

    def arm_kill(ctl, router):
        import threading
        import time as time_mod

        def hunt():
            t_end = time_mod.monotonic() + 30
            while time_mod.monotonic() < t_end:
                h = ctl.members["r1"].handle
                jr = h.read_journal(epoch=h.epoch)
                if any(not e.get("done")
                       and 1 <= len(e.get("tokens", ())) <= 12
                       for e in jr.values()):
                    break
                time_mod.sleep(0.01)
            ctl.kill("r1")
        threading.Thread(target=hunt, daemon=True).start()

    fleet_dir = str(tmp_path / "fleet")
    snap_path = os.path.join(fleet_dir, "fleet_snapshot.json")
    summary = run_fleet(
        fleet_dir=fleet_dir, replicas=2,
        base_args=["--mode", "serve", *common,
                   "--checkpoint-dir", ckpt,
                   "--serve.num-slots", "2",
                   "--serve.buckets", "48"],
        workload=load_workload(wl), ckpt_dir=ckpt, env=env,
        actions=[(0.2, arm_kill)],
        router_cfg=RC(dispatch_timeout_s=60.0),
        controller_cfg=CC(backoff_base_s=0.25),
        timeout_s=300.0, poll_s=0.02,
        jsonl=os.path.join(fleet_dir, "fleet.jsonl"),
        obs=FleetObsConfig(trace=True, slo="ttft_p95=30s",
                           export_path=snap_path,
                           export_every=0.5))
    assert summary["requests_lost"] == 0
    assert summary["requests_done"] == 8
    assert summary["deaths"] == 1
    # The tentpole artifact: ONE merged, balanced timeline.
    assert summary["stitch_balanced"]
    assert summary["stitch_sources"] >= 3    # router + r1 e0 + ...
    assert summary["stitch_closed_at_death"] >= 1
    merged = load_trace(os.path.join(fleet_dir, "fleet_trace.json"))
    assert not unbalanced_async(merged)
    assert any((e.get("args") or {}).get("process_death")
               for e in merged if e.get("ph") == "e")
    # Decomposition covered every request.
    assert summary["decomp_requests"] == 8
    # The control-plane snapshot parses and agrees with the report.
    with open(snap_path) as f:
        snap = json.load(f)
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    rep = summarize(load_records(
        os.path.join(fleet_dir, "fleet.jsonl")))["fleet"]
    keys = [k for k in snap if k.startswith(("ttft_ms_p95_",
                                             "ttft_ms_p50_"))]
    assert keys
    for k in keys:
        assert snap[k] == rep[k], k
