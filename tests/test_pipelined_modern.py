"""Pipelined LM with the modern knobs: RoPE and weight tying.

Round-3 VERDICT weak #3: these were hard-errored walls with soft
justifications — positions are microbatch-invariant (microbatches
slice batch, not sequence) and both tok_emb and lm_head live in the
same shell module. These tests pin that the walls are genuinely down:
the pipelined forward equals the non-pipelined CausalLM with the SAME
weights, and both schedules (GPipe AD / hand-rolled 1F1B) agree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.data.lm import synthetic_clm
from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
from tensorflow_distributed_tpu.models.transformer import CausalLM
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.pipeline import stack_stage_params
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.pipeline_step import (
    make_1f1b_train_step)
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step
from tensorflow_distributed_tpu.train.tasks import (
    mlm_batch_shardings, mlm_loss)

MODERN = dict(pos_emb="rope", tie_embeddings=True, n_layers=4,
              max_len=16, dropout_rate=0.0, compute_dtype=jnp.float32)


def _remap_to_pipelined(seq_params, n_layers, stages, tied):
    """CausalLM param tree -> PipelinedLM {shell, blocks} tree with the
    SAME weights (layer_i leaves stacked [S, layers_per_stage, ...])."""
    shell = {"tok_emb": seq_params["tok_emb"], "ln_f": seq_params["ln_f"]}
    if "pos_emb" in seq_params:
        shell["pos_emb"] = seq_params["pos_emb"]
    if not tied:
        shell["lm_head"] = seq_params["lm_head"]
    layers = [seq_params[f"layer_{i}"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)
    return {"params": {"shell": shell,
                       "blocks": stack_stage_params(stacked, stages)}}


@pytest.mark.parametrize("knobs", [
    dict(pos_emb="rope"),
    dict(tie_embeddings=True),
    dict(pos_emb="rope", tie_embeddings=True, mlp_variant="swiglu",
         norm="rmsnorm", n_kv_heads=2),  # the full Llama-shaped stack
])
def test_pipelined_forward_matches_causal_lm(devices8, knobs):
    """Pipelined logits == CausalLM logits with identical weights —
    the schedule is a layout, not a model change."""
    from tensorflow_distributed_tpu.models.transformer import tiny_config

    cfg = tiny_config(causal=True, tp_partitioning=False, n_layers=4,
                      max_len=16, dropout_rate=0.0,
                      compute_dtype=jnp.float32, use_flash=False, **knobs)
    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    tokens = np.arange(8 * 16, dtype=np.int32).reshape(8, 16) % 64

    seq_model = CausalLM(cfg, None)
    seq_vars = seq_model.init(jax.random.key(0), tokens)
    want = seq_model.apply(seq_vars, tokens)

    pipe_model = pipelined_lm(
        mesh, use_flash=False, n_layers=4, max_len=16,
        dropout_rate=0.0, compute_dtype=jnp.float32, **knobs)
    pipe_vars = _remap_to_pipelined(
        seq_vars["params"], 4, 4, tied=knobs.get("tie_embeddings", False))
    got = jax.jit(lambda v, t: pipe_model.apply(v, t))(pipe_vars, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-4)


def test_1f1b_matches_gpipe_with_rope_and_tying(devices8):
    """Schedule parity holds for the modern stack too: 1F1B's
    hand-rolled backward must reproduce GPipe-by-AD gradients when the
    head is the tied embedding (its gradient now has BOTH an
    embed-path and a head-path contribution)."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model = pipelined_lm(mesh, num_microbatches=8, use_flash=False,
                         **MODERN)
    state = create_train_state(model, optax.adam(1e-2),
                               np.zeros((2, 16), np.int32), mesh)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
    step_g = make_train_step(mesh, loss=mlm_loss,
                             batch_shardings=mlm_batch_shardings(mesh),
                             donate=False, grad_norm_metric=True)
    step_f = make_1f1b_train_step(model, mesh, donate=False,
                                  grad_norm_metric=True)
    st_g, met_g = step_g(state, batch)
    st_f, met_f = step_f(state, batch)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_g["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_f["grad_norm"]),
                               float(met_g["grad_norm"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_g.params, st_f.params)


def test_pipelined_ring_attention_parity(devices8):
    """Ring attention INSIDE the pipeline (VERDICT r4 item 3): on a
    data=2 x pipe=2 x seq=2 mesh the Block routes seq-sharded
    activations to ring_attention, whose shard_map nests over the
    remaining auto axes inside the pipe-manual region. The pipelined
    forward must equal the non-pipelined CausalLM with identical
    weights — the two flagship axes (long-context SP and pipeline)
    finally composing."""
    from tensorflow_distributed_tpu.models.transformer import tiny_config

    cfg = tiny_config(causal=True, tp_partitioning=False, n_layers=4,
                      max_len=16, dropout_rate=0.0,
                      compute_dtype=jnp.float32, use_flash=False,
                      pos_emb="rope")
    mesh = make_mesh(MeshConfig(data=2, pipe=2, seq=2), devices8)
    tokens = np.arange(8 * 16, dtype=np.int32).reshape(8, 16) % 64

    seq_model = CausalLM(cfg, None)
    seq_vars = seq_model.init(jax.random.key(0), tokens)
    want = seq_model.apply(seq_vars, tokens)

    pipe_model = pipelined_lm(
        mesh, use_flash=False, n_layers=4, max_len=16, dropout_rate=0.0,
        compute_dtype=jnp.float32, pos_emb="rope")
    pipe_vars = _remap_to_pipelined(seq_vars["params"], 4, 2, tied=False)
    with mesh:
        sharded = shard_batch(mesh, {"t": tokens}, seq_axis=1)["t"]
        got = jax.jit(lambda v, t: pipe_model.apply(v, t))(
            pipe_vars, sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-4)


def test_pipelined_ring_1f1b_matches_gpipe(devices8):
    """The hand-rolled 1F1B backward differentiates through the nested
    ring shard_map (ppermute transposes to the reverse rotation): both
    schedules agree on loss, grad norm, and updated params on the
    pipe x seq mesh."""
    mesh = make_mesh(MeshConfig(data=2, pipe=2, seq=2), devices8)
    model = pipelined_lm(mesh, num_microbatches=4, use_flash=False,
                         **MODERN)
    state = create_train_state(model, optax.adam(1e-2),
                               np.zeros((2, 16), np.int32), mesh)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
    step_g = make_train_step(mesh, loss=mlm_loss,
                             batch_shardings=mlm_batch_shardings(mesh),
                             donate=False, grad_norm_metric=True)
    step_f = make_1f1b_train_step(model, mesh, donate=False,
                                  grad_norm_metric=True)
    st_g, met_g = step_g(state, batch)
    st_f, met_f = step_f(state, batch)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_g["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_f["grad_norm"]),
                               float(met_g["grad_norm"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_g.params, st_f.params)


def test_config_accepts_pipelined_modern_knobs():
    """The round-3 validation walls are gone: rope + tying + pipelined
    is a legal TrainConfig."""
    TrainConfig(model="pipelined_lm", pos_emb="rope",
                tie_embeddings=True, rope_theta=500000.0).validate()


@pytest.mark.slow
def test_pipelined_modern_trains_end_to_end(devices8):
    """Full loop: pipelined Llama-shaped tiny model (rope + tied +
    swiglu + rmsnorm) learns the synthetic progression above chance."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      dataset="synthetic", batch_size=32, train_steps=40,
                      eval_every=0, log_every=0, eval_batch_size=32,
                      compute_dtype="float32", learning_rate=3e-3,
                      dropout_rate=0.0, pos_emb="rope",
                      tie_embeddings=True, mlp_variant="swiglu",
                      norm="rmsnorm", pipeline_schedule="1f1b",
                      mesh=MeshConfig(data=4, pipe=2))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.35, result.final_metrics
