"""Pallas flash attention vs the XLA oracle (interpret mode on CPU).

The kernel is validated the way SURVEY.md §4 prescribes for everything
else: run the real code path on the host platform and compare against
a plain-XLA reference — here ``full_attention``, which is also the
ring-attention building block, so the two attention paths are pinned
to each other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.ops.flash_attention import (
    NEG_INF, attention, flash_attention, supported)
from tensorflow_distributed_tpu.parallel.ring_attention import full_attention

B, L, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), dtype) * 0.5
    return mk(), mk(), mk()


def _causal_mask():
    from tensorflow_distributed_tpu.parallel.ring_attention import causal_bias
    return causal_bias(L, L)


def test_forward_matches_oracle():
    q, k, v = _qkv()
    got = flash_attention(q, k, v, interpret=True)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _qkv(1)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = full_attention(q, k, v, _causal_mask())
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_oracle(causal):
    q, k, v = _qkv(2)
    mask = _causal_mask() if causal else None

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(out))  # non-uniform cotangents

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(full_attention(q, k, v, mask)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_supported_gate():
    assert supported(256, 256, 64)
    assert supported(200, 256, 64)       # blocks clamp to short seqs
    assert not supported(250, 256, 64)   # ragged: 250 % 8 != 0
    assert supported(768, 256, 64)       # clamps to bq=768 (div by 8)
    assert not supported(1536, 256, 64)  # 1536 not divisible by bq=1024
    assert not supported(256, 256, 300)  # head dim too large


def test_short_seq_clamped_blocks():
    q = jnp.ones((1, 40, 2, 16), jnp.float32) * 0.1
    got = flash_attention(q, q, q, interpret=True)
    np.testing.assert_allclose(got, full_attention(q, q, q),
                               atol=2e-6, rtol=2e-6)


def test_flash_under_shard_map(mesh8):
    """The multi-device TPU path: kernel shard_mapped over the batch
    axis (interpret mode on the 8-device CPU mesh)."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(4)
    mk = lambda: jnp.asarray(rng.normal(size=(8, 256, 2, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    spec = P("data", None, None, None)
    got = jax.jit(jax.shard_map(
        lambda q, k, v: flash_attention(q, k, v, interpret=True),
        mesh=mesh8, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(got, full_attention(q, k, v),
                               atol=2e-5, rtol=2e-5)


def test_ragged_seq_raises():
    q = jnp.ones((1, 1500, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, q, q, interpret=True)


def test_dispatcher_falls_back_off_tpu():
    # On CPU the dispatcher must route to the XLA path and still be
    # numerically the oracle (incl. the causal-mask construction).
    q, k, v = _qkv(3)
    np.testing.assert_allclose(attention(q, k, v, causal=True),
                               full_attention(q, k, v, _causal_mask()),
                               atol=1e-6)


def test_causal_multiblock_skip_matches_oracle():
    """Small blocks at L=256 give an 8x8 block grid where the causal
    skip predicate and the DMA re-point index_maps actually fire on the
    28 above-diagonal pairs — an off-by-one in _kv_needed/_q_needed or
    the re-point floor-divs would corrupt exactly this case (the
    default-block tests run a 1x1 grid where skip degenerates away)."""
    rng = np.random.default_rng(7)
    B, L, H, D = 2, 256, 2, 16
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32,
                               block_k=64, interpret=True)

    from tensorflow_distributed_tpu.parallel.ring_attention import (
        causal_bias, full_attention)
    oracle = full_attention(q, k, v, causal_bias(L, L))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(oracle), rtol=2e-5, atol=2e-5)

    # Gradients through all three kernels on the same multi-block grid.
    gf = jax.grad(lambda q, k, v: jnp.sum(flash(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(
        lambda q, k, v: jnp.sum(full_attention(q, k, v,
                                               causal_bias(L, L)) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def _window_bias(L, window):
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        causal_bias)
    rows = np.arange(L)[:, None]
    cols = np.arange(L)[None, :]
    extra = jnp.where(jnp.asarray(cols > rows - window), 0.0,
                      float(NEG_INF))[None]
    return causal_bias(L, L) + extra


@pytest.mark.parametrize("window", [1, 17, 48, 64, 200, 256])
def test_window_multiblock_matches_oracle(window):
    """Sliding-window flash vs the dense masked oracle on an 8x4 block
    grid (bq=32, bk=64): windows smaller than a block, spanning
    several blocks, block-aligned, and >= L (== plain causal) all hit
    the band predicates (_kv_needed/_q_needed) and the clamp index
    maps differently. Forward AND all three gradient kernels."""
    rng = np.random.default_rng(9)
    B, L, H, D = 2, 256, 2, 16
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def flash(q, k, v):
        return flash_attention(q, k, v, causal=True, window=window,
                               block_q=32, block_k=64, interpret=True)

    oracle_fn = lambda q, k, v: full_attention(  # noqa: E731
        q, k, v, _window_bias(L, window))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(oracle_fn(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    gf = jax.grad(lambda q, k, v: jnp.sum(flash(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(lambda q, k, v: jnp.sum(oracle_fn(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_window_at_or_past_length_equals_causal():
    q, k, v = _qkv(seed=10)
    plain = flash_attention(q, k, v, causal=True, block_q=64,
                            block_k=64, interpret=True)
    for w in (L, L + 100):
        out = flash_attention(q, k, v, causal=True, window=w,
                              block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(plain),
                                   rtol=1e-6, atol=1e-6)


def test_window_requires_causal():
    q, k, v = _qkv(seed=11)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=8, interpret=True)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=True, window=-1, interpret=True)
    # The XLA-oracle dispatcher path must not silently drop the window
    # for non-causal configs either.
    with pytest.raises(ValueError, match="causal"):
        attention(q, k, v, causal=False, window=8, allow_flash=False)


def test_window_dispatcher_xla_fallback_matches_flash():
    """attention() with a window on the non-flash path (allow_flash=
    False) must agree with the windowed kernel — the two code paths a
    user can land on depending on backend/shapes."""
    rng = np.random.default_rng(12)
    B, L2, H, D = 2, 128, 2, 16
    mk = lambda: jnp.asarray(rng.normal(size=(B, L2, H, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    xla = attention(q, k, v, causal=True, window=24, allow_flash=False)
    fl = flash_attention(q, k, v, causal=True, window=24, block_q=32,
                         block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(xla), np.asarray(fl),
                               rtol=2e-5, atol=2e-5)


def test_causal_multiblock_uneven_blocks():
    """bq != bk with bq > bk and bk > bq both exercise the floor-div
    arithmetic in the skip maps."""
    rng = np.random.default_rng(8)
    B, L, H, D = 1, 128, 2, 8
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        causal_bias, full_attention)
    oracle = full_attention(q, k, v, causal_bias(L, L))
    for bq, bk in [(16, 64), (64, 16), (32, 32)]:
        out = flash_attention(q, k, v, causal=True, block_q=bq,
                              block_k=bk, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"bq={bq} bk={bk}")


# ---- partial-softmax variant (the ring's building block) ---------------

def _partial_oracle(q, k, v, causal):
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        _block_attend, causal_bias)
    bias = causal_bias(q.shape[1], k.shape[1]) if causal else None
    return _block_attend(q, k, v, bias)


@pytest.mark.parametrize("causal", [False, True])
def test_partial_matches_einsum_oracle(causal):
    """flash_attention_partial == the einsum streaming-softmax partials
    (m, l, unnormalized o) that the zigzag ring merges."""
    from tensorflow_distributed_tpu.ops.flash_attention import (
        flash_attention_partial)

    q, k, v = _qkv(11)
    gm, gl, go = flash_attention_partial(q, k, v, causal=causal,
                                         interpret=True)
    wm, wl, wo = _partial_oracle(q, k, v, causal)
    # m may differ by the oracle's fully-masked-row clamp only when a
    # row is fully masked — never the case here (diagonal visible).
    np.testing.assert_allclose(np.asarray(gm), np.asarray(wm), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(go), np.asarray(wo),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_partial_grads_match_einsum_oracle(causal):
    """Gradients THROUGH a ring-style merge+normalize consumer: the
    custom VJP (m as stop-grad stabilizer) must match AD through the
    einsum partials exactly where it matters — after the invariant
    merge/finish, not on the raw partials."""
    from tensorflow_distributed_tpu.ops.flash_attention import (
        flash_attention_partial)

    q, k, v = _qkv(12)
    q2, k2, v2 = _qkv(13)

    def consumer(attend):
        def f(q, k, v):
            m1, l1, o1 = attend(q, k, v)
            m2, l2, o2 = _partial_oracle(q2, k2, v2, False)
            from tensorflow_distributed_tpu.parallel.ring_attention \
                import _merge
            m, l, o = _merge(m1, l1, o1, m2, l2, o2)
            out = o / l.transpose(0, 2, 1)[..., None]
            return jnp.sum(out * out)
        return f

    flash = consumer(lambda q, k, v: flash_attention_partial(
        q, k, v, causal=causal, interpret=True))
    oracle = consumer(lambda q, k, v: _partial_oracle(q, k, v, causal))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-3)
