"""Generate the committed MNIST idx fixture (tests/fixtures/mnist/).

This environment has no network egress, so the repo cannot carry the
true MNIST pixels; what the fixture pins is the exact ON-DISK BYTE
FORMAT the reference's loader consumed (idx1/idx3, big-endian headers,
magic 0x801/0x803 — mnist_python_m.py:133 via input_data.read_data_sets)
so ``load_mnist`` and the C++ reader (native/tfd_native.cc tfd_idx_read)
are exercised on real idx bytes, gz and plain, not on synthetic arrays
handed past the parser. Pixel content is the deterministic glyph set
(data/mnist.py synthetic_mnist) quantized to u8.

Rerun to regenerate:  python tests/fixtures/make_mnist_fixture.py
"""
import gzip
import os
import struct

import numpy as np

from tensorflow_distributed_tpu.data.mnist import synthetic_mnist

OUT = os.path.join(os.path.dirname(__file__), "mnist")
N_TRAIN, N_TEST = 1024, 256


def idx3(images_u8: np.ndarray) -> bytes:
    n, r, c = images_u8.shape
    return struct.pack(">iiii", 2051, n, r, c) + images_u8.tobytes()


def idx1(labels_u8: np.ndarray) -> bytes:
    return struct.pack(">ii", 2049, len(labels_u8)) + labels_u8.tobytes()


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    train, val, test = synthetic_mnist(n_train=N_TRAIN, n_test=N_TEST,
                                       validation_size=0, seed=7)
    # Re-join train (validation_size=0 keeps it whole) and quantize.
    tr_img = (train.images[..., 0] * 255).round().astype(np.uint8)
    te_img = (test.images[..., 0] * 255).round().astype(np.uint8)
    blobs = {
        "train-images-idx3-ubyte.gz": idx3(tr_img),
        "train-labels-idx1-ubyte.gz": idx1(
            train.labels.astype(np.uint8)),
        # Test pair stays UNcompressed so both opener paths are pinned.
        "t10k-images-idx3-ubyte": idx3(te_img),
        "t10k-labels-idx1-ubyte": idx1(test.labels.astype(np.uint8)),
    }
    for name, blob in blobs.items():
        path = os.path.join(OUT, name)
        if name.endswith(".gz"):
            # mtime=0 => reproducible bytes.
            with open(path, "wb") as f:
                f.write(gzip.compress(blob, mtime=0))
        else:
            with open(path, "wb") as f:
                f.write(blob)
        print(name, os.path.getsize(path), "bytes")


if __name__ == "__main__":
    main()
