"""Causal-LM family: causal ring attention parity, GPT training."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.ring_attention import (
    causal_bias, full_attention, ring_attention)


def test_causal_ring_matches_full(devices8):
    """4-way seq-sharded causal ring == dense causal attention."""
    mesh = make_mesh(MeshConfig(data=2, seq=4), devices8)
    rng = np.random.default_rng(0)
    B, L, H, D = 4, 64, 2, 16
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
               for _ in range(3))
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
    want = full_attention(q, k, v, causal_bias(L, L))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_noncausal_ring_unchanged(devices8):
    """The clamp added for causal must not disturb the MLM path."""
    mesh = make_mesh(MeshConfig(data=2, seq=4), devices8)
    rng = np.random.default_rng(1)
    B, L, H, D = 2, 32, 2, 8
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
               for _ in range(3))
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(got, full_attention(q, k, v),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_gpt_learns_next_token(devices8):
    """Integration bar: tiny GPT on the stride-progression data must
    beat chance by a wide margin within a tiny budget (chance = 1/64;
    the stride is inferable from two preceding tokens)."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="gpt_lm", model_size="tiny", dataset="synthetic",
                      batch_size=64, train_steps=80, eval_every=0,
                      log_every=0, eval_batch_size=64,
                      compute_dtype="float32", learning_rate=3e-3,
                      mesh=MeshConfig(data=2, seq=2, model=2))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.5, result.final_metrics


def test_gpt_registry():
    from tensorflow_distributed_tpu.models import build_model
    from tensorflow_distributed_tpu.models.transformer import CausalLM

    m = build_model("gpt_lm", size="tiny")
    assert isinstance(m, CausalLM)
    assert m.cfg.causal
    assert m.extra_vocab == 0
