"""Data layer tests: idx parsing, synthetic set, sharded batching
(SURVEY.md N13 replacement)."""

import struct

import numpy as np
import pytest

from tensorflow_distributed_tpu.data.mnist import (
    Dataset, ShardedBatcher, parse_idx, synthetic_mnist)


def _idx_images(arr: np.ndarray) -> bytes:
    n, r, c = arr.shape
    return struct.pack(">iiii", 2051, n, r, c) + arr.tobytes()


def _idx_labels(arr: np.ndarray) -> bytes:
    return struct.pack(">ii", 2049, arr.shape[0]) + arr.tobytes()


def test_parse_idx_images_roundtrip():
    arr = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28)
    out = parse_idx(_idx_images(arr))
    np.testing.assert_array_equal(out, arr)


def test_parse_idx_labels_roundtrip():
    arr = np.array([3, 1, 4, 1, 5], dtype=np.uint8)
    np.testing.assert_array_equal(parse_idx(_idx_labels(arr)), arr)


def test_parse_idx_rejects_garbage():
    with pytest.raises(ValueError):
        parse_idx(b"\x00\x00\x00\x99" + b"\x00" * 16)
    with pytest.raises(ValueError):
        parse_idx(b"ab")


def test_synthetic_shapes_and_determinism():
    tr, va, te = synthetic_mnist(n_train=256, n_test=64, validation_size=32,
                                 seed=7)
    assert tr.images.shape == (224, 28, 28, 1)
    assert va.images.shape == (32, 28, 28, 1)
    assert te.images.shape == (64, 28, 28, 1)
    assert tr.images.dtype == np.float32
    assert 0.0 <= tr.images.min() and tr.images.max() <= 1.0
    assert set(np.unique(tr.labels)) <= set(range(10))
    tr2, _, _ = synthetic_mnist(n_train=256, n_test=64, validation_size=32,
                                seed=7)
    np.testing.assert_array_equal(tr.images, tr2.images)


from tests.conftest import FIXTURE_DIR


def test_load_mnist_fixture_real_idx_bytes():
    """load_mnist on the COMMITTED idx fixture (tests/fixtures/mnist):
    real on-disk idx1/idx3 bytes — big-endian headers, magic
    0x801/0x803, .gz and plain — through the full loader, not synthetic
    arrays handed past the parser (VERDICT r02 missing #3b)."""
    from tensorflow_distributed_tpu.data.mnist import load_mnist

    train, val, test = load_mnist(FIXTURE_DIR, validation_size=64)
    assert train.images.shape == (960, 28, 28, 1)   # 1024 - 64 val
    assert val.images.shape == (64, 28, 28, 1)
    assert test.images.shape == (256, 28, 28, 1)
    assert train.images.dtype == np.float32
    assert 0.0 <= train.images.min() and train.images.max() <= 1.0
    assert set(np.unique(test.labels)) <= set(range(10))
    # The pixels decode to the generator's content (u8-quantized
    # synthetic glyphs, seed 7) — full byte-level round trip.
    gen = synthetic_mnist(n_train=1024, n_test=256, validation_size=0,
                          seed=7)[0]
    want = (gen.images[64:, ..., 0] * 255).round() / 255.0
    np.testing.assert_allclose(train.images[..., 0], want, atol=1e-6)


def test_native_reader_parses_fixture():
    """The C++ idx reader (native/tfd_native.cc) on the committed
    fixture files, against the numpy parser — both .gz and plain."""
    import gzip

    from tensorflow_distributed_tpu.native import runtime as native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    plain = FIXTURE_DIR + "/t10k-images-idx3-ubyte"
    gz = FIXTURE_DIR + "/train-images-idx3-ubyte.gz"
    np.testing.assert_array_equal(
        native.idx_read(plain), parse_idx(open(plain, "rb").read()))
    np.testing.assert_array_equal(
        native.idx_read(gz), parse_idx(gzip.open(gz, "rb").read()))


def test_batcher_epoch_covers_dataset_once():
    ds = Dataset(np.arange(64, dtype=np.float32).reshape(64, 1, 1, 1),
                 np.arange(64, dtype=np.int32))
    b = ShardedBatcher(ds, global_batch=16, seed=0)
    seen = []
    for imgs, labels in b.epoch(0):
        assert imgs.shape == (16, 1, 1, 1)
        seen.extend(labels.tolist())
    assert sorted(seen) == list(range(64))


def test_batcher_process_shards_are_disjoint_and_union_to_global():
    """The upgrade over the reference's independent per-worker sampling
    (SURVEY.md N13): P processes partition each global batch exactly."""
    ds = Dataset(np.zeros((128, 1, 1, 1), np.float32),
                 np.arange(128, dtype=np.int32))
    global_stream = [
        labels for _, labels in ShardedBatcher(ds, 32, seed=3).epoch(0)]
    per_proc = [
        [labels for _, labels in
         ShardedBatcher(ds, 32, seed=3, num_processes=4,
                        process_index=p).epoch(0)]
        for p in range(4)
    ]
    for step, glabels in enumerate(global_stream):
        shards = [per_proc[p][step] for p in range(4)]
        np.testing.assert_array_equal(np.concatenate(shards), glabels)


def test_batcher_reshuffles_per_epoch():
    ds = Dataset(np.zeros((64, 1, 1, 1), np.float32),
                 np.arange(64, dtype=np.int32))
    b = ShardedBatcher(ds, 64, seed=0)
    (_, e0), (_, e1) = next(iter(b.epoch(0))), next(iter(b.epoch(1)))
    assert not np.array_equal(e0, e1)


def test_batcher_validates():
    ds = Dataset(np.zeros((8, 1, 1, 1), np.float32), np.zeros(8, np.int32))
    with pytest.raises(ValueError):
        ShardedBatcher(ds, global_batch=3, num_processes=2)
    with pytest.raises(ValueError):
        ShardedBatcher(ds, global_batch=16)
