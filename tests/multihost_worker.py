"""Subprocess entry for tests/test_multihost.py — NOT a pytest module.

One worker process of a 2-process CPU "cluster": the TPU-native
equivalent of launching the reference's differently-defaulted
ps/worker scripts on three VMs (mnist_python_m.py:146-161). Here every
process runs THIS same file; identity comes entirely from the
TPU_PROCESS_ID / TPU_NUM_PROCESSES / TPU_COORDINATOR_ADDRESS env vars
consumed by parallel.mesh.bootstrap -> jax.distributed.initialize.

Each process owns 4 virtual CPU devices (XLA_FLAGS set by the parent
test), so the global mesh is 8-wide; the full train() loop then
exercises the real multi-host code paths that a single-process run
never reaches:
  - bootstrap()'s jax.distributed.initialize branch,
  - ShardedBatcher's process-disjoint row slicing,
  - shard_batch's make_array_from_process_local_data branch,
  - process_slice() on the replicated eval batches,
  - chief-only logging and checkpoint writes.

Writes a JSON result (final metrics + a params checksum) for the
parent test to compare against its single-process 8-device baseline.
"""

import json
import os
import sys


def run_xaxes_scenarios(fetch):
    """Cross-process PIPELINE and EXPERT axis scenarios — THE shared
    definition run by both the 2-process workers and the parent test's
    single-process oracle, so the two can never drift apart. With
    data=1/pipe=8 the 1F1B schedule's per-tick activation/cotangent
    ppermutes cross the process boundary (the DCN analog of NCCL P2P);
    with expert=8 the MoE dispatch/combine all_to_alls do.

    ``fetch(params) -> host pytree``: checkpoint._fetch_host in the
    cluster (collective; params span processes), jax.device_get in the
    single-process oracle. Returns {pipe_loss, pipe_checksum,
    expert_loss, expert_checksum}.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.models.transformer import moe_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        mlm_batch_shardings, moe_loss)

    ds = synthetic_clm(n=64, seq_len=16, vocab_size=64, seed=0)

    def checksum(params):
        return float(sum(abs(x).sum()
                         for x in jax.tree_util.tree_leaves(fetch(params))))

    def run(mesh, model, step):
        state = create_train_state(model, optax.adam(1e-3),
                                   np.zeros((2, 16), np.int32), mesh)
        for i in range(3):
            state, m = step(state, shard_batch(
                mesh, ds.batch(np.arange(16 * i, 16 * (i + 1))),
                seq_axis=1))
        return float(jax.device_get(m["loss"])), checksum(state.params)

    mesh_p = make_mesh(MeshConfig(data=1, pipe=8))
    model_p = pipelined_lm(mesh_p, num_microbatches=8, n_layers=8,
                           max_len=16, use_flash=False,
                           compute_dtype=jnp.float32, dropout_rate=0.0)
    pipe_loss, pipe_sum = run(
        mesh_p, model_p, make_1f1b_train_step(model_p, mesh_p,
                                              donate=False))

    mesh_e = make_mesh(MeshConfig(data=1, expert=8))
    model_e = moe_lm(mesh_e, size="tiny", moe_experts=8, max_len=16,
                     compute_dtype=jnp.float32, dropout_rate=0.0)
    expert_loss, expert_sum = run(
        mesh_e, model_e, make_train_step(
            mesh_e, loss=moe_loss, donate=False,
            batch_shardings=mlm_batch_shardings(mesh_e)))

    return {"pipe_loss": pipe_loss, "pipe_checksum": pipe_sum,
            "expert_loss": expert_loss, "expert_checksum": expert_sum}


def run_fusedce_scenario(fetch):
    """Fused-CE (Pallas kernel formulation) with the token axes
    spanning BOTH processes: the loss's shard_map runs the per-device
    kernel on each process's (data, seq) shard and psums the CE /
    correct / mask reductions across the process boundary. Shared
    definition for workers and the single-process oracle (same
    pattern as run_xaxes_scenarios)."""
    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings)

    mesh = make_mesh(MeshConfig(data=4, seq=2))
    model = gpt_lm(mesh, size="tiny", max_len=16, dropout_rate=0.0,
                   compute_dtype=jax.numpy.float32)
    step = make_train_step(
        mesh, donate=False,
        loss=make_mlm_loss(ce_chunk=48, ce_impl="kernel", mesh=mesh),
        batch_shardings=mlm_batch_shardings(mesh))
    # Init sample: batch dim must divide the data axis (ring
    # attention's shard_map slices it).
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((4, 16), np.int32), mesh)
    ds = synthetic_clm(n=64, seq_len=16, vocab_size=64, seed=0)
    for i in range(3):
        state, m = step(state, shard_batch(
            mesh, ds.batch(np.arange(16 * i, 16 * (i + 1))),
            seq_axis=1))
    checksum = float(sum(abs(x).sum()
                         for x in jax.tree_util.tree_leaves(
                             fetch(state.params))))
    return {"fusedce_loss": float(jax.device_get(m["loss"])),
            "fusedce_accuracy": float(jax.device_get(m["accuracy"])),
            "fusedce_checksum": checksum}


def run_r5_scenarios(fetch):
    """Round-5 composition scenarios across the process boundary —
    shared worker/oracle definition (same pattern as
    run_xaxes_scenarios).

    ring-in-pipe: data=1/pipe=4/seq=2 — with 2 processes x 4 devices
    the PIPE axis crosses the boundary, so the 1F1B schedule's
    per-tick ppermutes (where-masked bubbles: the stage carries the
    ring's seq collectives) hop DCN while the nested ring's seq
    ppermutes run inside each process.

    zero1-pipe: data=2/pipe=4 — the DATA axis crosses the boundary,
    so the ZeRO-1 slot shards and the update's restore-layout
    allgather span processes while the schedule runs intra-process.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    ds = synthetic_clm(n=64, seq_len=16, vocab_size=64, seed=0)

    def checksum(params):
        return float(sum(abs(x).sum()
                         for x in jax.tree_util.tree_leaves(fetch(params))))

    def run(mesh, model, step):
        state = create_train_state(model, optax.adam(1e-3),
                                   np.zeros((2, 16), np.int32), mesh)
        for i in range(3):
            state, m = step(state, shard_batch(
                mesh, ds.batch(np.arange(16 * i, 16 * (i + 1))),
                seq_axis=1))
        return float(jax.device_get(m["loss"])), checksum(state.params)

    mesh_rs = make_mesh(MeshConfig(data=1, pipe=4, seq=2))
    model_rs = pipelined_lm(mesh_rs, num_microbatches=4, n_layers=4,
                            max_len=16, use_flash=False, pos_emb="rope",
                            compute_dtype=jnp.float32, dropout_rate=0.0)
    ring_loss, ring_sum = run(
        mesh_rs, model_rs, make_1f1b_train_step(model_rs, mesh_rs,
                                                donate=False))

    mesh_z = make_mesh(MeshConfig(data=2, pipe=4))
    model_z = pipelined_lm(mesh_z, num_microbatches=4, n_layers=4,
                           max_len=16, use_flash=False,
                           compute_dtype=jnp.float32, dropout_rate=0.0)
    state_z = create_train_state(model_z, optax.adam(1e-3),
                                 np.zeros((2, 16), np.int32), mesh_z,
                                 opt_fsdp=True, fsdp_min_size=1024)
    pos_z = jax.tree_util.tree_map(lambda a: a.sharding, state_z.params)
    step_z = make_1f1b_train_step(model_z, mesh_z, donate=False,
                                  params_out_shardings=pos_z)
    for i in range(3):
        state_z, m_z = step_z(state_z, shard_batch(
            mesh_z, ds.batch(np.arange(16 * i, 16 * (i + 1))),
            seq_axis=1))
    zero1_loss = float(jax.device_get(m_z["loss"]))
    zero1_sum = checksum(state_z.params)

    return {"ring_pipe_loss": ring_loss, "ring_pipe_checksum": ring_sum,
            "zero1_pipe_loss": zero1_loss,
            "zero1_pipe_checksum": zero1_sum}


def main() -> None:
    out_path = sys.argv[1]
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    def checksum(state):
        import jax as _jax
        params = _jax.device_get(state.params)
        return float(sum(abs(x).sum()
                         for x in _jax.tree_util.tree_leaves(params)))

    phase = os.environ.get("MH_PHASE", "")
    if phase == "xaxes":
        from tensorflow_distributed_tpu.parallel.mesh import bootstrap
        from tensorflow_distributed_tpu.train.checkpoint import _fetch_host

        bootstrap()
        with open(out_path, "w") as f:
            json.dump(run_xaxes_scenarios(_fetch_host), f)
        return
    if phase == "fusedce":
        from tensorflow_distributed_tpu.parallel.mesh import bootstrap
        from tensorflow_distributed_tpu.train.checkpoint import _fetch_host

        bootstrap()
        with open(out_path, "w") as f:
            json.dump(run_fusedce_scenario(_fetch_host), f)
        return
    if phase == "r5":
        from tensorflow_distributed_tpu.parallel.mesh import bootstrap
        from tensorflow_distributed_tpu.train.checkpoint import _fetch_host

        bootstrap()
        with open(out_path, "w") as f:
            json.dump(run_r5_scenarios(_fetch_host), f)
        return
    if phase == "orbax":
        # Orbax checkpointing with FSDP params sharded ACROSS the
        # process boundary: every process writes and restores ITS OWN
        # shards (no allgather — the backend's whole point), the chief
        # publishes the commit marker, and a same-cluster resume lands
        # exactly where an uninterrupted run does.
        base = dict(
            model="mnist_cnn", dataset="synthetic", batch_size=64,
            eval_every=0, log_every=0, eval_batch_size=128,
            checkpoint_dir=os.environ["MH_CKPT_DIR"],
            checkpoint_every=2, checkpoint_backend="orbax",
            param_partition="fsdp", compute_dtype="float32",
            dropout_rate=0.0, mesh=MeshConfig(data=8), seed=0)
        train(TrainConfig(**base, train_steps=4))
        result = train(TrainConfig(**base, train_steps=8, resume=True))
        from tensorflow_distributed_tpu.train.checkpoint import _fetch_host
        params = _fetch_host(result.state.params)
        with open(out_path, "w") as f:
            json.dump({
                "step": int(jax.device_get(result.state.step)),
                "final_metrics": {
                    k: float(v)
                    for k, v in result.final_metrics.items()},
                "params_checksum": float(sum(
                    abs(x).sum()
                    for x in jax.tree_util.tree_leaves(params))),
            }, f)
        return
    if phase == "local_sgd":
        # Local SGD with the 8 replicas spanning BOTH processes: the
        # replica-stacked step [8] is sharded across the process
        # boundary, so ckpt.host_step's index-before-device_get and
        # the stacked save/restore (collective fetch + per-process
        # shard placement) all execute cross-process — the exact
        # multi-host hazards round 4 hardened against. Train 3 steps
        # (stacked checkpoint at 3), resume to 6.
        base = dict(
            model="mnist_cnn", dataset="synthetic", batch_size=64,
            eval_every=0, log_every=0, eval_batch_size=128,
            checkpoint_dir=os.environ["MH_CKPT_DIR"],
            checkpoint_every=3, param_sync_every=2,
            compute_dtype="float32", dropout_rate=0.0,
            mesh=MeshConfig(data=8), seed=0)
        train(TrainConfig(**base, train_steps=3))
        result = train(TrainConfig(**base, train_steps=6, resume=True))
        with open(out_path, "w") as f:
            json.dump({
                "step": int(jax.device_get(result.state.step)),
                "final_metrics": {
                    k: float(v)
                    for k, v in result.final_metrics.items()},
                "params_checksum": checksum(result.state),
            }, f)
        return
    if phase == "fsdp":
        # FSDP with the data axis spanning BOTH processes: params and
        # Adam slots are sharded across the process boundary, so the
        # checkpoint path must do a collective host fetch
        # (train.checkpoint._fetch_host) and restore must re-place via
        # per-process shard callbacks. Train 4 steps (checkpoints at 2
        # and 4), then resume IN the same cluster to step 8 — save and
        # restore both executed cross-process.
        base = dict(
            model="mnist_cnn", dataset="synthetic", batch_size=64,
            eval_every=0, log_every=0, eval_batch_size=128,
            checkpoint_dir=os.environ["MH_CKPT_DIR"],
            checkpoint_every=2, param_partition="fsdp",
            compute_dtype="float32", dropout_rate=0.0,
            mesh=MeshConfig(data=8), seed=0)
        train(TrainConfig(**base, train_steps=4))
        result = train(TrainConfig(**base, train_steps=8, resume=True))
        from tensorflow_distributed_tpu.train.checkpoint import _fetch_host
        params = _fetch_host(result.state.params)
        with open(out_path, "w") as f:
            json.dump({
                "step": int(jax.device_get(result.state.step)),
                "final_metrics": {
                    k: float(v)
                    for k, v in result.final_metrics.items()},
                "params_checksum": float(sum(
                    abs(x).sum()
                    for x in jax.tree_util.tree_leaves(params))),
            }, f)
        return
    if phase:
        # Crash-recovery scenario (SURVEY.md §5: the reference's
        # Supervisor re-attach): phase "crash" trains to step 5 with
        # checkpointing and exits — simulating whole-job loss, the
        # documented TPU fault model; phase "resume" restarts the SAME
        # cluster with --resume and finishes to step 10.
        cfg = TrainConfig(
            model="mnist_cnn", dataset="synthetic", batch_size=64,
            train_steps=5 if phase == "crash" else 10,
            eval_every=0, log_every=0, eval_batch_size=128,
            checkpoint_dir=os.environ["MH_CKPT_DIR"],
            checkpoint_every=5, resume=(phase == "resume"),
            compute_dtype="float32", dropout_rate=0.0,
            mesh=MeshConfig(data=8), seed=0)
        result = train(cfg)
        with open(out_path, "w") as f:
            json.dump({
                "step": int(jax.device_get(result.state.step)),
                "final_metrics": {
                    k: float(v)
                    for k, v in result.final_metrics.items()},
                "params_checksum": checksum(result.state),
            }, f)
        return

    cfg = TrainConfig(
        model="mnist_cnn", dataset="synthetic", batch_size=64,
        train_steps=6, eval_every=0, log_every=0, eval_batch_size=128,
        checkpoint_dir=os.environ["MH_CKPT_DIR"], checkpoint_every=0,
        compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8), seed=0)
    result = train(cfg)

    # Second scenario: ring attention with the SEQUENCE axis spanning
    # both processes (seq=8 over 2 x 4 local devices) — the zigzag
    # causal ring's ppermutes cross the process boundary, i.e. the
    # long-context path over "DCN" rather than intra-host ICI.
    lm_cfg = TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="synthetic",
        batch_size=16, train_steps=4, eval_every=0, log_every=0,
        eval_batch_size=32, compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=1, seq=8), seed=0)
    lm_result = train(lm_cfg)

    with open(out_path, "w") as f:
        json.dump({
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "step": int(jax.device_get(result.state.step)),
            "final_metrics": {k: float(v)
                              for k, v in result.final_metrics.items()},
            "params_checksum": checksum(result.state),
            "lm_final_metrics": {
                k: float(v)
                for k, v in lm_result.final_metrics.items()},
            "lm_params_checksum": checksum(lm_result.state),
        }, f)


if __name__ == "__main__":
    main()
