"""FSDP (ZeRO-style) param/optimizer sharding over the data axis.

The reference kept ONE full copy of the weights (on the ps CPU,
mnist_python_m.py:177) and streamed it to every worker every step;
plain SPMD data parallelism keeps a full copy on EVERY device. FSDP
(param_partition="fsdp") is the third point: each data-parallel device
holds 1/N of every large tensor and its Adam slots, and GSPMD inserts
the all-gather/reduce-scatter pair — same math, proven here by exact
parity with the replicated layout on the same batches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step


def _model():
    return MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)


def _state(mesh, fsdp):
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    return create_train_state(_model(), optax.adam(1e-3), x, mesh,
                              seed=0, fsdp=fsdp)


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def _shard_fractions(tree):
    """leaf path -> local shard elements / global elements."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "addressable_shards") or leaf.ndim == 0:
            continue
        local = leaf.addressable_shards[0].data.size
        out[jax.tree_util.keystr(path)] = local / leaf.size
    return out


def test_fsdp_shards_large_params_and_slots(mesh8):
    state = _state(mesh8, fsdp=True)
    pf = _shard_fractions(state.params)
    # The big tensors live 1/8-sharded; small ones stay replicated.
    sharded = {k for k, f in pf.items() if f == 1 / 8}
    assert any("fc1" in k and "kernel" in k for k in sharded), pf
    assert all(f == 1.0 for k, f in pf.items() if "bias" in k), pf
    # Adam m/v mirror the param placement (train.state slot matching).
    of = _shard_fractions(state.opt_state)
    assert any(f == 1 / 8 for f in of.values()), of


def test_fsdp_exact_parity_with_replicated(mesh8):
    """Same seed, same batches: fsdp and replicated layouts are the
    same training run — GSPMD's gather/scatter changes layout, not
    math."""
    s_rep = _state(mesh8, fsdp=False)
    s_fsdp = _state(mesh8, fsdp=True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), s_rep.params, s_fsdp.params)

    step = make_train_step(mesh8, donate=False)
    for i in range(3):
        batch = shard_batch(mesh8, _batch(seed=i))
        s_rep, m_rep = step(s_rep, batch)
        s_fsdp, m_fsdp = step(s_fsdp, batch)
        np.testing.assert_allclose(float(m_rep["loss"]),
                                   float(m_fsdp["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        s_rep.params, s_fsdp.params)
    assert int(s_fsdp.step) == 3


def test_zero1_shards_slots_only_with_exact_parity(mesh8):
    """ZeRO-1 (param_partition=\"zero1\"): params replicated, Adam m/v
    sharded over data — same training run as fully-replicated."""
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    s_z1 = create_train_state(_model(), optax.adam(1e-3), x, mesh8,
                              seed=0, opt_fsdp=True)
    pf = _shard_fractions(s_z1.params)
    assert all(f == 1.0 for f in pf.values()), pf  # params replicated
    of = _shard_fractions(s_z1.opt_state)
    assert any(f == 1 / 8 for f in of.values()), of  # slots sharded

    s_rep = _state(mesh8, fsdp=False)
    step = make_train_step(mesh8, donate=False)
    step_z1 = make_train_step(
        mesh8, donate=False,
        params_out_shardings=jax.tree_util.tree_map(
            lambda a: a.sharding, s_z1.params))
    for i in range(3):
        batch = shard_batch(mesh8, _batch(seed=i))
        s_rep, m_rep = step(s_rep, batch)
        s_z1, m_z1 = step_z1(s_z1, batch)
        np.testing.assert_allclose(float(m_rep["loss"]),
                                   float(m_z1["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=5e-6),
        s_rep.params, s_z1.params)
    # The defining layout invariant HOLDS THROUGH TRAINING: params are
    # still replicated after 3 steps (GSPMD would otherwise propagate
    # the slot sharding into them), slots still sharded.
    assert all(f == 1.0 for f in _shard_fractions(s_z1.params).values())
    assert any(f == 1 / 8
               for f in _shard_fractions(s_z1.opt_state).values())


def test_fsdp_composes_with_tensor_parallel(devices8):
    """On a data=4 x model=2 mesh, TP-annotated dims keep their axis
    and FSDP takes a *different* dim — both appear in the sharding."""
    from tensorflow_distributed_tpu.models.transformer import (
        BertMLM, tiny_config)
    from tensorflow_distributed_tpu.train.tasks import (
        mlm_batch_shardings, mlm_loss)
    from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_mlm

    mesh = make_mesh(MeshConfig(data=4, model=2), devices8)
    model = BertMLM(tiny_config(max_len=32), mesh)
    sample = np.zeros((2, 32), np.int32)
    # tiny-config tensors sit below the production FSDP_MIN_SIZE
    # threshold; lower it so the composition logic is exercised.
    state = create_train_state(model, optax.adam(3e-3), sample, mesh,
                               seed=0, fsdp=True, fsdp_min_size=1024)
    specs = {
        jax.tree_util.keystr(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_flatten_with_path(
            state.params)[0]}
    both = [s for s in specs.values()
            if "data" in jax.tree_util.tree_leaves(tuple(s))
            and "model" in jax.tree_util.tree_leaves(tuple(s))]
    assert both, specs

    step = make_train_step(mesh, loss=mlm_loss,
                           batch_shardings=mlm_batch_shardings(mesh),
                           donate=False)
    ds = synthetic_mlm(n=64, seq_len=32, vocab_size=64, seed=0)
    batch = shard_batch(
        mesh, LmBatcher(ds, 16, 0).forever(0).__next__(), seq_axis=1)
    state2, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state2.step) == 1


def test_fsdp_checkpoint_roundtrip(mesh8, tmp_path):
    from tensorflow_distributed_tpu.train import checkpoint as ckpt

    state = _state(mesh8, fsdp=True)
    step = make_train_step(mesh8, donate=False)
    state, _ = step(state, shard_batch(mesh8, _batch()))
    ckpt.save(str(tmp_path), state)

    fresh = _state(mesh8, fsdp=True)
    restored = ckpt.restore(str(tmp_path), fresh)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), state.params, restored.params)
    # Restored leaves keep the FSDP placement of the template.
    assert _shard_fractions(restored.params) == _shard_fractions(
        state.params)


def test_zero1_pipelined_1f1b_exact_parity(devices8):
    """ZeRO-1 composes with the hand-scheduled 1F1B pipeline (VERDICT
    r4 item 2): optimizer slots are consumed in tx.update OUTSIDE the
    pipe shard_map, so sharding them over "data" must not change the
    training run. Pinned: (a) slots data-sharded while params keep the
    pipe-only layout, (b) exact parity with the replicated layout over
    3 steps, (c) both layout invariants HOLD THROUGH TRAINING (the
    params_out_shardings constraint is what stops GSPMD propagating
    the slot sharding into the params)."""
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.tasks import mlm_batch_shardings

    mesh = make_mesh(MeshConfig(data=2, pipe=2), devices8[:4])
    model = pipelined_lm(mesh, num_microbatches=4, n_layers=4,
                         max_len=16, dropout_rate=0.0, use_flash=False,
                         compute_dtype=jnp.float32)
    sample = np.zeros((2, 16), np.int32)
    s_rep = create_train_state(model, optax.adam(1e-2), sample, mesh,
                               seed=0)
    s_z1 = create_train_state(model, optax.adam(1e-2), sample, mesh,
                              seed=0, opt_fsdp=True, fsdp_min_size=1024)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), s_rep.params, s_z1.params)
    # (a) slots sharded over data; params identical placement to rep.
    assert any(f < 1.0 for f in _shard_fractions(s_z1.opt_state).values())
    param_layout = _shard_fractions(s_z1.params)
    assert param_layout == _shard_fractions(s_rep.params)

    ds = synthetic_clm(n=64, seq_len=16, vocab_size=64)
    pos = jax.tree_util.tree_map(lambda a: a.sharding, s_z1.params)
    step = make_1f1b_train_step(model, mesh, donate=False,
                                batch_shardings=mlm_batch_shardings(mesh))
    step_z1 = make_1f1b_train_step(model, mesh, donate=False,
                                   batch_shardings=mlm_batch_shardings(mesh),
                                   params_out_shardings=pos)
    for i in range(3):
        batch = shard_batch(mesh, ds.batch(np.arange(i * 16, i * 16 + 16)),
                            seq_axis=1)
        s_rep, m_rep = step(s_rep, batch)
        s_z1, m_z1 = step_z1(s_z1, batch)
        np.testing.assert_allclose(float(m_rep["loss"]),
                                   float(m_z1["loss"]), rtol=1e-5)
    # (b) same params after 3 steps. atol covers Adam's 1/sqrt(v)
    # amplifying reduction-order float noise: the slot-sharded update
    # legitimately reassociates the moment math per data slice
    # (measured max |diff| ~1.2e-5 over 3 steps on the CPU mesh).
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=3e-5),
        s_rep.params, s_z1.params)
    # (c) layouts held: params pipe-only, slots still data-sharded.
    assert _shard_fractions(s_z1.params) == param_layout
    assert any(f < 1.0 for f in _shard_fractions(s_z1.opt_state).values())


def test_zero1_pipelined_cli_end_to_end(devices8):
    """--param-partition zero1 --model pipelined_lm trains through the
    full loop (the config wall narrowed to fsdp, VERDICT r4 item 2)."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      dataset="synthetic", batch_size=16, train_steps=3,
                      eval_every=0, log_every=0, eval_batch_size=16,
                      compute_dtype="float32", pipeline_schedule="1f1b",
                      param_partition="zero1",
                      mesh=MeshConfig(data=4, pipe=2))
    cfg.validate()
    result = train(cfg)
    assert np.isfinite(result.final_metrics["loss"])


def test_config_rejects_fsdp_pipelined():
    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      param_partition="fsdp",
                      mesh=MeshConfig(data=1, pipe=2))
    with pytest.raises(ValueError, match="fsdp"):
        cfg.validate()
    with pytest.raises(ValueError, match="param_partition"):
        TrainConfig(param_partition="zero9").validate()
