"""Optimizer construction: schedules, and the weight-decay mask.

The reference used plain Adam (mnist_python_m.py:208, SURVEY N12); the
decay path is beyond-reference and must follow the standard recipe:
decay matrices only — decaying norm scales fights the normalization.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import TrainConfig
from tensorflow_distributed_tpu.train.optim import (
    decay_mask, make_optimizer, make_schedule)


def test_decay_mask_matrices_only():
    params = {"dense": {"kernel": jnp.ones((4, 8)), "bias": jnp.ones(8)},
              "ln": {"scale": jnp.ones(8)},
              "emb": {"embedding": jnp.ones((16, 8))},
              # Name-based on purpose: a DenseGeneral bias is rank 3
              # and the pipelined family stacks norm scales to rank 3 —
              # a shape rule (ndim >= 2) would wrongly decay both.
              "attn": {"qkv": {"bias": jnp.ones((3, 4, 8))}},
              "stacked_ln": {"scale": jnp.ones((2, 6, 8))},
              "moe_mlp": {"wi": jnp.ones((4, 8, 16)),
                          "gate": jnp.ones((8, 4))}}
    m = decay_mask(params)
    assert m["dense"]["kernel"] and m["emb"]["embedding"]
    assert m["moe_mlp"]["wi"] and m["moe_mlp"]["gate"]
    assert not m["dense"]["bias"] and not m["ln"]["scale"]
    assert not m["attn"]["qkv"]["bias"]
    assert not m["stacked_ln"]["scale"]


@pytest.mark.parametrize("opt", ["adam", "adafactor"])
def test_weight_decay_skips_1d_params(opt):
    """With decay on, a zero-gradient step must shrink the kernel but
    leave the bias/scale untouched (beyond momentum noise: gradients
    are exactly zero, so any 1-D movement would be pure decay)."""
    cfg = TrainConfig(optimizer=opt, weight_decay=0.1,
                      learning_rate=1e-2, batch_size=32)
    tx = make_optimizer(cfg)
    params = {"kernel": jnp.ones((4, 4)), "bias": jnp.ones(4)}
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(grads, state, params)
    new = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    assert float(jnp.max(jnp.abs(new["bias"] - 1.0))) == 0.0
    assert float(jnp.max(jnp.abs(new["kernel"] - 1.0))) > 0.0


def test_schedules():
    cfg = TrainConfig(lr_schedule="warmup_cosine", warmup_steps=10,
                      train_steps=100, learning_rate=1e-3, batch_size=32)
    s = make_schedule(cfg)
    assert float(s(0)) == 0.0
    np.testing.assert_allclose(float(s(10)), 1e-3, rtol=1e-6)
    assert float(s(100)) < 1e-4
    with pytest.raises(ValueError, match="lr_schedule"):
        make_schedule(TrainConfig(lr_schedule="linear", batch_size=32))


def test_resume_across_decay_mask_change(tmp_path, mesh8):
    """A checkpoint written by the PRE-mask adamw (plain
    optax.adamw(wd): no MaskedState level in the chain) must restore
    into today's masked optimizer — the structural shim
    (checkpoint._align_masked_opt) inserts/strips the empty
    inner_state wrapper instead of crashing from_state_dict."""
    import optax

    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    from tensorflow_distributed_tpu.train.optim import make_optimizer
    from tensorflow_distributed_tpu.train.state import create_train_state

    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    cfg = TrainConfig(weight_decay=0.1, learning_rate=1e-3,
                      batch_size=32)
    old_state = create_train_state(
        # The exact pre-mask layout make_optimizer built: schedule'd
        # adamw WITHOUT the mask wrapper.
        model, optax.adamw(optax.constant_schedule(1e-3),
                           weight_decay=0.1),
        jnp.zeros((2, 28, 28, 1), jnp.float32), mesh8)
    ckpt.save(str(tmp_path), old_state)

    new_tmpl = create_train_state(
        model, make_optimizer(cfg),                  # masked layout
        jnp.zeros((2, 28, 28, 1), jnp.float32), mesh8, seed=1)
    restored = ckpt.restore(str(tmp_path), new_tmpl)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(restored.params), jax.device_get(old_state.params))
