"""Device-time attribution (observe/xprof.py) + trace.preload clock.

Fast tier is jax-free: canned Perfetto event lists through the parse/
attribution pipeline, plus the value-pinned ChromeTracer.preload
clock-shift test. One slow e2e captures a real profiler window on a
tiny GPT step and attributes it.
"""

import gzip
import json
import os

import pytest

from tensorflow_distributed_tpu.observe import xprof
from tensorflow_distributed_tpu.observe.trace import ChromeTracer


def _op(module, op, ts, dur, pid=1, tid=1):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
            "name": op, "args": {"hlo_module": module, "hlo_op": op}}


def _procname(pid, name):
    return {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name}}


def test_union_counts_concurrent_lanes_once():
    # Two ops overlapping [0,10) and [5,15) on different threads:
    # wall is the union (15), op_ms the sum (20).
    events = [_op("jit_p", "dot.1", 0, 10, tid=1),
              _op("jit_p", "dot.2", 5, 10, tid=2)]
    mods = xprof.attribute(events)["modules"]
    assert mods["jit_p"]["wall_us"] == 15.0
    assert mods["jit_p"]["op_us"] == 20.0
    assert mods["jit_p"]["ops"] == 2


def test_calls_is_modal_op_count_scan_ops_dont_inflate():
    # 3 invocations: two ops appear 3x each, one scan-body op 30x.
    events = []
    t = 0.0
    for i in range(3):
        events.append(_op("jit_p", "dot.1", t, 1))
        events.append(_op("jit_p", "add.2", t + 1, 1))
        t += 2
    for i in range(30):
        events.append(_op("jit_p", "while.body.mul", t, 0.1))
        t += 0.1
    assert xprof.attribute(events)["modules"]["jit_p"]["calls"] == 3


def test_collective_family_split_and_exposed():
    # all-reduce [0, 10); compute overlaps [0, 6) -> exposed = 4.
    events = [_op("jit_p", "all-reduce.1", 0, 10, tid=1),
              _op("jit_p", "fusion.2", 0, 6, tid=2),
              _op("jit_p", "all-gather.3", 20, 5, tid=1)]
    m = xprof.attribute(events)["modules"]["jit_p"]
    assert m["collective_us"] == 15.0
    assert m["exposed_collective_us"] == pytest.approx(9.0)
    assert m["collective_families"] == {"all_gather": 5.0,
                                        "all_reduce": 10.0}


def test_device_pid_filter_beats_host_mirror():
    events = [_procname(1, "/host:CPU"),
              _procname(2, "/device:TPU:0"),
              _op("jit_p", "dot.1", 0, 100, pid=1),   # host mirror
              _op("jit_p", "dot.1", 0, 7, pid=2)]     # device truth
    attr = xprof.attribute(events)
    assert attr["coarse"] is False
    assert attr["modules"]["jit_p"]["wall_us"] == 7.0


def test_coarse_without_device_process():
    events = [_procname(1, "/host:CPU"),
              _op("jit_p", "dot.1", 0, 5, pid=1)]
    assert xprof.attribute(events)["coarse"] is True


def test_match_program_exact_prefix_and_sanitized():
    programs = ["train_step", "serve_prefill_b16",
                "generate_n8_t0.7_k5_p1"]
    assert xprof.match_program("jit_train_step", programs) \
        == "train_step"
    assert xprof.match_program("jit_serve_prefill_b16", programs) \
        == "serve_prefill_b16"
    # The sanitized name is what the module carries (dots -> _).
    assert xprof.match_program("jit_generate_n8_t0_7_k5_p1",
                               programs) == "generate_n8_t0.7_k5_p1"
    # Numeric suffixes a lowering may append fall back to the prefix.
    assert xprof.match_program("jit_train_step_1", programs) \
        == "train_step"
    assert xprof.match_program("jit_unrelated", programs) is None


def test_device_time_records_null_on_missing_trace(tmp_path):
    recs = xprof.device_time_records(str(tmp_path))
    assert len(recs) == 1
    rec = recs[0]
    # Explicit-null contract: every measurement field present and None.
    for field in xprof.DEVICE_TIME_FIELDS:
        assert rec[field] is None
    assert "no trace under" in rec["reason"]


def _write_trace(tmp_path, events, host="testhost"):
    run = tmp_path / "plugins" / "profile" / "2026_08_03_00_00_00"
    run.mkdir(parents=True)
    path = run / f"{host}.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(path)


def test_device_time_records_from_written_trace(tmp_path):
    events = [_procname(1, "/host:CPU")]
    t = 0.0
    for _ in range(4):
        events.append(_op("jit_train_step", "dot.1", t, 100))
        events.append(_op("jit_train_step", "fusion.2", t + 100, 50))
        t += 1000
    _write_trace(tmp_path, events)
    recs = xprof.device_time_records(str(tmp_path),
                                     programs=["train_step"])
    assert len(recs) == 1
    rec = recs[0]
    assert rec["program"] == "train_step"
    assert rec["calls"] == 4
    assert rec["device_ms"] == pytest.approx(0.6)
    assert rec["device_ms_per_call"] == pytest.approx(0.15)
    assert rec["coarse"] is True


def test_device_time_records_newest_run_dir_wins(tmp_path):
    old = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    old.mkdir(parents=True)
    with gzip.open(old / "h.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": [_op("jit_old", "dot.1", 0, 1)]}, f)
    _write_trace(tmp_path, [_op("jit_new", "dot.1", 0, 1)])
    found = xprof.find_trace_file(str(tmp_path))
    assert "2026_08_03" in found


def test_device_time_unmatched_module_still_reported(tmp_path):
    _write_trace(tmp_path, [_op("jit_mystery", "dot.1", 0, 10)])
    recs = xprof.device_time_records(str(tmp_path),
                                     programs=["train_step"])
    assert recs[0]["program"] is None
    assert recs[0]["module"] == "jit_mystery"


def test_with_predictions_joins_roofline():
    from tensorflow_distributed_tpu.analysis.planner.score import (
        Hardware)

    hw = Hardware(platform="cpu", device_kind="x", peak_flops=1e9,
                  hbm_bw=1e9, ici_bw=1e9, calibration_id="cpu-abc")
    recs = [{"program": "train_step", "device_ms_per_call": 5.0},
            {"program": None, "module": "jit_z", "device_ms": 1.0}]
    costs = {"train_step": {"flops": 2e6, "bytes_accessed": 1e6}}
    out = xprof.with_predictions(recs, costs, hw)
    # max(2e6/1e9, 1e6/1e9) * 1e3 = 2.0 ms
    assert out[0]["predicted_ms_per_call"] == pytest.approx(2.0)
    assert out[0]["calibration_id"] == "cpu-abc"
    assert "predicted_ms_per_call" not in out[1]
    # hw=None passes through untouched.
    assert xprof.with_predictions(recs, costs, None) == recs


def test_with_predictions_includes_calibrated_overhead():
    from tensorflow_distributed_tpu.analysis.planner.score import (
        Hardware)

    hw = Hardware(platform="cpu", device_kind="x", peak_flops=1e9,
                  hbm_bw=1e9, ici_bw=1e9, overhead_ms=3.5)
    out = xprof.with_predictions(
        [{"program": "p", "device_ms_per_call": 9.0}],
        {"p": {"flops": 1e6, "bytes_accessed": 1e6}}, hw)
    assert out[0]["predicted_ms_per_call"] == pytest.approx(4.5)


# --- trace.preload clock shift (satellite: resume-leg counters) -------

def test_preload_clock_shift_keeps_counters_monotone(tmp_path):
    """Value-pinned: after preloading a dead leg's events (including
    counter tracks), the resumed tracer's FIRST new counter sample
    must land exactly gap_us after the last preloaded event's end —
    a resumed leg's counter track never runs backwards."""
    path = str(tmp_path / "t.json")
    fake_now = [100.0]
    prior = [
        {"ph": "C", "name": "slots", "pid": 0, "tid": 0,
         "ts": 1_000.0, "args": {"slots": 2}},
        {"ph": "X", "name": "decode_step", "cat": "serve", "pid": 0,
         "tid": 0, "ts": 2_000.0, "dur": 500.0},
        {"ph": "C", "name": "slots", "pid": 0, "tid": 0,
         "ts": 2_400.0, "args": {"slots": 3}},
    ]
    tracer = ChromeTracer(path, clock=lambda: fake_now[0])
    tracer.preload(prior, gap_us=1_000.0)
    # Clock has not advanced since construction: the new event's ts is
    # exactly (last preloaded end = 2000 + 500) + gap = 3500.
    tracer.counter("slots", slots=4)
    tracer.close()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C"]
    assert [c["ts"] for c in counters] == [1_000.0, 2_400.0, 3_500.0]
    # And with wall time advancing, later samples stay monotone.
    tracer2 = ChromeTracer(path, clock=lambda: fake_now[0])
    tracer2.preload(prior, gap_us=1_000.0)
    fake_now[0] += 0.25  # +250 ms wall
    tracer2.counter("slots", slots=5)
    tracer2.close()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    assert events[-1]["ts"] == pytest.approx(3_500.0 + 250_000.0)
    assert events[-1]["ts"] > max(e["ts"] for e in prior)


# --- slow: real capture -> parse -> attribution e2e -------------------

@pytest.mark.slow
def test_xprof_e2e_tiny_gpt_step(tmp_path):
    """Capture a profiler window around real tiny-GPT train steps and
    attribute the trace: train_step must come back with positive
    device time and a calls estimate matching the traced steps."""
    import jax
    import numpy as np
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models import transformer
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.state import (
        create_train_state)
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings)
    from tensorflow_distributed_tpu.utils.profiling import StepProfiler

    mesh = make_mesh(MeshConfig(data=1), jax.devices()[:1])
    model = transformer.gpt_lm(mesh=mesh, size="tiny", max_len=16,
                               dropout_rate=0.0)
    sample = np.zeros((2, 16), np.int32)
    state = create_train_state(model, optax.adam(1e-3), sample, mesh)
    step = make_train_step(mesh, loss=make_mlm_loss(),
                           batch_shardings=mlm_batch_shardings(mesh))
    batch = {"tokens": np.ones((2, 16), np.int32),
             "targets": np.ones((2, 16), np.int32),
             "mask": np.ones((2, 16), np.float32)}
    state, m = step(state, batch)  # compile outside the window
    jax.block_until_ready(m)
    prof = StepProfiler(log_dir=str(tmp_path), start_step=1,
                        num_steps=3)
    for i in range(1, 6):
        prof.observe(i, pending=m)
        state, m = step(state, batch)
    prof.stop(pending=m)
    assert prof.captured
    recs = xprof.device_time_records(str(tmp_path),
                                     programs=["train_step"])
    by_prog = {r["program"]: r for r in recs}
    assert "train_step" in by_prog, recs
    rec = by_prog["train_step"]
    assert rec["device_ms"] and rec["device_ms"] > 0
    assert rec["calls"] == 3
    assert rec["collective_ms"] == 0.0
