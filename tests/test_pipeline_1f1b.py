"""1F1B pipeline schedule: parity, memory bound, dropout, composition.

The correctness bar: 1F1B is a SCHEDULE change, not a math change —
its step must reproduce the GPipe step (same state, same batch) to
float tolerance, while compiling to materially less temp memory at
large microbatch counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.data.lm import synthetic_clm
from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.pipeline import bubble_fraction
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.pipeline_step import (
    make_1f1b_train_step)
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step
from tensorflow_distributed_tpu.train.tasks import (
    mlm_batch_shardings, mlm_loss)


def _setup(mesh, microbatches=8, batch=16, dropout=0.0, **kw):
    kw.setdefault("n_layers", 4)
    kw.setdefault("max_len", 16)
    model = pipelined_lm(mesh, num_microbatches=microbatches,
                         dropout_rate=dropout,
                         compute_dtype=jnp.float32, **kw)
    state = create_train_state(model, optax.adam(1e-2),
                               np.zeros((2, 16), np.int32), mesh)
    ds = synthetic_clm(n=max(2 * batch, 32), seq_len=16, vocab_size=64)
    b = shard_batch(mesh, ds.batch(np.arange(batch)), seq_axis=1)
    return model, state, b


def test_1f1b_matches_gpipe(devices8):
    """Same state, same batch: 1F1B step == GPipe step (loss, metrics,
    updated params) to float tolerance."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model, state, batch = _setup(mesh)
    step_g = make_train_step(mesh, loss=mlm_loss,
                             batch_shardings=mlm_batch_shardings(mesh),
                             donate=False, grad_norm_metric=True)
    step_f = make_1f1b_train_step(model, mesh, donate=False,
                                  grad_norm_metric=True)
    st_g, met_g = step_g(state, batch)
    st_f, met_f = step_f(state, batch)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_g["loss"]), rtol=1e-5)
    # The hand-scheduled backward produces the SAME gradients — pinned
    # here via the global grad norm both schedules now report.
    np.testing.assert_allclose(float(met_f["grad_norm"]),
                               float(met_g["grad_norm"]), rtol=1e-4)
    np.testing.assert_allclose(float(met_f["accuracy"]),
                               float(met_g["accuracy"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_g.params, st_f.params)


@pytest.mark.parametrize("tie", [False, True])
def test_1f1b_fused_ce_matches_dense_head(devices8, tie):
    """ce_chunk > 0 swaps the last stage's dense head+loss for the
    chunked custom-VJP op INSIDE the scheduled head vjp — a loss-
    formulation change, not a math change: same batch + state must
    reproduce the dense 1F1B step (loss, accuracy, updated params),
    tied and untied heads both."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model, state, batch = _setup(mesh, tie_embeddings=tie,
                                 pos_emb="rope" if tie else "learned")
    dense_step = make_1f1b_train_step(model, mesh, donate=False)
    fused_step = make_1f1b_train_step(model, mesh, donate=False,
                                      ce_chunk=24)
    st_d, met_d = dense_step(state, batch)
    st_f, met_f = fused_step(state, batch)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_d["loss"]), rtol=1e-6)
    np.testing.assert_allclose(float(met_f["accuracy"]),
                               float(met_d["accuracy"]), rtol=1e-6)
    # Not bitwise: the fused op's streaming logsumexp reduces in a
    # different order than the dense one; Adam amplifies the last-ulp
    # grad differences on near-zero-grad params.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-3),
        st_d.params, st_f.params)


def test_gpipe_fused_ce_matches_dense_head(devices8):
    """The GPipe path reaches the fused loss through
    PipelinedLM.apply(features_only=True) — make_mlm_loss(ce_chunk)
    must reproduce the dense mlm_loss trajectory."""
    from tensorflow_distributed_tpu.train.tasks import make_mlm_loss

    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model, state, batch = _setup(mesh)
    dense = make_train_step(mesh, loss=mlm_loss, donate=False,
                            batch_shardings=mlm_batch_shardings(mesh))
    fused = make_train_step(mesh, loss=make_mlm_loss(ce_chunk=24),
                            donate=False,
                            batch_shardings=mlm_batch_shardings(mesh))
    st_d, met_d = dense(state, batch)
    st_f, met_f = fused(state, batch)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_d["loss"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-3),
        st_d.params, st_f.params)


def test_variant_residual_mask_splits_weights_from_activations():
    """The stash backward's hoist: residual leaves that are a pure
    function of params (weight matrices, their compute-dtype casts)
    must be flagged invariant — verified BEHAVIORALLY: leaves the mask
    calls invariant are bit-identical across different (x, m), leaves
    it calls variant include everything that moves. Dropout-mask
    residuals depend on the microbatch index through the key fold and
    must stay variant even though they don't depend on x."""
    from tensorflow_distributed_tpu.parallel.pipeline import (
        variant_residual_mask)

    base_key = jax.random.PRNGKey(7)
    params = {"w": jnp.linspace(0, 1, 64).reshape(8, 8)
              .astype(jnp.float32), "b": jnp.ones((8,), jnp.float32)}

    def stage(p, x, m):
        h = x @ p["w"].astype(jnp.bfloat16).astype(jnp.float32) + p["b"]
        keep = jax.random.bernoulli(
            jax.random.fold_in(base_key, m), 0.8, h.shape)
        return jnp.tanh(h) * keep

    def res_fn(p, x, m):
        _, vjp = jax.vjp(lambda pp, xx: stage(pp, xx, m), p, x)
        return jax.tree_util.tree_leaves(vjp)

    x1 = jnp.ones((4, 8), jnp.float32)
    x2 = 2.0 * x1
    mask = variant_residual_mask(res_fn, params, x1)
    ra = res_fn(params, x1, jnp.int32(0))
    rb = res_fn(params, x2, jnp.int32(1))
    assert len(mask) == len(ra)
    hoisted = [i for i, v in enumerate(mask) if not v]
    assert hoisted, "no leaf hoisted — the weight cast should be"
    for i in hoisted:
        np.testing.assert_array_equal(np.asarray(ra[i]),
                                      np.asarray(rb[i]))
    # Something must still be stashed (activations, dropout masks).
    assert any(mask)
    # The dropout mask moved with m at fixed x — the mask may not
    # call every moving leaf invariant.
    rc = res_fn(params, x1, jnp.int32(1))
    moved = [i for i in range(len(ra))
             if np.asarray(ra[i]).shape == np.asarray(rc[i]).shape
             and not np.array_equal(np.asarray(ra[i]),
                                    np.asarray(rc[i]))]
    assert all(mask[i] for i in moved)


def test_1f1b_stash_backward_matches_recompute(devices8):
    """backward="stash" (residual ring buffers, no forward recompute)
    is a memory/compute trade, not a math change: same batch + state
    must give the same loss and updated params as the default
    recompute backward, including with dropout active (the stashed
    residuals carry the forward-tick masks). On-chip outcome is in
    LMBENCH_r04_pipelined / PARITY.md: recompute WINS on v5e (the
    stash's HBM traffic costs more than re-running the stage forward
    on an underutilized MXU), so stash stays opt-in."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    # remat=True inside the stage: the vjp residual set shrinks to the
    # checkpoint-saved subset — the documented mitigation for stash's
    # memory cost — and must compose transparently (jax.vjp of a
    # rematted stage_fn just yields the smaller residual pytree).
    model, state, batch = _setup(mesh, dropout=0.2, remat=True)
    steps = {
        mode: make_1f1b_train_step(model, mesh, donate=False,
                                   backward=mode)
        for mode in ("recompute", "stash")}
    st_r, met_r = steps["recompute"](state, batch)
    st_s, met_s = steps["stash"](state, batch)
    assert float(met_r["loss"]) == pytest.approx(float(met_s["loss"]),
                                                 rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_r.params, st_s.params)


@pytest.mark.slow
def test_1f1b_temp_memory_bounded(devices8):
    """The point of 1F1B: compiled temp memory stays O(S) while GPipe's
    grows O(M). At M=16 the gap must be at least 3x (measured ~16x at
    M=32 on this backend)."""
    mesh = make_mesh(MeshConfig(data=1, pipe=2), devices8[:2])
    M = 16
    model = pipelined_lm(mesh, num_microbatches=M, n_layers=4,
                         max_len=64, d_model=64, d_ff=128,
                         dropout_rate=0.0, compute_dtype=jnp.float32)
    state = create_train_state(model, optax.adam(1e-2),
                               np.zeros((2, 64), np.int32), mesh)
    ds = synthetic_clm(n=32, seq_len=64, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(32)), seq_axis=1)
    step_g = make_train_step(mesh, loss=mlm_loss,
                             batch_shardings=mlm_batch_shardings(mesh),
                             donate=False)
    step_f = make_1f1b_train_step(model, mesh, donate=False)
    t_g = step_g.lower(state, batch).compile().memory_analysis()
    t_f = step_f.lower(state, batch).compile().memory_analysis()
    ratio = t_g.temp_size_in_bytes / t_f.temp_size_in_bytes
    assert ratio > 3.0, (
        f"1f1b should need far less temp memory: gpipe "
        f"{t_g.temp_size_in_bytes/1e6:.1f}MB vs 1f1b "
        f"{t_f.temp_size_in_bytes/1e6:.1f}MB ({ratio:.2f}x)")


@pytest.mark.slow
def test_1f1b_dropout_deterministic_and_active(devices8):
    """With dropout: the step is deterministic (same state+batch twice
    -> same result) and the masks are real (loss differs from the
    dropout-free model with identical params)."""
    mesh = make_mesh(MeshConfig(data=2, pipe=2), devices8[:4])
    model_d, state, batch = _setup(mesh, microbatches=4, dropout=0.3)
    step = make_1f1b_train_step(model_d, mesh, donate=False)
    _, met1 = step(state, batch)
    _, met2 = step(state, batch)
    assert float(met1["loss"]) == float(met2["loss"])

    model_n = pipelined_lm(mesh, num_microbatches=4, n_layers=4,
                           max_len=16, dropout_rate=0.0,
                           compute_dtype=jnp.float32)
    step_n = make_1f1b_train_step(model_n, mesh, donate=False)
    _, met_n = step_n(state, batch)
    assert float(met1["loss"]) != float(met_n["loss"])


def test_1f1b_composes_with_tp(devices8):
    """PP x TP x DP under 1F1B: mesh (data=2, pipe=2, model=2) produces
    the same step as (data=4, pipe=2) — TP is a layout, not math."""
    mesh_tp = make_mesh(MeshConfig(data=2, pipe=2, model=2), devices8)
    mesh_dp = make_mesh(MeshConfig(data=4, pipe=2), devices8)
    losses = []
    for mesh in (mesh_tp, mesh_dp):
        model, state, batch = _setup(mesh, microbatches=4)
        step = make_1f1b_train_step(model, mesh, donate=False)
        _, met = step(state, batch)
        losses.append(float(met["loss"]))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


def _layer_major(blocks, V):
    """Stage-stacked block leaves -> layer-major [n_layers, ...] so
    plain ([S, lps]) and interleaved ([S, V, lps], virtual stage
    j = v*S + s) layouts compare directly."""
    def one(p):
        if V == 1:
            return p.reshape(p.shape[0] * p.shape[1], *p.shape[2:])
        q = jnp.swapaxes(p, 0, 1)  # [V, S, lps, ...]; [v, s] = j=v*S+s
        return q.reshape(q.shape[0] * q.shape[1] * q.shape[2],
                         *q.shape[3:])
    return jax.tree_util.tree_map(one, blocks)


def test_interleaved_1f1b_matches_plain(devices8):
    """Interleaved virtual stages (VERDICT r4 item 4): the [S, V, lps]
    regrouping is a LAYOUT, not a math change. With the same per-layer
    weights (same init keys — regrouping happens after the per-layer
    vmap), the V=2 single-scan interleaved schedule must reproduce the
    plain 1F1B step: loss, accuracy, grad norm, and updated params
    (compared layer-major)."""
    mesh = make_mesh(MeshConfig(data=2, pipe=2), devices8[:4])
    kw = dict(n_layers=4, max_len=16, dropout_rate=0.0,
              compute_dtype=jnp.float32, use_flash=False)
    m_p = pipelined_lm(mesh, num_microbatches=8, **kw)
    m_i = pipelined_lm(mesh, num_microbatches=8, virtual_stages=2, **kw)
    sample = np.zeros((2, 16), np.int32)
    s_p = create_train_state(m_p, optax.adam(1e-2), sample, mesh)
    s_i = create_train_state(m_i, optax.adam(1e-2), sample, mesh)
    # Identical underlying layer weights despite different stackings.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        _layer_major(s_p.params["blocks"], 1),
        _layer_major(s_i.params["blocks"], 2))

    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)

    # Forward parity too (the GPipe/eval path chains V pipeline
    # passes over the chunk groups).
    lp = jax.jit(lambda v, t: m_p.apply(v, t))(
        {"params": s_p.params}, batch["tokens"])
    li = jax.jit(lambda v, t: m_i.apply(v, t))(
        {"params": s_i.params}, batch["tokens"])
    np.testing.assert_allclose(np.asarray(li), np.asarray(lp),
                               atol=2e-5, rtol=2e-4)

    step_p = make_1f1b_train_step(m_p, mesh, donate=False,
                                  grad_norm_metric=True)
    step_i = make_1f1b_train_step(m_i, mesh, donate=False,
                                  grad_norm_metric=True)
    st_p, met_p = step_p(s_p, batch)
    st_i, met_i = step_i(s_i, batch)
    np.testing.assert_allclose(float(met_i["loss"]),
                               float(met_p["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_i["accuracy"]),
                               float(met_p["accuracy"]), rtol=1e-6)
    np.testing.assert_allclose(float(met_i["grad_norm"]),
                               float(met_p["grad_norm"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        _layer_major(st_p.params["blocks"], 1),
        _layer_major(st_i.params["blocks"], 2))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_p.params["shell"], st_i.params["shell"])


@pytest.mark.slow
def test_interleaved_ring_matches_plain(devices8):
    """The full composition stack: interleaved virtual stages x ring
    attention (pipe=2 x seq=2 x V=2) — the interleaved schedule's
    where-masked bubble mode (seq collectives can't live under
    cond-skipped branches) must reproduce plain 1F1B on the same
    mesh."""
    mesh = make_mesh(MeshConfig(pipe=2, seq=2), devices8[:4])
    kw = dict(n_layers=4, max_len=16, dropout_rate=0.0,
              compute_dtype=jnp.float32, use_flash=False,
              pos_emb="rope")
    m_p = pipelined_lm(mesh, num_microbatches=4, **kw)
    m_i = pipelined_lm(mesh, num_microbatches=4, virtual_stages=2, **kw)
    sample = np.zeros((2, 16), np.int32)
    s_p = create_train_state(m_p, optax.adam(1e-2), sample, mesh)
    s_i = create_train_state(m_i, optax.adam(1e-2), sample, mesh)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
    step_p = make_1f1b_train_step(m_p, mesh, donate=False)
    step_i = make_1f1b_train_step(m_i, mesh, donate=False)
    _, met_p = step_p(s_p, batch)
    _, met_i = step_i(s_i, batch)
    np.testing.assert_allclose(float(met_i["loss"]),
                               float(met_p["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_i["accuracy"]),
                               float(met_p["accuracy"]), rtol=1e-6)


def test_interleaved_cli_end_to_end(devices8):
    """--pipeline-virtual-stages 2 trains through the full loop."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      dataset="synthetic", batch_size=16, train_steps=3,
                      eval_every=0, log_every=0, eval_batch_size=16,
                      compute_dtype="float32", pipeline_schedule="1f1b",
                      pipeline_virtual_stages=2,
                      pipeline_microbatches=4,
                      mesh=MeshConfig(data=4, pipe=2))
    cfg.validate()
    result = train(cfg)
    assert np.isfinite(result.final_metrics["loss"])


def test_interleaved_config_walls():
    """virtual stages: rejected off-family, with stash backward, and
    with too few microbatches."""
    with pytest.raises(ValueError, match="pipelined_lm"):
        TrainConfig(model="gpt_lm",
                    pipeline_virtual_stages=2).validate()
    with pytest.raises(ValueError, match="recompute"):
        TrainConfig(model="pipelined_lm", pipeline_schedule="1f1b",
                    pipeline_virtual_stages=2,
                    pipeline_backward="stash",
                    mesh=MeshConfig(pipe=2)).validate()
    with pytest.raises(ValueError, match="virtual"):
        TrainConfig(model="pipelined_lm", pipeline_schedule="1f1b",
                    pipeline_virtual_stages=4,
                    pipeline_microbatches=4, batch_size=32,
                    mesh=MeshConfig(pipe=2)).validate()


@pytest.mark.slow
def test_1f1b_trains_end_to_end(devices8):
    """The full loop with pipeline_schedule=1f1b learns the synthetic
    progression well above chance (the GPipe twin of this test is
    test_pipeline.py::test_pipelined_lm_trains)."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      dataset="synthetic", batch_size=32, train_steps=40,
                      eval_every=0, log_every=0, eval_batch_size=32,
                      compute_dtype="float32", learning_rate=3e-3,
                      dropout_rate=0.0, pipeline_schedule="1f1b",
                      mesh=MeshConfig(data=4, pipe=2))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.35, result.final_metrics


@pytest.mark.slow
def test_pipelined_moe_aux_collected_and_schedules_agree(devices8):
    """The router-collapse trap (VERDICT r02 weak #3): a pipelined MoE
    must NOT silently drop the load-balancing loss. Checks: (a) the
    collected aux is positive and reported by both schedules, (b) the
    two schedules agree on metrics AND updated params — GPipe gets the
    aux gradient from plain AD through pipeline_apply, so 1F1B matching
    its params proves the hand-seeded aux cotangents are right too,
    (c) the router (gate) gradient is nonzero, which is exactly what a
    dropped aux loss would zero out on a uniform-logit router."""
    from tensorflow_distributed_tpu.train.tasks import make_moe_loss

    mesh = make_mesh(MeshConfig(data=2, pipe=2), devices8[:4])
    model, _, batch = _setup(mesh, microbatches=4, moe_experts=4)
    # SGD, not Adam: updates are lr * grad, so param parity below is a
    # direct gradient-parity assertion (Adam's 1/sqrt(v) normalizer
    # amplifies float-order noise on near-zero-gradient elements).
    state = create_train_state(model, optax.sgd(1e-2),
                               np.zeros((2, 16), np.int32), mesh)
    moe_loss = make_moe_loss(0.01, 1e-3)
    step_g = make_train_step(mesh, loss=moe_loss,
                             batch_shardings=mlm_batch_shardings(mesh),
                             donate=False)
    step_f = make_1f1b_train_step(model, mesh, donate=False,
                                  moe_aux_weight=0.01,
                                  moe_zloss_weight=1e-3)
    st_g, met_g = step_g(state, batch)
    st_f, met_f = step_f(state, batch)
    assert float(met_g["aux_loss"]) > 0.0
    np.testing.assert_allclose(float(met_f["aux_loss"]),
                               float(met_g["aux_loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_f["z_loss"]),
                               float(met_g["z_loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_f["loss"]),
                               float(met_g["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        st_g.params, st_f.params)
    # The gate moved: optimizer update implies a nonzero router grad.
    gate_before = state.params["blocks"]["moe_mlp"]["gate"]
    gate_after = st_f.params["blocks"]["moe_mlp"]["gate"]
    assert float(jnp.max(jnp.abs(
        nn_unbox(gate_after) - nn_unbox(gate_before)))) > 0.0


def nn_unbox(x):
    import flax.linen as nn
    return nn.meta.unbox(x)


@pytest.mark.slow
def test_pipelined_flash_attention_matches_xla(devices8, monkeypatch):
    """The Pallas kernel INSIDE the pipe shard_map: the attention
    dispatcher nests a shard_map over the auto (data/model) axes, so
    the Mosaic call sits in fully-manual axes (interpret mode off-TPU
    via TFD_FLASH_INTERPRET). Must reproduce the XLA-attention step:
    same loss, same updated params, PP x TP x DP mesh."""
    monkeypatch.setenv("TFD_FLASH_INTERPRET", "1")
    mesh = make_mesh(MeshConfig(data=2, pipe=2, model=2), devices8)
    models = {
        flash: pipelined_lm(mesh, num_microbatches=4, n_layers=4,
                            max_len=16, dropout_rate=0.0,
                            compute_dtype=jnp.float32, use_flash=flash)
        for flash in (True, False)}
    state = create_train_state(models[True], optax.sgd(1e-2),
                               np.zeros((2, 16), np.int32), mesh)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
    results = {}
    for flash, model in models.items():
        step = make_1f1b_train_step(model, mesh, donate=False)
        results[flash] = step(state, batch)
    np.testing.assert_allclose(float(results[True][1]["loss"]),
                               float(results[False][1]["loss"]),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        results[True][0].params, results[False][0].params)


def test_pipelined_small_factory():
    """size="small" is the GPT-2-small flagship config (VERDICT r02
    weak #5 asked for exactly this); construction is lazy so this is
    cheap — the on-chip run is recorded in LMBENCH_r03_pipelined."""
    import jax as _jax
    mesh = make_mesh(MeshConfig(data=1, pipe=1), _jax.devices("cpu")[:1])
    m = pipelined_lm(mesh, size="small", num_microbatches=8)
    assert (m.cfg.n_layers, m.cfg.d_model, m.cfg.n_heads) == (12, 768, 12)
    assert m.cfg.use_flash and m.cfg.causal
    assert m.num_microbatches == 8


def test_bubble_fraction():
    assert bubble_fraction(8, 1, "gpipe") == 0.0
    assert bubble_fraction(8, 4, "gpipe") == pytest.approx(3 / 11)
    assert bubble_fraction(8, 4, "1f1b") == pytest.approx(6 / 14)
    # More microbatches shrink the bubble for both schedules.
    assert bubble_fraction(64, 4, "1f1b") < bubble_fraction(8, 4, "1f1b")
    with pytest.raises(ValueError, match="schedule"):
        bubble_fraction(8, 4, "interleaved")


def test_1f1b_config_validation():
    cfg = TrainConfig(pipeline_schedule="zigzag")
    with pytest.raises(ValueError, match="pipeline_schedule"):
        cfg.validate()
    cfg = TrainConfig(pipeline_backward="checkpointless")
    with pytest.raises(ValueError, match="pipeline_backward"):
        cfg.validate()
    # Reject silently-ignored combinations (GPipe's backward is AD;
    # non-pipelined families have no schedule at all).
    cfg = TrainConfig(model="pipelined_lm", pipeline_schedule="gpipe",
                      pipeline_backward="stash")
    with pytest.raises(ValueError, match="applies only"):
        cfg.validate()
    cfg = TrainConfig(model="gpt_lm", pipeline_backward="stash")
    with pytest.raises(ValueError, match="applies only"):
        cfg.validate()
    cfg = TrainConfig(model="pipelined_lm", pipeline_schedule="1f1b",
                      grad_accum_steps=2, batch_size=256)
    with pytest.raises(ValueError, match="accumulates"):
        cfg.validate()
    # The exclusion is gated on the pipelined model: other families
    # keep grad accumulation under the (now default) 1f1b setting.
    TrainConfig(model="gpt_lm", grad_accum_steps=2,
                batch_size=256).validate()
