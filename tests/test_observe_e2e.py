"""Acceptance e2e for the observe/ subsystem: a CPU-only tiny-
transformer run produces a metrics JSONL with step-time breakdown and
MFU fields plus a valid Chrome trace, and observe.report summarizes
the JSONL without error."""

import json

import jax
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import (
    MeshConfig, ObserveConfig, TrainConfig)
from tensorflow_distributed_tpu.observe import report
from tensorflow_distributed_tpu.observe.trace import load_trace
from tensorflow_distributed_tpu.train.loop import train


def test_tiny_transformer_end_to_end_observed(tmp_path):
    jsonl = str(tmp_path / "metrics.jsonl")
    trace = str(tmp_path / "trace.json")
    cfg = TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="synthetic",
        batch_size=16, train_steps=20, eval_every=10, log_every=5,
        eval_batch_size=16, compute_dtype="float32", dropout_rate=0.0,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=10,
        mesh=MeshConfig(data=8),
        observe=ObserveConfig(metrics_jsonl=jsonl, trace=trace,
                              metrics_csv=str(tmp_path / "metrics.csv"),
                              peak_tflops=0.001))
    result = train(cfg)
    assert int(jax.device_get(result.state.step)) == 20

    records = [json.loads(line) for line in open(jsonl)]
    events = {r["event"] for r in records}
    assert {"start", "step", "eval", "summary"} <= events
    # Host tags on every record.
    assert all(r["process_index"] == 0 and "config_hash" in r
               and r["mesh"] == "data=8" for r in records)

    steps = [r for r in records if r["event"] == "step"]
    assert steps, "no step records emitted"
    windowed = steps[-1]
    # Step-time breakdown fields (rolling window).
    for key in ("data_ms", "dispatch_ms", "device_ms", "step_ms_p50",
                "step_ms_p95"):
        assert key in windowed, f"missing {key} in {sorted(windowed)}"
    # Throughput/MFU fields (peak_tflops was configured).
    assert windowed["tokens_per_sec"] > 0
    assert windowed["model_tflops"] > 0
    assert windowed["mfu"] > 0

    summary = [r for r in records if r["event"] == "summary"][-1]
    assert 0 <= summary["goodput"] <= 1
    assert summary["checkpoint_seconds"] > 0  # cadence + final saves
    assert summary["eval_seconds"] > 0
    assert summary["steps"] == 20 and summary["preempted"] is False

    # Chrome trace: valid JSON, required keys, the host phases present.
    events_list = load_trace(trace)
    assert all("ph" in e and "name" in e for e in events_list)
    spans = [e for e in events_list if e["ph"] == "X"]
    assert all("ts" in s and "dur" in s for s in spans)
    names = {s["name"] for s in spans}
    assert {"data", "dispatch", "eval", "checkpoint",
            "compile"} <= names, names

    # The report tool regenerates the headline numbers from raw JSONL.
    assert report.main([jsonl]) == 0
    s = report.summarize(records)
    assert s["last_step"] == 20
    assert s["step_ms_p50"] > 0 and s["mean_mfu"] > 0
    assert s["goodput"] == summary["goodput"]

    # CSV sink: one row per step record, union header includes mfu.
    rows = list(open(tmp_path / "metrics.csv"))
    assert len(rows) == len(steps) + 1
    assert "mfu" in rows[0].split(",")


def test_vision_run_reports_images_per_sec(tmp_path):
    """The vision family flows through the same accountant with
    imgs/s + a real CNN FLOPs estimate (unit follows the task)."""
    jsonl = str(tmp_path / "metrics.jsonl")
    cfg = TrainConfig(
        dataset="synthetic", batch_size=128, train_steps=12,
        eval_every=0, log_every=4, eval_batch_size=128,
        compute_dtype="float32", mesh=MeshConfig(data=8),
        observe=ObserveConfig(metrics_jsonl=jsonl, peak_tflops=0.01))
    train(cfg)
    steps = [json.loads(line) for line in open(jsonl)
             if json.loads(line)["event"] == "step"]
    assert steps[-1]["images_per_sec"] > 0
    assert steps[-1]["mfu"] > 0


@pytest.mark.slow  # 158s on the CI box (jax.profiler capture startup
#                    dominates) — the single heaviest default-tier test
#                    before the round-6 curation moved it here
def test_profiler_window_closed_on_loop_exit(tmp_path):
    """Satellite regression: training that ends INSIDE the profiler's
    trace window must still finalize the trace (loop-exit stop), and
    stop() must be idempotent afterwards."""
    import glob
    import os

    from tensorflow_distributed_tpu.utils.profiling import StepProfiler

    profile_dir = str(tmp_path / "prof")
    cfg = TrainConfig(
        dataset="synthetic", batch_size=128, train_steps=8,
        eval_every=0, log_every=0, eval_batch_size=128,
        compute_dtype="float32", mesh=MeshConfig(data=8),
        profile_dir=profile_dir, profile_start_step=4,
        profile_num_steps=100)  # window extends past the last step
    train(cfg)
    files = glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                      recursive=True)
    assert files, "trace window left open at loop exit"
    StepProfiler(log_dir=profile_dir).stop()  # no-op, must not raise


def test_resumed_run_appends_to_jsonl(tmp_path):
    """A preempt-restart leg (--resume with a restorable checkpoint)
    APPENDS to the prior leg's JSONL; a fresh run replaces. The append
    decision keys off an actual restorable checkpoint, not the flag —
    schedulers pass --resume on every leg including the first.

    Runs in a subprocess with one retry, same rationale as
    test_loop_cli.test_train_resume_roundtrip_async_checkpoints: the
    resume-with-checkpoint pattern intermittently SIGSEGVs the XLA:CPU
    runtime on this container (seed-reproducible), and an in-process
    crash would abort the whole suite."""
    import subprocess
    import sys

    jsonl = str(tmp_path / "m.jsonl")
    script = """
import json
from tensorflow_distributed_tpu.config import (
    MeshConfig, ObserveConfig, TrainConfig)
from tensorflow_distributed_tpu.train.loop import train

jsonl, ckpt_dir = %r, %r

def run(steps):
    train(TrainConfig(
        dataset="synthetic", batch_size=128, train_steps=steps,
        eval_every=0, log_every=4, eval_batch_size=128,
        compute_dtype="float32", mesh=MeshConfig(data=8),
        checkpoint_dir=ckpt_dir, checkpoint_every=4, resume=True,
        observe=ObserveConfig(metrics_jsonl=jsonl)))

run(8)   # first leg: nothing to restore -> fresh file
first = [json.loads(line) for line in open(jsonl)]
assert [r["event"] for r in first].count("start") == 1
assert not any(r["event"] == "resumed" for r in first)

run(12)  # second leg: restores -> appends
both = [json.loads(line) for line in open(jsonl)]
events = [r["event"] for r in both]
assert events.count("start") == 2, events
assert "resumed" in events
assert both[:len(first)] == first  # leg 1 records preserved
print("RESUME_APPEND_OK")
""" % (jsonl, str(tmp_path / "ckpt"))
    for attempt in (1, 2):
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True,
                              timeout=300)
        if proc.returncode == 0:
            assert "RESUME_APPEND_OK" in proc.stdout
            return
        if proc.returncode >= 0:  # real assertion failure: no retry
            break
    raise AssertionError(
        f"resume-append subprocess failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr[-2000:]}")


def test_observatory_closed_on_exception(tmp_path):
    """A run that dies mid-loop must still close the Observatory: the
    buffered CSV gets written, the trace is durable, and the process-
    global goodput counter is uninstalled (a later un-observed run
    must not charge time into a dead run's ledger)."""
    import pytest

    from tensorflow_distributed_tpu.observe import goodput

    csv_path = tmp_path / "metrics.csv"
    cfg = TrainConfig(
        dataset="synthetic", batch_size=128, train_steps=12,
        eval_every=0, log_every=2, eval_batch_size=128,
        compute_dtype="float32", mesh=MeshConfig(data=8),
        # checkpoint_dir is an existing FILE: the first cadence save's
        # makedirs raises, escaping the steady loop mid-run.
        checkpoint_dir=str(tmp_path / "not_a_dir"), checkpoint_every=4,
        observe=ObserveConfig(metrics_jsonl=str(tmp_path / "m.jsonl"),
                              metrics_csv=str(csv_path),
                              trace=str(tmp_path / "t.json")))
    (tmp_path / "not_a_dir").write_text("in the way")
    with pytest.raises(OSError):
        train(cfg)
    assert goodput.get_active() is None
    assert csv_path.exists(), "CSV sink never closed on exception"
    rows = list(open(csv_path))
    assert len(rows) >= 2  # header + at least one step row
    assert load_trace(str(tmp_path / "t.json"))  # trace durable too


def test_steptime_device_wait_appears_under_deep_dispatch(tmp_path):
    """With > 3 steps the loop's bounded async dispatch blocks on the
    oldest in-flight step — the device_wait phase must be recorded."""
    jsonl = str(tmp_path / "m.jsonl")
    cfg = TrainConfig(
        dataset="synthetic", batch_size=128, train_steps=10,
        eval_every=0, log_every=9, eval_batch_size=128,
        compute_dtype="float32", mesh=MeshConfig(data=8),
        observe=ObserveConfig(metrics_jsonl=jsonl))
    train(cfg)
    steps = [json.loads(line) for line in open(jsonl)
             if json.loads(line)["event"] == "step"]
    assert steps and steps[-1]["device_ms"] >= 0
    # No peak configured and no flops change nothing else: breakdown
    # fields still present without MFU.
    assert "step_ms_p50" in steps[-1]
