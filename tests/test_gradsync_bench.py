"""Tests for the grad-sync latency A/B (BASELINE.json metric).

Checks that (a) both probes run on an 8-device mesh, (b) the ps
emulation's averaged gradients are numerically identical to the psum
path's — i.e. the A/B compares two implementations of the *same* sync
semantics, which is what makes the latency comparison meaningful.
"""

import jax
import numpy as np
import optax

from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.collectives import (
    allreduce_latency_probe, make_per_shard_grads, ps_style_grad_sync,
    ps_style_sync_probe)
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state


def _state_and_batch(mesh):
    model = MnistCNN(compute_dtype=jax.numpy.float32, dropout_rate=0.0)
    state = create_train_state(
        model, optax.adam(1e-3), np.zeros((2, 28, 28, 1), np.float32), mesh)
    rng = np.random.default_rng(0)
    n = 2 * mesh.devices.size
    batch = shard_batch(mesh, (
        rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
        rng.integers(0, 10, size=(n,)).astype(np.int32)))
    return state, batch


def test_probes_run_and_time(mesh8):
    state, batch = _state_and_batch(mesh8)
    stacked = make_per_shard_grads(mesh8)(state, batch[0], batch[1])
    jax.block_until_ready(stacked)

    ps = ps_style_sync_probe(mesh8, stacked)
    ar = allreduce_latency_probe(mesh8, state.params)
    assert ps() > 0.0
    assert ar() > 0.0


def test_ps_emulation_matches_psum_mean(mesh8):
    """The ps round-trip and the on-device mean must agree: same sync
    semantics, different transport — the whole point of the A/B."""
    state, batch = _state_and_batch(mesh8)
    sync = ps_style_grad_sync(mesh8)
    ps_grads, dt = sync(state, batch)
    assert dt > 0.0

    stacked = make_per_shard_grads(mesh8)(state, batch[0], batch[1])
    want = jax.tree_util.tree_map(
        lambda g: np.asarray(g).mean(axis=0), stacked)
    got = jax.tree_util.tree_map(np.asarray, ps_grads)
    flat_w = jax.tree_util.tree_leaves(want)
    flat_g = jax.tree_util.tree_leaves(got)
    assert len(flat_w) == len(flat_g)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(w, g, rtol=1e-6, atol=1e-6)
