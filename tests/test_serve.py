"""Continuous-batching serving engine (serve/).

The load-bearing contract: engine outputs are TOKEN-IDENTICAL to
one-shot greedy generate() for every request — batching must not
change results. Plus: slot reuse after completion, the scheduler's
decode-priority starvation bound, bounded prefill program count, the
serve metrics artifact, the compile-cache counter, and the
compilecache override fix.

Scheduler-policy tests run against a fake host-side engine (no jax
compiles — they stay in the default tier); everything that compiles
the tiny GPT is marked slow per the repo's tier rules.
"""

from __future__ import annotations

import functools
import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.serve.buckets import (
    default_buckets, parse_buckets, pick_bucket)
from tensorflow_distributed_tpu.serve.scheduler import Request, Scheduler


# --- buckets (pure host) -----------------------------------------------

def test_bucket_ladder_and_pick():
    assert default_buckets(100, min_bucket=16) == (16, 32, 64, 128)
    assert default_buckets(16) == (16,)
    # The cap clamps the ladder to the cache length: no unusable
    # power-of-two overshoot past max_len.
    assert default_buckets(100, cap=100) == (16, 32, 64, 100)
    assert default_buckets(128, cap=128) == (16, 32, 64, 128)
    assert default_buckets(8, min_bucket=16, cap=8) == (8,)
    with pytest.raises(ValueError, match="exceeds the bucket cap"):
        default_buckets(100, cap=64)
    assert parse_buckets("8,32,64") == (8, 32, 64)
    assert pick_bucket(1, (16, 32)) == 16
    assert pick_bucket(17, (16, 32)) == 32
    with pytest.raises(ValueError):
        pick_bucket(33, (16, 32))
    with pytest.raises(ValueError):
        parse_buckets("64,32")  # not ascending
    with pytest.raises(ValueError):
        parse_buckets("a,b")


def test_serve_config_validation():
    from tensorflow_distributed_tpu.config import TrainConfig

    cfg = TrainConfig(mode="serve", model="gpt_lm")
    cfg.validate()
    bad = TrainConfig(mode="serve", model="mnist_cnn")
    with pytest.raises(ValueError, match="causal LM"):
        bad.validate()
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.serve.num_slots = 0
    with pytest.raises(ValueError, match="num_slots"):
        bad.validate()
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.serve.buckets = "64,16"
    with pytest.raises(ValueError, match="ascending"):
        bad.validate()
    # The TRAIN mesh flags keep their pure-data contract under serve;
    # sharding the replica is --serve.mesh-model's job (and the
    # rejection must say so).
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.mesh.model = 2
    with pytest.raises(ValueError, match="serve.mesh-model"):
        bad.validate()
    bad = TrainConfig(mode="serve", model="gpt_lm")
    bad.serve.mesh_model = 0
    with pytest.raises(ValueError, match="mesh_model"):
        bad.validate()


# --- compile-program cache counter (pure host) -------------------------

def test_compile_cache_counter():
    from tensorflow_distributed_tpu.models.generate import (
        compile_cache_stats, lookup_program)

    @functools.lru_cache(maxsize=8)
    def factory(key):
        return object()

    base = compile_cache_stats()
    a = lookup_program(factory, 1)          # miss
    b = lookup_program(factory, 1)          # hit
    c = lookup_program(factory, 2)          # miss
    assert a is b and c is not a
    now = compile_cache_stats()
    assert now["misses"] - base["misses"] == 2
    assert now["hits"] - base["hits"] == 1


def test_compile_cache_miss_emits_observe_record():
    from tensorflow_distributed_tpu.models.generate import lookup_program
    from tensorflow_distributed_tpu.observe import registry as reg

    @functools.lru_cache(maxsize=8)
    def factory2(key):
        return object()

    r = reg.MetricsRegistry()
    reg.set_active(r)
    try:
        lookup_program(factory2, 7)
    finally:
        reg.set_active(None)
    events = [x for x in r.records if x["event"] == "compile_cache"]
    assert len(events) == 1 and events[0]["result"] == "miss"
    assert events[0]["program"] == "factory2"


# --- compilecache respects an existing setting -------------------------

def test_persistent_cache_respects_existing_dir(tmp_path, monkeypatch):
    import jax

    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)

    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        mine = str(tmp_path / "my-xla-cache")
        jax.config.update("jax_compilation_cache_dir", mine)
        # A user-set dir survives the idempotent enable...
        assert enable_persistent_cache() == mine
        assert jax.config.jax_compilation_cache_dir == mine
        # ...env var is honored when jax.config is unset...
        jax.config.update("jax_compilation_cache_dir", None)
        env_dir = str(tmp_path / "env-xla-cache")
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", env_dir)
        assert enable_persistent_cache() == env_dir
        # ...and an explicit path still wins over both.
        explicit = str(tmp_path / "explicit")
        assert enable_persistent_cache(explicit) == explicit
        assert jax.config.jax_compilation_cache_dir == explicit
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


# --- scheduler policy against a fake engine (no compiles) --------------

class _FakeEngine:
    """Host-only stand-in with the SlotDecodeEngine surface the
    scheduler drives: deterministic token stream (rid*100 + step)."""

    def __init__(self, num_slots=2, max_len=256):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (32, 64)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])  # tests encode rid in the prompt head
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = 0
        self.prefills += 1
        return rid * 100

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                self.counts[rid] += 1
                out[s] = rid * 100 + self.counts[rid]
        self.decode_steps += 1
        return out

    def free(self, slot):
        self.active[slot] = False


def _fake_requests(n, max_new=6):
    return [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def test_scheduler_fifo_and_tokens():
    eng = _FakeEngine(num_slots=2)
    done = Scheduler(eng, decode_priority=3).run(_fake_requests(5))
    assert len(done) == 5
    by_rid = {c.rid: c for c in done}
    for rid, c in by_rid.items():
        assert c.tokens == [rid * 100 + j for j in range(6)]
        assert c.finish == "length"
    # FIFO: a later request never FINISHES before an earlier one
    # STARTS (2 slots, equal lengths => finish order is start order).
    finish_order = [c.rid for c in done]
    assert finish_order == sorted(finish_order)


def test_scheduler_starvation_bound():
    K = 3
    eng = _FakeEngine(num_slots=2)
    done = Scheduler(eng, decode_priority=K).run(
        _fake_requests(7, max_new=9))
    # Head-of-line bound: no request waited more than K decode steps
    # once it was admittable (queue head + free slot).
    assert max(c.queue_steps for c in done) <= K
    assert eng.decode_steps > 0 and eng.prefills == 7


def test_scheduler_eos_and_budget_1():
    eng = _FakeEngine(num_slots=2)
    reqs = [Request(rid=0, prompt=np.asarray([0], np.int32),
                    max_new_tokens=8, eos_id=2),   # token 2 at step 2
            Request(rid=1, prompt=np.asarray([1], np.int32),
                    max_new_tokens=1),             # budget-1: prefill only
            Request(rid=3, prompt=np.asarray([3], np.int32),
                    max_new_tokens=4, eos_id=300)]  # eos IS first token
    done = {c.rid: c for c in Scheduler(eng, decode_priority=2).run(reqs)}
    assert done[0].finish == "eos" and done[0].tokens[-1] == 2
    assert done[1].finish == "length" and done[1].tokens == [100]
    assert done[3].finish == "eos" and done[3].tokens == [300]


def test_scheduler_streams_tokens():
    eng = _FakeEngine(num_slots=2)
    seen = []
    Scheduler(eng, decode_priority=2,
              on_token=lambda rid, tok, fin: seen.append(
                  (rid, tok, fin))).run(_fake_requests(3, max_new=3))
    for rid in range(3):
        toks = [(t, f) for r, t, f in seen if r == rid]
        assert [t for t, _ in toks] == [rid * 100 + j for j in range(3)]
        assert [f for _, f in toks] == [False, False, True]


def test_scheduler_rejects_oversized_request():
    eng = _FakeEngine(num_slots=2, max_len=16)
    with pytest.raises(ValueError, match="does not fit"):
        Scheduler(eng).run([Request(rid=0,
                                    prompt=np.zeros(10, np.int32),
                                    max_new_tokens=10)])


# --- observe.report serve summary (pure host) --------------------------

def test_report_summarizes_serve_records(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    path = tmp_path / "m.jsonl"
    recs = ([{"event": "serve_request", "rid": i, "ttft_ms": 10.0 + i,
              "tok_ms": 2.0, "queue_steps": 0} for i in range(10)]
            + [{"event": "serve_summary", "tokens_per_sec": 500.0,
                "mean_slot_occupancy": 0.9, "total_new_tokens": 320,
                "prefill_compiles": 3}])
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = summarize(load_records(str(path)))
    assert out["serve_requests"] == 10
    assert out["serve_ttft_ms_p50"] == pytest.approx(14.5, abs=1.0)
    assert out["serve_ttft_ms_p95"] == pytest.approx(19.0, abs=1.0)
    assert out["serve_tok_ms_mean"] == pytest.approx(2.0)
    assert out["serve_tokens_per_sec"] == 500.0
    assert out["serve_mean_slot_occupancy"] == 0.9
    assert out["serve_prefill_compiles"] == 3


# --- the real engine (compiles the tiny GPT — slow tier) ---------------

def _tiny_lm():
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)

    model = CausalLM(tiny_config(causal=True,
                                 compute_dtype=jnp.float32))
    prompt = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    return model, params


@pytest.mark.slow
def test_serve_e2e_token_identical_and_metrics(tmp_path):
    """N mixed-length requests through the engine produce
    token-identical outputs to one-shot greedy generate() per request;
    slots are reused after completion; prefill programs stay within
    the bucket ladder; the metrics JSONL carries TTFT and tokens/s."""
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.observe.registry import (
        JsonlSink, MetricsRegistry)
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    model, params = _tiny_lm()
    rng = np.random.default_rng(0)
    lens = [3, 9, 17, 30, 5, 12]
    reqs = [Request(rid=i,
                    prompt=rng.integers(0, 64, size=L).astype(np.int32),
                    max_new_tokens=10) for i, L in enumerate(lens)]

    path = tmp_path / "serve.jsonl"
    registry = MetricsRegistry(sinks=[JsonlSink(str(path))])
    engine = SlotDecodeEngine(model, params, num_slots=3)
    sched = Scheduler(engine, decode_priority=3, registry=registry)
    done = {c.rid: c for c in sched.run(reqs)}
    registry.close()

    # Token-identical to the one-shot path, every request.
    for r in reqs:
        ref = np.asarray(generate(model, params,
                                  jnp.asarray(r.prompt[None, :]), 10))[0]
        np.testing.assert_array_equal(
            np.asarray(done[r.rid].tokens), ref,
            err_msg=f"request {r.rid} (prompt len {len(r.prompt)}) "
                    f"diverged from one-shot generate()")

    # Slot reuse: 6 requests through 3 slots.
    assert engine.prefills == 6 and engine.num_slots == 3
    # Bounded prefill programs (the acceptance criterion): distinct
    # compiled prefill executables <= bucket-ladder size.
    assert engine.prefill_compiles <= len(engine.buckets)
    # Starvation bound honored on the real engine too.
    assert max(c.queue_steps for c in done.values()) <= 3

    # Metrics artifact: per-request TTFT + an aggregate tokens/s.
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    req_recs = [r for r in recs if r["event"] == "serve_request"]
    assert len(req_recs) == 6
    assert all(r["ttft_ms"] > 0 and r["tok_ms"] > 0 for r in req_recs)
    summ = [r for r in recs if r["event"] == "serve_summary"]
    assert len(summ) == 1 and summ[0]["tokens_per_sec"] > 0
    assert 0 < summ[0]["mean_slot_occupancy"] <= 1


@pytest.mark.slow
def test_serve_mode_driver(tmp_path):
    """mode=serve end-to-end through config parsing and serve_run:
    synthetic workload, fresh-init params, JSONL artifact."""
    from tensorflow_distributed_tpu.config import parse_args
    from tensorflow_distributed_tpu.serve.run import serve_run

    path = tmp_path / "serve.jsonl"
    cfg = parse_args([
        "--mode", "serve", "--model", "gpt_lm", "--model-size", "tiny",
        "--serve.num-slots", "4", "--serve.num-requests", "6",
        "--serve.prompt-len-min", "4", "--serve.prompt-len-max", "20",
        "--serve.max-new-tokens", "8",
        "--observe.metrics-jsonl", str(path)])
    summary = serve_run(cfg)
    assert summary["requests"] == 6
    assert summary["total_new_tokens"] == 6 * 8
    assert summary["tokens_per_sec"] > 0
    assert summary["prefill_compiles"] <= len(
        summary["buckets"].split(","))
    assert path.exists()
