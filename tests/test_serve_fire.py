"""Serve-under-fire suite: fault-injected serving, proven not believed.

Fast tier (jax-free, per the repo's tier rules): serve-phase fault-plan
grammar + config phase validation, slot-retry policy against a fake
engine (token identity through quarantine, budgets, SlotRetryExhausted),
journal write/replay round-trips, supervisor serve-awareness, and the
report's recovery summary. Slow tier (compiles the tiny GPT): real-
engine slot-NaN containment token identity, live-swap token identity,
the mode=serve fire driver, serve exit codes, and the supervised
SIGKILL-with-journal-resume e2e.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tensorflow_distributed_tpu.resilience.faults import parse_fault_plan
from tensorflow_distributed_tpu.serve import journal as journal_mod
from tensorflow_distributed_tpu.serve.scheduler import (
    Request, Scheduler, SlotRetryExhausted)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- fault-plan grammar (serve kinds) -----------------------------------

def test_serve_fault_plan_grammar():
    plan = parse_fault_plan(
        "decode_stall@3:0.5s,slot_nan@5:1,reload@8,sigkill@12")
    assert plan.kinds() == {"decode_stall", "slot_nan", "reload",
                            "sigkill"}
    assert plan.take_slot_nan(4) is None
    assert plan.take_slot_nan(5) == 1
    assert plan.take_slot_nan(5) is None        # one-shot
    assert not plan.take_reload(7)
    assert plan.take_reload(8) and not plan.take_reload(8)
    # slot_nan default slot is 0.
    assert parse_fault_plan("slot_nan@2").take_slot_nan(2) == 0
    for bad in ("slot_nan@5:1.5", "reload@5:2", "decode_stall@5:0s",
                "slot_nan@0:1"):
        with pytest.raises(ValueError):
            parse_fault_plan(bad)


def test_fault_plan_phase_validation():
    from tensorflow_distributed_tpu.config import (
        ResilienceConfig, TrainConfig)

    ok = TrainConfig(mode="serve", model="gpt_lm",
                     checkpoint_dir="/tmp/x",
                     resilience=ResilienceConfig(
                         fault_plan="slot_nan@2:0,reload@4,sigkill@9"))
    ok.validate()
    with pytest.raises(ValueError, match="train-phase only"):
        TrainConfig(mode="serve", model="gpt_lm",
                    resilience=ResilienceConfig(
                        fault_plan="nan_grad@2")).validate()
    with pytest.raises(ValueError, match="serve-phase only"):
        TrainConfig(resilience=ResilienceConfig(
            fault_plan="slot_nan@2:0")).validate()
    with pytest.raises(ValueError, match="swap source"):
        TrainConfig(mode="serve", model="gpt_lm",
                    resilience=ResilienceConfig(
                        fault_plan="reload@4")).validate()
    with pytest.raises(ValueError, match="no injection points"):
        TrainConfig(mode="eval", model="gpt_lm", checkpoint_dir="/t",
                    resilience=ResilienceConfig(
                        fault_plan="sigterm@2")).validate()


def test_serve_fire_config_validation():
    from tensorflow_distributed_tpu.config import TrainConfig

    cfg = TrainConfig(mode="serve", model="gpt_lm")
    cfg.serve.trace = "bursty"
    with pytest.raises(ValueError, match="arrival_rate"):
        cfg.validate()
    cfg.serve.arrival_rate = 8.0
    cfg.validate()
    cfg.serve.trace = "lunar"
    with pytest.raises(ValueError, match="unknown serve.trace"):
        cfg.validate()
    cfg.serve.trace = ""
    cfg.serve.slot_retries = -1
    with pytest.raises(ValueError, match="slot_retries"):
        cfg.validate()
    cfg.serve.slot_retries = 2
    bad = TrainConfig(serve=cfg.serve)
    bad.serve.journal = "/tmp/j"
    with pytest.raises(ValueError, match="journal"):
        bad.validate()


# --- fake engine with fire surface (no jax) -----------------------------

class _FireFakeEngine:
    """Host-only engine with the fire surface the scheduler drives.
    Token stream is a pure function of (rid, tokens-emitted-so-far):
    prefill of a continuation prompt resumes the SAME stream, so token
    identity through quarantine/retry is checkable exactly. The rid
    rides prompt[0]; tokens count as len(prompt) - 1 (base prompts
    are length 1)."""

    def __init__(self, num_slots=2, max_len=256, spec_tokens=0):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (64, 128)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0
        self.verify_steps = 0
        self.spec_tokens = spec_tokens
        self.swaps = 0
        self.params = object()
        self._poisoned = set()

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = len(prompt) - 1   # continuation-aware
        self.prefills += 1
        self._poisoned.discard(slot)         # full-row overwrite
        return rid * 100 + self.counts[rid]

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        self._bad = []
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            if s in self._poisoned:
                out[s] = 999_999             # garbage, must be dropped
                self._bad.append(s)
                continue
            rid = self.slot_rid[s]
            self.counts[rid] += 1
            out[s] = rid * 100 + self.counts[rid]
        self.decode_steps += 1
        return out

    def can_verify(self):
        return self.spec_tokens > 0

    def verify_step(self, props):
        """Verify dispatch mirroring the real contract: [S, k+1]
        tokens, per-slot accepted+1 counts, and the per-slot ok flag
        surfaced through take_bad_slots — a poisoned slot's whole row
        is garbage THIS dispatch, exactly like non-finite logits under
        the real verify program."""
        k = self.spec_tokens
        toks = np.zeros((self.num_slots, k + 1), np.int32)
        acc = np.zeros((self.num_slots,), np.int32)
        self._bad = []
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            if s in self._poisoned:
                toks[s, :] = 999_999         # garbage, must be dropped
                acc[s] = k + 1
                self._bad.append(s)
                continue
            rid = self.slot_rid[s]
            for j in range(k + 1):
                self.counts[rid] += 1
                toks[s, j] = rid * 100 + self.counts[rid]
            acc[s] = k + 1
        self.decode_steps += 1
        self.verify_steps += 1
        return toks, acc

    def take_bad_slots(self):
        bad, self._bad = getattr(self, "_bad", []), []
        return bad

    def poison_slot(self, slot):
        self._poisoned.add(slot)

    def swap_params(self, new_params):
        self.params = new_params
        self.swaps += 1

    def free(self, slot):
        self.active[slot] = False
        self._poisoned.discard(slot)


def _reqs(n, max_new=8):
    return [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def _expected(rid, max_new, plen=1):
    # First token continues the stream from the prompt's implied depth.
    return [rid * 100 + (plen - 1) + j for j in range(max_new)]


def test_slot_retry_token_identity_and_budget():
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    plan = parse_fault_plan("slot_nan@3:0,slot_nan@7:1")
    eng = _FireFakeEngine(num_slots=2)
    reg = MetricsRegistry()
    sched = Scheduler(eng, decode_priority=3, registry=reg,
                      fault_plan=plan, slot_retries=2)
    done = {c.rid: c for c in sched.run(_reqs(5))}
    assert len(done) == 5
    for rid, c in done.items():
        assert c.tokens == _expected(rid, 8), f"rid {rid} drifted"
    # Two quarantines happened, each charged to its request.
    assert sched.summary["retries"] == 2
    assert sum(c.retries for c in done.values()) == 2
    quars = [r for r in reg.records
             if r.get("kind") == "slot_quarantine"]
    assert len(quars) == 2 and all("t_s" in q for q in quars)
    # Retried requests flag the recovery window in their records.
    assert any(r.get("recovery_window")
               for r in reg.records if r["event"] == "serve_request")


class _FakeSpeculator:
    """Proposal source for the fake verify path. Content is ignored —
    the fake engine's verify_step derives truth from its own stream —
    so this only has to satisfy the scheduler's speculator surface."""

    needs_histories = False

    def __init__(self, num_slots, k):
        self.num_slots, self.k = num_slots, k

    def propose(self, histories):
        return np.zeros((self.num_slots, self.k), np.int32)

    def observe_admit(self, slot, prompt, first_tok):
        pass

    def observe_free(self, slot):
        pass

    def sync_from(self, engine):
        pass

    def warmup(self):
        pass


def test_mid_verify_slot_retry_token_identity():
    """slot_nan fired while speculation is armed lands INSIDE a verify
    dispatch: the dispatch's own per-slot ok flag (take_bad_slots)
    quarantines, the whole garbage row is dropped before retirement,
    and the requeued continuation resumes the exact stream."""
    plan = parse_fault_plan("slot_nan@2:0,slot_nan@3:1")
    eng = _FireFakeEngine(num_slots=2, spec_tokens=3)
    sched = Scheduler(eng, decode_priority=3, fault_plan=plan,
                      slot_retries=2, speculator=_FakeSpeculator(2, 3))
    done = {c.rid: c for c in sched.run(_reqs(5))}
    assert len(done) == 5
    for rid, c in done.items():
        assert c.tokens == _expected(rid, 8), f"rid {rid} drifted"
    assert sched.summary["retries"] == 2
    # Every dispatch this engine took was a verify dispatch, so both
    # containments necessarily rode the verify program's ok flag —
    # never a separate probe step.
    assert eng.verify_steps == eng.decode_steps >= 1
    assert sched.summary["verify_steps"] == eng.verify_steps


def test_slot_retry_budget_exhausted_is_diverged():
    # Poison the same slot every consultable step: the same request
    # re-poisons past its budget -> SlotRetryExhausted (exit 2 at the
    # CLI), never a hot loop.
    plan = parse_fault_plan("slot_nan@2:0,slot_nan@4:0,slot_nan@6:0")
    eng = _FireFakeEngine(num_slots=1)
    sched = Scheduler(eng, decode_priority=2, fault_plan=plan,
                      slot_retries=1)
    with pytest.raises(SlotRetryExhausted, match="quarantined 2"):
        sched.run(_reqs(1, max_new=12))


def test_scheduler_reload_swaps_params():
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    plan = parse_fault_plan("reload@4")
    eng = _FireFakeEngine(num_slots=2)
    fresh = object()
    reg = MetricsRegistry()
    sched = Scheduler(eng, decode_priority=3, registry=reg,
                      fault_plan=plan,
                      reload_fn=lambda: (fresh, 7))
    done = {c.rid: c for c in sched.run(_reqs(3))}
    assert eng.params is fresh and eng.swaps == 1
    assert sched.summary["swaps"] == 1
    assert sched.summary["swap_seconds"] >= 0
    swaps = [r for r in reg.records if r.get("kind") == "weight_swap"]
    assert len(swaps) == 1 and swaps[0]["ckpt_step"] == 7
    # Traffic unaffected: token streams identical to unfaulted.
    for rid, c in done.items():
        assert c.tokens == _expected(rid, 8)


def test_scheduler_reload_without_fn_is_clear_error():
    plan = parse_fault_plan("reload@2")
    sched = Scheduler(_FireFakeEngine(), fault_plan=plan)
    with pytest.raises(ValueError, match="no reload_fn"):
        sched.run(_reqs(1))


# --- journal -------------------------------------------------------------

def test_journal_write_replay_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    eng = _FireFakeEngine(num_slots=2)
    sched = Scheduler(eng, decode_priority=3,
                      journal=journal_mod.RequestJournal(path))
    done = {c.rid: c for c in sched.run(_reqs(4, max_new=5))}
    played = journal_mod.replay(path)
    assert set(played) == {0, 1, 2, 3}
    for rid, ent in played.items():
        assert ent["done"]
        assert ent["tokens"] == done[rid].tokens
        assert ent["req"]["prompt"] == [rid]
        assert ent["req"]["max_new"] == 5


def test_journal_replay_tolerates_truncated_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = journal_mod.RequestJournal(path)
    j.admit(0, [0], 8, -1)
    j.token(0, 100, 0.1)
    j.close()
    with open(path, "a") as f:
        f.write('{"e": "tok", "rid": 0, "t": 1')   # the kill's tail
    played = journal_mod.replay(path)
    assert played[0]["tokens"] == [100] and not played[0]["done"]


def test_apply_replay_continuations_and_arrival_shift():
    import dataclasses

    reqs = [Request(rid=0, prompt=np.asarray([0], np.int32),
                    max_new_tokens=6),
            Request(rid=1, prompt=np.asarray([1], np.int32),
                    max_new_tokens=6),
            Request(rid=2, prompt=np.asarray([2], np.int32),
                    max_new_tokens=6, arrival_s=9.0),
            Request(rid=3, prompt=np.asarray([3], np.int32),
                    max_new_tokens=6, eos_id=305)]
    played = {
        0: {"req": None, "tokens": [100, 101, 102], "done": False,
            "last_s": 2.0},                      # in flight -> cont.
        1: {"req": None, "tokens": [100] * 6, "done": True,
            "last_s": 1.0},                      # finished -> drop
        3: {"req": None, "tokens": [303, 304, 305], "done": False,
            "last_s": 1.5},                      # eos tail -> drop
    }
    out = journal_mod.apply_replay(reqs, played)
    by_rid = {r.rid: r for r in out}
    assert set(by_rid) == {0, 2}
    cont = by_rid[0]
    assert list(cont.prompt) == [0, 100, 101, 102]
    assert cont.max_new_tokens == 3 and cont.arrival_s == 0.0
    assert cont._base_tokens == [100, 101, 102]
    # Untouched request's arrival shifts by the dead leg's elapsed
    # serving time (clients kept sending while the process was down).
    assert by_rid[2].arrival_s == pytest.approx(7.0)
    assert dataclasses.is_dataclass(cont)


def test_resumed_continuation_serves_to_token_identity(tmp_path):
    """The full resume path at the scheduler level: a journal says rid
    0 had 3 tokens in flight; the continuation re-enters and the FINAL
    completion reports the full, unfaulted token stream."""
    reqs = _reqs(2, max_new=7)
    played = {0: {"req": None, "tokens": _expected(0, 7)[:3],
                  "done": False, "last_s": 0.5}}
    narrowed = journal_mod.apply_replay(reqs, played)
    eng = _FireFakeEngine(num_slots=2)
    done = {c.rid: c for c in Scheduler(eng, decode_priority=2).run(
        narrowed)}
    assert done[0].tokens == _expected(0, 7)
    assert done[1].tokens == _expected(1, 7)
    assert done[0].prompt_len == 1      # base tokens excluded


# --- supervisor serve-awareness -----------------------------------------

def test_supervisor_leg_args_serve_vs_train():
    from tensorflow_distributed_tpu.resilience.supervisor import (
        build_leg_args)

    train_args = ["--checkpoint-dir", "/c", "--train-steps", "5"]
    assert "--resume" not in build_leg_args(train_args, 0)
    assert build_leg_args(train_args, 1)[-2:] == ["--resume", "true"]
    # Explicit user setting survives.
    explicit = train_args + ["--resume", "false"]
    assert build_leg_args(explicit, 2) == explicit
    # Serve children restart with the UNCHANGED command: continuity is
    # the journal, and --resume would even fail serve validation
    # without a checkpoint dir.
    serve_args = ["--mode", "serve", "--model", "gpt_lm",
                  "--serve.journal", "/tmp/j"]
    assert build_leg_args(serve_args, 3) == serve_args
    serve_ckpt = serve_args + ["--checkpoint-dir", "/c"]
    assert build_leg_args(serve_ckpt, 3) == serve_ckpt


# --- observe.report recovery summary ------------------------------------

def test_report_folds_recovery_into_serve_summary(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, render, summarize)

    path = tmp_path / "m.jsonl"
    recs = (
        [{"event": "serve_request", "rid": i, "ttft_ms": 10.0 + i,
          "tok_ms": 2.0, "recovery_window": i < 3} for i in range(10)]
        + [{"event": "recovery", "kind": "slot_quarantine", "rid": 1,
            "slot": 0, "retry": 1, "t_s": 0.4},
           {"event": "recovery", "kind": "weight_swap",
            "seconds": 0.21, "ckpt_step": 2, "t_s": 0.9},
           {"event": "recovery", "kind": "weight_swap",
            "seconds": 0.14, "ckpt_step": 4, "t_s": 1.7},
           {"event": "recovery", "kind": "fault_injected",
            "fault": "decode_stall", "step": 3, "seconds": 0.5}]
        + [{"event": "serve_summary", "tokens_per_sec": 500.0,
            "total_new_tokens": 320, "retries": 1, "swaps": 2,
            "swap_seconds": 0.35, "seed": 7, "trace": "bursty"}])
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = summarize(load_records(str(path)))
    assert out["recovery_counts"] == {"fault_injected": 1,
                                      "slot_quarantine": 1,
                                      "weight_swap": 2}
    assert out["swap_seconds_total"] == pytest.approx(0.35)
    assert out["serve_retries"] == 1 and out["serve_swaps"] == 2
    assert out["serve_seed"] == 7 and out["serve_trace"] == "bursty"
    assert out["serve_ttft_ms_p99"] == pytest.approx(19.0, abs=1.0)
    assert out["serve_recovery_requests"] == 3
    assert out["serve_ttft_ms_p99_recovery"] == pytest.approx(
        12.0, abs=1.0)
    text = render(out)
    assert "Recovery" in text and "slot_quarantine" in text


# --- the real engine under fire (slow tier) ------------------------------

def _tiny_lm():
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)

    model = CausalLM(tiny_config(causal=True,
                                 compute_dtype=jnp.float32))
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _mixed_requests(n=4, max_new=10):
    return [Request(rid=i,
                    prompt=np.random.default_rng(i).integers(
                        0, 64, size=L).astype(np.int32),
                    max_new_tokens=max_new)
            for i, L in enumerate([3, 9, 17, 5][:n])]


@pytest.mark.slow
def test_slot_nan_containment_token_identical():
    """A NaN-poisoned KV row is detected ON DEVICE, the slot
    quarantined and re-prefilled, and the final token streams are
    identical to the unfaulted run — one poisoned slot never costs an
    engine restart or a changed answer."""
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    model, params = _tiny_lm()
    base_eng = SlotDecodeEngine(model, params, num_slots=2)
    base = {c.rid: c.tokens
            for c in Scheduler(base_eng, decode_priority=3).run(
                _mixed_requests())}

    plan = parse_fault_plan("slot_nan@3:0,slot_nan@8:1")
    eng = SlotDecodeEngine(model, params, num_slots=2, fault_plan=plan)
    sched = Scheduler(eng, decode_priority=3, fault_plan=plan,
                      slot_retries=2)
    done = {c.rid: c for c in sched.run(_mixed_requests())}
    assert {r: c.tokens for r, c in done.items()} == base
    assert sched.summary["retries"] >= 1


@pytest.mark.slow
def test_spec_slot_nan_mid_verify_token_identical():
    """slot_nan under ARMED speculation: the poison is detected by the
    VERIFY program's per-slot finiteness flag (the same fetch that
    returns the verify tokens — no extra probe dispatch), the slot
    quarantined, and the final streams are identical to the plain
    greedy run. Containment composes with speculation, not around it."""
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.speculate import SelfDraft

    model, params = _tiny_lm()
    base_eng = SlotDecodeEngine(model, params, num_slots=2)
    base = {c.rid: c.tokens
            for c in Scheduler(base_eng, decode_priority=3).run(
                _mixed_requests())}

    k = 3
    plan = parse_fault_plan("slot_nan@2:0,slot_nan@4:1")
    eng = SlotDecodeEngine(model, params, num_slots=2, fault_plan=plan,
                           spec_tokens=k)
    sched = Scheduler(eng, decode_priority=3, fault_plan=plan,
                      slot_retries=2, speculator=SelfDraft(2, k))
    done = {c.rid: c for c in sched.run(_mixed_requests())}
    assert {r: c.tokens for r, c in done.items()} == base
    assert sched.summary["retries"] >= 1
    # Headroom never ran out at these lengths, so EVERY dispatch was a
    # verify dispatch — the quarantines came off the verify ok flag.
    assert eng.verify_steps == eng.decode_steps >= 1


def _tiny_state(max_len=64):
    """A gpt_lm-tiny TrainState (the factory defaults TP off at
    mesh.model==1, so create_train_state composes on one device) —
    the checkpointable twin of _tiny_lm for the swap tests."""
    import jax
    import optax

    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import (
        single_device_mesh)
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh = single_device_mesh(jax.devices()[0])
    model = gpt_lm(mesh, size="tiny", max_len=max_len,
                   dropout_rate=0.0)
    state = create_train_state(model, optax.identity(),
                               np.zeros((2, 16), np.int32), mesh,
                               seed=0)
    return model, state


@pytest.mark.slow
def test_live_swap_preserves_in_flight_tokens(tmp_path):
    """Live weight swap mid-traffic to the SAME checkpoint: slots stay
    live (no drain — prefill count unchanged, occupancy continuous)
    and every output is token-identical to the no-swap run; the swap
    is latency, never a correctness event."""
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.train import checkpoint as ckpt

    model, state = _tiny_state()
    ckpt.save(str(tmp_path), state)
    params = state.params

    base_eng = SlotDecodeEngine(model, params, num_slots=2)
    base = {c.rid: c.tokens
            for c in Scheduler(base_eng, decode_priority=3).run(
                _mixed_requests())}

    plan = parse_fault_plan("reload@5")
    eng = SlotDecodeEngine(model, params, num_slots=2, fault_plan=plan)

    def reload_fn():
        return ckpt.restore_params(str(tmp_path), eng.params)

    sched = Scheduler(eng, decode_priority=3, fault_plan=plan,
                      reload_fn=reload_fn)
    done = {c.rid: c for c in sched.run(_mixed_requests())}
    assert eng.swaps == 1
    assert {r: c.tokens for r, c in done.items()} == base
    assert sched.summary["swaps"] == 1
    assert sched.summary["swap_seconds"] > 0
    # No drain: exactly one prefill per request — nobody was evicted
    # around the swap.
    assert eng.prefills == len(base)


@pytest.mark.slow
def test_swap_params_rejects_drift():
    import jax

    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    model, params = _tiny_lm()
    eng = SlotDecodeEngine(model, params, num_slots=1)
    bad = jax.tree_util.tree_map(lambda x: x[..., :1], params)
    with pytest.raises(ValueError, match="shape/dtype drift"):
        eng.swap_params(bad)


@pytest.mark.slow
def test_restore_params_walks_back_past_nonfinite(tmp_path):
    """The swap source honors the integrity contract: a newest
    checkpoint with intact bytes but NaN params is skipped (recovery
    event, no quarantine) and the older finite step swaps in."""
    import jax
    from flax import serialization

    from tensorflow_distributed_tpu.train import checkpoint as ckpt

    _, state = _tiny_state()
    ckpt.save(str(tmp_path), state)                       # step 0
    ckpt.save(str(tmp_path), state.replace(step=state.step + 1))
    # NaN-poison step 1 in place with VALID bytes (checksum refreshed).
    import hashlib

    sd = os.path.join(str(tmp_path), "step_00000001")
    with open(os.path.join(sd, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    raw["params"] = jax.tree_util.tree_map(
        lambda x: np.full_like(x, np.nan), raw["params"])
    blob = serialization.msgpack_serialize(raw)
    with open(os.path.join(sd, "state.msgpack"), "wb") as f:
        f.write(blob)
    with open(os.path.join(sd, "manifest.json")) as f:
        man = json.load(f)
    man["sha256"] = hashlib.sha256(blob).hexdigest()
    with open(os.path.join(sd, "manifest.json"), "w") as f:
        json.dump(man, f)

    new_params, step = ckpt.restore_params(str(tmp_path), state.params)
    assert step == 0
    leaf = jax.tree_util.tree_leaves(jax.device_get(new_params))[0]
    assert np.isfinite(leaf).all()
    # The skipped step was NOT quarantined (bytes are intact — a
    # training-side rewind may still want them for forensics).
    assert os.path.isdir(sd)


def _child_env():
    return {
        "PATH": os.environ["PATH"],
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
        "PYTHONUNBUFFERED": "1",
    }


_SERVE_ARGS = [
    "--mode", "serve", "--model", "gpt_lm", "--model-size", "tiny",
    "--seq-len", "48", "--compute-dtype", "float32",
    "--serve.num-slots", "2", "--serve.num-requests", "6",
    "--serve.prompt-len-min", "4", "--serve.prompt-len-max", "10",
    "--serve.max-new-tokens", "10",
]


@pytest.mark.slow
def test_serve_decode_stall_exits_3(tmp_path):
    """A decode stall past the watchdog deadline is a diagnosable
    StallError -> exit 3 (restart is the remedy), never a silent
    hang."""
    proc = subprocess.run(
        [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
         *_SERVE_ARGS, "--resilience.sync-timeout-s", "0.5",
         "--resilience.fault-plan", "decode_stall@4:2s"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 3, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "decode step" in proc.stderr


@pytest.mark.slow
def test_serve_slot_retry_exhausted_exits_2(tmp_path):
    """Repeated quarantine of the same request past its budget is
    serve's DIVERGED: exit 2, which the supervisor refuses to
    restart."""
    proc = subprocess.run(
        [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
         *_SERVE_ARGS, "--serve.slot-retries", "0",
         "--resilience.fault-plan", "slot_nan@3:0"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 2, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "slot-quarantined" in proc.stderr


@pytest.mark.slow
def test_supervisor_serve_sigkill_journal_resume(tmp_path):
    """The acceptance scenario: a serving process SIGKILLed
    mid-traffic is restarted by the supervisor; the restarted leg
    replays the journal, re-admits in-flight requests as
    continuations, and every request completes — zero lost."""
    journal = str(tmp_path / "serve.journal")
    jsonl = str(tmp_path / "m.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--max-restarts", "2", "--backoff-base-s", "0.2", "--",
         *_SERVE_ARGS, "--serve.max-new-tokens", "16",
         "--serve.journal", journal,
         "--observe.metrics-jsonl", jsonl,
         "--resilience.fault-plan", "sigkill@20"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=500)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert '"kind": "restart"' in proc.stdout
    played = journal_mod.replay(journal)
    assert len(played) == 6
    assert all(ent["done"] for ent in played.values())
    assert all(len(ent["tokens"]) == 16 for ent in played.values())
    recs = [json.loads(ln) for ln in open(jsonl)]
    sums = [r for r in recs if r["event"] == "serve_summary"]
    # The resumed leg's summary is tagged; both legs' request records
    # are in the ONE artifact (append-mode sink on resume).
    assert sums and sums[-1]["resumed"] is True
    req_rids = {r["rid"] for r in recs
                if r["event"] == "serve_request"}
    assert req_rids == set(range(6))
