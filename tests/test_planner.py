"""Auto-layout planner (analysis/planner) + the shared mesh rules.

Fast tier: enumeration completeness/pruning on stubbed constraints,
the roofline math on canned cost dicts, infeasible MARKING (never
dropping), plan.json round-trip, config validation, report folding —
no compiles, no device work. One default-tier e2e drives the real
thing: the standalone CLI plans tiny-gpt on the 8-device CPU mesh and
``--plan auto`` trains 2 steps on the chosen layout under ``--check``.
"""

import json

import pytest

from tensorflow_distributed_tpu.analysis.planner import candidates as C
from tensorflow_distributed_tpu.analysis.planner import plan as plan_lib
from tensorflow_distributed_tpu.analysis.planner import score as S


def _facts(family="gpt", heads=4, layers=2, experts=0):
    return C.ModelFacts(family=family, n_heads=heads, n_layers=layers,
                        n_experts=experts)


# --- enumeration -------------------------------------------------------

def test_enumeration_completeness_stubbed():
    # With the mesh rule stubbed permissive, every factorization x
    # partition that passes the family rules must appear exactly once.
    feasible, pruned = C.enumerate_candidates(
        _facts(), devices=8, batch=128,
        infeasible=lambda axes, d, b: None)
    keys = {(tuple(sorted(c.mesh.items())), c.partition)
            for c in feasible}
    assert len(keys) == len(feasible)  # no duplicates
    meshes = {frozenset((k, v) for k, v in c.mesh.items() if v != 1)
              for c in feasible}
    # (data=8), (4,2), (2,4) survive; model=8 is pruned on heads=4.
    assert frozenset({("data", 8)}) in meshes
    assert frozenset({("data", 4), ("model", 2)}) in meshes
    assert frozenset({("data", 2), ("model", 4)}) in meshes
    assert not any(c.mesh["model"] == 8 for c in feasible)
    reasons = {p.reason for p in pruned}
    assert any("n_heads" in r for r in reasons)
    # 3 factorizations x 3 partitions = 9 (model=8 pruned, and its
    # fsdp/zero1 variants pruned as degenerate-at-data-1), plus the
    # overlap strategy on the ONE pure-data shape (tensor-carrying
    # shapes prune it — the explicit grad-sync needs a pure data
    # mesh).
    assert len(feasible) == 10
    overlaps = [c for c in feasible if c.partition == "overlap"]
    assert len(overlaps) == 1 and overlaps[0].mesh["data"] == 8


def test_enumeration_prunes_all_on_stubbed_constraint():
    feasible, pruned = C.enumerate_candidates(
        _facts(), devices=8, batch=128,
        infeasible=lambda axes, d, b: "stubbed: no")
    assert feasible == []
    assert pruned and all(
        p.reason == "stubbed: no" or "identical to the plain" in p.reason
        or "n_heads" in p.reason or "pure data" in p.reason
        for p in pruned)


def test_enumeration_batch_divisibility_via_shared_rule():
    # The REAL shared rule (parallel.mesh.mesh_infeasible): batch 12
    # rejects data=8 (12 % 8 != 0) but keeps data=4 and data=2.
    feasible, pruned = C.enumerate_candidates(
        _facts(), devices=8, batch=12)
    assert not any(c.mesh["data"] == 8 for c in feasible)
    assert any("not divisible by data width 8" in p.reason
               for p in pruned)


def test_enumeration_strategy_filter():
    feasible, pruned = C.enumerate_candidates(
        _facts(), devices=8, batch=64,
        strategies=("data", "zero1"),
        infeasible=lambda axes, d, b: None)
    assert {c.strategy for c in feasible} == {"data", "zero1"}
    assert any("excluded by --strategies" in p.reason for p in pruned)


def test_enumeration_moe_expert_axis_and_pipelined():
    feasible, _ = C.enumerate_candidates(
        _facts("moe", experts=4), devices=8, batch=64,
        infeasible=lambda axes, d, b: None)
    assert any(c.mesh["expert"] == 4 for c in feasible)
    assert not any(c.mesh["expert"] == 8 for c in feasible)  # 4 experts
    feasible, pruned = C.enumerate_candidates(
        _facts("pipelined", layers=4), devices=8, batch=64,
        infeasible=lambda axes, d, b: None)
    assert any(c.mesh["pipe"] == 4 and c.microbatches == 4
               for c in feasible)
    # pipe=8 > 4 layers is pruned; fsdp never composes with pipelined.
    assert not any(c.mesh["pipe"] == 8 for c in feasible)
    assert not any(c.partition == "fsdp" for c in feasible)
    assert any("fsdp does not compose" in p.reason for p in pruned)


def test_strategy_names_and_cli_args():
    c = C.Candidate.make({"data": 4, "model": 2}, "fsdp")
    assert c.strategy == "fsdp+tensor"
    assert c.cli_args()[:2] == ["--mesh.data", "4"]
    assert "--param-partition" in c.cli_args()
    assert C.Candidate.make({"data": 8}).strategy == "data"
    assert C.Candidate.make({"data": 1}).strategy == "data"
    p = C.Candidate.make({"data": 2, "pipe": 4}, microbatches=4)
    assert p.strategy == "data+pipe"
    assert "--pipeline-microbatches" in p.cli_args()


# --- scoring math (canned dicts, no jax) -------------------------------

HW = S.Hardware(platform="test", device_kind="test",
                peak_flops=1e12, hbm_bw=1e11, ici_bw=2.5e10)


def test_roofline_compute_vs_memory_bound():
    compute_bound = S.roofline_ms(
        {"flops": 2e9, "bytes_accessed": 1e8}, 0.0, HW)
    assert compute_bound["compute_ms"] == pytest.approx(2.0)
    assert compute_bound["memory_ms"] == pytest.approx(1.0)
    assert compute_bound["step_ms"] == pytest.approx(2.0)
    memory_bound = S.roofline_ms(
        {"flops": 1e8, "bytes_accessed": 1e9}, 2.5e7, HW)
    assert memory_bound["step_ms"] == pytest.approx(10.0 + 1.0)
    assert memory_bound["collective_ms"] == pytest.approx(1.0)


def test_roofline_null_costs_stay_null():
    out = S.roofline_ms({"flops": None, "bytes_accessed": None},
                        0.0, HW)
    assert out == {"compute_ms": None, "memory_ms": None,
                   "collective_ms": None, "step_ms": None}


def test_mark_feasibility_marks_never_drops():
    rows = [{"peak_hbm_bytes": 100}, {"peak_hbm_bytes": 300},
            {"peak_hbm_bytes": None}, {"error": "boom"}]
    out = S.mark_feasibility(rows, hbm_budget=200)
    assert len(out) == 4                      # nothing dropped
    assert out[0]["feasible"] is True
    assert out[1]["feasible"] is False
    assert "exceeds" in out[1]["infeasible_reason"]
    assert out[2]["feasible"] is True         # unknown != overflow
    assert out[3]["feasible"] is False


def test_rank_orders_feasible_scored_first():
    rows = [{"strategy": "a", "feasible": False, "step_ms": 0.1},
            {"strategy": "b", "feasible": True, "step_ms": 3.0},
            {"strategy": "c", "feasible": True, "step_ms": 1.0},
            {"strategy": "d", "feasible": True, "step_ms": None}]
    ranked = S.rank(rows)
    assert [r["strategy"] for r in ranked] == ["c", "b", "d", "a"]


# --- plan.json round-trip ----------------------------------------------

def test_plan_json_round_trip(tmp_path):
    plan = {"version": 1, "family": "gpt", "devices": 8,
            "batch_size": 64,
            "candidates": [{"mesh": {"data": 8}, "strategy": "data",
                            "step_ms": 0.5, "feasible": True}],
            "pruned": [], "chosen": {"mesh": {"data": 8}}}
    path = str(tmp_path / "plan.json")
    plan_lib.write_plan(plan, path)
    assert plan_lib.load_plan(path) == plan


# --- shared mesh rules (parallel.mesh <-> supervisor) ------------------

def test_shared_mesh_rules_match_supervisor():
    from tensorflow_distributed_tpu.parallel import mesh as mesh_lib
    from tensorflow_distributed_tpu.resilience import supervisor as sup

    axes = {"data": -1, "model": 2, "seq": 1, "pipe": 1, "expert": 1}
    assert mesh_lib.pick_data_width(axes, 5, 64) == 2
    assert mesh_lib.pick_data_width(axes, 1, 64) is None
    picked = sup.pick_elastic_mesh(axes, 5, 64)
    assert picked["data"] == mesh_lib.pick_data_width(axes, 5, 64)
    assert mesh_lib.mesh_infeasible({"data": 4, "model": 2}, 8, 64) \
        is None
    assert "not divisible by data width" in mesh_lib.mesh_infeasible(
        {"data": 3}, 3, 64)
    assert "!=" in mesh_lib.mesh_infeasible({"data": 4}, 8, 64)
    assert "must be >= 1" in mesh_lib.mesh_infeasible({"data": 0}, 8,
                                                      64)


def test_model_facts_track_factory_constants():
    # The facts pruning runs on must be the factories' OWN numbers —
    # a tiny_config/factory-default change may not silently
    # desynchronize enumeration from the model the scorer builds.
    from tensorflow_distributed_tpu.models.pipelined import (
        PIPELINED_TINY_LAYERS)
    from tensorflow_distributed_tpu.models.transformer import (
        MOE_DEFAULT_EXPERTS, tiny_config)

    tiny = tiny_config()
    gpt = C.model_facts("gpt", "tiny")
    assert (gpt.n_heads, gpt.n_layers) == (tiny.n_heads, tiny.n_layers)
    assert C.model_facts("pipelined").n_layers == PIPELINED_TINY_LAYERS
    assert C.model_facts("moe").n_experts == MOE_DEFAULT_EXPERTS
    assert C.model_facts("moe", moe_experts=8).n_experts == 8


def test_supervisor_refuses_elastic_plus_plan_auto(capsys):
    # Two mesh owners: --elastic rewrites --mesh.* on every leg, which
    # the child's "--plan auto owns the mesh" guard rejects — the
    # supervisor must refuse up front (rc 2, no leg spawned), not
    # crash-loop the restart budget away.
    from tensorflow_distributed_tpu.resilience import supervisor as sup

    rc = sup.main(["--elastic", "--max-restarts", "1", "--",
                   "--model", "gpt_lm", "--plan", "auto",
                   "--checkpoint-dir", "/tmp/nope"])
    assert rc == 2
    assert "does not compose" in capsys.readouterr().err
    rc = sup.main(["--elastic", "--", "--plan=auto"])
    assert rc == 2


# --- config validation -------------------------------------------------

def test_plan_config_validation():
    from tensorflow_distributed_tpu.config import TrainConfig

    def cfg(**kw):
        c = TrainConfig(model="gpt_lm", dataset="synthetic", **kw)
        c.validate()
        return c

    cfg(plan="auto")                      # the valid combination
    with pytest.raises(ValueError, match="unknown plan"):
        cfg(plan="bogus")
    with pytest.raises(ValueError, match="no effect without"):
        cfg(plan_hbm_budget_gb=1.0)
    with pytest.raises(ValueError, match="owns the mesh"):
        from tensorflow_distributed_tpu.config import MeshConfig
        cfg(plan="auto", mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="owns the partition"):
        cfg(plan="auto", param_partition="fsdp")
    with pytest.raises(ValueError, match="LM training families"):
        c = TrainConfig(model="mnist_cnn", plan="auto")
        c.validate()
    with pytest.raises(ValueError, match="mode="):
        cfg(plan="auto", mode="eval", checkpoint_dir="/tmp/x")
    cfg(plan="auto", plan_hbm_budget_gb=4.0)  # the budget composes
    with pytest.raises(ValueError, match="moe_lm"):
        # A dense family with experts bolted on would be scored as
        # dense — rejected rather than misplanned.
        cfg(plan="auto", moe_experts=8)
    c = TrainConfig(model="moe_lm", dataset="synthetic", plan="auto",
                    moe_experts=8)
    c.validate()  # experts on the moe family plan fine


# --- report folding ----------------------------------------------------

def test_report_plan_section():
    from tensorflow_distributed_tpu.observe.report import (
        render, summarize)

    records = [
        {"event": "plan", "family": "gpt",
         "mesh": {"data": 8, "model": 1}, "strategy": "data",
         "partition": "replicated", "predicted_step_ms": 0.17,
         "predicted_peak_hbm_bytes": 2406280, "candidates": 9,
         "feasible": 9, "infeasible": 0},
        {"event": "step", "step": 2, "loss": 4.0, "step_ms_p50": 34.6},
    ]
    out = summarize(records)
    assert out["plan"]["strategy"] == "data"
    assert out["plan"]["measured_step_ms_p50"] == 34.6
    text = render(out)
    assert "Plan" in text and "predicted=0.17" in text
    assert "data=8 [data]" in text


# --- the real thing (default-tier e2e; CPU 8-device mesh) --------------

def test_planner_cli_and_plan_auto_e2e(tmp_path):
    # 1. Standalone CLI: rank tiny-gpt candidates, write plan.json.
    out = str(tmp_path / "plan.json")
    rc = plan_lib.main(["--family", "gpt", "--devices", "8",
                        "--batch-size", "32", "--size", "tiny",
                        "--seq-len", "32", "--out", out])
    assert rc == 0
    plan = plan_lib.load_plan(out)
    rows = plan["candidates"]
    assert rows and plan["chosen"] == rows[0]
    scored = [r["step_ms"] for r in rows
              if r["feasible"] and r["step_ms"] is not None]
    assert scored == sorted(scored)          # ranked
    assert len(scored) >= 3                  # a real sweep, not one row
    assert plan["pruned"]                    # reasons reported
    assert all(p["reason"] for p in plan["pruned"])
    # The AOT pass really ran: every scored row carries compile wall.
    assert all(r["compile_s"] is not None for r in rows
               if r["step_ms"] is not None)

    # 2. An impossible budget MARKS everything infeasible (not drop).
    tight = plan_lib.make_plan("gpt", 8, 32, size="tiny", seq_len=32,
                               strategies=("data",), hbm_budget=1e3)
    assert tight["chosen"] is None
    assert tight["candidates"]
    assert all(not r["feasible"] for r in tight["candidates"])
    assert all("exceeds" in r["infeasible_reason"]
               for r in tight["candidates"])

    # 3. --plan auto: train 2 steps on the chosen layout under --check.
    from tensorflow_distributed_tpu.config import parse_args
    from tensorflow_distributed_tpu.train.loop import train

    jsonl = str(tmp_path / "m.jsonl")
    cfg = parse_args([
        "--model", "gpt_lm", "--model-size", "tiny",
        "--dataset", "synthetic", "--seq-len", "32",
        "--batch-size", "32", "--train-steps", "2",
        "--eval-every", "0", "--eval-batch-size", "32",
        "--log-every", "1", "--plan", "auto", "--check", "true",
        "--observe.metrics-jsonl", jsonl])
    result = train(cfg)
    assert int(result.state.step) == 2
    records = [json.loads(ln) for ln in open(jsonl)]
    plans = [r for r in records if r.get("event") == "plan"]
    assert len(plans) == 1
    # The run's mesh IS the plan's choice.
    starts = [r for r in records if r.get("event") == "start"]
    assert plans[0]["mesh"]["data"] == cfg.mesh.data
    assert starts and cfg.param_partition == plans[0]["partition"]
