"""Pallas flash-CE kernels vs the scan/dense oracles (interpret mode).

Same contract as tests/test_fused_ce.py, one level down: the kernel
triple (fwd, dx, dw/db) must reproduce ops.losses.masked_ce_sums on
logits = x @ w (+ bias) — values AND gradients — in f32 where the
comparison is tight. interpret=True runs the exact kernel code on CPU
(the flash-attention test convention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.ops.fused_ce_kernel import (
    fused_ce_sums_kernel, kernel_supported)
from tensorflow_distributed_tpu.ops.losses import masked_ce_sums

B, L, D = 2, 64, 128   # T = 128 tokens; D must be a lane multiple
V = 179                # prime: exercises vocab padding in every kernel
BT, BV = 64, 128


def _mk(seed=0, bias=True):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, L, D).astype(np.float32)) * 0.3
    w = jnp.asarray((0.1 * rng.randn(V, D)).astype(np.float32))
    b = jnp.asarray((0.1 * rng.randn(V)).astype(np.float32)) \
        if bias else None
    t = jnp.asarray(rng.randint(0, V, size=(B, L)).astype(np.int32))
    m = jnp.asarray((rng.rand(B, L) < 0.7).astype(np.float32))
    return x, w, b, t, m


def _dense(x, w, b, t, m, smoothing=0.0):
    logits = jnp.einsum("bld,vd->blv", x, w)
    if b is not None:
        logits = logits + b
    return masked_ce_sums(logits, t, m, smoothing)


def _kernel(x, w, b, t, m, smoothing=0.0, w_vocab_axis=0):
    return fused_ce_sums_kernel(
        x, w, b, t, m, V, bt=BT, bv=BV, label_smoothing=smoothing,
        w_vocab_axis=w_vocab_axis, interpret=True)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_values_match_dense(smoothing):
    x, w, b, t, m = _mk()
    want = _dense(x, w, b, t, m, smoothing)
    got = _kernel(x, w, b, t, m, smoothing)
    for g, wnt in zip(got, want):
        np.testing.assert_allclose(g, wnt, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_dense(smoothing):
    x, w, b, t, m = _mk(seed=1)

    def dense_loss(x, w, b):
        ce, _, n = _dense(x, w, b, t, m, smoothing)
        return ce / n

    def kern_loss(x, w, b):
        ce, _, n = _kernel(x, w, b, t, m, smoothing)
        return ce / n

    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(x, w, b)
    gk = jax.jit(jax.grad(kern_loss, argnums=(0, 1, 2)))(x, w, b)
    for a, e in zip(gk, gd):
        np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)


def test_untied_orientation_no_bias():
    """w_vocab_axis=1 ([D, V] untied-kernel layout), bias=None."""
    x, w, _, t, m = _mk(seed=2, bias=False)
    wk = w.T

    def dense_loss(x, wk):
        ce, _, n = masked_ce_sums(jnp.einsum("bld,dv->blv", x, wk), t, m)
        return ce / n

    def kern_loss(x, wk):
        ce, _, n = _kernel(x, wk, None, t, m, w_vocab_axis=1)
        return ce / n

    np.testing.assert_allclose(kern_loss(x, wk), dense_loss(x, wk),
                               rtol=2e-5)
    gd = jax.grad(dense_loss, argnums=(0, 1))(x, wk)
    gk = jax.grad(kern_loss, argnums=(0, 1))(x, wk)
    for a, e in zip(gk, gd):
        np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)


def test_kernel_matches_scan_formulation():
    """The two fused formulations must agree with each other too (the
    scan path is the fallback the dispatcher drops to)."""
    from tensorflow_distributed_tpu.ops.fused_ce import fused_ce_sums

    x, w, b, t, m = _mk(seed=3)
    scan = fused_ce_sums(x, w, b, t, m, V, 48, 0.1, 0)
    kern = _kernel(x, w, b, t, m, 0.1)
    for a, e in zip(kern, scan):
        np.testing.assert_allclose(a, e, rtol=2e-5, atol=2e-5)


def test_first_max_argmax_across_blocks():
    """Duplicated max columns straddling a vocab-block edge: the
    earlier column wins, matching jnp.argmax (dense) semantics."""
    x = jnp.ones((1, 8, D), jnp.float32) / D
    w = np.zeros((V, D), np.float32)
    w[1] = w[BV + 9] = 3.0   # identical rows, different blocks
    t = jnp.full((1, 8), 1, jnp.int32)
    m = jnp.ones((1, 8), jnp.float32)
    _, correct, _ = fused_ce_sums_kernel(
        x, jnp.asarray(w), None, t, m, V, bt=8, bv=BV, interpret=True)
    assert float(correct) == 8.0
    t2 = jnp.full((1, 8), BV + 9, jnp.int32)
    _, correct, _ = fused_ce_sums_kernel(
        x, jnp.asarray(w), None, t2, m, V, bt=8, bv=BV, interpret=True)
    assert float(correct) == 0.0


def test_supported_gate():
    assert kernel_supported(256, 768)
    assert kernel_supported(256, 32)         # D rides as a full block
    assert not kernel_supported(250, 768)    # ragged tokens
    assert not kernel_supported(256, 100)    # D not sublane-aligned


def test_train_step_parity_scan_vs_kernel_sharded(devices8):
    """ce_impl='kernel' through the FULL jitted train step on a
    dp x sp mesh: the dispatcher's shard_map wrap (per-device kernel,
    psummed reductions) must reproduce the scan formulation's
    trajectory. Off-TPU the kernel auto-runs in interpret mode, so
    this exercises the exact kernel code on the CPU mesh."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    base = dict(model="gpt_lm", model_size="tiny", dataset="synthetic",
                batch_size=16, train_steps=3, eval_every=0, log_every=0,
                eval_batch_size=16, compute_dtype="float32",
                learning_rate=1e-3, label_smoothing=0.1, seq_len=64,
                # > DEFAULT_BV=2048 so the dispatcher's kernel call
                # really runs the multi-block online recurrence (it
                # exposes no bv override).
                synthetic_vocab=2304,
                mesh=MeshConfig(data=4, seq=2))
    scan = train(TrainConfig(**base, ce_chunk=64, ce_impl="scan"))
    kern = train(TrainConfig(**base, ce_chunk=64, ce_impl="kernel"))
    np.testing.assert_allclose(kern.final_metrics["loss"],
                               scan.final_metrics["loss"],
                               rtol=2e-4, atol=2e-4)
