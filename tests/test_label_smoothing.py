"""Label smoothing: math against the explicit smoothed-one-hot oracle,
zero-eps equivalence, and the knob reaching every loss path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.ops.losses import (
    masked_ce_sums, masked_softmax_cross_entropy, softmax_cross_entropy)


def _oracle(logits, labels, eps):
    """CE against the materialized (1-eps)*onehot + eps/V mixture."""
    logits = np.asarray(logits, np.float64)
    v = logits.shape[-1]
    onehot = np.eye(v)[np.asarray(labels)]
    target = (1 - eps) * onehot + eps / v
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    return float(-(target * logp).sum(-1).mean())


def test_smoothed_ce_matches_oracle():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32)
    for eps in (0.0, 0.1, 0.3):
        got = float(softmax_cross_entropy(logits, labels, eps))
        np.testing.assert_allclose(got, _oracle(logits, labels, eps),
                                   rtol=1e-5)
    # eps=0 is bit-identical to the unsmoothed path.
    np.testing.assert_array_equal(
        np.asarray(softmax_cross_entropy(logits, labels)),
        np.asarray(softmax_cross_entropy(logits, labels, 0.0)))


def test_masked_smoothed_ce_matches_oracle():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 8, 11)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 11, size=(4, 8)), jnp.int32)
    mask = jnp.asarray(rng.random((4, 8)) < 0.5, jnp.float32)
    eps = 0.2
    got = float(masked_softmax_cross_entropy(logits, targets, mask, eps))
    flat_l = np.asarray(logits).reshape(-1, 11)
    flat_t = np.asarray(targets).reshape(-1)
    flat_m = np.asarray(mask).reshape(-1).astype(bool)
    want = _oracle(flat_l[flat_m], flat_t[flat_m], eps)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # Smoothing never changes the accuracy pieces.
    _, c0, n0 = masked_ce_sums(logits, targets, mask)
    _, c1, n1 = masked_ce_sums(logits, targets, mask, eps)
    assert float(c0) == float(c1) and float(n0) == float(n1)


def test_eval_loss_stays_unsmoothed(devices8):
    """Validation numbers must be comparable across smoothing settings:
    the task's eval_loss is the raw objective."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train import step as step_lib
    from tensorflow_distributed_tpu.train.tasks import make_task

    mesh = make_mesh(MeshConfig(data=8))
    v = make_task(TrainConfig(dataset="synthetic", label_smoothing=0.3,
                              mesh=MeshConfig(data=8)), mesh)
    assert v.eval_loss is step_lib.loss_fn  # the unsmoothed default
    lm = make_task(TrainConfig(model="gpt_lm", model_size="tiny",
                               dataset="synthetic", label_smoothing=0.3,
                               mesh=MeshConfig(data=8)), mesh)
    assert lm.eval_loss is not None and lm.eval_loss is not lm.loss


@pytest.mark.slow
def test_smoothing_reaches_train_and_pipeline(devices8):
    """The config knob changes the reported loss in both the standard
    step and the 1F1B pipeline, identically (shared last_fn math)."""
    import optax
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        make_mlm_loss, mlm_batch_shardings)

    mesh = make_mesh(MeshConfig(data=2, pipe=4), devices8)
    model = pipelined_lm(mesh, num_microbatches=4, max_len=16,
                         use_flash=False)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64, seed=0)
    batch = shard_batch(mesh, next(LmBatcher(ds, 8, 0).forever(0)),
                        seq_axis=1)

    eps = 0.25
    _, m_plain = make_train_step(
        mesh, loss=make_mlm_loss(), donate=False,
        batch_shardings=mlm_batch_shardings(mesh))(state, batch)
    _, m_smooth = make_train_step(
        mesh, loss=make_mlm_loss(eps), donate=False,
        batch_shardings=mlm_batch_shardings(mesh))(state, batch)
    assert float(m_smooth["loss"]) > float(m_plain["loss"])

    _, p_smooth = make_1f1b_train_step(model, mesh, donate=False,
                                       label_smoothing=eps)(state, batch)
    np.testing.assert_allclose(float(p_smooth["loss"]),
                               float(m_smooth["loss"]), rtol=1e-5)
