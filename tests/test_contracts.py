"""graftcheck v2: the cross-process contract passes.

Three new self-hosting rule families under test, plus the machinery
they check against:

- **telemetry schema contract** (analysis/rules/telemetry.py +
  observe/schemas.py): every ``emit``/``emit_event``/record-literal
  producer writes only declared fields, every cross-process consumer
  reads only fields some producer declares, and the generated
  RECORDS.md tracks the registry byte-for-byte.
- **durability lint** (analysis/rules/durability.py +
  utils/atomicio.py): raw writes to a declared cross-process path
  family must go through the blessed atomic/durable helpers.
- **argv protocol contract** (analysis/rules/argvproto.py +
  config.known_flags/child_flag): every flag literal the supervisor
  and fleet controller spell for a child is a flag ``config.py``
  actually parses.

All rule fixtures are jax-free (the passes are pure stdlib by
contract — the poisoned-import subprocess test proves it), and the
SELF-HOSTING pins hold the real tree clean under each pass.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tensorflow_distributed_tpu.analysis.lint import (
    PACKAGE_ROOT, lint_paths, lint_source)
from tensorflow_distributed_tpu.analysis import schema as schema_cli
from tensorflow_distributed_tpu.observe import schemas
from tensorflow_distributed_tpu.utils.atomicio import (
    atomic_write_json, atomic_write_jsonl, durable_append)


def findings(src: str, path: str = "mod.py"):
    return lint_source(textwrap.dedent(src), path)


def rules_of(src: str, path: str = "mod.py"):
    return [f.rule for f in findings(src, path)]


# --- telemetry: producer pass ------------------------------------------

def test_undeclared_record_kind():
    src = """
    from tensorflow_distributed_tpu.observe.registry import emit_event

    def f():
        emit_event("totally_unknown_kind", step=1)
    """
    assert rules_of(src) == ["undeclared-record-kind"]


def test_undeclared_record_field():
    src = """
    def f(registry):
        registry.emit("health", module="lm", step=3, bogus_field=1)
    """
    assert rules_of(src) == ["undeclared-record-field"]


def test_missing_required_field():
    # health requires module + step; step alone is a producer bug.
    src = """
    def f(registry):
        registry.emit("health", step=3)
    """
    assert rules_of(src) == ["missing-required-field"]


def test_splat_disables_required_check():
    # A ** splat may carry the required fields — only literal kwargs
    # are checkable, so the required check stands down (undeclared
    # literal kwargs are still flagged).
    src = """
    def f(registry, extra):
        registry.emit("health", **extra)
    """
    assert rules_of(src) == []


def test_declared_emit_is_clean():
    src = """
    def f(registry):
        registry.emit("health", module="lm", step=3, grad_norm=0.5)
    """
    assert rules_of(src) == []


def test_open_schema_allows_extra_fields():
    # "step" is an open rollup kind: producers may splat beyond the
    # table (the loop's computed metrics).
    src = """
    def f(registry):
        registry.emit("step", step=1, loss=0.2, my_rollup=3.0)
    """
    assert rules_of(src) == []


def test_pattern_fields_allowed():
    src = """
    def f(registry):
        registry.emit("eval", step=1, val_loss=0.5, val_accuracy=0.9)
    """
    assert rules_of(src) == []


def test_record_dict_literal_checked():
    # The supervisor's journal records are plain dict literals with an
    # "event" key — same contract, no emit call required.
    src = """
    def f():
        return {"event": "recovery", "kind": "bad_kind_name"}
    """
    # An out-of-vocabulary recovery kind is an undeclared KIND — the
    # recovery sub-vocabulary is part of the kind namespace.
    assert rules_of(src) == ["undeclared-record-kind"]


def test_recovery_kind_vocabulary():
    good = """
    def f(registry):
        registry.emit("recovery", kind="restart", leg=2)
    """
    assert rules_of(good) == []


def test_suppression_honored():
    src = """
    def f(registry):
        # graftcheck: disable=undeclared-record-kind -- test-only kind
        registry.emit("totally_unknown_kind", step=1)
    """
    assert rules_of(src) == []


# --- telemetry: consumer pass ------------------------------------------

def test_consumer_read_checked_in_consumer_modules():
    src = """
    def summarize(rec):
        return rec.get("field_nobody_declares")
    """
    assert rules_of(src, "observe/report.py") == [
        "undeclared-consumer-read"]
    # Same source outside the consumer set: not a cross-process
    # reader, not checked.
    assert rules_of(src, "observe/somewhere_else.py") == []


def test_consumer_subscript_read_checked():
    src = """
    def summarize(rec):
        return rec["field_nobody_declares"]
    """
    assert rules_of(src, "fleet/router.py") == [
        "undeclared-consumer-read"]


def test_consumer_declared_reads_clean():
    src = """
    def summarize(rec):
        return (rec.get("step"), rec.get("grad_norm"),
                rec["event"], rec.get("kind"))
    """
    assert rules_of(src, "observe/report.py") == []


# --- durability lint ---------------------------------------------------

def test_raw_write_to_shared_path():
    src = """
    import json

    def export(export_path, snap):
        with open(export_path, "w") as f:
            json.dump(snap, f)
    """
    assert rules_of(src, "serve/thing.py") == [
        "raw-write-to-shared-path"]


def test_replace_without_fsync():
    src = """
    import json, os

    def export(export_path, snap):
        tmp = export_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, export_path)
    """
    rules = rules_of(src, "serve/thing.py")
    assert "missing-fsync-on-durable-path" in rules


def test_replace_with_fsync_only_flags_raw_open():
    src = """
    import json, os

    def export(export_path, snap):
        tmp = export_path + ".tmp"
        f = open(tmp, "w")
        json.dump(snap, f)
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, export_path)
    """
    # The tmp+fsync+rename idiom spelled by hand: no fsync finding,
    # but the open against a family-matching name is still steered to
    # the blessed helper.
    assert rules_of(src, "serve/thing.py") == [
        "raw-write-to-shared-path"]


def test_read_mode_and_unrelated_paths_clean():
    src = """
    import json

    def load(export_path, scratch):
        with open(export_path) as f:
            data = json.load(f)
        with open(scratch, "w") as f:
            json.dump(data, f)
        return data
    """
    assert rules_of(src, "serve/thing.py") == []


def test_family_resolved_through_local_assignment():
    src = """
    import json

    def export(cfg, snap):
        path = cfg.export_path
        with open(path, "w") as f:
            json.dump(snap, f)
    """
    assert rules_of(src, "serve/thing.py") == [
        "raw-write-to-shared-path"]


def test_atomicio_module_exempt():
    src = """
    import json, os

    def atomic_write_json(path, obj):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    """
    assert rules_of(src, "utils/atomicio.py") == []


# --- argv protocol -----------------------------------------------------

def test_unparsed_child_flag_literal():
    src = """
    def build(args):
        return list(args) + ["--no-such-flag", "1"]
    """
    assert rules_of(src, "resilience/supervisor.py") == [
        "unparsed-child-flag"]
    # Outside the argv-constructing modules, plain "--" literals are
    # someone else's CLI — not checked.
    assert rules_of(src, "serve/run.py") == []


def test_known_child_flags_clean():
    src = """
    def build(args):
        return list(args) + ["--checkpoint-dir", "/tmp/ck",
                             "--observe.metrics-jsonl", "m.jsonl"]
    """
    assert rules_of(src, "fleet/controller.py") == []


def test_fstring_flag_prefix_checked():
    good = """
    def mesh_flags(mesh):
        return [f"--mesh.{name}" for name in mesh]
    """
    assert rules_of(good, "resilience/supervisor.py") == []
    bad = """
    def mesh_flags(mesh):
        return [f"--bogus.{name}" for name in mesh]
    """
    assert rules_of(bad, "resilience/supervisor.py") == [
        "unparsed-child-flag"]


def test_child_flag_helper_checked_everywhere():
    bad = """
    from tensorflow_distributed_tpu.config import child_flag

    def f():
        return child_flag("no_such_flag")
    """
    assert rules_of(bad, "serve/whatever.py") == ["unparsed-child-flag"]
    good = bad.replace("no_such_flag", "batch_size")
    assert rules_of(good, "serve/whatever.py") == []


def test_child_flag_runtime_contract():
    from tensorflow_distributed_tpu.config import child_flag, known_flags

    assert child_flag("observe.metrics_jsonl") == \
        "--observe.metrics-jsonl"
    assert child_flag("batch_size") == "--batch-size"
    assert "--mesh.data" in known_flags()
    with pytest.raises(KeyError):
        child_flag("no_such_flag")


def test_supervisor_and_controller_share_flag_spelling():
    """The carried ROADMAP item: both child-argv constructors route
    through config.child_flag, so every flag they spell parses."""
    from tensorflow_distributed_tpu.config import known_flags
    from tensorflow_distributed_tpu.resilience.supervisor import (
        build_leg_args)

    args = build_leg_args(
        ["--mode", "train", "--checkpoint-dir", "/tmp/ck"], restarts=1)
    flags = {a for a in args if a.startswith("--")}
    assert "--resume" in flags
    assert flags <= known_flags()


# --- utils/atomicio ----------------------------------------------------

def test_atomic_write_json_roundtrip(tmp_path):
    path = str(tmp_path / "snap.json")
    obj = {"a": 1, "b": [1, 2, 3]}
    assert atomic_write_json(path, obj) == path
    with open(path) as f:
        assert json.load(f) == obj
    # No tmp litter: the pid-suffixed staging file was renamed away.
    assert os.listdir(tmp_path) == ["snap.json"]


def test_atomic_write_json_indent_and_newline(tmp_path):
    path = str(tmp_path / "profile.json")
    atomic_write_json(path, {"k": 1}, indent=2, trailing_newline=True)
    text = open(path).read()
    assert text.endswith("\n") and "\n  " in text


def test_atomic_write_jsonl(tmp_path):
    path = str(tmp_path / "bundle.jsonl")
    atomic_write_jsonl(path, [{"i": 0}, {"i": 1}])
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert lines == [{"i": 0}, {"i": 1}]


def test_durable_append(tmp_path):
    path = str(tmp_path / "events.jsonl")
    durable_append(path, {"event": "recovery", "kind": "restart"})
    durable_append(path, {"event": "recovery", "kind": "rewind"})
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert [l["kind"] for l in lines] == ["restart", "rewind"]


# --- observe/schemas: runtime validation -------------------------------

def test_validate_record_accepts_declared():
    rec = {"event": "health", "t": 0.1, "process_index": 0,
           "module": "lm", "step": 3, "grad_norm": 0.5}
    assert schemas.validate_record("health", rec) == []


def test_validate_record_catches_violations():
    assert schemas.validate_record("no_such_kind", {"event": "x"})
    assert schemas.validate_record(
        "health", {"event": "health", "step": 1})      # missing module
    errs = schemas.validate_record(
        "health", {"event": "health", "module": "lm", "step": 1,
                   "bogus": 1})
    assert any("bogus" in e for e in errs)
    # Explicit null in a non-nullable field is a producer bug.
    errs = schemas.validate_record(
        "health", {"event": "health", "module": None, "step": 1})
    assert errs


def test_validate_record_open_and_patterns():
    assert schemas.validate_record(
        "step", {"event": "step", "step": 1, "loss": 0.1,
                 "anything_extra": 2}) == []
    assert schemas.validate_record(
        "eval", {"event": "eval", "step": 1, "val_loss": 0.2}) == []


def test_registry_validate_raises_on_bad_emit():
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    reg = MetricsRegistry(validate=True)
    reg.emit("health", module="lm", step=1)
    with pytest.raises(ValueError, match="bogus"):
        reg.emit("health", module="lm", step=1, bogus=1)
    # Off by default: the same emit is accepted (library inspection
    # paths and tests construct ad-hoc records freely).
    MetricsRegistry().emit("health", module="lm", step=1, bogus=1)


# --- RECORDS.md generation ---------------------------------------------

def test_records_md_is_generated_and_current():
    """The drift gate's clean pin: the committed RECORDS.md equals the
    registry rendering byte-for-byte."""
    assert not schema_cli.records_md_drift()


def test_records_md_update_flow(tmp_path):
    path = str(tmp_path / "RECORDS.md")
    assert schema_cli.records_md_drift(path)        # absent = drift
    schema_cli.update_records_md(path)
    assert not schema_cli.records_md_drift(path)
    text = open(path).read()
    # Every registry-emitted kind is documented.
    for s in schemas.SCHEMAS:
        assert f"`{s.kind}`" in text


# --- CLI exit codes ----------------------------------------------------

def test_schema_cli_exit_codes(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("def f(r):\n"
                     "    r.emit('no_such_kind', step=1)\n")
    clean = tmp_path / "clean.py"
    clean.write_text("def f(r):\n"
                     "    r.emit('health', module='lm', step=1)\n")
    assert schema_cli.main([str(dirty)]) == 1
    assert schema_cli.main([str(clean)]) == 0


def test_schema_cli_default_run_is_clean():
    """SELF-HOSTING + drift gate: the packaged tree and the committed
    RECORDS.md pass the full schema CLI (what scripts/lint.sh runs)."""
    assert schema_cli.main([]) == 0


# --- jax-free contract -------------------------------------------------

def test_contract_passes_are_jax_free():
    """Schema registry, atomicio, config flag namespace, and the
    schema CLI all import and run with jax poisoned away — the
    supervisor/controller/lint tier must never touch a backend."""
    code = textwrap.dedent("""
        import builtins
        real = builtins.__import__
        def guard(name, *a, **k):
            if name == "jax" or name.startswith("jax."):
                raise ModuleNotFoundError(
                    f"No module named {name!r}", name="jax")
            return real(name, *a, **k)
        builtins.__import__ = guard
        from tensorflow_distributed_tpu.observe import schemas
        assert schemas.validate_record(
            "health", {"event": "health", "module": "m", "step": 1}
        ) == []
        from tensorflow_distributed_tpu.utils.atomicio import (
            atomic_write_json)
        from tensorflow_distributed_tpu.config import child_flag
        assert child_flag("batch_size") == "--batch-size"
        from tensorflow_distributed_tpu.analysis.schema import (
            schema_findings)
        from tensorflow_distributed_tpu.analysis.lint import lint_source
        fs = lint_source(
            "def f(r):\\n    r.emit('no_such_kind', x=1)\\n", "m.py")
        assert [f.rule for f in fs] == ["undeclared-record-kind"], fs
        print("OK")
    """)
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


# --- self-hosting pins -------------------------------------------------

@pytest.mark.parametrize("rule_group", [
    ("undeclared-record-kind", "undeclared-record-field",
     "missing-required-field", "undeclared-consumer-read"),
    ("raw-write-to-shared-path", "missing-fsync-on-durable-path"),
    ("unparsed-child-flag",),
])
def test_repo_clean_under_pass(rule_group):
    """Each contract pass holds the real tree clean (suppressions with
    reasons excepted) — graftcheck v2 gates the code that ships it."""
    hits = [f.render() for f in lint_paths([PACKAGE_ROOT])
            if f.rule in rule_group]
    assert hits == []
