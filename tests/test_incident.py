"""Incident-observatory suite: anomaly detectors, crash flight
recorder, postmortem forensics.

Fast tier (jax-free except the one Observatory wiring test):
value-pinned detector units on canned streams (a spike fires at the
EXACT step, a clean stream stays silent), the hub's train/serve feeds
and snapshot state, ring-buffer overflow/flush semantics, bundle
round-trip with truncated-tail tolerance, postmortem CLI output shape
and likely-cause heuristics, scheduler snapshot/export wiring on a
fake engine, supervisor bundle collection, and the config knob
matrix. Slow tier: the supervised-SIGKILL bundle e2e via the
detectbench bundle phase (real CLI subprocesses under the
supervisor).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import signal

import numpy as np
import pytest

from tensorflow_distributed_tpu.observe import flightrec, postmortem
from tensorflow_distributed_tpu.observe.anomaly import (
    AnomalyHub, MadSpikeDetector, NonFiniteDetector, PlateauDetector,
    QueueGrowthDetector, RatioCollapseDetector, RollingMedianSpike,
    SlopeDegradationDetector)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- detector units (value-pinned on canned streams) --------------------

def test_mad_spike_fires_at_exact_step():
    det = MadSpikeDetector("t", window=32, min_samples=8)
    for i in range(20):
        assert det.observe(10.0) is None, f"fired on clean sample {i}"
    f = det.observe(500.0)
    assert f is not None
    assert f["baseline"] == 10.0 and f["value"] == 500.0
    assert f["zscore"] > 8.0
    assert f["evidence"][-1] == 10.0


def test_mad_spike_needs_min_samples():
    det = MadSpikeDetector("t", min_samples=8)
    for _ in range(7):
        det.observe(10.0)
    assert det.observe(500.0) is None  # 7 samples < 8: still arming


def test_mad_spike_outlier_not_absorbed_and_cooldown():
    det = MadSpikeDetector("t", window=16, min_samples=4)
    for _ in range(8):
        det.observe(10.0)
    assert det.observe(500.0) is not None
    # Cooldown: the next min_samples high values absorb silently
    # (regime shift re-baselines instead of paging per step)...
    for _ in range(det.min_samples):
        assert det.observe(500.0) is None
    # ...and the spiking sample was NOT added at fire time: baseline
    # still reflects mostly-clean history.
    assert 10.0 in det._buf


def test_mad_spike_scale_guards():
    # Relative jitter on a small baseline: z is huge (constant
    # series, MAD 0) but the ratio/abs guards hold.
    det = MadSpikeDetector("t", min_samples=4, ratio_min=4.0)
    for _ in range(8):
        det.observe(1.0)
    assert det.observe(3.0) is None          # 3x < ratio_min 4x
    det2 = MadSpikeDetector("t", min_samples=4, abs_min=50.0)
    for _ in range(8):
        det2.observe(1.0)
    assert det2.observe(8.0) is None         # excess 7 < abs_min 50
    assert det2.observe(80.0) is not None    # both guards cleared


def test_rolling_median_spike_semantics():
    det = RollingMedianSpike(window=4, factor=3.0)
    for v in (1.0, 1.0, 1.0):
        assert det.observe(v) is None
    assert det.observe(10.0) is None         # window not yet full
    assert det.observe(10.0) == 1.0          # full -> spike, median 1
    # The spike was not absorbed: the window median is unchanged and
    # the same value re-flags.
    assert det.observe(10.0) == 1.0
    det.reset()
    assert det.observe(10.0) is None         # empty window re-arms


def test_policies_loss_spike_is_the_anomaly_core():
    from tensorflow_distributed_tpu.resilience.policies import (
        LossSpikeDetector)

    assert issubclass(LossSpikeDetector, RollingMedianSpike)
    # Exact decision parity with an inline reference implementation
    # over a mixed stream (the behavior the resilience suite pins).
    import collections
    import statistics
    rng = np.random.default_rng(0)
    stream = list(rng.uniform(0.5, 1.5, size=64)) + [9.0] + \
        list(rng.uniform(0.5, 1.5, size=16))
    det = LossSpikeDetector(window=8, factor=4.0)
    ref_win: collections.deque = collections.deque(maxlen=8)
    for v in stream:
        got = det.observe(float(v))
        want = None
        if len(ref_win) == 8:
            med = statistics.median(ref_win)
            if v > 4.0 * max(med, 1e-12):
                want = med
        if want is None:
            ref_win.append(v)
        assert got == want


def test_slope_degradation_fires_on_sustained_drop():
    det = SlopeDegradationDetector("t", window=8, drop=0.4)
    for v in [100.0] * 6 + [50.0] * 2:
        assert det.observe(v) is None
    f = det.observe(50.0)                    # window now 5x100 + 3x50
    assert f is not None and f["baseline"] == 100.0 and f["value"] == 50.0
    # Cleared on fire: silent until a fresh full window accumulates.
    assert all(det.observe(50.0) is None for _ in range(7))


def test_slope_degradation_silent_on_stable_and_improving():
    det = SlopeDegradationDetector("t", window=8, drop=0.4)
    assert all(det.observe(v) is None
               for v in list(range(100, 140)))  # improving
    det.reset()
    assert all(det.observe(100.0 + (i % 3)) is None
               for i in range(40))               # stable jitter


def test_plateau_detector():
    det = PlateauDetector("t", window=8, min_improve=0.01)
    # Improving halves: silent.
    for v in (4.0, 4.0, 4.0, 4.0, 2.0, 2.0, 2.0):
        assert det.observe(v) is None
    assert det.observe(2.0) is None
    det.reset()
    f = None
    for v in [3.0] * 8:
        f = det.observe(v)
    assert f is not None and f["value"] == 3.0


def test_nonfinite_detector():
    det = NonFiniteDetector("t")
    assert det.observe(1.0) is None
    assert det.observe(float("nan")) is not None
    assert det.observe(float("inf")) is not None
    assert det.observe(None) is None         # not a number: no claim


def test_ratio_collapse_fires_on_frozen_module():
    det = RatioCollapseDetector("t", window=8, factor=50.0)
    for _ in range(8):
        assert det.observe(1e-3) is None
    f = det.observe(1e-6)                    # 1000x under the median
    assert f is not None and f["baseline"] == 1e-3
    assert all(det.observe(1e-3) is None for _ in range(16))  # healthy


def test_queue_growth_fires_at_exact_step():
    det = QueueGrowthDetector("t", window=8, min_growth=5)
    fired_at = None
    for i in range(12):
        if det.observe(float(i)) is not None:
            fired_at = i
            break
    assert fired_at == 7                     # the step the window filled
    det.reset()
    # Oscillating (draining) backlog: net growth but not at the max.
    for i in range(40):
        assert det.observe(float(10 - (i % 5))) is None


# --- the hub ------------------------------------------------------------

def _hub(phase="train", **kw):
    recs = []
    hub = AnomalyHub(emit=lambda ev, **f: recs.append((ev, dict(f))),
                     phase=phase, **kw)
    return hub, recs


def test_hub_train_nan_and_step_spike():
    hub, recs = _hub()
    for s in range(1, 20):
        assert hub.observe_train_step(s, {"loss": 2.0},
                                      step_wall_ms=10.0) == []
    out = hub.observe_train_step(20, {"loss": float("nan")},
                                 step_wall_ms=900.0)
    assert {r["detector"] for r in out} == {"loss_nonfinite",
                                            "step_time_spike"}
    assert all(r["step"] == 20 for r in out)
    assert [ev for ev, _ in recs] == ["anomaly", "anomaly"]
    crit = next(r for r in out if r["detector"] == "loss_nonfinite")
    assert crit["severity"] == "critical"


def test_hub_train_throughput_slope():
    hub, _ = _hub(window=64)   # slope window = 16
    fired = []
    for s in range(1, 40):
        tput = 1000.0 if s < 20 else 100.0
        fired += hub.observe_train_step(
            s, {"loss": 1.0, "tokens_per_sec": tput})
    assert any(r["detector"] == "throughput_slope" for r in fired)


def test_hub_health_explosion_and_collapse():
    hub, _ = _hub()
    fired = []
    for s in range(1, 40):
        fired += hub.observe_health(s, "layer_1",
                                    {"grad_norm": 0.5,
                                     "update_ratio": 1e-3})
    assert fired == []
    f1 = hub.observe_health(40, "layer_1", {"grad_norm": 1e3,
                                            "update_ratio": 1e-3})
    assert [r["detector"] for r in f1] == ["grad_norm_spike/layer_1"]
    assert f1[0]["severity"] == "critical" and f1[0]["module"] == "layer_1"
    f2 = hub.observe_health(41, "layer_1", {"grad_norm": 0.5,
                                            "update_ratio": 1e-9})
    assert [r["detector"] for r in f2] == [
        "update_ratio_collapse/layer_1"]


def test_hub_serve_decode_spike_and_queue_growth():
    hub, _ = _hub(phase="serve", window=64)  # queue window = 32
    fired = []
    for s in range(1, 40):
        fired += hub.observe_decode_step(s, queue_depth=s,
                                         step_wall_ms=5.0)
    growth = [r for r in fired if r["detector"] == "queue_growth"]
    assert growth and growth[0]["step"] == 32
    f = hub.observe_decode_step(40, queue_depth=0, step_wall_ms=800.0)
    assert [r["detector"] for r in f] == ["decode_time_spike"]


def test_hub_serve_ttft_and_slot_nonfinite():
    hub, recs = _hub(phase="serve")
    for s in range(1, 12):
        assert hub.observe_completion(s, 20.0) == []
    f = hub.observe_completion(12, 900.0)
    assert [r["detector"] for r in f] == ["ttft_spike"]
    f = hub.note_slot_nonfinite(13, slot=1, rid=7)
    assert f[0]["detector"] == "slot_nonfinite"
    assert f[0]["severity"] == "critical"
    assert f[0]["slot"] == 1 and f[0]["rid"] == 7
    assert len(recs) == 2


def test_hub_snapshot_and_active_horizon():
    hub, _ = _hub(window=16)
    for s in range(1, 12):
        hub.observe_train_step(s, {"loss": 1.0})
    hub.observe_train_step(12, {"loss": float("nan")})
    snap = hub.snapshot()
    assert snap["anomalies"] == 1
    assert snap["active"] == ["loss_nonfinite"]
    assert snap["by_detector"] == {"loss_nonfinite": 1}
    assert snap["last"]["detector"] == "loss_nonfinite"
    assert snap["last"]["step"] == 12
    # Past the active horizon (window steps) the detector drops out of
    # "active" but stays in the counts.
    for s in range(13, 40):
        hub.observe_train_step(s, {"loss": 1.0})
    snap = hub.snapshot()
    assert snap["active"] == [] and snap["anomalies"] == 1


def test_hub_validation():
    with pytest.raises(ValueError, match="phase"):
        AnomalyHub(phase="eval")
    with pytest.raises(ValueError, match="window"):
        AnomalyHub(window=4)


# --- flight recorder ----------------------------------------------------

def test_ring_overflow_and_tails(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), ring=8,
                                   snapshot_every=1000)
    for i in range(20):
        rec.record({"event": "step", "step": i})
    rec.record({"event": "compile", "program": "train_step"})
    assert len(rec.ring) == 8                # bounded
    assert rec.ring[-1]["event"] == "compile"
    assert [r["step"] for r in rec.ring if r.get("event") == "step"] \
        == list(range(13, 20))               # oldest dropped
    assert len(rec._tails["compile"]) == 1   # kind tail survives churn


def test_snapshot_cadence_and_flush_on_anomaly(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), ring=32,
                                   snapshot_every=5)
    for i in range(4):
        rec.record({"event": "step", "step": i})
    assert not os.path.exists(rec.snapshot_path)   # cadence not hit
    rec.record({"event": "step", "step": 4})
    assert os.path.exists(rec.snapshot_path)       # 5th record
    os.remove(rec.snapshot_path)
    rec.record({"event": "anomaly", "detector": "x", "step": 5})
    assert os.path.exists(rec.snapshot_path)       # incident: immediate
    b = flightrec.load_bundle(rec.snapshot_path)
    assert b["meta"]["bundle"] == "snapshot"
    assert b["last"]["anomaly"][0]["detector"] == "x"


def test_bundle_round_trip_and_truncated_tail(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), ring=16,
                                   snapshot_every=1000,
                                   meta={"git_sha": "abc123",
                                         "config": {"model": "x"}})
    for i in range(10):
        rec.record({"event": "step", "step": i, "t": i * 0.1})
    rec.record({"event": "recovery", "kind": "fault_injected",
                "fault": "nan_grad", "step": 9})
    path = rec.dump("FloatingPointError: non-finite loss nan at step 10")
    b = flightrec.load_bundle(path)
    assert b["meta"]["reason"].startswith("FloatingPointError")
    assert b["meta"]["git_sha"] == "abc123"
    assert b["meta"]["config"] == {"model": "x"}
    assert len(b["records"]) == 11 and b["torn"] == 0
    assert b["last"]["recovery"][0]["fault"] == "nan_grad"
    assert b["tracebacks"]                    # thread stacks captured
    # First dump wins; later calls return the same path.
    assert rec.dump("other") == path
    # Torn tail (the death cut the final write): every complete line
    # still loads, the torn one is counted.
    with open(path, "ab") as f:
        f.write(b'{"kind": "record", "data": {"event": "ste')
    b2 = flightrec.load_bundle(path)
    assert b2["torn"] == 1
    assert len(b2["records"]) == len(b["records"])


def test_flightrec_sink_rides_registry(tmp_path):
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    rec = flightrec.FlightRecorder(str(tmp_path), snapshot_every=1000)
    reg = MetricsRegistry([flightrec.FlightRecorderSink(rec)],
                          tags={"process_index": 0})
    reg.emit("step", step=1, loss=2.0)
    reg.emit("anomaly", detector="loss_spike", step=1)
    assert rec.ring[0]["event"] == "step"
    assert rec.ring[0]["process_index"] == 0  # tags ride along
    assert os.path.exists(rec.snapshot_path)  # anomaly flushed
    reg.close()                               # sink close -> recorder close


def test_sigterm_hook_dumps_then_chains(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), snapshot_every=1000)
    rec.record({"event": "step", "step": 1})
    called = []
    rec._prev_sigterm = lambda signum, frame: called.append(signum)
    rec._on_sigterm(signal.SIGTERM, None)
    assert rec.dumped and os.path.exists(rec.dumped)
    assert called == [signal.SIGTERM]         # previous handler ran
    b = flightrec.load_bundle(rec.dumped)
    assert b["meta"]["reason"] == "sigterm"
    assert b["meta"]["signal"] == int(signal.SIGTERM)


def test_install_close_restores_sigterm(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    rec = flightrec.FlightRecorder(str(tmp_path))
    rec.install()
    try:
        assert signal.getsignal(signal.SIGTERM) == rec._on_sigterm
    finally:
        rec.close()
    assert signal.getsignal(signal.SIGTERM) == prev
    assert os.path.exists(rec.snapshot_path)  # close left a snapshot


def test_newest_bundle_prefers_postmortem(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), snapshot_every=1000)
    rec.record({"event": "step", "step": 1})
    snap = rec.snapshot()
    assert flightrec.newest_bundle(str(tmp_path)) == snap
    dump = rec.dump("boom")
    os.utime(snap, None)                      # snapshot is NEWER...
    assert flightrec.newest_bundle(str(tmp_path)) == dump  # ...still
    assert flightrec.newest_bundle(str(tmp_path),
                                   since=os.path.getmtime(dump)
                                   + 3600) is None
    assert flightrec.newest_bundle(str(tmp_path / "missing")) is None


# --- postmortem CLI -----------------------------------------------------

def _canned_bundle(tmp_path, reason=None, kind="dump"):
    rec = flightrec.FlightRecorder(str(tmp_path), ring=32,
                                   snapshot_every=1000,
                                   meta={"git_sha": "abc123"})
    hub = AnomalyHub(emit=lambda ev, **f: rec.record(
        {"event": ev, **f}), phase="train")
    for s in range(1, 20):
        rec.record({"event": "step", "step": s, "t": s * 0.1,
                    "loss": 2.0})
        hub.observe_train_step(s, {"loss": 2.0}, step_wall_ms=10.0)
    hub.observe_health(38, "layer_1", {"grad_norm": 1.0})
    for s in range(21, 38):
        hub.observe_health(s, "layer_1", {"grad_norm": 1.0})
    fired = hub.observe_health(38, "layer_1", {"grad_norm": 1e4})
    assert fired
    rec.record({"event": "step", "step": 40, "t": 4.0,
                "loss": float("nan")})
    if kind == "dump":
        return rec.dump(reason or
                        "FloatingPointError: non-finite loss at 40")
    return rec.snapshot()


def test_postmortem_report_shape(tmp_path):
    path = _canned_bundle(tmp_path)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert postmortem.main([path]) == 0
    out = buf.getvalue()
    for section in ("== postmortem:", "Anomalies preceding death",
                    "Likely cause", "Timeline", "Last by kind",
                    "Tracebacks"):
        assert section in out, f"missing section {section!r}"
    assert "grad_norm_spike/layer_1" in out
    assert "git_sha=abc123" in out


def test_postmortem_likely_cause_nonfinite(tmp_path):
    b = flightrec.load_bundle(_canned_bundle(tmp_path))
    cause = postmortem.likely_cause(b)
    assert "grad-norm explosion in layer_1 at step 38" in cause
    assert "nonfinite halt at step 40" in cause


def test_postmortem_likely_cause_untrapped_kill(tmp_path):
    b = flightrec.load_bundle(_canned_bundle(tmp_path,
                                             kind="snapshot"))
    assert "untrapped process death" in postmortem.likely_cause(b)


def test_postmortem_likely_cause_no_anomalies(tmp_path):
    rec = flightrec.FlightRecorder(str(tmp_path), snapshot_every=1000)
    rec.record({"event": "step", "step": 3})
    b = flightrec.load_bundle(rec.dump("StallError: data stall"))
    cause = postmortem.likely_cause(b)
    assert cause.startswith("no anomalies preceded the stall halt")


def test_postmortem_json_and_bad_input(tmp_path):
    path = _canned_bundle(tmp_path)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert postmortem.main([path, "--json"]) == 0
    obj = json.loads(buf.getvalue())
    assert obj["likely_cause"]
    junk = tmp_path / "junk.jsonl"
    junk.write_text("not json\n")
    assert postmortem.main([str(junk)]) == 1


# --- scheduler / snapshot wiring (fake engine, jax-free) ----------------

class _FakeEngine:
    """Deterministic stream: token = rid * 100 + count (the serve-slo
    suite's fake, trimmed)."""

    def __init__(self, num_slots=2, max_len=256):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (64, 128)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots)
                if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = len(prompt) - 1
        self.prefills += 1
        return rid * 100 + self.counts[rid]

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                self.counts[rid] += 1
                out[s] = rid * 100 + self.counts[rid]
        self.decode_steps += 1
        return out

    def free(self, slot):
        self.active[slot] = False


class _QuarantineOnceEngine(_FakeEngine):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._fired = False

    def take_bad_slots(self):
        if not self._fired and self.decode_steps >= 1:
            self._fired = True
            return [0]
        return []


def _reqs(n, max_new=6):
    from tensorflow_distributed_tpu.serve.scheduler import Request
    return [Request(rid=i, prompt=np.asarray([i], np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def test_scheduler_feeds_hub_and_snapshot_carries_anomaly_state():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler

    hub, recs = _hub(phase="serve", window=8)
    sched = Scheduler(_QuarantineOnceEngine(num_slots=2),
                      decode_priority=2, anomaly_hub=hub,
                      slot_retries=2)
    done = sched.run(_reqs(3))
    assert len(done) == 3
    # The quarantined slot surfaced as a critical anomaly...
    assert hub.by_detector.get("slot_nonfinite") == 1
    assert recs and recs[0][1]["detector"] == "slot_nonfinite"
    # ...and the export payload carries the incident state.
    snap = sched.metrics_snapshot()
    assert snap["anomaly"]["anomalies"] == 1
    assert "slot_nonfinite" in snap["anomaly"]["by_detector"]
    assert sched.summary["anomalies"] == 1


def test_scheduler_without_hub_shape_stable():
    from tensorflow_distributed_tpu.serve.scheduler import Scheduler

    sched = Scheduler(_FakeEngine(num_slots=2), decode_priority=2)
    sched.run(_reqs(2))
    assert "anomaly" not in sched.metrics_snapshot()
    assert "anomalies" not in sched.summary


def test_serve_observatory_arms_hub_and_flightrec(tmp_path):
    from tensorflow_distributed_tpu.config import ObserveConfig
    from tensorflow_distributed_tpu.observe.hub import ServeObservatory

    ocfg = ObserveConfig(
        metrics_jsonl=str(tmp_path / "m.jsonl"), anomaly=True,
        flightrec=str(tmp_path / "flight"))
    ocfg.validate()
    obs = ServeObservatory(ocfg, tags={"process_index": 0},
                           run_config={"serve": {"num_slots": 2}})
    try:
        kwargs = obs.scheduler_kwargs()
        assert kwargs["anomaly_hub"] is obs.anomalies
        assert obs.anomalies.phase == "serve"
        assert obs.flightrec is not None
        # Serve bundles carry the launch config like train bundles.
        assert obs.flightrec.meta["config"] == {
            "serve": {"num_slots": 2}}
        obs.registry.emit("anomaly", detector="x", step=1)
        assert obs.flightrec.ring[-1]["detector"] == "x"
    finally:
        obs.close()
    assert os.path.exists(obs.flightrec.snapshot_path)


# --- supervisor bundle collection ---------------------------------------

def test_supervisor_leg_bundle(tmp_path):
    from tensorflow_distributed_tpu.resilience.supervisor import (
        _leg_bundle)

    rec = flightrec.FlightRecorder(str(tmp_path), snapshot_every=1000)
    rec.record({"event": "step", "step": 1})
    snap = rec.snapshot()
    assert _leg_bundle(str(tmp_path), since=0.0) == snap
    assert _leg_bundle(None, since=0.0) is None
    assert _leg_bundle(str(tmp_path / "nope"), since=0.0) is None


# --- report folding -----------------------------------------------------

def test_report_folds_anomalies_and_postmortem():
    from tensorflow_distributed_tpu.observe.report import (
        render, summarize)

    records = [
        {"event": "step", "step": 1, "loss": 1.0},
        {"event": "anomaly", "detector": "loss_nonfinite",
         "severity": "critical", "step": 8},
        {"event": "anomaly", "detector": "step_time_spike",
         "severity": "warn", "step": 9},
        {"event": "anomaly", "detector": "step_time_spike",
         "severity": "warn", "step": 14},
        {"event": "postmortem", "bundle": "/tmp/p.jsonl",
         "reason": "boom"},
    ]
    out = summarize(records)
    assert out["anomalies"]["count"] == 3
    assert out["anomalies"]["by_detector"] == {
        "loss_nonfinite": 1, "step_time_spike": 2}
    assert out["anomalies"]["last"]["step"] == 14
    assert out["postmortem_bundles"] == ["/tmp/p.jsonl"]
    text = render(out)
    assert "Anomalies" in text and "Postmortem bundles" in text
    # Plain reports stay shape-stable.
    plain = summarize([{"event": "step", "step": 1, "loss": 1.0}])
    assert "anomalies" not in plain and "postmortem_bundles" not in plain


# --- config knobs -------------------------------------------------------

def test_observe_config_incident_validation():
    from tensorflow_distributed_tpu.config import ObserveConfig

    ObserveConfig(anomaly=True, anomaly_window=32).validate()
    ObserveConfig(flightrec="/tmp/f", flightrec_ring=64,
                  flightrec_snapshot_every=10).validate()
    with pytest.raises(ValueError, match="anomaly_window must be"):
        ObserveConfig(anomaly=True, anomaly_window=4).validate()
    with pytest.raises(ValueError, match="no effect without "
                                         "observe.anomaly"):
        ObserveConfig(anomaly_window=32).validate()
    with pytest.raises(ValueError, match="flightrec_ring must be"):
        ObserveConfig(flightrec="/tmp/f",
                      flightrec_ring=4).validate()
    with pytest.raises(ValueError, match="flightrec_snapshot_every"):
        ObserveConfig(flightrec="/tmp/f",
                      flightrec_snapshot_every=0).validate()
    with pytest.raises(ValueError, match="no effect without "
                                         "observe.flightrec"):
        ObserveConfig(flightrec_ring=64).validate()


# --- Observatory wiring (needs the observe hub's jax-adjacent deps) ----

def test_observatory_feeds_hub_and_dumps_on_exception(tmp_path):
    from tensorflow_distributed_tpu.config import ObserveConfig
    from tensorflow_distributed_tpu.observe.hub import Observatory

    ocfg = ObserveConfig(metrics_jsonl=str(tmp_path / "m.jsonl"),
                         anomaly=True,
                         flightrec=str(tmp_path / "flight"))
    ocfg.validate()
    clock = iter(np.arange(0.0, 100.0, 0.01))
    obs = Observatory(ocfg, tags={"process_index": 0},
                      clock=lambda: float(next(clock)),
                      run_config={"model": "unit"})
    try:
        assert obs.anomalies is not None and obs.flightrec is not None
        assert obs.flightrec.meta["config"] == {"model": "unit"}
        for s in range(1, 12):
            obs.log_step(s, {"loss": 2.0})
        obs.log_step(12, {"loss": float("nan")})
        # The health tee routes through emit().
        obs.emit("health", step=12, module="layer_0", grad_norm=0.5)
        assert obs.anomalies.by_detector.get("loss_nonfinite") == 1
        anoms = [r for r in obs.registry.records
                 if r["event"] == "anomaly"]
        assert anoms and anoms[0]["detector"] == "loss_nonfinite"
        try:
            raise FloatingPointError("non-finite loss nan at step 12")
        except FloatingPointError:
            obs.close()
        assert obs.flightrec.dumped
        post = [r for r in obs.registry.records
                if r["event"] == "postmortem"]
        assert post and post[0]["bundle"] == obs.flightrec.dumped
        b = flightrec.load_bundle(obs.flightrec.dumped)
        assert "FloatingPointError" in b["meta"]["reason"]
        assert b["last"]["anomaly"][-1]["detector"] == "loss_nonfinite"
    finally:
        obs.close()  # idempotent


# --- supervised SIGKILL bundle e2e (slow: real CLI subprocesses) --------

@pytest.mark.slow
def test_detectbench_bundle_phase_e2e(tmp_path):
    from tensorflow_distributed_tpu.benchmarks import detectbench

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = detectbench.main(["--phases", "bundle",
                               "--train-steps", "24", "--out", "",
                               "--workdir", str(tmp_path)])
    assert rc == 0, buf.getvalue()
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    bundle = next(ln for ln in lines
                  if ln["metric"] == "detect_bundle")
    assert bundle["named_in_restart"]
    assert bundle["bundle_kind"] == "snapshot"   # SIGKILL: no dump ran
    assert bundle["last_anomaly_detector"] == "loss_nonfinite"
    assert bundle["postmortem_cli_ok"]
    checks = next(ln for ln in lines
                  if ln["metric"] == "detect_checks")
    assert checks["bundle_ok"]
