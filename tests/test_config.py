"""Config surface tests (replaces the reference's 14-flag system,
SURVEY.md Appendix A)."""

import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig, parse_args


def test_defaults_valid():
    cfg = TrainConfig()
    cfg.validate()
    assert cfg.model == "mnist_cnn"
    # Global batch 256 == reference's 2 workers x 128 per-worker batch
    # (mnist_python_m.py:62-70).
    assert cfg.batch_size == 256


def test_parse_args_roundtrip():
    cfg = parse_args([
        "--batch-size", "512", "--learning-rate", "0.01",
        "--train-steps", "42", "--init-scheme", "reference",
        "--mesh.data", "4", "--mesh.model", "2",
    ])
    assert cfg.batch_size == 512
    assert cfg.learning_rate == 0.01
    assert cfg.train_steps == 42
    assert cfg.init_scheme == "reference"
    assert cfg.mesh.data == 4 and cfg.mesh.model == 2


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        TrainConfig(batch_size=0).validate()
    with pytest.raises(ValueError):
        TrainConfig(dropout_rate=1.5).validate()
    with pytest.raises(ValueError):
        TrainConfig(init_scheme="bogus").validate()
    with pytest.raises(ValueError):
        TrainConfig(resume=True).validate()  # resume without checkpoint_dir
    with pytest.raises(ValueError):
        MeshConfig(model=0).validate()
    with pytest.raises(ValueError):
        TrainConfig(moe_top_k=0).validate()
    with pytest.raises(ValueError):
        TrainConfig(model="gpt_lm", moe_experts=2,
                    moe_top_k=4).validate()
    with pytest.raises(ValueError):
        TrainConfig(moe_capacity_factor=0.0).validate()
    with pytest.raises(ValueError):
        TrainConfig(label_smoothing=1.0).validate()
    with pytest.raises(ValueError):
        TrainConfig(ema_decay=-0.1).validate()


def test_moe_routing_knobs_reach_the_model():
    """--moe-top-k / --moe-capacity-factor flow into the built model."""
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.loop import _build_model_and_state
    from tensorflow_distributed_tpu.train.tasks import make_task

    cfg = TrainConfig(model="moe_lm", model_size="tiny", moe_top_k=1,
                      moe_capacity_factor=2.0, dataset="synthetic",
                      mesh=MeshConfig(data=8))
    cfg.validate()
    mesh = make_mesh(cfg.mesh)
    model, _ = _build_model_and_state(cfg, mesh, make_task(cfg, mesh))
    assert model.cfg.moe_top_k == 1
    assert model.cfg.moe_capacity_factor == 2.0


def test_reference_dead_flags_are_gone():
    # hidden_units was a dead relic in the reference (SURVEY.md Appendix
    # B.2); role flags are replaced by env bootstrap.
    names = {f.name for f in __import__("dataclasses").fields(TrainConfig)}
    for dead in ("hidden_units", "job_name", "task_index", "ps_hosts",
                 "worker_hosts", "existing_servers", "num_gpus"):
        assert dead not in names
