"""Config surface tests (replaces the reference's 14-flag system,
SURVEY.md Appendix A)."""

import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig, parse_args


def test_defaults_valid():
    cfg = TrainConfig()
    cfg.validate()
    assert cfg.model == "mnist_cnn"
    # Global batch 256 == reference's 2 workers x 128 per-worker batch
    # (mnist_python_m.py:62-70).
    assert cfg.batch_size == 256


def test_parse_args_roundtrip():
    cfg = parse_args([
        "--batch-size", "512", "--learning-rate", "0.01",
        "--train-steps", "42", "--init-scheme", "reference",
        "--mesh.data", "4", "--mesh.model", "2",
    ])
    assert cfg.batch_size == 512
    assert cfg.learning_rate == 0.01
    assert cfg.train_steps == 42
    assert cfg.init_scheme == "reference"
    assert cfg.mesh.data == 4 and cfg.mesh.model == 2


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        TrainConfig(batch_size=0).validate()
    with pytest.raises(ValueError):
        TrainConfig(dropout_rate=1.5).validate()
    with pytest.raises(ValueError):
        TrainConfig(init_scheme="bogus").validate()
    with pytest.raises(ValueError):
        TrainConfig(resume=True).validate()  # resume without checkpoint_dir
    with pytest.raises(ValueError):
        MeshConfig(model=0).validate()


def test_reference_dead_flags_are_gone():
    # hidden_units was a dead relic in the reference (SURVEY.md Appendix
    # B.2); role flags are replaced by env bootstrap.
    names = {f.name for f in __import__("dataclasses").fields(TrainConfig)}
    for dead in ("hidden_units", "job_name", "task_index", "ps_hosts",
                 "worker_hosts", "existing_servers", "num_gpus"):
        assert dead not in names
