"""Rotary position embeddings: the defining relative-position property,
cache-decode parity, seq-sharded parity, and end-to-end training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig
from tensorflow_distributed_tpu.models.transformer import (
    CausalLM, rope_rotate, tiny_config)
from tensorflow_distributed_tpu.parallel.mesh import make_mesh


def test_rope_scores_depend_on_relative_position_only():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 1, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 2, 16)), jnp.float32)

    def score(qpos, kpos):
        qr = rope_rotate(q, jnp.asarray([[qpos]]))
        kr = rope_rotate(k, jnp.asarray([[kpos]]))
        return jnp.einsum("blhd,bmhd->bhlm", qr, kr)

    np.testing.assert_allclose(score(7, 3), score(107, 103),
                               rtol=1e-4, atol=1e-5)
    # ...and DOES change when the relative offset changes.
    assert not np.allclose(score(7, 3), score(7, 5), atol=1e-3)
    # Position 0 is the identity rotation.
    np.testing.assert_array_equal(
        np.asarray(rope_rotate(q, jnp.asarray([[0]]))), np.asarray(q))


def _model(**overrides):
    return CausalLM(tiny_config(causal=True, pos_emb="rope",
                                compute_dtype=jnp.float32, **overrides))


def test_rope_decode_matches_full_forward():
    """Teacher-forced cache decode reproduces the full causal forward —
    cached keys are stored rotated, so no re-rotation per step."""
    model = _model()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 12)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    assert "pos_emb" not in params  # no additive table under rope
    full = model.apply({"params": params}, tokens)

    logits5, state = model.apply({"params": params}, tokens[:, :5],
                                 decode=True,
                                 positions=jnp.arange(5)[None, :],
                                 mutable=["cache"])
    np.testing.assert_allclose(logits5, full[:, :5], atol=1e-4, rtol=1e-3)
    cache = state["cache"]
    for t in range(5, 12):
        step_logits, state = model.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, positions=jnp.full((1, 1), t), mutable=["cache"])
        cache = state["cache"]
        np.testing.assert_allclose(step_logits[:, 0], full[:, t],
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"position {t}")


@pytest.mark.slow  # 82s on the CI box — the seq-sharded ring compile
#                    is the heaviest single default-tier compile
#                    (round-6 curation)
def test_rope_seq_sharded_matches_unsharded(devices8):
    """RoPE under ring attention: the rotation is elementwise along the
    seq dim, so a seq=8 mesh forward equals the unsharded forward."""
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch

    mesh = make_mesh(MeshConfig(data=1, seq=8), devices8)
    model_m = CausalLM(tiny_config(causal=True, pos_emb="rope",
                                   compute_dtype=jnp.float32), mesh)
    tokens = np.random.default_rng(1).integers(
        0, 64, size=(2, 64)).astype(np.int32)
    params = model_m.init(jax.random.key(0), jnp.asarray(tokens))["params"]
    with mesh:
        sharded = jax.jit(
            lambda p, t: model_m.apply({"params": p}, t))(
                params, shard_batch(mesh, tokens, seq_axis=1))
    oracle = _model().apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(oracle),
                               atol=2e-4, rtol=1e-3)


@pytest.mark.slow
def test_rope_trains_and_generates(devices8):
    from tensorflow_distributed_tpu.models.generate import generate
    from tensorflow_distributed_tpu.parallel.mesh import single_device_mesh

    model = _model(max_len=32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(4, 16)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    out = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 6)
    assert out.shape == (1, 6)

    # One train step via the standard machinery stays finite.
    import optax
    from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_clm
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        mlm_batch_shardings, mlm_loss)

    mesh = make_mesh(MeshConfig(data=8), devices8)
    model_m = CausalLM(tiny_config(causal=True, pos_emb="rope",
                                   compute_dtype=jnp.float32), mesh)
    state = create_train_state(model_m, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    step = make_train_step(mesh, loss=mlm_loss,
                           batch_shardings=mlm_batch_shardings(mesh),
                           donate=False)
    ds = synthetic_clm(n=64, seq_len=16, vocab_size=64, seed=0)
    batch = shard_batch(mesh, next(LmBatcher(ds, 16, 0).forever(0)),
                        seq_axis=1)
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_rope_theta_flows_and_changes_rotation():
    """--rope-theta reaches the model; a higher base rotates slower
    (positions stay resolvable at longer context)."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import _build_model_and_state
    from tensorflow_distributed_tpu.train.tasks import make_task

    cfg = TrainConfig(model="gpt_lm", model_size="tiny", pos_emb="rope",
                      rope_theta=500000.0, dataset="synthetic",
                      mesh=MeshConfig(data=8))
    cfg.validate()
    mesh = make_mesh(cfg.mesh)
    model, _ = _build_model_and_state(cfg, mesh, make_task(cfg, mesh))
    assert model.cfg.rope_theta == 500000.0

    # Higher theta -> strictly lower per-frequency rotation rate for
    # every i >= 1 (i=0 is theta**0 = 1 for any base).
    half = 8
    i = np.arange(half)
    f_slow = 500000.0 ** (-i / half)
    f_fast = 10000.0 ** (-i / half)
    assert f_slow[0] == f_fast[0] == 1.0
    assert (f_slow[1:] < f_fast[1:]).all()

    # Displacement comparison is only monotone while no angle wraps
    # past pi (angles are mod 2*pi!). At pos=8 the largest fast i>=1
    # angle is 8 * 10000**(-1/8) ~ 2.5 < pi, so smaller angles mean a
    # vector strictly closer to unrotated; the equal i=0 contributions
    # cancel.
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 1, 2, 16)),
                    jnp.float32)
    pos = jnp.asarray([[8]])
    d_slow = float(jnp.abs(rope_rotate(x, pos, theta=500000.0) - x).sum())
    d_fast = float(jnp.abs(rope_rotate(x, pos, theta=10000.0) - x).sum())
    assert d_slow < d_fast

    with pytest.raises(ValueError, match="rope_theta"):
        TrainConfig(model="gpt_lm", rope_theta=500000.0).validate()


def test_pipelined_accepts_rope_and_tying():
    """Round-4 change: the pipelined family supports RoPE (positions
    derived inside stage_fn) and tied embeddings (shell-local) — the
    former walls are gone. Parity with the non-pipelined family is
    pinned in tests/test_pipelined_modern.py."""
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm

    mesh = make_mesh(MeshConfig(data=8))
    m = pipelined_lm(mesh, pos_emb="rope", tie_embeddings=True)
    assert m.cfg.pos_emb == "rope" and m.cfg.tie_embeddings
