"""Fast-path serving suite: speculative decoding, int8 KV serving
knobs, and the SLO-aware scheduler.

Fast tier (jax-free, per the repo's tier rules): speculation host math
(accept_length, k-gram proposer, draft-config grammar), slo_mix
grammar, the new ServeConfig knob validation, the SLO policy against a
continuation-aware fake engine (priority inversion impossible, quota
exhaustion requeues instead of starving, preempted request's final
stream token-identical), speculative multi-token retirement semantics
(budget/EOS truncation mid-chain, accept telemetry), the journal's
class/tenant-tagged admits, and the report's new serve folding. Slow
tier (compiles the tiny GPT): real-engine self-draft token identity,
the perfect-draft accept-rate pin, int8 cache accounting on a real
engine, and a mode=serve e2e with speculation + SLO armed.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from tensorflow_distributed_tpu.serve.scheduler import (
    Request, Scheduler, parse_slo_mix)
from tensorflow_distributed_tpu.serve.speculate import (
    accept_length, kgram_propose, parse_draft_config)


# --- speculation host math ---------------------------------------------

def test_accept_length():
    # Full accept, partial, none; the bonus token is NOT counted here.
    assert accept_length([5, 6, 7], [5, 6, 7, 8]) == 3
    assert accept_length([5, 6, 9], [5, 6, 7, 8]) == 2
    assert accept_length([1, 2, 3], [9, 9, 9, 9]) == 0
    with pytest.raises(ValueError, match="k \\+ 1"):
        accept_length([1, 2], [1, 2])


def test_kgram_propose_periodic_history():
    # Period-4 history: the most recent earlier suffix occurrence is
    # one period back, so proposals continue the cycle exactly.
    hist = [1, 2, 3, 4] * 3
    assert kgram_propose(hist, k=4, g=3) == [1, 2, 3, 4]
    # Continuation shorter than k pads by repeating its final token.
    assert kgram_propose(hist, k=6, g=3) == [1, 2, 3, 4, 4, 4]


def test_kgram_propose_fallbacks():
    # No earlier occurrence -> repeat the last token (the degenerate
    # argmax-loop case); empty history -> zeros.
    assert kgram_propose([7, 8, 9], k=3, g=3) == [9, 9, 9]
    assert kgram_propose([], k=2) == [0, 0]
    # History shorter than the suffix still proposes.
    assert kgram_propose([4], k=2, g=3) == [4, 4]
    # Match whose continuation is shorter than k pads by extension.
    assert kgram_propose([5, 1, 2, 3, 5, 1, 2, 3], k=6, g=3)[:4] == [
        5, 1, 2, 3]


def test_parse_draft_config():
    assert parse_draft_config("tiny") == {"size": "tiny",
                                          "overrides": {}}
    parsed = parse_draft_config("size=tiny,n_layers=1,pos_emb=rope")
    assert parsed["size"] == "tiny"
    assert parsed["overrides"] == {"n_layers": 1, "pos_emb": "rope"}
    with pytest.raises(ValueError, match="key=value"):
        parse_draft_config("tiny,n_layers=1")
    with pytest.raises(ValueError, match="empty"):
        parse_draft_config("")


def test_parse_slo_mix():
    mix = parse_slo_mix("high:0.25,batch:0.25")
    assert mix == {"high": 0.25, "batch": 0.25, "standard": 0.5}
    assert parse_slo_mix("high:1")["standard"] == 0.0
    with pytest.raises(ValueError, match="unknown SLO class"):
        parse_slo_mix("gold:0.5")
    with pytest.raises(ValueError, match="class:fraction"):
        parse_slo_mix("high=0.5")
    with pytest.raises(ValueError, match="twice"):
        parse_slo_mix("high:0.2,high:0.2")
    with pytest.raises(ValueError, match="> 1"):
        parse_slo_mix("high:0.8,batch:0.4")


# --- config validation (the new serve knobs) ---------------------------

def _serve_cfg(**kw):
    from tensorflow_distributed_tpu.config import TrainConfig

    cfg = TrainConfig(mode="serve", model="gpt_lm")
    for k, v in kw.items():
        setattr(cfg.serve, k, v)
    return cfg


def test_serve_config_new_knobs_valid():
    _serve_cfg(spec_tokens=4).validate()
    _serve_cfg(spec_tokens=4, draft_config="tiny").validate()
    _serve_cfg(kv_dtype="int8").validate()
    _serve_cfg(policy="slo", tenant_quota=64, tenants=2,
               slo_mix="high:0.25").validate()
    # A request file carries its own tenant fields — quota without
    # --serve.tenants is meaningful there.
    _serve_cfg(policy="slo", tenant_quota=64,
               requests="r.jsonl").validate()


@pytest.mark.parametrize("kw,match", [
    (dict(spec_tokens=-1), "spec_tokens"),
    (dict(draft_config="tiny"), "spec-tokens"),
    (dict(spec_tokens=2, spec_kgram=0), "spec_kgram"),
    (dict(kv_dtype="fp8"), "kv_dtype"),
    (dict(policy="edf"), "policy"),
    (dict(tenant_quota=-1), "tenant_quota"),
    (dict(tenant_quota=5), "policy slo"),
    (dict(policy="slo", tenant_quota=5), "tenants to meter"),
    (dict(slo_mix="high:0.5"), "policy slo"),
    (dict(policy="slo", slo_mix="gold:0.5"), "unknown SLO class"),
    (dict(policy="slo", slo_mix="high:0.5", requests="r.jsonl"),
     "SYNTHETIC"),
    (dict(tenants=0), "tenants"),
])
def test_serve_config_new_knob_rejections(kw, match):
    with pytest.raises(ValueError, match=match):
        _serve_cfg(**kw).validate()


# --- fake engines (no jax; continuation-aware streams) ------------------

class _SLOFakeEngine:
    """Host-only engine: token stream is a pure function of
    (rid, tokens-emitted-so-far) — prefill of a continuation prompt
    resumes the SAME stream, so token identity through preemption is
    checkable exactly. rid rides prompt[0]; emitted count =
    len(prompt) - 1 (base prompts are length 1)."""

    def __init__(self, num_slots=1, max_len=256):
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = (64, 128)
        self.active = np.zeros((num_slots,), bool)
        self.slot_rid = {}
        self.counts = {}
        self.prefills = 0
        self.prefill_compiles = 0
        self.decode_steps = 0

    def fits(self, plen, max_new):
        return plen + max_new <= self.max_len

    def free_slots(self):
        return [s for s in range(self.num_slots) if not self.active[s]]

    def occupancy(self):
        return float(self.active.sum()) / self.num_slots

    def prefill(self, prompt, slot):
        rid = int(prompt[0])
        self.active[slot] = True
        self.slot_rid[slot] = rid
        self.counts[rid] = len(prompt) - 1   # continuation-aware
        self.prefills += 1
        return rid * 100 + self.counts[rid]

    def step(self):
        out = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if self.active[s]:
                rid = self.slot_rid[s]
                self.counts[rid] += 1
                out[s] = rid * 100 + self.counts[rid]
        self.decode_steps += 1
        return out

    def free(self, slot):
        self.active[slot] = False


class _SpecFakeEngine(_SLOFakeEngine):
    """Adds the speculative surface: every verify dispatch accepts
    ``accept`` proposals (+ the bonus), emitting the same deterministic
    stream in chunks."""

    def __init__(self, num_slots=1, max_len=256, spec_tokens=3,
                 accept=None):
        super().__init__(num_slots, max_len)
        self.spec_tokens = spec_tokens
        self.accept = (spec_tokens if accept is None else accept)
        self.verify_steps = 0

    def can_verify(self):
        return True

    def verify_step(self, props):
        k = self.spec_tokens
        assert np.asarray(props).shape == (self.num_slots, k)
        toks = np.zeros((self.num_slots, k + 1), np.int32)
        acc = np.zeros((self.num_slots,), np.int32)
        for s in range(self.num_slots):
            if not self.active[s]:
                continue
            rid = self.slot_rid[s]
            a = min(self.accept, k)
            for j in range(a + 1):
                self.counts[rid] += 1
                toks[s, j] = rid * 100 + self.counts[rid]
            acc[s] = a + 1
        self.decode_steps += 1
        self.verify_steps += 1
        return toks, acc


class _CountingSpeculator:
    """Records the scheduler's lifecycle calls; proposes zeros."""

    def __init__(self, num_slots, k):
        self.num_slots, self.k = num_slots, k
        self.admits = []
        self.frees = []
        self.syncs = 0

    def propose(self, histories):
        # Histories must cover exactly the live slots.
        assert all(len(h) > 0 for h in histories.values())
        return np.zeros((self.num_slots, self.k), np.int32)

    def observe_admit(self, slot, prompt, first_tok):
        self.admits.append((slot, int(first_tok)))

    def observe_free(self, slot):
        self.frees.append(slot)

    def sync_from(self, engine):
        self.syncs += 1


def _expected(rid, max_new, plen=1):
    return [rid * 100 + (plen - 1) + j for j in range(max_new)]


# --- SLO policy against the fake engine --------------------------------

def _admission_order(reqs, **kw):
    eng = _SLOFakeEngine(num_slots=1)
    seen = []
    sched = Scheduler(eng, decode_priority=2,
                      on_token=lambda rid, tok, fin: (
                          seen.append(rid) if rid not in seen else None),
                      **kw)
    done = sched.run(reqs)
    assert len(done) == len(reqs)
    return seen, done, sched


def test_slo_no_priority_inversion():
    """A high-class arrival never queues behind a lower class while a
    slot frees: with everything queued at t=0 on one slot, admission
    order is class order (then arrival), not arrival order."""
    reqs = [Request(rid=0, prompt=np.asarray([0], np.int32),
                    max_new_tokens=4, slo="standard"),
            Request(rid=1, prompt=np.asarray([1], np.int32),
                    max_new_tokens=4, slo="batch"),
            Request(rid=2, prompt=np.asarray([2], np.int32),
                    max_new_tokens=4, slo="standard"),
            Request(rid=3, prompt=np.asarray([3], np.int32),
                    max_new_tokens=4, slo="high"),
            Request(rid=4, prompt=np.asarray([4], np.int32),
                    max_new_tokens=4, slo="high")]
    fifo_order, _, _ = _admission_order(reqs, policy="fifo")
    assert fifo_order == [0, 1, 2, 3, 4]          # arrival order
    slo_order, done, _ = _admission_order(reqs, policy="slo")
    # The t=0 pick is already class-ordered: highs (arrival order
    # within the class), then standards, then batch LAST.
    assert slo_order == [3, 4, 0, 2, 1]
    # Streams are unaffected by admission order (identical per rid).
    for c in done:
        assert c.tokens == _expected(c.rid, 4)


def test_slo_quota_exhaustion_requeues_not_starves():
    """A tenant at its token quota is deferred while an under-quota
    tenant waits — and still served once nothing under-quota remains
    (work-conserving: exhaustion cannot starve)."""
    reqs = [Request(rid=0, prompt=np.asarray([0], np.int32),
                    max_new_tokens=6, tenant="a"),
            Request(rid=1, prompt=np.asarray([1], np.int32),
                    max_new_tokens=6, tenant="a"),
            Request(rid=2, prompt=np.asarray([2], np.int32),
                    max_new_tokens=6, tenant="b")]
    order, done, sched = _admission_order(reqs, policy="slo",
                                          tenant_quota=4)
    # rid0 exhausts tenant a's quota (6 tokens > 4): rid2 (tenant b,
    # under quota) jumps rid1 despite arriving later; rid1 still
    # completes with its full exact stream.
    assert order == [0, 2, 1]
    assert all(c.tokens == _expected(c.rid, 6) for c in done)
    # Without quotas, arrival order holds.
    order2, _, _ = _admission_order(
        [Request(rid=r.rid, prompt=r.prompt,
                 max_new_tokens=r.max_new_tokens, tenant=r.tenant)
         for r in reqs], policy="slo")
    assert order2 == [0, 1, 2]


def test_slo_preempt_token_identity():
    """Preempt-and-requeue: a late high-class arrival evicts the live
    batch request once it has waited out the decode-priority clock;
    the preempted request's FINAL stream is token-identical to the
    unpreempted (FIFO) run, and the preemption is accounted."""
    import itertools

    # A fake clock the test drives: arrivals keyed to decode steps.
    t = itertools.count()

    def reqs():
        return [Request(rid=0, prompt=np.asarray([0], np.int32),
                        max_new_tokens=12, slo="batch"),
                Request(rid=1, prompt=np.asarray([1], np.int32),
                        max_new_tokens=4, arrival_s=3.0, slo="high")]

    def run(policy):
        eng = _SLOFakeEngine(num_slots=1)
        sched = Scheduler(eng, decode_priority=2, policy=policy,
                          clock=lambda: float(next(t)))
        return {c.rid: c for c in sched.run(reqs())}, sched

    done_f, _ = run("fifo")
    t = itertools.count()
    done_s, sched = run("slo")
    assert sched.summary["preemptions"] == 1
    assert done_s[0].preempts == 1
    # The high request was served mid-batch-request, so it FINISHED
    # before the preempted one despite arriving later.
    assert done_s[1].decoded == 4
    # Token identity: the preemption continuation re-derives exactly
    # the stream the unpreempted run produced.
    for rid in (0, 1):
        assert done_s[rid].tokens == done_f[rid].tokens
        assert done_s[rid].tokens == _expected(rid, len(
            done_f[rid].tokens))


def test_slo_preempt_emits_event_not_recovery():
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    import itertools
    t = itertools.count()
    eng = _SLOFakeEngine(num_slots=1)
    reg = MetricsRegistry()
    sched = Scheduler(eng, decode_priority=2, policy="slo",
                      registry=reg, clock=lambda: float(next(t)))
    sched.run([Request(rid=0, prompt=np.asarray([0], np.int32),
                       max_new_tokens=12, slo="batch"),
               Request(rid=1, prompt=np.asarray([1], np.int32),
                       max_new_tokens=4, arrival_s=3.0, slo="high")])
    events = [r["event"] for r in reg.records]
    assert "preempt" in events
    assert "recovery" not in events   # policy, not failure
    req_recs = [r for r in reg.records if r["event"] == "serve_request"]
    assert {r["slo"] for r in req_recs} == {"high", "batch"}
    # Preemption continuations are NOT the recovery population.
    assert not any(r["recovery_window"] for r in req_recs)
    summary = [r for r in reg.records if r["event"] == "serve_summary"]
    assert summary[-1]["policy"] == "slo"
    assert summary[-1]["preemptions"] == 1


def test_preempt_skips_victim_outgrowing_ladder():
    """Preemption is ELECTIVE: a victim whose continuation prompt
    would exceed a user-pinned bucket ladder is skipped instead of
    crashing the run — the high request waits for a natural free."""
    import itertools

    t = itertools.count()
    eng = _SLOFakeEngine(num_slots=1)
    eng.buckets = (8,)                  # tight user-pinned ladder
    reqs = [Request(rid=0, prompt=np.asarray([0] * 7, np.int32),
                    max_new_tokens=10, slo="batch"),
            Request(rid=1, prompt=np.asarray([1], np.int32),
                    max_new_tokens=3, arrival_s=4.0, slo="high")]
    sched = Scheduler(eng, decode_priority=2, policy="slo",
                      clock=lambda: float(next(t)))
    done = {c.rid: c for c in sched.run(reqs)}
    assert sched.summary["preemptions"] == 0    # skipped, not crashed
    assert len(done[0].tokens) == 10 and len(done[1].tokens) == 3


def test_preempt_keeps_recovery_provenance():
    """A journal-replay continuation (recovery base tokens) that later
    gets preempted must STAY in the recovery-window population — the
    policy flag must not erase recovery provenance."""
    import itertools

    t = itertools.count()
    eng = _SLOFakeEngine(num_slots=1)
    cont = Request(rid=0, prompt=np.asarray([0, 100, 101], np.int32),
                   max_new_tokens=10, slo="batch")
    cont._base_tokens = [100, 101]     # replayed by a dead leg
    high = Request(rid=1, prompt=np.asarray([1], np.int32),
                   max_new_tokens=4, arrival_s=3.0, slo="high")
    sched = Scheduler(eng, decode_priority=2, policy="slo",
                      clock=lambda: float(next(t)))
    done = {c.rid: c for c in sched.run([cont, high])}
    assert sched.summary["preemptions"] == 1
    assert done[0].preempts == 1
    assert done[0].recovery_window     # provenance survived preemption
    # A preempted FRESH request stays out of the recovery population.
    assert not done[1].recovery_window


# --- speculative retirement semantics (fake engine) --------------------

def test_spec_multi_token_retirement_and_stats():
    """One verify dispatch retires accepted+1 tokens per slot in
    stream order; the summary carries the accept telemetry."""
    eng = _SpecFakeEngine(num_slots=2, spec_tokens=3)
    spec = _CountingSpeculator(2, 3)
    sched = Scheduler(eng, decode_priority=2, speculator=spec)
    done = {c.rid: c for c in sched.run(
        [Request(rid=i, prompt=np.asarray([i], np.int32),
                 max_new_tokens=9) for i in range(3)])}
    for rid, c in done.items():
        assert c.tokens == _expected(rid, 9)
    s = sched.summary
    assert s["verify_steps"] == eng.verify_steps > 0
    assert s["accept_rate"] == 1.0          # fake accepts everything
    assert s["spec_proposed"] >= s["spec_accepted"] > 0
    # Lifecycle hooks: every admission/free mirrored to the
    # speculator, one sync per decode iteration.
    assert len(spec.admits) == 3 and len(spec.frees) == 3
    assert spec.syncs == eng.decode_steps


def test_spec_budget_truncated_mid_chain():
    """A request whose budget lands mid-chain stops exactly at the
    budget — surplus accepted tokens are discarded, never streamed or
    journaled."""
    eng = _SpecFakeEngine(num_slots=1, spec_tokens=4)
    spec = _CountingSpeculator(1, 4)
    streamed = []
    sched = Scheduler(eng, decode_priority=2, speculator=spec,
                      on_token=lambda rid, tok, fin: streamed.append(
                          tok))
    done = sched.run([Request(rid=1, prompt=np.asarray([1], np.int32),
                              max_new_tokens=7)])   # 1 + 5 + trunc
    assert done[0].tokens == _expected(1, 7)
    assert done[0].finish == "length"
    assert len(done[0].tokens) == 7
    assert streamed == done[0].tokens   # nothing past the budget


def test_spec_eos_truncates_mid_chain():
    eos = 1 * 100 + 3                  # 4th emitted token of rid 1
    #                                    (prefill emits rid*100 + 0)
    eng = _SpecFakeEngine(num_slots=1, spec_tokens=4)
    sched = Scheduler(eng, decode_priority=2,
                      speculator=_CountingSpeculator(1, 4))
    done = sched.run([Request(rid=1, prompt=np.asarray([1], np.int32),
                              max_new_tokens=20, eos_id=eos)])
    assert done[0].finish == "eos"
    assert done[0].tokens == _expected(1, 4)
    assert done[0].tokens[-1] == eos


def test_spec_falls_back_without_headroom():
    """can_verify() False routes the iteration through the plain
    step — the stream is seamless across the mode switch."""

    class _Flaky(_SpecFakeEngine):
        def can_verify(self):
            return self.decode_steps % 2 == 0   # alternate modes

    eng = _Flaky(num_slots=1, spec_tokens=3)
    sched = Scheduler(eng, decode_priority=2,
                      speculator=_CountingSpeculator(1, 3))
    done = sched.run([Request(rid=2, prompt=np.asarray([2], np.int32),
                              max_new_tokens=10)])
    assert done[0].tokens == _expected(2, 10)
    assert 0 < eng.verify_steps < eng.decode_steps


# --- journal: class/tenant-tagged admits -------------------------------

def test_journal_admit_carries_slo_tenant(tmp_path):
    from tensorflow_distributed_tpu.serve import journal as journal_mod

    path = str(tmp_path / "j.jsonl")
    j = journal_mod.RequestJournal(path)
    j.admit(0, [5, 6], 8, -1, slo="high", tenant="acme")
    j.admit(1, [7], 8, -1)                 # defaults stay compact
    j.token(0, 9, 0.5)
    j.close()
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["slo"] == "high" and lines[0]["tenant"] == "acme"
    assert "slo" not in lines[1] and "tenant" not in lines[1]
    # Replay (the resume path) is untouched by the new fields.
    played = journal_mod.replay(path)
    assert played[0]["tokens"] == [9] and not played[0]["done"]


# --- report folding ----------------------------------------------------

def test_report_folds_slo_and_spec(tmp_path):
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    recs = ([{"event": "serve_request", "rid": i,
              "ttft_ms": 10.0 + 50.0 * (i % 2), "tok_ms": 2.0,
              "slo": ("high" if i % 2 == 0 else "batch")}
             for i in range(10)]
            + [{"event": "preempt", "rid": 3, "slot": 0},
               {"event": "serve_summary", "tokens_per_sec": 900.0,
                "policy": "slo", "preemptions": 1, "spec_tokens": 4,
                "verify_steps": 42, "accept_rate": 0.8}])
    path = tmp_path / "m.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = summarize(load_records(str(path)))
    assert out["serve_policy"] == "slo"
    assert out["serve_preemptions"] == 1
    assert out["serve_preempt_events"] == 1
    assert out["serve_accept_rate"] == 0.8
    assert out["serve_spec_tokens"] == 4
    assert out["serve_ttft_ms_p95_high"] == pytest.approx(10.0)
    assert out["serve_ttft_ms_p95_batch"] == pytest.approx(60.0)


def test_report_plain_fifo_unchanged(tmp_path):
    """No classes beyond the default -> no per-class keys (plain
    reports keep their exact shape)."""
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)

    recs = [{"event": "serve_request", "rid": i, "ttft_ms": 5.0,
             "slo": "standard"} for i in range(4)]
    path = tmp_path / "m.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = summarize(load_records(str(path)))
    assert not any(k.startswith("serve_ttft_ms_p95_") for k in out)


# --- real engine (slow tier) -------------------------------------------

def _tiny_serving_model(max_len=96, **overrides):
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.models.transformer import gpt_lm

    model = gpt_lm(None, size="tiny", max_len=max_len,
                   dropout_rate=0.0, **overrides)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.mark.slow
def test_spec_self_draft_token_identity_real_engine():
    """Speculation is token-identical to plain continuous decode on
    the REAL engine (fresh-init chains are chaotic — accept rate ~0 —
    which is exactly the adversarial case for identity)."""
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.speculate import SelfDraft

    model, params = _tiny_serving_model()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32)
               for n in rng.integers(4, 24, size=6)]
    buckets = default_buckets(32)

    def run(spec_tokens):
        eng = SlotDecodeEngine(model, params, 2, buckets=buckets,
                               spec_tokens=spec_tokens)
        spec = (SelfDraft(2, spec_tokens) if spec_tokens else None)
        sched = Scheduler(eng, decode_priority=3, speculator=spec)
        reqs = [Request(rid=i, prompt=p, max_new_tokens=24)
                for i, p in enumerate(prompts)]
        return {c.rid: c.tokens for c in sched.run(reqs)}, sched

    ref, _ = run(0)
    out, sched = run(4)
    assert all(ref[i] == out[i] for i in range(len(prompts)))
    assert sched.summary["verify_steps"] > 0
    assert 0.0 <= sched.summary["accept_rate"] <= 1.0


@pytest.mark.slow
def test_perfect_draft_accepts_everything_real_engine():
    """A DraftSpeculator whose draft IS the target model proposes the
    target's own argmax chain — every proposal accepted, accept_rate
    exactly 1.0, output still token-identical. Pins the draft-model
    mirror (prefill/insert/scan/sync) end to end."""
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine
    from tensorflow_distributed_tpu.serve.speculate import (
        DraftSpeculator)

    model, params = _tiny_serving_model()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, model.cfg.vocab_size,
                            size=int(n)).astype(np.int32)
               for n in rng.integers(4, 16, size=4)]
    buckets = default_buckets(16)
    K = 3

    def run(spec):
        eng = SlotDecodeEngine(model, params, 2, buckets=buckets,
                               spec_tokens=K if spec else 0)
        drafter = (DraftSpeculator(model, params, 2, buckets, K)
                   if spec else None)
        sched = Scheduler(eng, decode_priority=3, speculator=drafter)
        reqs = [Request(rid=i, prompt=p, max_new_tokens=16)
                for i, p in enumerate(prompts)]
        return {c.rid: c.tokens for c in sched.run(reqs)}, sched

    ref, _ = run(False)
    out, sched = run(True)
    assert all(ref[i] == out[i] for i in range(len(prompts)))
    assert sched.summary["accept_rate"] == 1.0


@pytest.mark.slow
def test_int8_engine_cache_accounting_and_serving():
    """kv_cache_quant=int8 really shrinks HBM per slot (scale leaves
    included) at head dim 64, and the quantized engine serves a
    workload end to end."""
    from tensorflow_distributed_tpu.serve.buckets import default_buckets
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    kw = dict(d_model=64, n_heads=1, d_ff=128, max_len=48)
    model_b, params = _tiny_serving_model(**kw)
    model_q, _ = _tiny_serving_model(kv_cache_quant="int8", **kw)
    buckets = default_buckets(16, cap=48)
    eng_b = SlotDecodeEngine(model_b, params, 2, buckets=buckets)
    eng_q = SlotDecodeEngine(model_q, params, 2, buckets=buckets)
    ratio = eng_b.cache_bytes_per_slot() / eng_q.cache_bytes_per_slot()
    assert ratio >= 1.8          # 2*dh/(dh+4) = 1.88 at dh=64
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, model_q.cfg.vocab_size,
                            size=8).astype(np.int32) for _ in range(3)]
    done = Scheduler(eng_q, decode_priority=3).run(
        [Request(rid=i, prompt=p, max_new_tokens=12)
         for i, p in enumerate(prompts)])
    assert all(len(c.tokens) == 12 for c in done)
    assert all(0 <= t < model_q.cfg.vocab_size
               for c in done for t in c.tokens)


@pytest.mark.slow
def test_serve_run_spec_slo_e2e(tmp_path):
    """mode=serve with speculation + the SLO scheduler armed: the
    summary carries accept telemetry and per-class p95s, and the
    JSONL folds through observe.report."""
    from tensorflow_distributed_tpu.config import TrainConfig
    from tensorflow_distributed_tpu.observe.report import (
        load_records, summarize)
    from tensorflow_distributed_tpu.serve.run import serve_run

    cfg = TrainConfig(mode="serve", model="gpt_lm", model_size="tiny",
                      seed=3)
    cfg.serve.num_requests = 6
    cfg.serve.num_slots = 2
    cfg.serve.max_new_tokens = 10
    cfg.serve.arrival_rate = 200.0
    cfg.serve.policy = "slo"
    cfg.serve.slo_mix = "high:0.3,batch:0.3"
    cfg.serve.spec_tokens = 3
    cfg.serve.kv_dtype = "int8"
    cfg.observe.metrics_jsonl = str(tmp_path / "m.jsonl")
    cfg.validate()
    summary = serve_run(cfg)
    assert summary["requests"] == 6
    assert summary["policy"] == "slo"
    assert "accept_rate" in summary
    assert any(k.startswith("ttft_ms_p95_") for k in summary)
    out = summarize(load_records(cfg.observe.metrics_jsonl))
    assert out["serve_policy"] == "slo"
    assert "serve_accept_rate" in out
