"""Device-side telemetry: compiled-program registry (observe/device.py)
and on-device model-health metrics (observe/health.py).

Covers the acceptance surface: program records for train AND serve
jits with cost/memory fields present-or-explicitly-null, health
records landing in the JSONL only on cadence steps with zero extra
host transfers off-cadence (transfer-counting shim), the report's
Programs/Health sections, and the malformed-JSONL skip path."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import (
    MeshConfig, ObserveConfig, TrainConfig)
from tensorflow_distributed_tpu.observe import device, health, report


@pytest.fixture(autouse=True)
def _device_registry_isolation():
    """Each test sees a clean process-level program registry and a
    disarmed instrument gate."""
    device.reset()
    device.set_enabled(False)
    yield
    device.set_enabled(False)
    device.reset()


# --- register_compiled / instrument ------------------------------------

def test_register_compiled_degrades_to_explicit_nulls():
    rec = device.register_compiled("nothing", None, None)
    for key in ("flops", "bytes_accessed", "argument_bytes",
                "output_bytes", "temp_bytes", "generated_code_bytes",
                "donated_bytes", "peak_hbm_bytes", "lower_s",
                "compile_s"):
        assert key in rec and rec[key] is None, key
    assert device.programs()[-1]["program"] == "nothing"


def test_register_compiled_real_program_cost_and_memory():
    @jax.jit
    def f(x):
        return jnp.tanh(x @ x)

    x = jnp.ones((32, 32))
    lowered = f.lower(x)
    compiled = lowered.compile()
    rec = device.register_compiled("matmul", lowered, compiled,
                                   lower_s=0.01, compile_s=0.5)
    assert rec["flops"] and rec["flops"] > 0
    assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
    assert rec["argument_bytes"] == 32 * 32 * 4
    assert rec["peak_hbm_bytes"] is not None
    assert rec["compile_s"] == 0.5


def test_register_compiled_donated_bytes():
    # A run-unique constant keeps this program out of the persistent
    # compile cache: only a FRESH compile reliably reports alias
    # (donation) bytes — cache-deserialized executables can report 0.
    import os
    salt = float(int.from_bytes(os.urandom(4), "little") % 997 + 1)
    jitted = jax.jit(lambda x: x + salt, donate_argnums=(0,))
    x = jnp.ones((64, 64))
    lowered = jitted.lower(x)
    rec = device.register_compiled("donating", lowered,
                                   lowered.compile())
    # The donated input aliases the output: the savings are real bytes
    # and the peak estimate counts the buffer once.
    assert rec["donated_bytes"] == 64 * 64 * 4
    assert rec["peak_hbm_bytes"] is not None


def test_instrument_registers_once_per_enable_and_delegates():
    calls = []

    @jax.jit
    def f(x):
        return x * 2

    wrapped = device.instrument("double", f)
    # Disarmed: executes, registers nothing.
    assert float(wrapped(jnp.asarray(3.0))) == 6.0
    assert device.programs() == []
    # Armed: first call registers, later calls don't re-register.
    device.set_enabled(True)
    assert float(wrapped(jnp.asarray(4.0))) == 8.0
    assert [r["program"] for r in device.programs()] == ["double"]
    wrapped(jnp.asarray(5.0))
    assert len(device.programs()) == 1
    # A re-enable (new run in the same process, e.g. the lru-cached
    # generate/serve programs) registers again so the new run's JSONL
    # gets its own compile record.
    device.set_enabled(False)
    device.set_enabled(True)
    wrapped(jnp.asarray(6.0))
    assert [r["program"] for r in device.programs()] == ["double"] * 2
    del calls


def test_instrument_never_breaks_the_call_on_bad_registration():
    device.set_enabled(True)
    wrapped = device.instrument("plain_python", lambda x: x + 1)
    assert wrapped(41) == 42  # no .lower -> null record, call intact
    rec = device.programs()[-1]
    assert rec["program"] == "plain_python"
    assert rec["flops"] is None and "error" in rec


def test_budget_table_and_rollup():
    device.register_compiled("big", None, None)
    # Hand-shape a record via a real compiled program for the table.
    jitted = jax.jit(lambda x: x @ x)
    x = jnp.ones((16, 16))
    lo = jitted.lower(x)
    device.register_compiled("small", lo, lo.compile())
    table = device.budget_table()
    assert "big" in table and "small" in table
    budget = device.hbm_budget()
    assert budget["programs"] == 2
    assert budget["peak_hbm_bytes_sum"] > 0


# --- the real train + serve programs -----------------------------------

def _tiny_causal_model():
    from tensorflow_distributed_tpu.models.transformer import (
        CausalLM, tiny_config)
    model = CausalLM(tiny_config(causal=True, max_len=32))
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_train_step_program_registered(mesh8):
    import optax

    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step

    device.set_enabled(True)
    state = create_train_state(MnistCNN(), optax.adam(1e-3),
                               np.zeros((2, 28, 28, 1), np.float32),
                               mesh8)
    step = make_train_step(mesh8)
    batch = (jnp.zeros((16, 28, 28, 1)), jnp.zeros((16,), jnp.int32))
    state, metrics = step(state, batch)
    jax.block_until_ready(metrics)
    by_name = {r["program"]: r for r in device.programs()}
    assert "train_step" in by_name
    rec = by_name["train_step"]
    # Fields present — real values on this backend, or explicit nulls.
    for key in ("flops", "peak_hbm_bytes", "donated_bytes",
                "compile_s"):
        assert key in rec
    # CPU exposes the analyses; the step donates its state. The
    # donated-bytes VALUE is cache-dependent — an executable
    # deserialized from the warm persistent compile cache reports
    # alias bytes as 0 (same class of cache-deserialization quirk
    # train/checkpoint.py::launder_buffers documents) — so assert the
    # field is populated, not its magnitude (the fresh-compile
    # magnitude is pinned by test_register_compiled_donated_bytes).
    assert rec["flops"] and rec["flops"] > 0
    assert rec["donated_bytes"] is not None and rec["donated_bytes"] >= 0


def test_serve_engine_programs_registered():
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    device.set_enabled(True)
    model, params = _tiny_causal_model()
    engine = SlotDecodeEngine(model, params, num_slots=2,
                              buckets=(8, 16))
    engine.prefill(np.arange(5, dtype=np.int32) % 7, slot=0)
    engine.step()
    names = {r["program"] for r in device.programs()}
    assert {"serve_prefill_b8", "serve_insert_row",
            "serve_decode_step"} <= names, names
    for rec in device.programs():
        assert "peak_hbm_bytes" in rec and "flops" in rec


# --- health stats (unit) ------------------------------------------------

def test_health_stats_cadence_gating_on_device():
    params = {"layer_0": {"w": jnp.ones((4, 4))},
              "head": {"w": jnp.full((2, 2), 2.0)}}
    grads = {"layer_0": {"w": jnp.full((4, 4), 0.5)},
             "head": {"w": jnp.full((2, 2), 0.25)}}
    updates = {"layer_0": {"w": jnp.full((4, 4), -0.01)},
               "head": {"w": jnp.full((2, 2), -0.02)}}

    @jax.jit
    def at_step(step):
        return health.stats(params, grads, updates, step,
                            health_every=10)

    on = at_step(jnp.asarray(9))    # (9 + 1) % 10 == 0 -> emit
    off = at_step(jnp.asarray(3))
    assert float(on[health.EMIT_KEY]) == 1.0
    assert float(off[health.EMIT_KEY]) == 0.0
    # Emitting step: real vitals.
    assert float(on["health/layer_0/grad_norm"]) == pytest.approx(
        0.5 * 4, rel=1e-5)          # sqrt(16 * 0.25)
    assert float(on["health/layer_0/param_rms"]) == pytest.approx(
        1.0, rel=1e-5)
    assert float(on["health/head/update_ratio"]) == pytest.approx(
        (0.02 * 2) / (2.0 * 2), rel=1e-5)
    # Off-cadence: zeros (the cond's cheap branch), same key set.
    assert set(on) == set(off)
    assert all(float(v) == 0.0 for v in off.values())


def test_health_split_and_group():
    host = {"loss": 1.5, "health_emit": 1.0,
            "health/layer_0/grad_norm": 0.1,
            "health/layer_0/act_rms": 0.9,
            "health/head/update_ratio": 2e-3}
    plain, scalars, emitted = health.split(host)
    assert plain == {"loss": 1.5} and emitted
    groups = dict(health.group(scalars))
    assert groups["layer_0"] == {"grad_norm": 0.1, "act_rms": 0.9}
    assert groups["head"] == {"update_ratio": 2e-3}


# --- e2e: tiny GPT with health + program registry -----------------------

def _health_cfg(tmp_path, *, health, steps=20, log_every=10):
    return TrainConfig(
        model="gpt_lm", model_size="tiny", dataset="synthetic",
        batch_size=16, train_steps=steps, eval_every=0,
        log_every=log_every, eval_batch_size=16,
        compute_dtype="float32", dropout_rate=0.0,
        mesh=MeshConfig(data=8),
        observe=ObserveConfig(
            metrics_jsonl=str(tmp_path / "m.jsonl"),
            health=health, health_taps=health))


def test_health_e2e_records_only_on_cadence(tmp_path):
    from tensorflow_distributed_tpu.train.loop import train

    train(_health_cfg(tmp_path, health=True))
    records = [json.loads(line) for line in open(tmp_path / "m.jsonl")]
    healths = [r for r in records if r["event"] == "health"]
    assert healths, "no health records emitted"
    # Per-layer records land ONLY on cadence steps.
    assert sorted({h["step"] for h in healths}) == [10, 20]
    modules = {h["module"] for h in healths}
    assert {"layer_0", "layer_1", "tok_emb", "lm_head"} <= modules
    by_mod = {h["module"]: h for h in healths if h["step"] == 20}
    for mod in ("layer_0", "tok_emb"):
        assert by_mod[mod]["grad_norm"] > 0
        assert by_mod[mod]["update_ratio"] > 0
        assert by_mod[mod]["param_rms"] > 0
    # Activation taps rode the same records for the blocks.
    assert by_mod["layer_0"]["act_rms"] > 0
    # Health scalars must NOT pollute the step records' columns.
    steps = [r for r in records if r["event"] == "step"]
    assert steps and not any(k.startswith("health/") or k == "health_emit"
                             for k in steps[-1])
    # The program registry rode the same run (observe.programs default).
    compiled = {r["program"] for r in records if r["event"] == "compile"}
    assert "train_step" in compiled and "eval_step" in compiled
    assert any(r["event"] == "hbm_budget" for r in records)


def test_health_off_cadence_adds_zero_device_gets(tmp_path,
                                                  monkeypatch):
    """The acceptance contract: enabling health changes WHAT the
    cadence fetch carries, never HOW OFTEN the host reads the device —
    counted through a jax.device_get shim over two otherwise-identical
    tiny runs."""
    from tensorflow_distributed_tpu.train import loop as loop_mod

    real_get = jax.device_get

    def run(health):
        count = [0]

        def counting_get(*a, **k):
            count[0] += 1
            return real_get(*a, **k)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            loop_mod.train(_health_cfg(
                tmp_path / ("on" if health else "off"), health=health,
                steps=12, log_every=4))
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        return count[0]

    (tmp_path / "on").mkdir()
    (tmp_path / "off").mkdir()
    assert run(health=True) == run(health=False)


# --- report sections ----------------------------------------------------

def test_report_programs_and_health_sections():
    records = [
        {"event": "compile", "program": "train_step", "flops": 1e9,
         "peak_hbm_bytes": 3 * 1024 * 1024, "donated_bytes": 1024,
         "compile_s": 1.25},
        {"event": "compile", "program": "no_analysis", "flops": None,
         "peak_hbm_bytes": None, "donated_bytes": None,
         "compile_s": None},
        {"event": "hbm_budget", "programs": 2,
         "peak_hbm_bytes_sum": 3 * 1024 * 1024},
        {"event": "health", "step": 10, "module": "layer_0",
         "grad_norm": 0.5, "update_ratio": 1e-3, "param_rms": 0.1},
        {"event": "health", "step": 20, "module": "layer_0",
         "grad_norm": 0.7, "update_ratio": 5e-3, "param_rms": 0.11},
    ]
    summary = report.summarize(records)
    progs = {p["program"]: p for p in summary["programs"]}
    assert progs["train_step"]["flops"] == 1e9
    assert progs["no_analysis"]["flops"] is None
    assert summary["peak_hbm_bytes_sum"] == 3 * 1024 * 1024
    h = summary["health"]["layer_0"]
    assert h["worst_update_ratio"] == pytest.approx(5e-3)
    assert h["worst_update_ratio_step"] == 20
    assert h["grad_norm_first"] == pytest.approx(0.5)
    assert h["grad_norm_last"] == pytest.approx(0.7)
    text = report.render(summary)
    assert "Programs" in text and "Health" in text
    assert "train_step" in text and "3.0MiB" in text
    assert "layer_0" in text and "worst_update_ratio" in text


def test_load_records_skips_malformed_lines(tmp_path, capsys):
    path = tmp_path / "m.jsonl"
    path.write_text(
        json.dumps({"event": "step", "step": 1}) + "\n"
        + "\n"                                  # blank: fine, skipped
        + '{"event": "step", "ste'              # truncated (crash)
        + "\n"
        + "not json at all\n"
        + json.dumps({"event": "summary"}) + "\n")
    records = report.load_records(str(path))
    assert [r["event"] for r in records] == ["step", "summary"]
    err = capsys.readouterr().err
    assert "skipped 2 malformed line(s)" in err
    assert "first at line 3" in err
    # The CLI still summarizes the survivors.
    assert report.main([str(path)]) == 0
