"""shard_vocab: Megatron vocab-parallel embedding (round-3 VERDICT
weak #6a — the docstring claimed a knob that didn't exist; now it does).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models import build_model
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_train_step
from tensorflow_distributed_tpu.train.tasks import (
    mlm_batch_shardings, mlm_loss)


def _one_step(mesh, **model_kw):
    from tensorflow_distributed_tpu.data.lm import synthetic_clm

    model = build_model("gpt_lm", mesh=mesh, size="tiny",
                        dropout_rate=0.0, compute_dtype=jnp.float32,
                        **model_kw)
    state = create_train_state(model, optax.adam(1e-2),
                               np.zeros((2, 16), np.int32), mesh, seed=0)
    ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
    batch = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
    step = make_train_step(mesh, loss=mlm_loss,
                           batch_shardings=mlm_batch_shardings(mesh),
                           donate=False)
    new_state, met = step(state, batch)
    return state, new_state, met


def test_vocab_table_is_model_sharded(devices8):
    """The table's vocab dim actually lands on the "model" axis, and
    the step's math is unchanged vs the replicated layout."""
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2), devices8)
    state_s, new_s, met_s = _one_step(mesh, shard_vocab=True)
    spec = state_s.params["tok_emb"]["embedding"].sharding.spec
    assert tuple(spec) == ("model", None), spec

    state_r, new_r, met_r = _one_step(mesh, shard_vocab=False)
    spec_r = state_r.params["tok_emb"]["embedding"].sharding.spec
    assert tuple(spec_r) != ("model", None)
    np.testing.assert_allclose(float(met_s["loss"]), float(met_r["loss"]),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4),
        jax.device_get(new_s.params), jax.device_get(new_r.params))


def test_tied_sharded_logits_match(devices8):
    """Tied + sharded: the vocab-sharded tied einsum equals the
    replicated tied logits."""
    mesh = make_mesh(MeshConfig(data=2, model=4), devices8)
    _, new_s, met_s = _one_step(mesh, shard_vocab=True,
                                tie_embeddings=True)
    _, new_r, met_r = _one_step(mesh, shard_vocab=False,
                                tie_embeddings=True)
    np.testing.assert_allclose(float(met_s["loss"]), float(met_r["loss"]),
                               rtol=1e-5)


def test_shard_vocab_validation():
    TrainConfig(model="gpt_lm", shard_vocab=True).validate()
    with pytest.raises(ValueError, match="no effect"):
        TrainConfig(model="mnist_cnn", shard_vocab=True).validate()
    with pytest.raises(ValueError, match="pipelined_lm"):
        TrainConfig(model="pipelined_lm", shard_vocab=True).validate()
    with pytest.raises(ValueError, match="tp_partitioning"):
        from tensorflow_distributed_tpu.models.transformer import (
            CausalLM, tiny_config)
        cfg = tiny_config(causal=True, tp_partitioning=False,
                          shard_vocab=True)
        CausalLM(cfg, None).init(jax.random.key(0),
                                 np.zeros((2, 16), np.int32))
