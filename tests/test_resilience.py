"""CPU fault-injection suite: every recovery path, exercised.

The resilience/ package's contract is that recovery is PROVEN, not
believed: each scenario here injects a real fault through the
deterministic plan (``--resilience.fault-plan``) and demands the run
recovers — and that the recovery left its event trail in the metrics
JSONL and the goodput ledger.
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.config import (
    MeshConfig, ObserveConfig, ResilienceConfig, TrainConfig)
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.loop import train

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    base = dict(dataset="synthetic", batch_size=64, train_steps=10,
                eval_every=0, log_every=0, eval_batch_size=64,
                compute_dtype="float32", mesh=MeshConfig(data=8))
    base.update(kw)
    return TrainConfig(**base)


def _recovery(path):
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    return recs, [r for r in recs if r["event"] == "recovery"]


def _summary(recs):
    return [r for r in recs if r["event"] == "summary"][-1]


# --- fault-plan grammar --------------------------------------------------

def test_fault_plan_grammar():
    from tensorflow_distributed_tpu.resilience.faults import (
        parse_fault_plan)

    plan = parse_fault_plan(
        "nan_grad@40,ckpt_io_fail@80:2,data_stall@120:5s,sigterm@200")
    assert bool(plan)
    assert not parse_fault_plan("")
    for bad in ("nan_grad", "nan_grad@0", "bogus@5", "nan_grad@5:3",
                "data_stall@5:0s", "ckpt_io_fail@5:1.5"):
        with pytest.raises(ValueError):
            parse_fault_plan(bad)
    # Config-time validation catches plan syntax at startup.
    with pytest.raises(ValueError):
        _cfg(resilience=ResilienceConfig(
            fault_plan="bogus@5")).validate()


def test_policy_config_validation():
    with pytest.raises(ValueError, match="rewind"):
        _cfg(resilience=ResilienceConfig(nonfinite="rewind")).validate()
    with pytest.raises(ValueError, match="halt_on_nonfinite"):
        _cfg(halt_on_nonfinite=True,
             resilience=ResilienceConfig(nonfinite="halt")).validate()
    with pytest.raises(ValueError, match="skip_batch"):
        _cfg(model="pipelined_lm", batch_size=64,
             resilience=ResilienceConfig(
                 nonfinite="skip_batch")).validate()


# --- NaN policies --------------------------------------------------------

def test_nan_skip_batch_trains_past(tmp_path):
    """Injected NaN at step 5: the device discards that update, the
    budget decrements, and training reaches the final step with finite
    loss."""
    jsonl = str(tmp_path / "m.jsonl")
    r = train(_cfg(
        observe=ObserveConfig(metrics_jsonl=jsonl),
        resilience=ResilienceConfig(fault_plan="nan_grad@5",
                                    nonfinite="skip_batch",
                                    max_skips=2)))
    assert int(jax.device_get(r.state.step)) == 10
    assert np.isfinite(r.final_metrics["loss"])
    recs, rec = _recovery(jsonl)
    kinds = [(x.get("kind"), x.get("step"), x.get("action"))
             for x in rec]
    assert ("fault_injected", 5, None) in kinds
    assert ("nonfinite", 5, "skip") in kinds
    skip = [x for x in rec if x.get("action") == "skip"][0]
    assert (skip["used"], skip["budget"]) == (1, 2)
    assert _summary(recs)["skip_nonfinite_count"] == 1


def test_nan_skip_budget_exhausted(tmp_path):
    from tensorflow_distributed_tpu.resilience.policies import (
        RecoveryBudgetExceeded)

    with pytest.raises(RecoveryBudgetExceeded, match="skips used"):
        train(_cfg(
            observe=ObserveConfig(
                metrics_jsonl=str(tmp_path / "m.jsonl")),
            resilience=ResilienceConfig(
                fault_plan="nan_grad@4,nan_grad@6", nonfinite="skip_batch",
                max_skips=1)))


def test_nan_halt_policy_raises(tmp_path):
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        train(_cfg(resilience=ResilienceConfig(
            fault_plan="nan_grad@4", nonfinite="halt")))


def test_nan_rewind_restores_and_completes(tmp_path):
    """Injected NaN at step 5 under rewind: checkpoints saved after
    the bad update are quarantined (they hold the poisoned state),
    the run restores step 4, replays, and reaches the final step."""
    ckpt_dir = str(tmp_path / "ckpt")
    jsonl = str(tmp_path / "m.jsonl")
    r = train(_cfg(
        checkpoint_dir=ckpt_dir, checkpoint_every=2,
        observe=ObserveConfig(metrics_jsonl=jsonl),
        resilience=ResilienceConfig(fault_plan="nan_grad@5",
                                    nonfinite="rewind",
                                    max_rewinds=1)))
    assert int(jax.device_get(r.state.step)) == 10
    assert np.isfinite(r.final_metrics["loss"])
    recs, rec = _recovery(jsonl)
    rewinds = [x for x in rec if x.get("kind") == "rewind"]
    # NaN injected via the BATCH at 5: the save at 4 (params entering
    # 5) is clean, passes the restore-time finiteness check, and is
    # the target; the cadence save at 6 (taken between the bad update
    # and its lagged detection) is quarantined.
    assert rewinds and rewinds[0]["to_step"] == 4
    assert rewinds[0]["from_step"] == 5
    # The cadence save taken between the bad update and its detection
    # held NaN params — it must be quarantined, not a resume target.
    assert any(x.get("kind") == "quarantine" for x in rec)
    assert any(n.startswith("quarantined_")
               for n in os.listdir(ckpt_dir))
    summ = _summary(recs)
    assert summ["rewind_count"] == 1
    assert summ["rewind_seconds"] > 0
    # Post-rewind saves are clean: a fresh restore of the latest must
    # carry finite params.
    final = ckpt.restore(ckpt_dir, r.state)
    leaf = jax.tree_util.tree_leaves(jax.device_get(final.params))[0]
    assert np.isfinite(leaf).all()


def test_nonfinite_policy_unit():
    from tensorflow_distributed_tpu.resilience.policies import (
        NonFinitePolicy)

    p = NonFinitePolicy("skip_batch", max_skips=2, max_rewinds=1)
    assert p.on_nonfinite(3, float("nan")) == "skip"
    assert p.on_nonfinite(4, float("nan")) == "skip"
    assert p.on_nonfinite(5, float("nan")) == "halt"  # budget spent
    # Spikes don't rewind under skip_batch (the update already
    # applied) — event-only.
    assert p.on_spike(6, 99.0, median=1.0) is None

    r = NonFinitePolicy("rewind", max_skips=0, max_rewinds=2)
    assert r.on_nonfinite(3, float("inf")) == "rewind"
    assert r.on_spike(9, 99.0, median=1.0) == "rewind"  # shares budget
    assert r.on_nonfinite(12, float("nan")) == "halt"
    assert "rewinds used 2/2" in r.halt_message(12, float("nan"), 8)


def test_rewind_skips_poisoned_params_checkpoint(tmp_path):
    """Param-side damage: the latest checkpoint has intact bytes but
    NaN values (backward-only overflow saved before detection). The
    rewind's restore-time finiteness check must quarantine it and
    walk back to the older clean step instead of burning the budget
    on an instant re-NaN."""
    from flax import serialization

    ckpt_dir = str(tmp_path / "ckpt")
    train(_cfg(train_steps=6, checkpoint_dir=ckpt_dir,
               checkpoint_every=2))
    assert ckpt.available_steps(ckpt_dir) == [2, 4, 6]
    # NaN-poison step 6's params in place, keeping bytes VALID
    # (re-serialize + refresh the manifest checksum) so only the
    # value check can catch it.
    import hashlib

    sd = os.path.join(ckpt_dir, "step_00000006")
    with open(os.path.join(sd, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    raw["params"] = jax.tree_util.tree_map(
        lambda x: np.full_like(x, np.nan), raw["params"])
    blob = serialization.msgpack_serialize(raw)
    with open(os.path.join(sd, "state.msgpack"), "wb") as f:
        f.write(blob)
    with open(os.path.join(sd, "manifest.json")) as f:
        man = json.load(f)
    man["sha256"] = hashlib.sha256(blob).hexdigest()
    with open(os.path.join(sd, "manifest.json"), "w") as f:
        json.dump(man, f)

    jsonl = str(tmp_path / "m.jsonl")
    r = train(_cfg(
        train_steps=10, checkpoint_dir=ckpt_dir, checkpoint_every=2,
        resume=True,
        observe=ObserveConfig(metrics_jsonl=jsonl),
        resilience=ResilienceConfig(nonfinite="rewind",
                                    max_rewinds=1)))
    # Resume restored the poisoned step 6, the first losses were NaN,
    # and ONE rewind recovered: step 6 failed the finiteness check,
    # was quarantined, and step 4 became the target.
    assert int(jax.device_get(r.state.step)) == 10
    assert np.isfinite(r.final_metrics["loss"])
    recs, rec = _recovery(jsonl)
    rewinds = [x for x in rec if x.get("kind") == "rewind"]
    assert rewinds and rewinds[0]["to_step"] == 4
    quars = [x for x in rec if x.get("kind") == "quarantine"]
    assert any("non-finite" in q.get("reason", "") for q in quars)


def test_loss_spike_detector_unit():
    from tensorflow_distributed_tpu.resilience.policies import (
        LossSpikeDetector)

    det = LossSpikeDetector(window=4, factor=10.0)
    for v in (1.0, 1.1, 0.9, 1.0):
        assert det.observe(v) is None  # window filling
    assert det.observe(1.2) is None
    med = det.observe(50.0)
    assert med is not None and 0.9 <= med <= 1.2  # spike flagged
    det.reset()
    assert det.observe(50.0) is None  # fresh window after rewind


# --- checkpoint integrity ------------------------------------------------

def _state(mesh8):
    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.train.state import create_train_state

    model = MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)
    return create_train_state(model, optax.adam(1e-3),
                              jnp.zeros((2, 28, 28, 1)), mesh8, seed=0)


def _save_n(tmp_path, mesh8, n=3):
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.step import make_train_step

    state = _state(mesh8)
    step = make_train_step(mesh8, donate=False)
    rng = np.random.default_rng(0)
    b = shard_batch(mesh8, (
        rng.normal(size=(16, 28, 28, 1)).astype(np.float32),
        rng.integers(0, 10, size=(16,)).astype(np.int32)))
    for _ in range(n):
        state, _ = step(state, b)
        ckpt.save(str(tmp_path), state)
    return state


def test_corrupt_latest_falls_back_and_quarantines(tmp_path, mesh8):
    """Bit-flipped latest checkpoint: restore() falls back to the
    previous verifiable step, quarantines the bad one, and emits the
    recovery event."""
    from tensorflow_distributed_tpu.observe import registry as reg
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    _save_n(tmp_path, mesh8, 3)
    p = tmp_path / "step_00000003" / "state.msgpack"
    blob = bytearray(p.read_bytes())
    blob[1000] ^= 0xFF
    p.write_bytes(bytes(blob))

    r = MetricsRegistry()
    reg.set_active(r)
    try:
        restored = ckpt.restore(str(tmp_path), _state(mesh8))
    finally:
        reg.set_active(None)
    assert int(jax.device_get(restored.step)) == 2
    assert ckpt.available_steps(str(tmp_path)) == [1, 2]
    assert (tmp_path / "quarantined_step_00000003").exists()
    assert any(x["event"] == "recovery" and x["kind"] == "quarantine"
               and x["step"] == 3 for x in r.records)


def test_truncated_latest_falls_back(tmp_path, mesh8):
    _save_n(tmp_path, mesh8, 2)
    with open(tmp_path / "step_00000002" / "state.msgpack",
              "r+b") as f:
        f.truncate(1000)
    restored = ckpt.restore(str(tmp_path), _state(mesh8))
    assert int(jax.device_get(restored.step)) == 1


def test_all_corrupt_raises_clear_error(tmp_path, mesh8):
    _save_n(tmp_path, mesh8, 2)
    for n in (1, 2):
        with open(tmp_path / f"step_0000000{n}" / "state.msgpack",
                  "r+b") as f:
            f.truncate(100)
    with pytest.raises(ckpt.CheckpointCorruptError,
                       match="failed verification"):
        ckpt.restore(str(tmp_path), _state(mesh8))


def test_explicit_corrupt_step_raises_without_quarantine(
        tmp_path, mesh8):
    _save_n(tmp_path, mesh8, 2)
    with open(tmp_path / "step_00000002" / "state.msgpack",
              "r+b") as f:
        f.truncate(1000)
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.restore(str(tmp_path), _state(mesh8), step=2)
    # Explicit inspection does not rename the dir away.
    assert (tmp_path / "step_00000002").exists()


def test_restore_averaged_corrupt_latest_falls_back(tmp_path, mesh8):
    """restore_averaged shares restore()'s integrity contract: a
    corrupt latest STACKED checkpoint is quarantined and the
    next-newest verifiable one restores."""
    from tensorflow_distributed_tpu.train.local_sgd import stack_state

    stacked = stack_state(_state(mesh8), mesh8)
    ckpt.save(str(tmp_path), stacked)               # step 0
    ckpt.save(str(tmp_path),
              stacked.replace(step=stacked.step + 1))  # step 1
    with open(tmp_path / "step_00000001" / "state.msgpack",
              "r+b") as f:
        f.truncate(1000)
    restored = ckpt.restore_averaged(str(tmp_path), _state(mesh8))
    assert int(jax.device_get(restored.step)) == 0
    assert (tmp_path / "quarantined_step_00000001").exists()


def test_save_io_failure_retries_and_succeeds(tmp_path, mesh8):
    """Armed injected write failures are consumed by the capped-
    backoff retry loop; the save lands."""
    from tensorflow_distributed_tpu.observe import registry as reg
    from tensorflow_distributed_tpu.observe.registry import (
        MetricsRegistry)

    state = _state(mesh8)
    ckpt.set_io_policy(retries=2, backoff_s=0.01)
    r = MetricsRegistry()
    reg.set_active(r)
    try:
        ckpt.arm_io_fault(2)
        ckpt.save(str(tmp_path), state)
    finally:
        reg.set_active(None)
        ckpt.set_io_policy()
    assert ckpt.available_steps(str(tmp_path)) == [0]
    retries = [x for x in r.records if x.get("kind") == "ckpt_retry"]
    assert [x["attempt"] for x in retries] == [1, 2]


def test_save_io_failure_exhausts_retries(tmp_path, mesh8):
    state = _state(mesh8)
    ckpt.set_io_policy(retries=1, backoff_s=0.01)
    try:
        ckpt.arm_io_fault(5)
        with pytest.raises(OSError, match="injected"):
            ckpt.save(str(tmp_path), state)
    finally:
        ckpt.arm_io_fault(0)
        ckpt.set_io_policy()


def test_ckpt_io_fail_in_training_run(tmp_path):
    """End-to-end: ckpt_io_fail@4 injected into the cadence save is
    absorbed by the retry policy; every checkpoint lands."""
    ckpt_dir = str(tmp_path / "ckpt")
    jsonl = str(tmp_path / "m.jsonl")
    train(_cfg(
        train_steps=6, checkpoint_dir=ckpt_dir, checkpoint_every=2,
        observe=ObserveConfig(metrics_jsonl=jsonl),
        resilience=ResilienceConfig(fault_plan="ckpt_io_fail@4:2",
                                    save_retries=3,
                                    save_retry_backoff_s=0.01)))
    assert ckpt.available_steps(ckpt_dir) == [2, 4, 6]
    recs, rec = _recovery(jsonl)
    assert [x["attempt"] for x in rec
            if x.get("kind") == "ckpt_retry"] == [1, 2]
    assert _summary(recs)["ckpt_retry_count"] == 2


# --- watchdog ------------------------------------------------------------

def test_data_stall_raises_stallerror(tmp_path):
    """An injected 1.5s fetch stall against a 0.3s deadline becomes a
    diagnosable StallError, with the stall event in the JSONL."""
    from tensorflow_distributed_tpu.resilience.watchdog import (
        StallError)

    jsonl = str(tmp_path / "m.jsonl")
    with pytest.raises(StallError, match="next-batch fetch"):
        train(_cfg(
            observe=ObserveConfig(metrics_jsonl=jsonl),
            resilience=ResilienceConfig(
                fault_plan="data_stall@4:1.5s", data_timeout_s=0.3)))
    _, rec = _recovery(jsonl)
    stalls = [x for x in rec if x.get("kind") == "stall"]
    assert stalls and stalls[0]["what"] == "next-batch fetch"
    assert stalls[0]["step"] == 4


def test_watchdog_unit_passthrough_and_timeout():
    import time

    from tensorflow_distributed_tpu.resilience.watchdog import (
        StallError, Watchdog)

    wd = Watchdog(data_timeout_s=0.2, sync_timeout_s=0.0)
    try:
        assert wd.fetch(lambda: 42, step=1) == 42
        # sync with timeout 0 is an unwatched plain block.
        assert int(wd.sync(jnp.ones(()), step=1)) == 1
        with pytest.raises(StallError):
            wd.fetch(lambda: time.sleep(1.0), step=2)
    finally:
        wd.close()


# --- supervisor ----------------------------------------------------------

def _child_env():
    return {
        "PATH": os.environ["PATH"],
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
        "PYTHONUNBUFFERED": "1",
    }


def test_supervisor_restarts_sigkilled_child(tmp_path):
    """The acceptance scenario: a child SIGKILLed mid-run (no notice,
    no graceful drain) is restarted with --resume and the run reaches
    the target step with state continuous across the restart."""
    ckpt_dir = str(tmp_path / "ckpt")
    jsonl = str(tmp_path / "m.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--max-restarts", "3", "--backoff-base-s", "0.2", "--",
         "--dataset", "synthetic", "--mesh.data", "8",
         "--batch-size", "64", "--train-steps", "8",
         "--eval-every", "0", "--log-every", "0",
         "--eval-batch-size", "64", "--compute-dtype", "float32",
         "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "2",
         "--observe.metrics-jsonl", jsonl,
         "--resilience.fault-plan", "sigkill@5"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=500)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert '"kind": "restart"' in proc.stdout
    recs, rec = _recovery(jsonl)
    # Leg 0 injected the kill at step 5; leg 1 resumed from the last
    # durable save (step 4) and ran to completion.
    assert any(x.get("fault") == "sigkill" for x in rec)
    assert any(x.get("kind") == "restart" and x.get("rc") == -9
               for x in rec)
    resumed = [x for x in recs if x["event"] == "resumed"]
    assert resumed and resumed[0]["step"] == 4
    assert [x.get("steps") for x in recs
            if x["event"] == "summary"] == [8]
    assert ckpt.latest_step(ckpt_dir) == 8


def test_supervisor_does_not_restart_diverged_child(tmp_path):
    """A child that halts on divergence (exit 2) is NOT restarted —
    a deterministic data stream would just re-diverge at the same
    step, burning the whole restart budget for nothing."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--max-restarts", "3", "--backoff-base-s", "0.1", "--",
         "--dataset", "synthetic", "--mesh.data", "8",
         "--batch-size", "64", "--train-steps", "8",
         "--eval-every", "0", "--log-every", "0",
         "--eval-batch-size", "64", "--compute-dtype", "float32",
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--resilience.fault-plan", "nan_grad@3",
         "--resilience.nonfinite", "halt"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 2, proc.stdout[-2000:] + proc.stderr[-1000:]
    assert "not restarting" in proc.stdout
    assert '"kind": "restart"' not in proc.stdout


def test_supervisor_gives_up_after_budget(tmp_path):
    """A child that always fails exhausts the restart budget; the
    supervisor exits nonzero with the child's failure code."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--max-restarts", "1", "--backoff-base-s", "0.1", "--",
         "--dataset", "synthetic", "--train-steps", "-1"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode != 0
    assert "restart budget exhausted" in proc.stdout


def test_supervisor_usage_error():
    from tensorflow_distributed_tpu.resilience.supervisor import main

    assert main([]) == 2  # no "--" separator
