"""Fused (vocab-chunked) linear+CE vs the dense oracle.

The contract: ops.fused_ce.fused_ce_sums computes EXACTLY what
ops.losses.masked_ce_sums computes on logits = x @ w (+ bias) — values
AND gradients wrt x / w / bias — while never materializing the full
logits. Oracle parity runs in f32 where the comparison is tight.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.ops.fused_ce import (
    fused_ce_sums, fused_masked_cross_entropy)
from tensorflow_distributed_tpu.ops.losses import masked_ce_sums

B, L, D, V = 2, 16, 24, 51  # V deliberately prime: never chunk-aligned


def _mk(seed=0, vocab=V, bias=True):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, L, D).astype(np.float32))
    w = jnp.asarray((0.1 * rng.randn(vocab, D)).astype(np.float32))
    b = jnp.asarray((0.1 * rng.randn(vocab)).astype(np.float32)) \
        if bias else None
    t = jnp.asarray(rng.randint(0, vocab, size=(B, L)).astype(np.int32))
    m = jnp.asarray((rng.rand(B, L) < 0.7).astype(np.float32))
    return x, w, b, t, m


def _dense(x, w, b, t, m, smoothing=0.0):
    logits = jnp.einsum("bld,vd->blv", x, w)
    if b is not None:
        logits = logits + b
    return masked_ce_sums(logits, t, m, smoothing)


@pytest.mark.parametrize("chunk", [8, 16, 51, 64])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_values_match_dense(chunk, smoothing):
    x, w, b, t, m = _mk()
    want = _dense(x, w, b, t, m, smoothing)
    got = fused_ce_sums(x, w, b, t, m, V, chunk, smoothing, 0)
    for g, wnt in zip(got, want):
        np.testing.assert_allclose(g, wnt, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_dense(smoothing):
    x, w, b, t, m = _mk(seed=1)

    def dense_loss(x, w, b):
        ce, _, n = _dense(x, w, b, t, m, smoothing)
        return ce / n

    def fused_loss(x, w, b):
        ce, _, n = fused_ce_sums(x, w, b, t, m, V, 16, smoothing, 0)
        return ce / n

    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(x, w, b)
    gf = jax.jit(jax.grad(fused_loss, argnums=(0, 1, 2)))(x, w, b)
    for a, e in zip(gf, gd):
        np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)


def test_untied_orientation_and_no_bias():
    """w_vocab_axis=1 ([D, V] untied-kernel layout), bias=None."""
    x, w, _, t, m = _mk(seed=2, bias=False)
    wk = w.T  # [D, V]

    def dense_loss(x, wk):
        ce, _, n = masked_ce_sums(jnp.einsum("bld,dv->blv", x, wk), t, m)
        return ce / n

    def fused_loss(x, wk):
        ce, _, n = fused_ce_sums(x, wk, None, t, m, V, 16, 0.0, 1)
        return ce / n

    np.testing.assert_allclose(fused_loss(x, wk), dense_loss(x, wk),
                               rtol=2e-5)
    gd = jax.grad(dense_loss, argnums=(0, 1))(x, wk)
    gf = jax.grad(fused_loss, argnums=(0, 1))(x, wk)
    for a, e in zip(gf, gd):
        np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)


def test_accuracy_matches_argmax_first_max():
    """The running argmax keeps the FIRST maximum across chunk
    boundaries, like jnp.argmax on the full row — pin it with
    duplicated columns straddling a chunk edge."""
    x = jnp.ones((1, 1, 2), jnp.float32)
    # Columns 1 and 9 are identical rows of w -> identical logits;
    # chunk=4 puts them in different chunks. argmax must say 1.
    w = np.zeros((12, 2), np.float32)
    w[1] = w[9] = 3.0
    t = jnp.asarray([[1]], jnp.int32)
    m = jnp.ones((1, 1), jnp.float32)
    _, correct, _ = fused_ce_sums(x, jnp.asarray(w), None, t, m,
                                  12, 4, 0.0, 0)
    assert float(correct) == 1.0
    t9 = jnp.asarray([[9]], jnp.int32)
    _, correct, _ = fused_ce_sums(x, jnp.asarray(w), None, t9, m,
                                  12, 4, 0.0, 0)
    assert float(correct) == 0.0  # argmax picked 1, the first max


def test_wrapper_matches_mean_forms():
    from tensorflow_distributed_tpu.ops.losses import (
        masked_accuracy, masked_softmax_cross_entropy)
    x, w, b, t, m = _mk(seed=3)
    logits = jnp.einsum("bld,vd->blv", x, w) + b
    loss, acc = fused_masked_cross_entropy(x, w, b, t, m, vocab_size=V,
                                           chunk=16)
    np.testing.assert_allclose(loss, masked_softmax_cross_entropy(
        logits, t, m), rtol=2e-5)
    np.testing.assert_allclose(acc, masked_accuracy(logits, t, m),
                               rtol=2e-5)


def test_bf16_features_close_to_dense_bf16():
    """The real call site hands bf16 features; the fused path (f32
    accumulation) must stay within bf16-roundoff of the dense path."""
    x, w, b, t, m = _mk(seed=4)
    xb = x.astype(jnp.bfloat16)
    logits = jnp.einsum("bld,vd->blv", xb, w.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) + b
    want = masked_ce_sums(logits, t, m)
    got = fused_ce_sums(xb, w, b, t, m, V, 16, 0.0, 0)
    np.testing.assert_allclose(got[0], want[0], rtol=2e-2)
    assert float(got[2]) == float(want[2])


@pytest.mark.parametrize("tie", [False, True])
def test_model_features_mode_consistent_with_logits(tie):
    """apply(features_only=True) hands out exactly the pieces whose
    product is the dense logits path."""
    from tensorflow_distributed_tpu.models import build_model

    model = build_model("gpt_lm", size="tiny", tie_embeddings=tie,
                        compute_dtype=jnp.float32)
    tokens = jnp.asarray(np.arange(8, dtype=np.int32).reshape(2, 4) % 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    feats, w, b, v_axis = model.apply(params, tokens, features_only=True)
    eq = "bld,vd->blv" if v_axis == 0 else "bld,dv->blv"
    rebuilt = jnp.einsum(eq, feats, w) + (0.0 if b is None else b)
    np.testing.assert_allclose(rebuilt, logits, rtol=1e-5, atol=1e-5)
    assert (b is None) == tie


def test_train_step_parity_dense_vs_fused(devices8):
    """Same tiny GPT, same seeds: --ce-chunk must reproduce the dense
    path's training trajectory (f32 compute keeps parity tight)."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    base = dict(model="gpt_lm", model_size="tiny", dataset="synthetic",
                batch_size=16, train_steps=5, eval_every=0, log_every=0,
                eval_batch_size=16, compute_dtype="float32",
                learning_rate=1e-3, label_smoothing=0.1,
                mesh=MeshConfig(data=4, seq=2))
    dense = train(TrainConfig(**base))
    fused = train(TrainConfig(**base, ce_chunk=24))
    np.testing.assert_allclose(fused.final_metrics["loss"],
                               dense.final_metrics["loss"],
                               rtol=2e-4, atol=2e-4)


def test_config_rejects_bad_combinations():
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig

    # The Mosaic kernel wants the whole head per device — scan's
    # vocab-parallel form covers TP/shard_vocab instead.
    with pytest.raises(ValueError, match="shard_vocab"):
        TrainConfig(model="gpt_lm", ce_chunk=8192, ce_impl="kernel",
                    shard_vocab=True).validate()
    with pytest.raises(ValueError, match="mesh.model"):
        TrainConfig(model="gpt_lm", ce_chunk=8192, ce_impl="kernel",
                    mesh=MeshConfig(model=2)).validate()
    with pytest.raises(ValueError, match="pipelined_lm"):
        TrainConfig(model="pipelined_lm", ce_chunk=8192,
                    ce_impl="kernel").validate()
    with pytest.raises(ValueError, match="LM families"):
        TrainConfig(model="mnist_cnn", ce_chunk=8192).validate()
    # The scan impl composes with all of these.
    TrainConfig(model="gpt_lm", ce_chunk=8192,
                shard_vocab=True, mesh=MeshConfig(model=2)).validate()


def test_vocab_parallel_matches_dense(devices8):
    """The Megatron vocab-parallel form (head rows split over the
    model axis, stats combined with pmax/psum) must reproduce the
    dense oracle — values AND grads — including a vocab that does NOT
    divide the rank count (padding rows masked and zero-grad)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.ops.fused_ce import (
        fused_masked_cross_entropy)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, model=4), devices8)
    x, w, b, t, m = _mk(seed=6)

    def dense_loss(x, w, b):
        from tensorflow_distributed_tpu.ops.losses import (
            masked_softmax_cross_entropy)
        logits = jnp.einsum("bld,vd->blv", x, w) + b
        return masked_softmax_cross_entropy(logits, t, m, 0.1)

    def tp_loss(x, w, b):
        loss, _ = fused_masked_cross_entropy(
            x, w, b, t, m, vocab_size=V, chunk=16,
            label_smoothing=0.1, w_vocab_axis=0, mesh=mesh)
        return loss

    with mesh:
        got = jax.jit(tp_loss)(x, w, b)
        gk = jax.jit(jax.grad(tp_loss, argnums=(0, 1, 2)))(x, w, b)
    np.testing.assert_allclose(got, dense_loss(x, w, b), rtol=2e-5)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(gk, gd):
        np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)


def test_vocab_parallel_accuracy_first_max(devices8):
    """Cross-RANK argmax ties: identical max columns on different TP
    ranks — the smallest global id must win (dense argmax semantics)."""
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.ops.fused_ce import (
        fused_masked_cross_entropy)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, model=4), devices8)
    vocab = 48  # 12 rows per rank
    x = jnp.ones((2, 4, D), jnp.float32)
    w = np.zeros((vocab, D), np.float32)
    w[3] = w[30] = 2.0  # same logit on ranks 0 and 2
    t3 = jnp.full((2, 4), 3, jnp.int32)
    m = jnp.ones((2, 4), jnp.float32)
    with mesh:
        _, acc = fused_masked_cross_entropy(
            jnp.asarray(x), jnp.asarray(w), None, t3, m,
            vocab_size=vocab, chunk=8, mesh=mesh)
    assert float(acc) == 1.0
    t30 = jnp.full((2, 4), 30, jnp.int32)
    with mesh:
        _, acc = fused_masked_cross_entropy(
            jnp.asarray(x), jnp.asarray(w), None, t30, m,
            vocab_size=vocab, chunk=8, mesh=mesh)
    assert float(acc) == 0.0


def test_vocab_parallel_all_padding_rank_no_nan(devices8):
    """A TP rank whose head shard is ENTIRELY padding (vocab_size <
    mesh.model) must contribute cleanly-zero stats, not NaN: a true
    -inf running-max init made the online normalizer compute
    0*exp(-inf - (-inf)) on such a rank (ADVICE r4). Values and grads
    must still match the dense oracle."""
    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.ops.fused_ce import (
        fused_masked_cross_entropy)
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, model=4), devices8)
    vocab = 3  # < model=4: rank 3 owns only the pad row
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(B, L, D).astype(np.float32))
    w = jnp.asarray((0.1 * rng.randn(vocab, D)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, vocab, (B, L)).astype(np.int32))
    m = jnp.ones((B, L), jnp.float32)

    def dense_loss(x, w):
        from tensorflow_distributed_tpu.ops.losses import (
            masked_softmax_cross_entropy)
        return masked_softmax_cross_entropy(
            jnp.einsum("bld,vd->blv", x, w), t, m)

    def tp_loss(x, w):
        loss, _ = fused_masked_cross_entropy(
            x, w, None, t, m, vocab_size=vocab, chunk=8, mesh=mesh)
        return loss

    with mesh:
        got = jax.jit(tp_loss)(x, w)
        gx, gw = jax.jit(jax.grad(tp_loss, argnums=(0, 1)))(x, w)
    assert np.isfinite(float(got))
    np.testing.assert_allclose(got, dense_loss(x, w), rtol=2e-5)
    ex, ew = jax.grad(dense_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx, ex, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gw, ew, rtol=1e-4, atol=1e-5)


def test_bool_mask_differentiable():
    """masked_ce_sums accepts bool/int masks via astype; the custom
    VJP must return a float0 cotangent for them (a dense zeros_like
    has the wrong tangent type and AD rejects it — ADVICE r4)."""
    x, w, b, t, m = _mk(seed=8)
    mb = m > 0.5  # bool mask

    def fused_loss(x):
        ce, _, n = fused_ce_sums(x, w, b, t, mb, V, 16, 0.0, 0)
        return ce / n

    def dense_loss(x):
        ce, _, n = _dense(x, w, b, t, mb.astype(jnp.float32))
        return ce / n

    g = jax.grad(fused_loss)(x)
    np.testing.assert_allclose(g, jax.grad(dense_loss)(x),
                               rtol=1e-4, atol=1e-5)


def test_tp_train_step_parity_dense_vs_fused(devices8):
    """ce_chunk under a real TP mesh (model=2), with the Megatron
    vocab-sharded embedding on: the vocab-parallel fused loss must
    reproduce the dense shard_vocab path's trajectory."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    base = dict(model="gpt_lm", model_size="tiny", dataset="synthetic",
                batch_size=16, train_steps=3, eval_every=0, log_every=0,
                eval_batch_size=16, compute_dtype="float32",
                learning_rate=1e-3, shard_vocab=True,
                mesh=MeshConfig(data=2, seq=2, model=2))
    dense = train(TrainConfig(**base))
    fused = train(TrainConfig(**base, ce_chunk=24))
    np.testing.assert_allclose(fused.final_metrics["loss"],
                               dense.final_metrics["loss"],
                               rtol=2e-4, atol=2e-4)


def test_moe_train_step_parity_dense_vs_fused(devices8):
    """The MoE loss's fused branch must reproduce its dense branch —
    including the router-aux terms collected through the mutable
    'moe_aux' apply."""
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    base = dict(model="moe_lm", model_size="tiny", dataset="synthetic",
                batch_size=16, train_steps=3, eval_every=0, log_every=0,
                eval_batch_size=16, compute_dtype="float32",
                learning_rate=1e-3, mesh=MeshConfig(data=4, expert=2))
    dense = train(TrainConfig(**base))
    fused = train(TrainConfig(**base, ce_chunk=24))
    np.testing.assert_allclose(fused.final_metrics["loss"],
                               dense.final_metrics["loss"],
                               rtol=2e-4, atol=2e-4)


def test_sharded_matches_single_device():
    """Under pjit with batch over 'data' and seq over 'seq', the chunk
    scan runs per-shard with no resharding; results match 1-device."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "seq"))
    x, w, b, t, m = _mk(seed=5)

    def f(x, w, b, t, m):
        ce, correct, n = fused_ce_sums(x, w, b, t, m, V, 16, 0.1, 0)
        return ce, correct, n

    want = f(x, w, b, t, m)
    s = NamedSharding(mesh, P("data", "seq"))
    xs = jax.device_put(x, NamedSharding(mesh, P("data", "seq", None)))
    ts, ms = jax.device_put(t, s), jax.device_put(m, s)
    got = jax.jit(f)(xs, w, b, ts, ms)
    for g, wnt in zip(got, want):
        np.testing.assert_allclose(g, wnt, rtol=2e-5, atol=2e-5)
