"""Calibration loop (analysis/planner/calibrate.py): fit, profile IO,
and the score.detect_hardware preference. Fast tier is jax-free except
the two detect_hardware tests (CPU backend only)."""

import json
import os
import random

import pytest

from tensorflow_distributed_tpu.analysis.planner import calibrate
from tensorflow_distributed_tpu.analysis.planner.score import (
    Hardware, roofline_ms)


def _synthetic(F=5e9, B=2e9, C=1e8, overhead=0.0, n=16, noise=0.04,
               seed=0):
    rng = random.Random(seed)
    samples = []
    for _ in range(n):
        f = rng.uniform(1e6, 5e7)
        b = rng.uniform(1e5, 5e6)
        c = rng.choice([0.0, rng.uniform(1e4, 1e5)])
        ms = overhead + max(1e3 * f / F, 1e3 * b / B) + (
            1e3 * c / C if c else 0.0)
        samples.append({"flops": f, "bytes_accessed": b,
                        "collective_bytes": c,
                        "measured_ms": ms * rng.uniform(1 - noise,
                                                        1 + noise)})
    return samples


def test_fit_recovers_rates():
    fit = calibrate.fit_rates(_synthetic())
    assert fit["peak_flops"] == pytest.approx(5e9, rel=0.2)
    assert fit["ici_bw"] == pytest.approx(1e8, rel=0.3)
    assert fit["median_abs_rel_err"] < 0.1


def test_fit_recovers_overhead_intercept():
    # Two scales of the same shape: without the intercept no single
    # rate can fit both; with it the fit nails all four.
    fit = calibrate.fit_rates(_synthetic(overhead=12.0, noise=0.01))
    assert fit["overhead_ms"] == pytest.approx(12.0, rel=0.25)
    assert fit["median_abs_rel_err"] < 0.05


def test_fit_without_collectives_leaves_ici_none():
    samples = [s for s in _synthetic() if s["collective_bytes"] == 0]
    fit = calibrate.fit_rates(samples)
    assert fit["ici_bw"] is None


def test_fit_raises_on_empty():
    with pytest.raises(ValueError):
        calibrate.fit_rates([])
    with pytest.raises(ValueError):
        calibrate.fit_rates([{"flops": None, "bytes_accessed": 1,
                              "measured_ms": 0.0}])


def test_rel_errors_improve_under_fit():
    samples = _synthetic()
    fit = calibrate.fit_rates(samples)
    fitted = calibrate.rel_errors(samples, fit["peak_flops"],
                                  fit["hbm_bw"], fit["ici_bw"],
                                  fit["overhead_ms"])
    generic = calibrate.rel_errors(samples, 1e12, 2.5e10, 2.5e10)
    assert sorted(fitted)[len(fitted) // 2] \
        < sorted(generic)[len(generic) // 2]


def test_profile_roundtrip_atomic(tmp_path):
    fit = calibrate.fit_rates(_synthetic())
    profile = calibrate.make_profile(fit, "cpu", "kind-x",
                                     source="test", devices=8)
    assert profile["calibration_id"].startswith("cpu-")
    path = str(tmp_path / "calibration.json")
    calibrate.write_calibration(profile, path)
    assert not os.path.exists(path + ".tmp")  # tmp+rename
    loaded = calibrate.load_calibration(path)
    assert loaded == profile
    assert loaded["effective"]["peak_flops"] == fit["peak_flops"]


def test_profile_id_stable_under_provenance_changes():
    fit = calibrate.fit_rates(_synthetic())
    a = calibrate.make_profile(fit, "cpu", "k", source="one")
    b = calibrate.make_profile(fit, "cpu", "k", source="two")
    c = calibrate.make_profile(fit, "tpu", "k", source="one")
    assert a["calibration_id"] == b["calibration_id"]  # rates define it
    assert a["calibration_id"] != c["calibration_id"]


def test_load_calibration_rejects_junk(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"not": "a profile"}))
    with pytest.raises(ValueError):
        calibrate.load_calibration(str(path))
    path.write_text(json.dumps({"version": 99, "effective": {}}))
    with pytest.raises(ValueError):
        calibrate.load_calibration(str(path))


def test_samples_from_planbench(tmp_path):
    path = tmp_path / "PLANBENCH.json"
    lines = [
        {"metric": "planbench_candidate", "key": "data=8/data",
         "flops": 5e7, "bytes_accessed": 2e7, "collective_bytes": 0.0,
         "measured_step_ms_min": 18.5},
        # No measurement (infeasible candidate) -> dropped.
        {"metric": "planbench_candidate", "key": "data=8/fsdp",
         "flops": 5e7, "bytes_accessed": 2e7},
        {"metric": "plan_checks", "pick_tol": 0.15},
    ]
    path.write_text("\n".join(json.dumps(ln) for ln in lines))
    samples = calibrate.samples_from_planbench(str(path))
    assert len(samples) == 1
    assert samples[0]["key"] == "data=8/data"
    assert samples[0]["measured_ms"] == 18.5


def test_samples_from_metrics_joins_compile_and_device_time(tmp_path):
    path = tmp_path / "m.jsonl"
    lines = [
        {"event": "compile", "program": "train_step", "flops": 6.5e8,
         "bytes_accessed": 3e8},
        {"event": "device_time", "program": "train_step",
         "device_ms_per_call": 31.5},
        # device_time without a compile record -> no sample.
        {"event": "device_time", "program": "mystery",
         "device_ms_per_call": 5.0},
        # explicit-null device_time -> no sample.
        {"event": "device_time", "program": "eval_step",
         "device_ms_per_call": None},
    ]
    path.write_text("\n".join(json.dumps(ln) for ln in lines))
    samples = calibrate.samples_from_metrics(str(path))
    assert len(samples) == 1
    assert samples[0]["key"] == "train_step"
    assert samples[0]["measured_ms"] == 31.5


def test_roofline_adds_calibrated_overhead():
    hw = Hardware(platform="cpu", device_kind="x", peak_flops=1e9,
                  hbm_bw=1e9, ici_bw=1e9, overhead_ms=7.0)
    out = roofline_ms({"flops": 1e6, "bytes_accessed": 1e6}, 0.0, hw)
    assert out["step_ms"] == pytest.approx(8.0)
    # Table hardware (overhead 0) is unchanged — committed PLANBENCH
    # predictions stay stable.
    hw0 = Hardware(platform="cpu", device_kind="x", peak_flops=1e9,
                   hbm_bw=1e9, ici_bw=1e9)
    assert roofline_ms({"flops": 1e6, "bytes_accessed": 1e6},
                       0.0, hw0)["step_ms"] == pytest.approx(1.0)


def test_detect_hardware_prefers_matching_calibration():
    import jax

    from tensorflow_distributed_tpu.analysis.planner.score import (
        detect_hardware)

    kind = getattr(jax.devices()[0], "device_kind", "unknown")
    profile = {"version": 1, "calibration_id": "cpu-test123",
               "platform": jax.default_backend(),
               "device_kind": kind,
               "effective": {"peak_flops": 3e9, "hbm_bw": 1.5e9,
                             "ici_bw": None, "overhead_ms": 9.0}}
    hw = detect_hardware(calibration=profile)
    assert hw.peak_flops == 3e9
    assert hw.hbm_bw == 1.5e9
    assert hw.overhead_ms == 9.0
    assert hw.calibration_id == "cpu-test123"
    # Explicit overrides still beat the profile.
    assert detect_hardware(peak_tflops=2.0,
                           calibration=profile).peak_flops == 2e12


def test_detect_hardware_ignores_mismatched_calibration(capsys):
    from tensorflow_distributed_tpu.analysis.planner.score import (
        detect_hardware)

    profile = {"version": 1, "calibration_id": "tpu-zzz",
               "platform": "tpu", "device_kind": "TPU v5",
               "effective": {"peak_flops": 3e9, "hbm_bw": 1.5e9,
                             "ici_bw": 1e9}}
    hw = detect_hardware(calibration=profile)
    assert hw.calibration_id is None
    assert hw.peak_flops != 3e9
    assert "ignoring calibration profile" in capsys.readouterr().err


def test_cli_from_planbench(tmp_path):
    src = tmp_path / "PLANBENCH.json"
    lines = []
    rng = random.Random(1)
    for i in range(6):
        f = rng.uniform(1e6, 5e7)
        lines.append({"metric": "planbench_candidate", "key": f"k{i}",
                      "flops": f, "bytes_accessed": f / 4,
                      "collective_bytes": 0.0,
                      "measured_step_ms_min": 1e3 * f / 4e9 + 2.0,
                      "platform": "cpu", "devices": 8})
    src.write_text("\n".join(json.dumps(ln) for ln in lines))
    out = tmp_path / "calibration.json"
    rc = calibrate.main(["--from-planbench", str(src),
                         "--platform", "cpu",
                         "--device-kind", "test-kind",
                         "--out", str(out)])
    assert rc == 0
    profile = calibrate.load_calibration(str(out))
    assert profile["platform"] == "cpu"
    assert profile["device_kind"] == "test-kind"
    assert profile["effective"]["peak_flops"] == pytest.approx(
        4e9, rel=0.3)
    assert profile["effective"]["overhead_ms"] == pytest.approx(
        2.0, rel=0.3)


def test_cli_no_samples_fails(tmp_path):
    src = tmp_path / "empty.json"
    src.write_text("")
    rc = calibrate.main(["--from-planbench", str(src),
                         "--device-kind", "k",
                         "--out", str(tmp_path / "c.json")])
    assert rc == 1


def test_plan_calibration_config_surface():
    """--plan-calibration feeds exactly two consumers (plan auto's
    roofline, the profiled device-time join); alone it is rejected as
    a silent no-op, like every other orphaned knob."""
    from tensorflow_distributed_tpu.config import TrainConfig, parse_args

    with pytest.raises(ValueError, match="plan_calibration"):
        TrainConfig(plan_calibration="calibration.json").validate()
    TrainConfig(plan="auto", model="gpt_lm", model_size="tiny",
                dataset="synthetic",
                plan_calibration="calibration.json").validate()
    TrainConfig(profile_dir="/tmp/prof",
                plan_calibration="calibration.json").validate()
    cfg = parse_args(["--profile-dir", "/tmp/prof",
                      "--plan-calibration", "cal.json"])
    assert cfg.plan_calibration == "cal.json"
