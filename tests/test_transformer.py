"""Transformer/BERT-MLM tests: TP sharding metadata, SP training, and
the dp+tp+sp composite mesh the reference never had."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tensorflow_distributed_tpu.config import MeshConfig
from tensorflow_distributed_tpu.data.lm import LmBatcher, synthetic_mlm
from tensorflow_distributed_tpu.models.transformer import (
    BertMLM, bert_tiny_mlm, tiny_config)
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import make_eval_step, make_train_step
from tensorflow_distributed_tpu.train.tasks import mlm_batch_shardings, mlm_loss


def _tokens(b=4, l=32, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(b, l)).astype(np.int32)


def test_forward_shape_no_mesh():
    model = bert_tiny_mlm()
    toks = jnp.asarray(_tokens())
    variables = model.init(jax.random.key(0), toks, train=False)
    logits = model.apply(variables, toks, train=False)
    assert logits.shape == (4, 32, 64)
    assert logits.dtype == jnp.float32


def test_tied_embeddings():
    """tie_embeddings drops lm_head, shares tok_emb as the output
    projection, and still produces vocab-sized logits (sentinel rows
    sliced off for the MLM family's [MASK])."""
    tied = bert_tiny_mlm(tie_embeddings=True)
    toks = _tokens()
    var_t = tied.init(jax.random.key(0), toks)
    assert "lm_head" not in var_t["params"]
    out = tied.apply(var_t, toks)
    assert out.shape == (*toks.shape, 64)  # vocab only, no [MASK] row

    untied = bert_tiny_mlm()
    var_u = untied.init(jax.random.key(0), toks)
    n_tied = sum(x.size for x in jax.tree_util.tree_leaves(var_t["params"]))
    n_untied = sum(x.size for x in
                   jax.tree_util.tree_leaves(var_u["params"]))
    assert n_untied - n_tied == 32 * 64 + 64  # lm_head kernel + bias

    # Gradients flow into the shared table from BOTH uses.
    def loss(p):
        return jnp.sum(tied.apply({"params": p}, toks) ** 2)
    g = jax.grad(loss)(var_t["params"])
    assert float(jnp.abs(g["tok_emb"]["embedding"]).sum()) > 0


def test_partition_metadata_present():
    model = bert_tiny_mlm()
    toks = jnp.asarray(_tokens(b=2))
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), toks, train=False))
    p = variables["params"]
    import flax.linen as nn
    qkv = p["layer_0"]["attn"]["qkv"]["kernel"]
    assert isinstance(qkv, nn.Partitioned)
    assert qkv.names == (None, None, "model", None)
    up = p["layer_0"]["mlp"]["up"]["kernel"]
    assert up.names == (None, "model")
    down = p["layer_0"]["mlp"]["down"]["kernel"]
    assert down.names == ("model", None)


def _mlm_state(mesh, l=32):
    model = BertMLM(tiny_config(max_len=l), mesh)
    sample = np.zeros((2, l), np.int32)
    return create_train_state(model, optax.adam(3e-3), sample, mesh, seed=0)


def test_params_sharded_on_tp_mesh(devices8):
    mesh = make_mesh(MeshConfig(data=2, seq=2, model=2), devices8)
    state = _mlm_state(mesh)
    qkv = state.params["layer_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, None, "model", None)
    # Each device holds half the heads (2 of 4).
    assert qkv.addressable_shards[0].data.shape[2] == 2
    # Adam slots follow the param sharding (path-suffix matching).
    mu_qkv = state.opt_state[0].mu["layer_0"]["attn"]["qkv"]["kernel"]
    assert mu_qkv.sharding.spec == P(None, None, "model", None)


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8, seq=1, model=1),   # pure DP
    MeshConfig(data=2, seq=2, model=2),   # dp + sp + tp composite
    MeshConfig(data=1, seq=4, model=2),   # sp-dominant long-context
])
@pytest.mark.slow
def test_mlm_trains_on_mesh(devices8, mesh_cfg):
    mesh = make_mesh(mesh_cfg, devices8)
    state = _mlm_state(mesh)
    step = make_train_step(mesh, loss=mlm_loss,
                           batch_shardings=mlm_batch_shardings(mesh))
    ds = synthetic_mlm(n=512, seq_len=32, vocab_size=64, seed=0)
    it = LmBatcher(ds, 64, seed=0).forever()
    losses = []
    for _ in range(80):
        batch = shard_batch(mesh, next(it), seq_axis=1)
        # dict batches: shard_batch handles pytrees; tokens are [B, L]
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0] * 0.5, losses[::20]


@pytest.mark.slow
def test_remat_trains(devices8):
    """cfg.remat=True (jax.checkpoint per block) must produce the same
    loss as the non-remat path — it changes memory, not math."""
    mesh = make_mesh(MeshConfig(data=2), devices8[:2])
    ds = synthetic_mlm(n=64, seq_len=32, vocab_size=64, seed=2)
    b = next(LmBatcher(ds, 16, seed=0).forever())
    losses = {}
    for remat in (False, True):
        model = BertMLM(tiny_config(max_len=32, remat=remat), mesh)
        state = create_train_state(model, optax.adam(3e-3),
                                   np.zeros((2, 32), np.int32), mesh, seed=0)
        step = make_train_step(mesh, loss=mlm_loss,
                               batch_shardings=mlm_batch_shardings(mesh),
                               donate=False)
        _, metrics = step(state, shard_batch(mesh, b, seq_axis=1))
        losses[remat] = float(jax.device_get(metrics["loss"]))
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)


@pytest.mark.slow
def test_bert_mlm_via_registry_and_loop(devices8):
    """The user-facing path: --model bert_mlm through build_model and
    the full train loop."""
    from tensorflow_distributed_tpu.config import TrainConfig
    from tensorflow_distributed_tpu.train.loop import train
    cfg = TrainConfig(model="bert_mlm", batch_size=32, train_steps=8,
                      eval_every=4, log_every=0, eval_batch_size=32,
                      compute_dtype="float32",
                      mesh=MeshConfig(data=2, seq=2, model=2))
    # tiny transformer via the registry's override path
    from tensorflow_distributed_tpu.models import build_model
    import tensorflow_distributed_tpu.models as models_pkg
    orig = models_pkg.build_model

    def tiny_build(name, **kw):
        kw["size"] = "tiny"
        kw.setdefault("max_len", 128)
        return orig(name, **kw)

    import tensorflow_distributed_tpu.train.loop as loop_mod
    old = loop_mod.build_model
    loop_mod.build_model = tiny_build
    try:
        result = train(cfg)
    finally:
        loop_mod.build_model = old
    assert int(jax.device_get(result.state.step)) == 8
    assert np.isfinite(result.final_metrics["loss"])


@pytest.mark.slow
def test_mesh_equivalence_dp_vs_composite(devices8):
    """Same batch, same init: a dp-only mesh and a dp+sp+tp mesh compute
    the same loss (the TP/SP decomposition is exact, not approximate)."""
    ds = synthetic_mlm(n=128, seq_len=32, vocab_size=64, seed=1)
    batch_np = LmBatcher(ds, 32, seed=0).forever()
    b = next(batch_np)

    losses = {}
    for name, cfg in [("dp", MeshConfig(data=2, seq=1, model=1)),
                      ("comp", MeshConfig(data=2, seq=2, model=2))]:
        n = 2 if name == "dp" else 8
        mesh = make_mesh(cfg, devices8[:n])
        state = _mlm_state(mesh)
        ev = make_eval_step(mesh, loss=mlm_loss,
                            batch_shardings=mlm_batch_shardings(mesh))
        m = ev(state, shard_batch(mesh, b, seq_axis=1))
        losses[name] = float(jax.device_get(m["loss"]))
    np.testing.assert_allclose(losses["dp"], losses["comp"], rtol=2e-5)


def test_gpt2_size_ladder_param_counts():
    """The medium/large/xl presets must land on the published GPT-2
    backbone sizes (with tied embeddings, the configuration the
    124M/355M/774M/1.56B numbers count) — abstractly, no init FLOPs."""
    from tensorflow_distributed_tpu.models.transformer import gpt_lm

    expected = {"small": 124e6, "medium": 355e6, "large": 774e6,
                "xl": 1558e6}
    for size, want in expected.items():
        model = gpt_lm(size=size, tie_embeddings=True)
        shapes = jax.eval_shape(
            model.init, jax.random.PRNGKey(0),
            np.zeros((1, 8), np.int32))
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(shapes))
        assert 0.95 * want < n < 1.06 * want, (size, n, want)
