"""Pipeline parallelism: schedule correctness, gradients, end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.parallel.mesh import make_mesh
from tensorflow_distributed_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)


def _mesh_pipe4(devices8):
    return make_mesh(MeshConfig(data=2, pipe=4), devices8)


def _mlp_stage(params, x):
    # One pipeline stage = scan over its layers; each layer a tanh MLP.
    def layer(x, p):
        return jnp.tanh(x @ p["w"] + p["b"]), None
    y, _ = jax.lax.scan(layer, x, params)
    return y


def _stacked_mlp_params(n_layers, d, key):
    ks = jax.random.split(key, n_layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (d, d)) * 0.3 for k in ks]),
        "b": jnp.zeros((n_layers, d)),
    }


def _sequential(params, x):
    return _mlp_stage(params, x)  # scan over ALL layers in order


@pytest.mark.parametrize("microbatches", [4, 8])
def test_pipeline_matches_sequential(devices8, microbatches):
    mesh = _mesh_pipe4(devices8)
    d, n_layers, B = 16, 8, 32
    params = _stacked_mlp_params(n_layers, d, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (B, d))
    staged = stack_stage_params(params, 4)
    got = jax.jit(lambda p, x: pipeline_apply(
        _mlp_stage, p, x, mesh, microbatches))(staged, x)
    want = _sequential(params, x)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match(devices8):
    mesh = _mesh_pipe4(devices8)
    d, n_layers, B = 8, 4, 16
    params = _stacked_mlp_params(n_layers, d, jax.random.key(2))
    x = jax.random.normal(jax.random.key(3), (B, d))

    def loss_pipe(p, x):
        staged = stack_stage_params(p, 4)
        return jnp.sum(jnp.sin(pipeline_apply(_mlp_stage, staged, x,
                                              mesh, 4)))

    def loss_seq(p, x):
        return jnp.sum(jnp.sin(_sequential(p, x)))

    gp = jax.jit(jax.grad(loss_pipe))(params, x)
    gs = jax.grad(loss_seq)(params, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-4),
        gp, gs)


def test_pipeline_validates():
    import jax as j
    mesh = make_mesh(MeshConfig(data=2, pipe=4), j.devices())
    x = jnp.zeros((10, 4))
    p = {"w": jnp.zeros((4, 1, 4, 4))}
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(_mlp_stage, p, x, mesh, 3)
    with pytest.raises(ValueError, match="microbatches >= stages"):
        pipeline_apply(_mlp_stage, p, jnp.zeros((8, 4)), mesh, 2)


@pytest.mark.slow
def test_pipelined_lm_trains(devices8):
    """End-to-end: 4-stage pipelined causal LM under dp=2 learns the
    stride progression well above chance."""
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="pipelined_lm", model_size="tiny",
                      dataset="synthetic", batch_size=64, train_steps=60,
                      eval_every=0, log_every=0, eval_batch_size=64,
                      compute_dtype="float32", learning_rate=3e-3,
                      pipeline_schedule="gpipe",  # this is the GPipe test
                      mesh=MeshConfig(data=2, pipe=4))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.4, result.final_metrics
