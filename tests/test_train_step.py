"""Sync-semantics parity tests — the heart of the port (SURVEY.md §7).

Proves the psum train step is *semantically* the reference's sync mode
(mean of per-replica gradients, one Adam apply, one global_step bump per
aggregate, mnist_python_m.py:216-222):

1. 8-device and 1-device runs on the same global batch produce the same
   params/loss (the reference could never test this — its replicas
   sampled data independently).
2. The implicit-jit formulation == the explicit shard_map/psum
   formulation.
3. Loss decreases; step counts like global_step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.data.mnist import ShardedBatcher
from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.collectives import (
    make_per_shard_grads, make_shardmap_train_step, ps_style_grad_sync)
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import (
    TrainState, create_train_state, param_count)
from tensorflow_distributed_tpu.train.step import make_eval_step, make_train_step


def _model():
    # dropout off + f32 so N-vs-1 comparisons are exact
    return MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def _state(mesh, lr=1e-3):
    model = _model()
    tx = optax.adam(lr)
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    return create_train_state(model, tx, x, mesh, seed=0)


def test_state_creation_and_param_count(mesh8):
    state = _state(mesh8)
    assert param_count(state.params) == 3_274_634
    assert int(state.step) == 0


def test_params_identical_across_meshes(mesh1, mesh8):
    s1, s8 = _state(mesh1), _state(mesh8)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s1.params, s8.params)


def test_loss_decreases_and_step_counts(mesh8):
    state = _state(mesh8, lr=1e-3)
    step = make_train_step(mesh8)
    imgs, labels = _batch(64)
    batch = shard_batch(mesh8, (imgs, labels))
    losses = []
    for _ in range(12):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 12  # global_step semantics (SURVEY.md N15)
    assert losses[-1] < losses[0] * 0.6


def test_adafactor_optimizer_trains(mesh8):
    """adafactor (factored second moments — the TPU-scale optimizer)
    drives the same jitted step; its state shards like params."""
    from tensorflow_distributed_tpu.config import TrainConfig
    from tensorflow_distributed_tpu.train.optim import make_optimizer

    tx = make_optimizer(TrainConfig(optimizer="adafactor",
                                    learning_rate=1e-2))
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    state = create_train_state(_model(), tx, x, mesh8, seed=0)
    step = make_train_step(mesh8)
    batch = shard_batch(mesh8, _batch(64))
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8
    # Factored state is strictly smaller than Adam's 2x param count.
    opt_elems = sum(x.size for x in jax.tree_util.tree_leaves(
        state.opt_state) if hasattr(x, "size"))
    assert opt_elems < param_count(state.params)


def test_n_device_equals_1_device(mesh1, mesh8):
    """THE parity test: same global batch stream -> same training
    trajectory on a 1-device mesh and an 8-device mesh."""
    s1, s8 = _state(mesh1), _state(mesh8)
    step1, step8 = make_train_step(mesh1, donate=False), make_train_step(
        mesh8, donate=False)
    for i in range(3):
        imgs, labels = _batch(64, seed=i)
        s1, m1 = step1(s1, shard_batch(mesh1, (imgs, labels)))
        s8, m8 = step8(s8, shard_batch(mesh8, (imgs, labels)))
        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                                   rtol=1e-5)
    # f32 psum reassociation differs from a single-device sum by ~1 ulp;
    # Adam's rsqrt amplifies that on near-zero second moments, so the
    # bound is loose in rtol but tight in atol.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=5e-5),
        s1.params, s8.params)


def test_jit_equals_explicit_shardmap_psum(mesh8):
    """The implicit-XLA-collective step == the hand-written psum step."""
    s_jit, s_map = _state(mesh8), _state(mesh8)
    jstep = make_train_step(mesh8, donate=False)
    mstep = make_shardmap_train_step(mesh8)
    for i in range(3):
        batch = shard_batch(mesh8, _batch(64, seed=10 + i))
        s_jit, mj = jstep(s_jit, batch)
        s_map, mm = mstep(s_map, batch)
        np.testing.assert_allclose(float(mj["loss"]), float(mm["loss"]),
                                   rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6),
        s_jit.params, s_map.params)


def test_ps_emulation_matches_psum_mean(mesh8):
    """The ps-style host-gather baseline computes the same mean gradient
    the psum does — it's the transport that differs (that's the A/B)."""
    state = _state(mesh8)
    batch = shard_batch(mesh8, _batch(64, seed=42))
    sync = ps_style_grad_sync(mesh8)
    ps_grads, _latency = sync(state, batch)

    grad_stack = make_per_shard_grads(mesh8)(state, batch[0], batch[1])
    psum_mean = jax.tree_util.tree_map(
        lambda g: np.asarray(g).mean(axis=0), grad_stack)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), b, rtol=1e-5, atol=1e-7),
        ps_grads, psum_mean)


def test_eval_step_replicated_metrics(mesh8):
    state = _state(mesh8)
    ev = make_eval_step(mesh8)
    metrics = ev(state, shard_batch(mesh8, _batch(128, seed=5)))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0
    assert float(metrics["loss"]) > 0.0


def test_train_batch_not_divisible_raises(mesh8):
    state = _state(mesh8)
    step = make_train_step(mesh8)
    imgs, labels = _batch(30)  # 30 % 8 != 0
    with pytest.raises(Exception):
        step(state, shard_batch(mesh8, (imgs, labels)))


@pytest.mark.slow
def test_grad_accum_matches_full_batch(mesh8, tiny_data):
    """accum_steps=4 must produce the same update as one full-batch
    step (dropout-free model config => exact same math up to fp
    reassociation)."""
    import optax

    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step

    train, _, _ = tiny_data
    batch = shard_batch(mesh8, (train.images[:64], train.labels[:64]))

    def run(accum):
        model = MnistCNN(compute_dtype=jnp.float32, dropout_rate=0.0)
        state = create_train_state(
            model, optax.sgd(0.1),
            np.zeros((2, 28, 28, 1), np.float32), mesh8, seed=0)
        step = make_train_step(mesh8, accum_steps=accum)
        state, metrics = step(state, batch)
        return state, metrics

    s1, m1 = run(1)
    s4, m4 = run(4)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-6, rtol=2e-5),
        s1.params, s4.params)
