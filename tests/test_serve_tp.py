"""Tensor-parallel serve engine: the replica itself sharded.

Both tests are slow tier (they compile 2-device SPMD decode programs
on the virtual 8-CPU topology the conftest forces). The first pins the
cache sharding CONTRACT — the decode cache comes back from step 1 in
the exact head-sharded layout it was created with, and the per-device
byte arithmetic is honest (a width-1 twin reports 2x). The second is
the resilience acceptance at TP: a model=2 serving process SIGKILLed
mid-traffic resumes from its journal and finishes every stream
token-identical to an unfaulted model=2 run.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from tensorflow_distributed_tpu.serve import journal as journal_mod
from tensorflow_distributed_tpu.serve.scheduler import Request, Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tp_engine(num_slots=2):
    """A SlotDecodeEngine over a model=2 mesh: gpt_lm-tiny (4 heads,
    divisible) with params placed by its own partition metadata."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import param_sharding
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    mesh = make_mesh(MeshConfig(data=1, model=2), jax.devices()[:2])
    model = gpt_lm(mesh, size="tiny", max_len=64, dropout_rate=0.0,
                   compute_dtype=jnp.float32)
    sample = jnp.zeros((1, 8), jnp.int32)
    abstract = jax.eval_shape(lambda k: model.init(k, sample),
                              jax.random.key(0))
    variables = jax.jit(
        lambda k: nn.meta.unbox(model.init(k, sample)),
        out_shardings=param_sharding(mesh, abstract))(jax.random.key(0))
    return SlotDecodeEngine(model, variables["params"],
                            num_slots=num_slots), model, mesh


def _requests(n=3, max_new=8):
    return [Request(rid=i,
                    prompt=np.random.default_rng(i).integers(
                        0, 64, size=L).astype(np.int32),
                    max_new_tokens=max_new)
            for i, L in enumerate([3, 9, 5][:n])]


@pytest.mark.slow
def test_tp_cache_sharding_contract_and_per_device_bytes():
    """The decode cache's head-sharded layout survives real traffic:
    the contract is ARMED automatically at tp_width>1 (step 1 asserts
    inside step()), the final cache still matches the creation-time
    snapshot, a KV leaf is physically split over the model axis, and
    cache_bytes_per_slot reports per-DEVICE bytes (width-1 twin = 2x)."""
    import jax
    import jax.numpy as jnp

    from tensorflow_distributed_tpu.analysis import runtime as graftcheck
    from tensorflow_distributed_tpu.models.transformer import gpt_lm
    from tensorflow_distributed_tpu.serve.engine import SlotDecodeEngine

    eng, model, mesh = _tp_engine()
    assert eng.tp_width == 2
    declared = eng._declared_cache
    assert declared is not None, "TP must arm the contract without --check"
    specs = [str(getattr(s, "spec", "")) for s in
             jax.tree_util.tree_leaves(declared) if s is not None]
    assert any("model" in s for s in specs), specs

    done = {c.rid: c for c in
            Scheduler(eng, decode_priority=2).run(_requests())}
    assert len(done) == 3 and eng.decode_steps >= 1
    assert all(len(c.tokens) == 8 for c in done.values())
    # Post-traffic re-assertion (step() checked step 1; this pins that
    # later steps didn't drift either). Raises on violation.
    graftcheck.assert_sharding_contract(eng.cache, declared,
                                        what="decode cache")
    # Physical split: a rank-4 KV leaf holds half its heads per device.
    kv = [lf for lf in jax.tree_util.tree_leaves(eng.cache)
          if getattr(lf, "ndim", 0) == 4]
    assert kv, "no rank-4 KV leaves in the dense cache?"
    leaf = kv[0]
    assert leaf.addressable_shards[0].data.shape[2] * 2 == leaf.shape[2]

    m1 = gpt_lm(None, size="tiny", max_len=64, dropout_rate=0.0,
                compute_dtype=jnp.float32)
    params1 = m1.init(jax.random.key(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]
    eng1 = SlotDecodeEngine(m1, params1, num_slots=2)
    assert eng1.cache_bytes_per_slot() == 2 * eng.cache_bytes_per_slot()


def _child_env():
    # Unlike test_serve_fire's children, TP children NEED the forced
    # multi-device CPU topology, and it must be set before the child's
    # backend initializes.
    return {
        "PATH": os.environ["PATH"],
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
        "PYTHONUNBUFFERED": "1",
    }


_TP_SERVE_ARGS = [
    "--mode", "serve", "--model", "gpt_lm", "--model-size", "tiny",
    "--seq-len", "48", "--compute-dtype", "float32",
    "--serve.mesh-model", "2",
    "--serve.num-slots", "2", "--serve.num-requests", "6",
    "--serve.prompt-len-min", "4", "--serve.prompt-len-max", "10",
    "--serve.max-new-tokens", "16",
]


@pytest.mark.slow
def test_tp_supervisor_sigkill_journal_resume_identity(tmp_path):
    """SIGKILL a model=2 serving process mid-traffic; the supervisor
    restarts it, the new leg replays the journal onto a FRESH
    tensor-parallel engine (sharded cache re-prefilled from
    continuations), and every final stream is identical to an
    unfaulted model=2 run — resume composes with TP."""
    clean_j = str(tmp_path / "clean.journal")
    proc = subprocess.run(
        [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
         *_TP_SERVE_ARGS, "--serve.journal", clean_j],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=500)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    clean = journal_mod.replay(clean_j)
    assert len(clean) == 6 and all(e["done"] for e in clean.values())

    journal = str(tmp_path / "tp.journal")
    proc = subprocess.run(
        [sys.executable, "-m",
         "tensorflow_distributed_tpu.resilience.supervisor",
         "--max-restarts", "2", "--backoff-base-s", "0.2", "--",
         *_TP_SERVE_ARGS, "--serve.journal", journal,
         "--resilience.fault-plan", "sigkill@20"],
        env=_child_env(), cwd=REPO, capture_output=True, text=True,
        timeout=500)
    assert proc.returncode == 0, \
        proc.stdout[-3000:] + proc.stderr[-2000:]
    assert '"kind": "restart"' in proc.stdout
    played = journal_mod.replay(journal)
    assert len(played) == 6 and all(e["done"] for e in played.values())
    assert {r: e["tokens"] for r, e in played.items()} == \
        {r: e["tokens"] for r, e in clean.items()}
