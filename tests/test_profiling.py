"""Profiling subsystem: step-windowed traces produce XPlane artifacts."""

import glob
import os

import jax
import jax.numpy as jnp

from tensorflow_distributed_tpu.utils.profiling import (
    StepProfiler, annotate, trace)


def _work():
    x = jnp.ones((64, 64))
    jax.block_until_ready(jnp.dot(x, x))


def test_step_profiler_window(tmp_path):
    p = StepProfiler(log_dir=str(tmp_path), start_step=2, num_steps=2)
    for step in range(1, 6):
        p.observe(step)
        with annotate(f"step{step}"):
            _work()
    p.stop()
    files = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                      recursive=True)
    assert files, "no trace artifact written"


def test_step_profiler_disabled_is_noop(tmp_path):
    p = StepProfiler(log_dir="")
    for step in range(5):
        p.observe(step)
    p.stop()
    assert not os.listdir(tmp_path)


def test_trace_span(tmp_path):
    with trace(str(tmp_path)):
        _work()
    files = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                      recursive=True)
    assert files
