"""graftcheck: the self-hosting static-analysis toolchain.

Three layers under test:

- the AST lint engine (analysis/lint.py + rules/): per-rule
  positive/negative fixtures, suppression handling, CLI exit codes,
  and the SELF-HOSTING gate — the whole package must lint clean. The
  engine is pure stdlib by contract (a subprocess test proves it
  imports with jax poisoned away).
- the jaxpr census (analysis/jaxprcheck.py): the audited programs'
  collective/upcast counts vs the committed goldens — the
  failing-on-drift test — plus the drift reporter itself.
- the runtime layer (analysis/runtime.py): the sharding-contract
  assertion catches a drifted layout and accepts equivalent ones; the
  transfer guard blocks implicit transfers; check-mode training runs
  end to end.

The lint fixtures are jax-free; census/runtime tests import jax inside
the test body (tracing only — no SPMD compiles, so they stay in the
default tier).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from tensorflow_distributed_tpu.analysis.lint import (
    lint_paths, lint_source, main as lint_main, PACKAGE_ROOT)


def findings(src: str, path: str = "mod.py"):
    return lint_source(textwrap.dedent(src), path)


def rules_of(src: str, path: str = "mod.py"):
    return [f.rule for f in findings(src, path)]


# --- host-sync-under-trace ---------------------------------------------

def test_host_sync_under_trace_positive():
    src = """
    import jax

    @jax.jit
    def f(x):
        return float(x) + x.item()
    """
    assert rules_of(src) == ["host-sync-under-trace"] * 2


def test_host_sync_under_trace_via_jit_reference():
    # Not decorated — passed to jax.jit by name, like every step
    # builder in train/.
    src = """
    import jax

    def make(mesh):
        def step(state, batch):
            return jax.device_get(state)
        return jax.jit(step, donate_argnums=(0,))
    """
    assert rules_of(src) == ["host-sync-under-trace"]


def test_host_sync_under_trace_transitive_callee():
    # step is traced; helper is called from step's body — traced too.
    src = """
    import jax
    import numpy as np

    def make():
        def helper(x):
            return np.asarray(x)

        def step(x):
            return helper(x) + 1
        return jax.jit(step)
    """
    assert rules_of(src) == ["host-sync-under-trace"]


def test_host_sync_negative_outside_trace():
    src = """
    import jax

    def report(metrics):
        return float(jax.device_get(metrics)["loss"])
    """
    assert rules_of(src) == []


# --- host-sync-in-loop -------------------------------------------------

def test_host_sync_in_loop_positive_hot_module():
    src = """
    import jax

    def train(step_fn, state, batches):
        for b in batches:
            state, m = step_fn(state, b)
            loss = jax.device_get(m)
        return state
    """
    assert rules_of(src, "pkg/train/loop.py") == ["host-sync-in-loop"]


def test_host_sync_in_loop_transitive_helper():
    # No loop inside _inspect — it is called from one (the actual
    # shape of train/loop.py's per-step policy helper).
    src = """
    import jax

    def train(step_fn, state, batches):
        def _inspect(m):
            return float(jax.device_get(m)) > 0

        for b in batches:
            state, m = step_fn(state, b)
            _inspect(m)
        return state
    """
    assert rules_of(src, "pkg/train/loop.py") == ["host-sync-in-loop"]


def test_host_sync_methods_in_hot_module():
    # Methods can't be followed through self.engine.step() attribute
    # calls, so in a hot module EVERY method is assumed hot (the serve
    # engine's per-decode-step device reads are the real case).
    src = """
    import jax
    import numpy as np

    class Engine:
        def step(self):
            return np.asarray(jax.device_get(self.tok))
    """
    assert rules_of(src, "pkg/serve/engine.py") == [
        "host-sync-in-loop"] * 2
    assert rules_of(src, "pkg/models/thing.py") == []


def test_host_sync_in_loop_cold_module_not_flagged():
    src = """
    import jax

    def summarize(records):
        for r in records:
            yield jax.device_get(r)
    """
    assert rules_of(src, "pkg/observe/report.py") == []


# --- prng-reuse --------------------------------------------------------

def test_prng_reuse_positive():
    src = """
    import jax

    def sample(seed):
        k = jax.random.key(seed)
        a = jax.random.normal(k, (3,))
        b = jax.random.uniform(k, (3,))
        return a + b
    """
    assert rules_of(src) == ["prng-reuse"]


def test_prng_reuse_rngs_keyword():
    src = """
    import jax

    def init_and_apply(model, x, seed):
        k = jax.random.key(seed)
        params = model.init(x, rngs={"dropout": k})
        out = model.apply(params, x, rngs={"dropout": k})
        return out
    """
    assert rules_of(src) == ["prng-reuse"]


def test_prng_reuse_in_loop():
    # The canonical bug: one key drawn from on every iteration.
    bad = """
    import jax

    def sample(seed, n):
        k = jax.random.key(seed)
        out = []
        for i in range(n):
            out.append(jax.random.normal(k, (3,)))
        return out
    """
    good = """
    import jax

    def sample(seed, n):
        k = jax.random.key(seed)
        out = []
        for i in range(n):
            k, sub = jax.random.split(k)
            out.append(jax.random.normal(sub, (3,)))
        return out
    """
    assert rules_of(bad) == ["prng-reuse"]
    assert rules_of(good) == []


def test_prng_split_and_fold_in_negative():
    src = """
    import jax

    def sample(seed):
        k = jax.random.key(seed)
        k, sub = jax.random.split(k)
        a = jax.random.normal(sub, (3,))
        k = jax.random.fold_in(k, 1)
        b = jax.random.uniform(k, (3,))
        return a + b
    """
    assert rules_of(src) == []


# --- jit-in-loop -------------------------------------------------------

def test_jit_in_loop_positive_and_hoisted_negative():
    bad = """
    import jax

    def run(xs):
        out = []
        for x in xs:
            out.append(jax.jit(lambda y: y + 1)(x))
        return out
    """
    good = """
    import jax

    def run(xs):
        f = jax.jit(lambda y: y + 1)
        return [f(x) for x in xs]
    """
    assert rules_of(bad) == ["jit-in-loop"]
    assert rules_of(good) == []


# --- use-after-donation ------------------------------------------------

def test_use_after_donation_positive():
    src = """
    import jax

    def run(f, state, batch):
        step = jax.jit(f, donate_argnums=(0,))
        new_state, m = step(state, batch)
        return new_state, state.params
    """
    assert rules_of(src) == ["use-after-donation"]


def test_use_after_donation_factory_registry():
    src = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def run(mesh, state, batch):
        step = make_train_step(mesh)
        new_state, m = step(state, batch)
        print(state)
        return new_state
    """
    assert rules_of(src) == ["use-after-donation"]


def test_use_after_donation_loop_without_rebind():
    src = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def bench(mesh, state, batches):
        step = make_train_step(mesh)
        for b in batches:
            _, m = step(state, b)
        return m
    """
    assert rules_of(src) == ["use-after-donation"]


def test_use_after_donation_safe_rebind_negative():
    # The repo idiom: same-statement rebind, including in a loop.
    src = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def run(mesh, state, batches):
        step = make_train_step(mesh)
        for b in batches:
            state, m = step(state, b)
        return state, m
    """
    assert rules_of(src) == []


def test_use_after_donation_is_scope_and_flow_sensitive():
    # A sibling scope's `step = make_train_step(...)` must not
    # contaminate a scope where `step` is something else — and a name
    # rebound to a non-donor later in the SAME scope stops donating.
    siblings = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def build(mesh):
        step = make_train_step(mesh)
        return step

    def unrelated(step_impl, state, batch):
        step = step_impl
        out = step(state, batch)
        return state
    """
    rebound = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def run(mesh, undonated, state, batch):
        step = make_train_step(mesh)
        new_state, m = step(state, batch)
        step = undonated
        out = step(new_state, batch)
        return new_state
    """
    inherited = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def run(state, batch):
        new_state = step(state, batch)
        return state
    """
    assert rules_of(siblings) == []
    assert rules_of(rebound) == []
    # Module-level donor bindings ARE visible inside functions.
    assert rules_of(inherited) == ["use-after-donation"]


def test_use_after_donation_suppressed_read_keeps_tracking():
    # A suppressed read must not consume the one-finding-per-donation
    # budget — the NEXT unsuppressed read still reports.
    src = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def run(mesh, state, batch):
        step = make_train_step(mesh)
        new_state, m = step(state, batch)
        x = state.meta  # graftcheck: disable=use-after-donation -- host field
        return new_state, state.params
    """
    assert rules_of(src) == ["use-after-donation"]


def test_hot_module_suffix_is_separator_anchored():
    src = """
    import jax

    def run(batches):
        for b in batches:
            out = jax.device_get(b)
        return out
    """
    # observe/run.py must NOT match the serve/run.py hot suffix.
    assert rules_of(src, "pkg/observe/run.py") == []
    assert rules_of(src, "pkg/serve/run.py") == ["host-sync-in-loop"]


def test_use_after_donation_undonated_factory_negative():
    src = """
    from tensorflow_distributed_tpu.train.step import make_train_step

    def run(mesh, state, batch):
        step = make_train_step(mesh, donate=False)
        new_state, m = step(state, batch)
        return new_state, state.params
    """
    assert rules_of(src) == []


def test_donation_audit_repo_call_sites_clean():
    """The executable audit of the satellite task: the four donating
    step builders' real call sites (train loop + benchmarks) contain
    no use-after-donation finding — every site uses the safe
    same-statement rebind."""
    import os
    audited = [
        "train/loop.py", "train/step.py", "train/multistep.py",
        "train/local_sgd.py", "train/pipeline_step.py",
        "benchmarks/lm_perf.py", "benchmarks/moebench.py",
        "benchmarks/gradsync.py",
    ]
    paths = [os.path.join(PACKAGE_ROOT, p) for p in audited]
    assert [f for f in lint_paths(paths)
            if f.rule == "use-after-donation"] == []


# --- effect-under-trace ------------------------------------------------

def test_effect_under_trace_positive():
    src = """
    import jax
    import time

    @jax.jit
    def f(x):
        print("tracing")
        t = time.time()
        return x + t
    """
    assert rules_of(src) == ["effect-under-trace"] * 2


def test_effect_in_scan_body():
    src = """
    import jax

    def run(xs):
        def body(c, x):
            print(x)
            return c, x
        return jax.lax.scan(body, 0, xs)
    """
    assert rules_of(src) == ["effect-under-trace"]


def test_effect_outside_trace_negative():
    src = """
    def report(x):
        print(x)
    """
    assert rules_of(src) == []


# --- suppressions ------------------------------------------------------

def test_suppression_same_line():
    src = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # graftcheck: disable=host-sync-under-trace -- fixture
    """
    assert rules_of(src) == []


def test_suppression_comment_block_above():
    src = """
    import jax

    @jax.jit
    def f(x):
        # this value is static by construction (documented why)
        # graftcheck: disable=host-sync-under-trace -- static config read
        return x.item()
    """
    assert rules_of(src) == []


def test_suppression_multiline_statement():
    src = """
    import jax

    def train(step_fn, state, batches):
        for b in batches:
            # graftcheck: disable=host-sync-in-loop -- fixture
            loss = float(jax.device_get(
                b["loss"]))
        return state
    """
    assert rules_of(src, "pkg/train/loop.py") == []


def test_suppression_wrong_rule_does_not_silence():
    src = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # graftcheck: disable=prng-reuse -- wrong rule
    """
    assert rules_of(src) == ["host-sync-under-trace"]


def test_suppression_on_code_line_above_does_not_leak():
    # A trailing suppression on the PREVIOUS code line belongs to that
    # line, not to the statement below it.
    src = """
    import jax

    @jax.jit
    def f(x, y):
        a = y.item()  # graftcheck: disable=host-sync-under-trace -- this line
        return x.item() + a
    """
    assert rules_of(src) == ["host-sync-under-trace"]


def test_suppression_multiple_rules():
    src = """
    import jax

    def train(step_fn, state, batches):
        for b in batches:
            # graftcheck: disable=host-sync-in-loop,jit-in-loop -- fixture
            loss = jax.device_get(jax.jit(lambda y: y)(b))
        return state
    """
    assert rules_of(src, "pkg/train/loop.py") == []
    # A suppression covers ONLY the statement below its comment block —
    # the next statement still reports.
    src_two = """
    import jax

    def train(step_fn, state, batches):
        for b in batches:
            # graftcheck: disable=jit-in-loop -- fixture
            f = jax.jit(lambda y: y)
            loss = jax.device_get(b)
        return state
    """
    assert rules_of(src_two, "pkg/train/loop.py") == ["host-sync-in-loop"]


# --- driver / CLI ------------------------------------------------------

def test_lint_cli_exit_codes(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x + 1\n")
    assert lint_main([str(dirty)]) == 1
    assert lint_main([str(clean)]) == 0
    assert lint_main([str(tmp_path)]) == 1   # directory recursion
    assert lint_main(["--list-rules"]) == 0


def test_lint_engine_is_jax_free():
    """The lint tier's contract: importing and running the linter must
    not touch jax (proven by poisoning the import in a subprocess)."""
    code = textwrap.dedent("""
        import builtins
        real = builtins.__import__
        def guard(name, *a, **k):
            if name == "jax" or name.startswith("jax."):
                # name= matters: the package root re-raises any
                # ModuleNotFoundError that is not jax/jaxlib itself.
                raise ModuleNotFoundError(
                    f"No module named {name!r}", name="jax")
            return real(name, *a, **k)
        builtins.__import__ = guard
        from tensorflow_distributed_tpu.analysis.lint import lint_source
        fs = lint_source("import jax\\n\\n@jax.jit\\ndef f(x):\\n"
                         "    return x.item()\\n", "m.py")
        assert [f.rule for f in fs] == ["host-sync-under-trace"], fs
        print("OK")
    """)
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def test_repo_lints_clean():
    """SELF-HOSTING: the whole package must have zero unsuppressed
    findings — graftcheck gates the code that ships it."""
    assert [f.render() for f in lint_paths([PACKAGE_ROOT])] == []


# --- jaxpr census vs goldens -------------------------------------------

def test_census_matches_golden():
    """The failing-on-drift gate: the audited programs' collective and
    upcast counts equal the committed budgets. A red here means a PR
    changed the program contract — fix it, or regenerate the golden
    with `python -m tensorflow_distributed_tpu.analysis.jaxprcheck
    --update` and justify the diff."""
    from tensorflow_distributed_tpu.analysis import jaxprcheck

    current = jaxprcheck.census()
    drift = jaxprcheck.diff_censuses(jaxprcheck.load_golden(), current)
    assert drift == [], "\n".join(drift)


def test_census_structure_sane():
    """Ground truths the census must reflect regardless of exact
    counts: the pipelined schedule moves activations with ppermute;
    the single-device LM/decode programs have no collectives; every
    bf16 program upcasts somewhere (loss/norm math)."""
    from tensorflow_distributed_tpu.analysis import jaxprcheck

    golden = jaxprcheck.load_golden()
    assert set(golden) == {"gpt_train", "moe_train", "pipelined_train",
                           "serve_decode", "gpt_train_health",
                           "moe_train_health",
                           "pipelined_train_health",
                           "gpt_train_overlap", "moe_train_overlap",
                           "serve_verify", "serve_decode_int8",
                           "serve_decode_paged", "serve_verify_paged",
                           "serve_prefill_paged", "serve_decode_tp",
                           "serve_verify_tp"}
    assert golden["pipelined_train"]["collectives"].get("ppermute", 0) > 0
    assert golden["gpt_train"]["collectives"] == {}
    assert golden["serve_decode"]["collectives"] == {}
    # Fast-path serving invariants: the speculative verify and the
    # int8 decode stay collective-free (per-token cost work is local),
    # and int8's quantize-on-write/scale-adjusted-attend adds only a
    # BOUNDED number of converts next to the plain decode program.
    assert golden["serve_verify"]["collectives"] == {}
    assert golden["serve_decode_int8"]["collectives"] == {}
    plain_up = golden["serve_decode"]["upcasts"].get(
        "bfloat16->float32", 0)
    int8_up = golden["serve_decode_int8"]["upcasts"].get(
        "bfloat16->float32", 0)
    # <= 8 extra converts per layer (tiny = 2): the q8 absmax/scale
    # math + the two scale-adjusted dots — NOT a chain-wide f32 drift.
    assert plain_up < int8_up <= plain_up + 16
    # Paged-KV serving invariants (serve/paging): page-table
    # addressing is local gather/scatter — zero collectives in all
    # three paged executables, and the paged decode's upcast count
    # EQUALS the dense decode's (same attend math over the same
    # logical layout; paging relocates bytes, it does not widen them).
    for name in ("serve_decode_paged", "serve_verify_paged",
                 "serve_prefill_paged"):
        assert golden[name]["collectives"] == {}, name
    assert (golden["serve_decode_paged"]["upcasts"]
            == golden["serve_decode"]["upcasts"])
    # Tensor-parallel serving invariants: the model=2 decode/verify
    # programs MUST carry collectives (head-sharded attention + MLP
    # reassemble activations every step — TP that compiles to zero
    # collectives silently replicated somewhere), while the upcast
    # counts equal the dense program's (sharding relocates math, it
    # does not widen it). These census entries are HLO-derived
    # (GSPMD emits the collectives after partitioning), hence the
    # hyphenated names.
    for name in ("serve_decode_tp", "serve_verify_tp"):
        tp_coll = golden[name]["collectives"]
        assert sum(tp_coll.values()) > 0, name
        assert (golden[name]["upcasts"]
                == golden["serve_decode"]["upcasts"]), name
    # The overlap grad-sync invariant: an explicit reduce-scatter AND
    # an explicit all-gather per scatter bucket (counts equal — a
    # bucket that scatters but never gathers back would train on
    # stale params), plus >= 1 psum (replicated small leaves + the
    # metric pmean).
    for name in ("gpt_train_overlap", "moe_train_overlap"):
        c = golden[name]["collectives"]
        assert c.get("reduce_scatter", 0) > 1, name
        assert c["reduce_scatter"] == c["all_gather"], name
        assert c.get("psum", 0) >= 1, name
    for prog in golden.values():
        assert prog["upcasts"].get("bfloat16->float32", 0) > 0
    # The device-telemetry invariant the health entries exist to pin:
    # enabling per-layer vitals adds NO collectives to any schedule
    # (the stats are local reductions riding the existing metrics).
    for name in ("gpt_train", "moe_train", "pipelined_train"):
        assert (golden[f"{name}_health"]["collectives"]
                == golden[name]["collectives"]), name


def test_census_drift_reporting():
    from tensorflow_distributed_tpu.analysis.jaxprcheck import (
        diff_censuses)

    golden = {"p": {"collectives": {"psum": 2}, "upcasts": {}}}
    current = {"p": {"collectives": {"psum": 2, "all_gather": 1},
                     "upcasts": {"bfloat16->float32": 3}}}
    drift = diff_censuses(golden, current)
    assert any("all_gather] 0 -> 1" in d for d in drift)
    assert any("bfloat16->float32] 0 -> 3" in d for d in drift)
    assert diff_censuses(golden, {"p": golden["p"]}) == []
    # A FULL run missing a golden program is drift (a deleted PROGRAMS
    # entry must not silently disarm its budget)...
    assert any("missing from the run" in d
               for d in diff_censuses(golden, {}))
    # ...but an explicit partial run compares only what it traced.
    assert diff_censuses(golden, {}, required=[]) == []
    assert diff_censuses({"p": golden["p"], "q": golden["p"]},
                         {"p": golden["p"]}, required=["p"]) == []


# --- runtime layer (--check) -------------------------------------------

def test_sharding_contract_assertion(mesh8):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflow_distributed_tpu.analysis.runtime import (
        ShardingContractError, assert_sharding_contract, sharding_tree)

    x = jax.device_put(np.ones((8, 4), np.float32),
                       NamedSharding(mesh8, P("data")))
    declared = sharding_tree({"w": x})
    # Equivalent spec spelled differently still satisfies the contract.
    x_eq = jax.device_put(np.ones((8, 4), np.float32),
                          NamedSharding(mesh8, P("data", None)))
    assert_sharding_contract({"w": x_eq}, declared)
    # A genuinely different layout does not.
    x_drifted = jax.device_put(np.ones((8, 4), np.float32),
                               NamedSharding(mesh8, P()))
    with pytest.raises(ShardingContractError, match=r"\['w'\]"):
        assert_sharding_contract({"w": x_drifted}, declared)


def test_transfer_guard_blocks_implicit():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_distributed_tpu.analysis.runtime import (
        transfer_guard)

    f = jax.jit(lambda x: x + 1)
    f(jnp.ones(4))  # warm: compile outside the guard
    with transfer_guard(True):
        f(jax.device_put(np.ones(4, np.float32)))  # explicit: allowed
    with pytest.raises(Exception, match="[Dd]isallowed.*transfer"):
        with transfer_guard(True):
            f(np.ones(4, np.float32))              # implicit: caught
    with transfer_guard(False):                    # off: transparent
        f(np.ones(4, np.float32))


def test_check_mode_rewind_recovers(mesh8, tmp_path):
    """--check must not strangle recovery: a policy-ordered rewind
    restores a checkpoint (implicit warm-up transfers by design) from
    INSIDE the guarded steady-state loop — the cold path is exempted
    via runtime.transfer_allowed, so the run recovers instead of dying
    on 'Disallowed host-to-device transfer'."""
    import jax

    from tensorflow_distributed_tpu.config import (
        MeshConfig, ResilienceConfig, TrainConfig)
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(dataset="synthetic", batch_size=64,
                      train_steps=16, eval_every=0, log_every=0,
                      eval_batch_size=64, compute_dtype="float32",
                      mesh=MeshConfig(data=8), check=True,
                      checkpoint_dir=str(tmp_path / "ckpt"),
                      checkpoint_every=4,
                      resilience=ResilienceConfig(
                          nonfinite="rewind", max_rewinds=1,
                          fault_plan="nan_grad@8"))
    result = train(cfg)
    assert int(jax.device_get(result.state.step)) == 16


def test_check_mode_train_e2e(mesh8):
    """--check end to end: a short training run under the transfer
    guard + sharding contract completes (the loop's transfers are all
    explicit, and the step hands the params back in their declared
    layout)."""
    import jax

    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(dataset="synthetic", batch_size=64, train_steps=4,
                      eval_every=0, log_every=0, eval_batch_size=64,
                      compute_dtype="float32",
                      mesh=MeshConfig(data=8), check=True)
    result = train(cfg)
    assert int(jax.device_get(result.state.step)) == 4
