"""MoE layer: routing correctness, capacity, EP parity, training."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
from tensorflow_distributed_tpu.models.moe import MoeMlp
from tensorflow_distributed_tpu.parallel.mesh import make_mesh


def _layer(E=4, top_k=2, cap=10.0, d=16, ff=32):
    # Huge default capacity => no drops => exact reference comparison.
    return MoeMlp(d_model=d, d_ff=ff, num_experts=E, top_k=top_k,
                  capacity_factor=cap, compute_dtype=jnp.float32,
                  partitioned=False)


def _reference_moe(params, x, E, top_k):
    """Naive per-token loop oracle (no capacity)."""
    gate, wi, wo = params["gate"], params["wi"], params["wo"]
    probs = jax.nn.softmax(x.astype(jnp.float32) @ gate, axis=-1)
    out = np.zeros_like(np.asarray(x), dtype=np.float32)
    G, S, _ = x.shape
    for g in range(G):
        for s in range(S):
            p = np.asarray(probs[g, s])
            top = np.argsort(-p)[:top_k]
            denom = p[top].sum() if top_k > 1 else 1.0
            for e in top:
                h = np.asarray(jax.nn.gelu(x[g, s] @ wi[e]))
                out[g, s] += (p[e] / denom) * np.asarray(h @ wo[e])
    return out


def test_moe_matches_naive_routing():
    layer = _layer()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 16)),
                    jnp.float32)
    params = layer.init(jax.random.key(0), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])
    want = _reference_moe(params, x, E=4, top_k=2)
    np.testing.assert_allclose(y, want, atol=1e-4, rtol=1e-3)


def test_moe_group_len_matches_naive_routing():
    """group_len splits the sequence into independent routing groups;
    with capacity high enough that nothing drops, routing is per-token
    so the chunked result must equal the oracle AND the unchunked
    layer exactly. The knob's purpose is the dispatch-tensor envelope
    (models/moe.py docstring): [.., S', E, C'] scales with the group
    length, not the sequence."""
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 16)),
                    jnp.float32)
    layer = _layer()
    params = layer.init(jax.random.key(0), x)["params"]
    chunked = MoeMlp(d_model=16, d_ff=32, num_experts=4, top_k=2,
                     capacity_factor=10.0, compute_dtype=jnp.float32,
                     partitioned=False, group_len=4)
    y_c, _ = chunked.apply({"params": params}, x, mutable=["moe_aux"])
    want = _reference_moe(params, x, E=4, top_k=2)
    np.testing.assert_allclose(y_c, want, atol=1e-4, rtol=1e-3)
    y_full, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])
    np.testing.assert_allclose(y_c, y_full, atol=1e-5, rtol=1e-5)

    with pytest.raises(ValueError, match="group_len"):
        bad = MoeMlp(d_model=16, d_ff=32, num_experts=4,
                     partitioned=False, group_len=3)
        bad.init(jax.random.key(0), x)

    cfg = TrainConfig(model="moe_lm", moe_experts=4, seq_len=128,
                      moe_group_len=48, batch_size=32)
    with pytest.raises(ValueError, match="moe_group_len"):
        cfg.validate()

    # Sequences AT OR BELOW group_len route as one group — decode
    # (S=1) and short prefills must work on a model trained with a
    # long-sequence group_len, not crash on divisibility.
    short = jnp.asarray(
        np.random.default_rng(3).normal(size=(2, 1, 16)), jnp.float32)
    y_s, _ = chunked.apply({"params": params}, short,
                           mutable=["moe_aux"])
    assert y_s.shape == short.shape


@pytest.mark.slow  # 10.7s compile on the CI box (second-heaviest
#                    default-tier test; round-6 curation)
def test_moe_scatter_dispatch_matches_dense():
    """dispatch="scatter" is the SAME routing as the dense one-hot
    formulation — identical masks, positions, capacity-drop rule, and
    gates — expressed as a slot scatter-add + gather instead of
    [S, E, C] einsums. Outputs and GRADIENTS must match the dense path
    bit-for-tolerance, both with no drops (huge capacity) and with
    real capacity drops; the aux sows must be identical too."""
    x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 16, 16)),
                    jnp.float32)
    for cap, topk in ((10.0, 2), (0.5, 2), (0.5, 1)):
        dense = MoeMlp(d_model=16, d_ff=32, num_experts=4, top_k=topk,
                       capacity_factor=cap, compute_dtype=jnp.float32,
                       partitioned=False)
        scat = MoeMlp(d_model=16, d_ff=32, num_experts=4, top_k=topk,
                      capacity_factor=cap, compute_dtype=jnp.float32,
                      partitioned=False, dispatch="scatter")
        params = dense.init(jax.random.key(0), x)["params"]

        def loss(layer, p):
            y, aux = layer.apply({"params": p}, x, mutable=["moe_aux"])
            return jnp.sum(y * y), (y, aux)

        (ld, (yd, auxd)), gd = jax.value_and_grad(
            lambda p: loss(dense, p), has_aux=True)(params)
        (ls, (ys, auxs)), gs = jax.value_and_grad(
            lambda p: loss(scat, p), has_aux=True)(params)
        np.testing.assert_allclose(yd, ys, atol=1e-5, rtol=1e-5,
                                   err_msg=f"cap={cap} k={topk}")
        np.testing.assert_allclose(ld, ls, rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, atol=1e-5, rtol=1e-4), gd, gs)
        for name in ("load_balance", "z_loss", "dropped_fraction"):
            np.testing.assert_allclose(
                np.asarray(auxd["moe_aux"][name]),
                np.asarray(auxs["moe_aux"][name]), rtol=1e-6,
                err_msg=name)

    with pytest.raises(ValueError, match="dispatch"):
        MoeMlp(d_model=16, d_ff=32, num_experts=4, partitioned=False,
               dispatch="ragged").init(jax.random.key(0), x)
    with pytest.raises(ValueError, match="moe_dispatch"):
        TrainConfig(model="moe_lm", moe_experts=4, batch_size=32,
                    moe_dispatch="ragged").validate()


@pytest.mark.slow
def test_moe_scatter_dispatch_ep_sharded_step_parity(devices8):
    """The EP-sharded A/B: one full train step of moe_lm on a
    data=4 x expert=2 mesh, scatter vs dense — same loss, same updated
    params. GSPMD partitions the scatter/gather HLOs over the expert
    axis instead of the one-hot einsums; this pins that the layout
    change is not a math change under sharding either."""
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.transformer import moe_lm
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step
    from tensorflow_distributed_tpu.train.tasks import (
        mlm_batch_shardings, moe_loss)

    mesh = make_mesh(MeshConfig(data=4, expert=2), devices8)
    outs = {}
    for disp in ("dense", "scatter"):
        model = moe_lm(mesh, size="tiny", moe_experts=2, max_len=16,
                       moe_dispatch=disp, compute_dtype=jnp.float32,
                       dropout_rate=0.0)
        state = create_train_state(model, optax.sgd(1e-2),
                                   np.zeros((2, 16), np.int32), mesh, 0)
        step = make_train_step(mesh, loss=moe_loss, donate=False,
                               batch_shardings=mlm_batch_shardings(mesh))
        ds = synthetic_clm(n=16, seq_len=16, vocab_size=64)
        b = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
        s2, m = step(state, b)
        outs[disp] = (float(m["loss"]), jax.device_get(s2.params))
    np.testing.assert_allclose(outs["dense"][0], outs["scatter"][0],
                               rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        outs["dense"][1], outs["scatter"][1])


def test_moe_top1():
    layer = _layer(top_k=1)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 16)),
                    jnp.float32)
    params = layer.init(jax.random.key(1), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])
    want = _reference_moe(params, x, E=4, top_k=1)
    np.testing.assert_allclose(y, want, atol=1e-4, rtol=1e-3)


def test_capacity_drops_tokens():
    """With capacity 1 per expert, most tokens must be dropped (zero
    output), never mangled."""
    layer = MoeMlp(d_model=8, d_ff=16, num_experts=2, top_k=1,
                   capacity_factor=2.0 / 16.0,  # C = 1
                   compute_dtype=jnp.float32, partitioned=False)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(1, 16, 8)),
                    jnp.float32)
    params = layer.init(jax.random.key(2), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])
    # At most 2 tokens (1 per expert) produce nonzero output.
    nonzero = np.sum(np.any(np.abs(np.asarray(y)) > 1e-9, axis=-1))
    assert nonzero <= 2


def test_moe_aux_loss_sown():
    from tensorflow_distributed_tpu.models.moe import AUX_NAMES, collect_aux

    layer = _layer()
    x = jnp.ones((2, 8, 16), jnp.float32)
    params = layer.init(jax.random.key(3), x)["params"]
    _, mut = layer.apply({"params": params}, x, mutable=["moe_aux"])
    aux = collect_aux(mut["moe_aux"])
    assert set(aux) == set(AUX_NAMES)
    # With identical tokens, every token routes to the top-k experts,
    # whose mean prob >= the overall mean 1/E => aux >= 1 (== 1 iff
    # perfectly uniform).
    assert float(aux["load_balance"]) >= 1.0 - 1e-5
    assert float(aux["z_loss"]) >= 0.0
    # Huge capacity => nothing dropped.
    assert float(aux["dropped_fraction"]) == 0.0


def test_moe_dropped_fraction_reported_on_overflow():
    """Induce capacity overflow; the drop fraction must be reported and
    nonzero (drops are otherwise silent zeros in the math)."""
    from tensorflow_distributed_tpu.models.moe import collect_aux

    layer = MoeMlp(d_model=8, d_ff=16, num_experts=2, top_k=1,
                   capacity_factor=2.0 / 16.0,  # C = 1 per expert
                   compute_dtype=jnp.float32, partitioned=False)
    x = jnp.asarray(np.random.default_rng(5).normal(size=(1, 16, 8)),
                    jnp.float32)
    params = layer.init(jax.random.key(5), x)["params"]
    _, mut = layer.apply({"params": params}, x, mutable=["moe_aux"])
    aux = collect_aux(mut["moe_aux"])
    # 16 tokens, 2 experts x capacity 1 => at least 14/16 dropped.
    assert float(aux["dropped_fraction"]) >= 14.0 / 16.0 - 1e-6


@pytest.mark.slow
def test_moe_loss_surfaces_router_metrics(devices8):
    """The train-metric path: moe_loss must report dropped_frac and
    z_loss, and the z-loss knob must change the objective."""
    import optax

    from tensorflow_distributed_tpu.models import build_model
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.tasks import make_moe_loss

    mesh = make_mesh(MeshConfig(data=8), devices8)
    model = build_model("moe_lm", mesh=mesh, size="tiny",
                        compute_dtype=jnp.float32,
                        moe_capacity_factor=0.25)  # force overflow
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    rng = np.random.default_rng(6)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32),
        "mask": jnp.ones((8, 16), jnp.float32),
    }
    key = jax.random.key(0)

    def run(loss_fn):
        total, (metrics, _) = loss_fn(model.apply, state.params,
                                      state.extra, batch, key, True)
        return float(total), jax.device_get(metrics)

    base, m = run(make_moe_loss(0.01, 0.0))
    assert m["dropped_frac"] > 0.0, m
    assert m["z_loss"] > 0.0, m
    zed, mz = run(make_moe_loss(0.01, 1.0))
    np.testing.assert_allclose(zed - base, float(mz["z_loss"]),
                               rtol=1e-5, atol=1e-6)


def test_moe_dedicated_expert_axis(devices8):
    """EP over a dedicated "expert" mesh axis (not aliasing "model")
    matches the unsharded oracle."""
    layer = MoeMlp(d_model=16, d_ff=32, num_experts=4, top_k=2,
                   capacity_factor=10.0, compute_dtype=jnp.float32,
                   expert_axis="expert", partitioned=False)
    x = jnp.asarray(np.random.default_rng(7).normal(size=(4, 8, 16)),
                    jnp.float32)
    params = layer.init(jax.random.key(7), x)["params"]
    want, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])

    mesh = make_mesh(MeshConfig(data=2, expert=4), devices8)
    from tensorflow_distributed_tpu.parallel.sharding import batch_sharding
    from jax.sharding import NamedSharding, PartitionSpec as P
    with mesh:
        xs = jax.device_put(x, batch_sharding(mesh, 3))
        ps = jax.tree_util.tree_map(
            lambda p: jax.device_put(p, NamedSharding(mesh, P())), params)
        for k in ("wi", "wo"):
            ps[k] = jax.device_put(params[k],
                                   NamedSharding(mesh, P("expert")))
        got, _ = jax.jit(
            lambda p, x: layer.apply({"params": p}, x,
                                     mutable=["moe_aux"]))(ps, xs)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


def test_moe_lm_auto_selects_expert_axis(devices8):
    from tensorflow_distributed_tpu.models import build_model

    mesh = make_mesh(MeshConfig(data=4, expert=2), devices8)
    model = build_model("moe_lm", mesh=mesh, size="tiny",
                        compute_dtype=jnp.float32)
    assert model.cfg.moe_expert_axis == "expert"


def test_expert_parallel_matches_single(devices8):
    """EP over the model axis == unsharded, same params and tokens."""
    layer = _layer()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 8, 16)),
                    jnp.float32)
    params = layer.init(jax.random.key(4), x)["params"]
    want, _ = layer.apply({"params": params}, x, mutable=["moe_aux"])

    mesh = make_mesh(MeshConfig(data=2, model=4), devices8)
    from tensorflow_distributed_tpu.parallel.sharding import batch_sharding
    from jax.sharding import NamedSharding, PartitionSpec as P
    with mesh:
        xs = jax.device_put(x, batch_sharding(mesh, 3))
        ps = jax.tree_util.tree_map(
            lambda p: jax.device_put(p, NamedSharding(mesh, P())), params)
        # Shard expert weights over "model".
        for k in ("wi", "wo"):
            ps[k] = jax.device_put(params[k],
                                   NamedSharding(mesh, P("model")))
        got, _ = jax.jit(
            lambda p, x: layer.apply({"params": p}, x,
                                     mutable=["moe_aux"]))(ps, xs)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


def test_moe_aux_not_persisted_in_state(devices8):
    """Init-time sown moe_aux must not ride along in TrainState.extra
    (it would stack onto every step's fresh value, halving the aux
    gradient and biasing the metric)."""
    import optax

    from tensorflow_distributed_tpu.models import build_model
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh = make_mesh(MeshConfig(data=8), devices8)
    model = build_model("moe_lm", mesh=mesh, size="tiny",
                        compute_dtype=jnp.float32)
    state = create_train_state(model, optax.adam(1e-3),
                               np.zeros((2, 16), np.int32), mesh)
    assert "moe_aux" not in state.extra
    # And a fresh apply sows exactly one scalar per MoE layer.
    # (batch divisible by the data axis: the model pins activation
    # sharding P("data", "seq") when it holds a mesh.)
    _, mut = model.apply({"params": state.params},
                         jnp.zeros((8, 16), jnp.int32),
                         mutable=["moe_aux"])
    # Each MoE layer sows load_balance + z_loss + dropped_fraction.
    assert len(jax.tree_util.tree_leaves(mut["moe_aux"])) == \
        3 * model.cfg.n_layers


@pytest.mark.slow
def test_moe_lm_trains(devices8):
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(model="moe_lm", model_size="tiny",
                      dataset="synthetic", batch_size=64, train_steps=60,
                      eval_every=0, log_every=0, eval_batch_size=64,
                      compute_dtype="float32", learning_rate=3e-3,
                      mesh=MeshConfig(data=4, model=2))
    result = train(cfg)
    assert result.final_metrics["accuracy"] >= 0.4, result.final_metrics


def test_moe_scatter_dispatch_through_1f1b_pipeline(devices8):
    """Scatter dispatch INSIDE the pipe-manual shard_map: the 1F1B
    step with MoE blocks (router aux hand-seeded as vjp cotangents)
    must produce identical loss, aux, and updated params under either
    token-movement formulation — the scatter/gather ops partition the
    same way the one-hot einsums did."""
    import optax

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.lm import synthetic_clm
    from tensorflow_distributed_tpu.models.pipelined import pipelined_lm
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.pipeline_step import (
        make_1f1b_train_step)
    from tensorflow_distributed_tpu.train.state import create_train_state

    mesh = make_mesh(MeshConfig(data=4, pipe=2), devices8)
    outs = {}
    for disp in ("dense", "scatter"):
        model = pipelined_lm(mesh, num_microbatches=4, n_layers=4,
                             max_len=16, moe_experts=4,
                             moe_dispatch=disp, dropout_rate=0.0,
                             compute_dtype=jnp.float32)
        state = create_train_state(model, optax.sgd(1e-2),
                                   np.zeros((2, 16), np.int32), mesh, 0)
        step = make_1f1b_train_step(model, mesh, donate=False,
                                    moe_aux_weight=0.01,
                                    moe_zloss_weight=1e-3)
        ds = synthetic_clm(n=32, seq_len=16, vocab_size=64)
        b = shard_batch(mesh, ds.batch(np.arange(16)), seq_axis=1)
        s2, m = step(state, b)
        outs[disp] = (float(m["loss"]), float(m["aux_loss"]),
                      jax.device_get(s2.params))
    np.testing.assert_allclose(outs["dense"][0], outs["scatter"][0],
                               rtol=1e-6)
    np.testing.assert_allclose(outs["dense"][1], outs["scatter"][1],
                               rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        outs["dense"][2], outs["scatter"][2])
