"""EMA (Polyak) weight averaging: update math, eval preference,
checkpoint persistence, and the loop-level knob."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_distributed_tpu.models.cnn import MnistCNN
from tensorflow_distributed_tpu.parallel.sharding import shard_batch
from tensorflow_distributed_tpu.train.state import create_train_state
from tensorflow_distributed_tpu.train.step import (
    make_eval_step, make_train_step)


def _model():
    return MnistCNN(dropout_rate=0.0, compute_dtype=jnp.float32)


def _state(mesh, ema):
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    return create_train_state(_model(), optax.adam(1e-2), x, mesh,
                              seed=0, ema=ema)


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            rng.integers(0, 10, size=(n,)).astype(np.int32))


def test_ema_update_math_and_init(mesh8):
    state = _state(mesh8, ema=True)
    # EMA starts AS the init params.
    jax.tree_util.tree_map(
        lambda e, p: np.testing.assert_array_equal(
            np.asarray(e), np.asarray(p)), state.ema, state.params)

    decay = 0.9
    step = make_train_step(mesh8, donate=False, ema_decay=decay)
    p0 = jax.device_get(state.params)
    s1, _ = step(state, shard_batch(mesh8, _batch()))
    # Warmup debias: effective decay at step 0 is min(0.9, 1/10) = 0.1
    # — early EMA tracks the params instead of averaging in the init.
    d = min(decay, (1.0 + 0.0) / (10.0 + 0.0))
    jax.tree_util.tree_map(
        lambda e, p_old, p_new: np.testing.assert_allclose(
            np.asarray(e), d * np.asarray(p_old)
            + (1 - d) * np.asarray(p_new), rtol=1e-6, atol=1e-7),
        jax.device_get(s1.ema), p0, jax.device_get(s1.params))


def test_eval_prefers_ema(mesh8):
    state = _state(mesh8, ema=True)
    step = make_train_step(mesh8, donate=False, ema_decay=0.99)
    batch = shard_batch(mesh8, _batch())
    for i in range(5):
        state, _ = step(state, shard_batch(mesh8, _batch(seed=i)))

    ev = make_eval_step(mesh8)
    with_ema = jax.device_get(ev(state, batch))
    # Oracle: a state whose RAW params are the ema tree.
    raw = state.replace(params=state.ema, ema=None)
    oracle = jax.device_get(make_eval_step(mesh8)(raw, batch))
    np.testing.assert_allclose(with_ema["loss"], oracle["loss"],
                               rtol=1e-6)
    # ...and differs from evaluating the raw params (they moved away).
    no_ema = jax.device_get(ev(state.replace(ema=None), batch))
    assert abs(float(no_ema["loss"]) - float(with_ema["loss"])) > 1e-4


def test_ema_checkpoints_and_loop(tmp_path, mesh8):
    from tensorflow_distributed_tpu.config import MeshConfig, TrainConfig
    from tensorflow_distributed_tpu.train import checkpoint as ckpt
    from tensorflow_distributed_tpu.train.loop import train

    cfg = TrainConfig(dataset="synthetic", batch_size=64, train_steps=8,
                      eval_every=8, log_every=0, eval_batch_size=64,
                      compute_dtype="float32", ema_decay=0.9,
                      checkpoint_dir=str(tmp_path),
                      mesh=MeshConfig(data=8))
    r = train(cfg)
    assert r.state.ema is not None
    assert np.isfinite(r.final_metrics["loss"])

    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.optim import make_optimizer

    template = create_train_state(
        _model(), make_optimizer(cfg),
        jnp.zeros((2, 28, 28, 1), jnp.float32), make_mesh(cfg.mesh),
        seed=0, ema=True)
    restored = ckpt.restore(str(tmp_path), template)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), r.state.ema, restored.ema)

    # Toggling EMA across save/restore must not brick the restore:
    # disabling drops the average; enabling seeds it from the params.
    no_ema_tmpl = create_train_state(
        _model(), make_optimizer(cfg),
        jnp.zeros((2, 28, 28, 1), jnp.float32), make_mesh(cfg.mesh),
        seed=0, ema=False)
    no_ema = ckpt.restore(str(tmp_path), no_ema_tmpl)
    assert no_ema.ema is None

    plain_dir = str(tmp_path / "plain")
    ckpt.save(plain_dir, no_ema)
    enabled = ckpt.restore(plain_dir, template)
    jax.tree_util.tree_map(
        lambda e, p: np.testing.assert_array_equal(
            np.asarray(e), np.asarray(p)), enabled.ema, enabled.params)
