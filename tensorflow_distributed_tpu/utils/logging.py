"""Structured per-step logging + the reference `performance` table format.

The reference's observability was bare ``print()`` (timestamps + steps at
mnist_python_m.py:297-299, loss every 10 steps at mnist_single.py:113-116,
including one malformed print at mnist_python_m.py:316) and a
hand-maintained 6-line ``performance`` file.

``MetricLogger`` is now a thin COMPATIBILITY SHIM over the observe/
subsystem (observe.registry owns formatting and sink dispatch; this
class keeps the historical ``log``/``log_json``/``performance_table``
surface and the in-memory ``records`` list the table renders from).
New code should use :class:`observe.registry.MetricsRegistry` (or the
train loop's :class:`observe.hub.Observatory`) directly. The records
list is a bounded ring buffer (``max_records``) so multi-million-step
runs don't grow host memory unboundedly.
"""

from __future__ import annotations

import collections
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, TextIO

from tensorflow_distributed_tpu.observe.registry import (
    MetricsRegistry, StdoutSink)


@dataclass
class StepRecord:
    step: int
    wall_time: float
    metrics: Dict[str, float]


class MetricLogger:
    """Collects per-step metrics; one process (the chief) prints them.

    Compatibility shim: emission flows through a MetricsRegistry with a
    StdoutSink (observe.registry). ``records`` keeps the StepRecord
    view ``performance_table`` and callers expect, capped at
    ``max_records`` (ring buffer — oldest rows drop first).
    """

    def __init__(self, enabled: bool = True,
                 stream: TextIO = sys.stdout,
                 max_records: int = 100_000,
                 registry: Optional[MetricsRegistry] = None):
        self.enabled = enabled
        self.stream = stream
        self.records: collections.deque = collections.deque(
            maxlen=max_records)
        # The shim keeps its own StepRecord buffer (performance_table's
        # input); the internal registry is emission-only, so its ring
        # buffer stays at 1 — no double-buffering of every record.
        self._registry = registry or MetricsRegistry(
            [StdoutSink(stream)], enabled=enabled, max_records=1)
        self._t0 = time.time()

    def log(self, step: int, **metrics: float) -> None:
        rec = StepRecord(step=step, wall_time=time.time() - self._t0,
                         metrics={k: float(v) for k, v in metrics.items()})
        self.records.append(rec)
        self._registry.emit("step", step=step, t=rec.wall_time,
                            **rec.metrics)

    def log_json(self, payload: Dict[str, Any]) -> None:
        if self.enabled:
            event = payload.get("event", "log")
            fields = {k: v for k, v in payload.items() if k != "event"}
            self._registry.emit(event, **fields)

    def performance_table(self, learning_rate: float) -> str:
        """Render EVAL records (val_accuracy rows only — per-step training
        accuracies don't belong in it) in the reference's `performance`
        file format: ``Steps, Time, Accuracy, Learning rate``
        (performance:1-6)."""
        lines = ["Steps,        Time,      Accuracy,  Learning rate"]
        for rec in self.records:
            if "val_accuracy" not in rec.metrics:
                continue
            lines.append(
                f"{rec.step},        {rec.wall_time:.0f} seconds,  "
                f"{100.0 * rec.metrics['val_accuracy']:.2f},      {learning_rate}")
        return "\n".join(lines)


@dataclass
class Timer:
    """Wall-clock span timer, mirroring the reference's train/infer timing
    prints (mnist_single.py:102,119-120,133-134)."""

    _start: Optional[float] = None
    elapsed: float = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.time()
        return self

    def __exit__(self, *exc) -> None:
        if self._start is None:
            # __exit__ without __enter__ (manually driven context):
            # keep elapsed at 0.0 instead of TypeError-ing on None.
            return
        self.elapsed = time.time() - self._start
