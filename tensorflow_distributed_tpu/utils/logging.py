"""Structured per-step logging + the reference `performance` table format.

The reference's observability was bare ``print()`` (timestamps + steps at
mnist_python_m.py:297-299, loss every 10 steps at mnist_single.py:113-116,
including one malformed print at mnist_python_m.py:316) and a
hand-maintained 6-line ``performance`` file. This module logs structured
rows and can regenerate that exact table automatically.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, TextIO


@dataclass
class StepRecord:
    step: int
    wall_time: float
    metrics: Dict[str, float]


@dataclass
class MetricLogger:
    """Collects per-step metrics; one process (the chief) prints them."""

    enabled: bool = True
    stream: TextIO = sys.stdout
    records: List[StepRecord] = field(default_factory=list)
    _t0: float = field(default_factory=time.time)

    def log(self, step: int, **metrics: float) -> None:
        rec = StepRecord(step=step, wall_time=time.time() - self._t0,
                         metrics={k: float(v) for k, v in metrics.items()})
        self.records.append(rec)
        if self.enabled:
            parts = " ".join(f"{k}={v:.6g}" for k, v in rec.metrics.items())
            print(f"[step {step:>6}] t={rec.wall_time:8.2f}s {parts}",
                  file=self.stream, flush=True)

    def log_json(self, payload: Dict[str, Any]) -> None:
        if self.enabled:
            print(json.dumps(payload), file=self.stream, flush=True)

    def performance_table(self, learning_rate: float) -> str:
        """Render EVAL records (val_accuracy rows only — per-step training
        accuracies don't belong in it) in the reference's `performance`
        file format: ``Steps, Time, Accuracy, Learning rate``
        (performance:1-6)."""
        lines = ["Steps,        Time,      Accuracy,  Learning rate"]
        for rec in self.records:
            if "val_accuracy" not in rec.metrics:
                continue
            lines.append(
                f"{rec.step},        {rec.wall_time:.0f} seconds,  "
                f"{100.0 * rec.metrics['val_accuracy']:.2f},      {learning_rate}")
        return "\n".join(lines)


@dataclass
class Timer:
    """Wall-clock span timer, mirroring the reference's train/infer timing
    prints (mnist_single.py:102,119-120,133-134)."""

    _start: Optional[float] = None
    elapsed: float = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.time()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.time() - self._start
