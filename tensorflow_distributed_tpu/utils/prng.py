"""PRNG key discipline.

The reference relied on process-local ``tf.random_normal`` ops with no
seed control (mnist_python_m.py:185-196) — every run and every worker got
different init, and only the ps's copy mattered. Here a single root seed
derives every stream deterministically, so N-device and 1-device runs are
bit-comparable (the basis of the sync-parity tests, SURVEY.md §7).
"""

from __future__ import annotations

import jax


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def init_key(seed: int) -> jax.Array:
    """Key for parameter init — shared across all processes so every host
    materializes identical params (replaces the chief-initializes-ps
    variables dance, mnist_python_m.py:272-275)."""
    return jax.random.fold_in(root_key(seed), 0)


def step_key(seed: int, step) -> jax.Array:
    """Per-step key (dropout etc.), derived inside the jitted step from
    the step counter so it needs no host round-trip."""
    return jax.random.fold_in(jax.random.fold_in(root_key(seed), 1), step)