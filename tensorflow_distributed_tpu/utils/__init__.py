"""Shared utilities: PRNG discipline, structured logging, timing."""
