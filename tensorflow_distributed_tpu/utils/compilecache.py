"""Persistent XLA compilation cache.

The reference pays graph (re)construction + session setup on every
process start (mnist_python_m.py:177-275) with nothing cached. Here
every jitted step is an XLA compile — ~20-40s cold on TPU — so the
framework enables JAX's persistent compile cache by default: repeat
runs (tests, bench, CLI restarts, resume-after-crash) hit the disk
cache instead of recompiling.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_DIR = os.environ.get(
    "TFD_TPU_COMPILE_CACHE", os.path.join(_REPO_ROOT, ".cache", "xla"))


def enable_persistent_cache(path: str | None = None) -> str:
    """Idempotently turn on the JAX persistent compilation cache.

    Precedence: an explicit ``path`` argument wins; otherwise a
    user-set ``jax_compilation_cache_dir`` (via jax.config or the
    ``JAX_COMPILATION_CACHE_DIR`` env var) is RESPECTED rather than
    silently overridden; only with neither does the repo-local default
    apply. Returns the effective cache directory either way."""
    import jax

    if path is None:
        path = (getattr(jax.config, "jax_compilation_cache_dir", None)
                or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                or _DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
