"""Tracing/profiling subsystem.

The reference had none — its only observability was `time.time()`
deltas around the train and validation loops (SURVEY.md §5 "tracing:
none"; mnist_python_m.py:285-307, mnist_single.py:102,119-134). Here
profiling is a first-class switch: a step-windowed `jax.profiler`
trace (XPlane/TensorBoard format, viewable in Perfetto) captures the
XLA execution timeline — per-op device time, HBM traffic, and the ICI
collectives that replaced the reference's gRPC ps round-trip.

Captures also write the Perfetto JSON export
(``create_perfetto_trace``) beside the XPlane, which is what
``observe/xprof.py`` PARSES to attribute device wall time back to the
instrumented programs — the capture is no longer write-only.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator, Optional

import jax


def _start_trace(log_dir: str, perfetto: bool) -> None:
    """start_trace with the Perfetto JSON export when this jax
    supports the kwarg (older versions write XPlane only — xprof then
    degrades to its explicit-null records)."""
    if perfetto:
        try:
            jax.profiler.start_trace(log_dir,
                                     create_perfetto_trace=True)
            return
        except TypeError:
            pass
    jax.profiler.start_trace(log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span that shows up on the host timeline of a trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


@dataclasses.dataclass
class StepProfiler:
    """Trace a window of steps: [start_step, start_step + num_steps).

    Inactive (zero overhead) when ``log_dir`` is empty. Only the chief
    process traces — one XPlane per job, like one `performance` table
    per job in the reference.
    """

    log_dir: str = ""
    start_step: int = 10
    num_steps: int = 5
    # Also write the Perfetto JSON export observe/xprof.py parses for
    # device-time attribution (XPlane alone is write-only here).
    perfetto: bool = True
    # True once a window actually started — the loop's device-time
    # emission keys on it (a run whose horizon never reached the
    # window has nothing to parse).
    captured: bool = dataclasses.field(default=False, init=False)
    _running: bool = dataclasses.field(default=False, init=False)

    def observe(self, step: int, pending=None) -> None:
        """Call once per step with the just-issued step number.

        ``pending``: device values the last traced step produced (e.g.
        the metrics dict). The training loop dispatches steps
        asynchronously, so without draining them before stop_trace the
        XPlane would be missing the tail of the traced window.
        """
        if not self.log_dir:
            return
        in_window = (self.start_step <= step
                     < self.start_step + self.num_steps)
        if not self._running and in_window:
            # Window test, not equality: a resumed run whose first step
            # is already past start_step still gets (the tail of) a trace.
            _start_trace(self.log_dir, self.perfetto)
            self._running = True
            self.captured = True
        elif self._running and step >= self.start_step + self.num_steps:
            self.stop(pending)

    def stop(self, pending=None) -> None:
        """Finalize an open trace window. Safe to call when no window is
        open; the drain is try/finally-wrapped so a failing device_get
        (e.g. the very exception that ended training) still closes the
        trace instead of leaving it running into interpreter exit."""
        if self._running:
            try:
                if pending is not None:
                    jax.device_get(pending)  # drain in-flight traced steps
            finally:
                jax.profiler.stop_trace()
                self._running = False


@contextlib.contextmanager
def trace(log_dir: Optional[str], perfetto: bool = True
          ) -> Iterator[None]:
    """Whole-span trace: ``with trace('/tmp/tb'): run()``."""
    if not log_dir:
        yield
        return
    _start_trace(log_dir, perfetto)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
