"""Conditional shims for jax API skew (robustness to container drift).

This repo targets the modern jax surface — ``jax.shard_map`` with
``check_vma=``/``axis_names=`` and ``jax.sharding.get_abstract_mesh``.
Container images drift: the currently-baked jax (0.4.x) predates all
three, which took out 50+ tier-1 tests in one environment rotation.
Rather than fork every call site, :func:`install` (run once from the
package ``__init__``) fills the gaps IN TERMS OF the old API, and is a
strict no-op wherever the real attribute already exists — on a current
jax nothing here executes.

Mappings (new -> old):
- ``jax.shard_map(f, mesh, in_specs, out_specs, check_vma=, axis_names=)``
  -> ``jax.experimental.shard_map.shard_map(..., check_rep=check_vma,
  auto=mesh_axes - axis_names)`` (``axis_names`` lists the axes the
  shard_map manualizes; the old ``auto`` lists the ones it does NOT).
- ``jax.sharding.get_abstract_mesh()`` -> a static empty-context
  object (``manual_axes == frozenset()``): old jax has no queryable
  manual-axes context, so callers behave as if never nested inside an
  enclosing shard_map. The nested compositions (flash/ring inside the
  pipelined family's pipe-manual region) are genuinely inexpressible
  on the old API and stay broken there — but every non-nested caller
  (the overwhelming majority) works.
"""

from __future__ import annotations

import jax


class _EmptyAbstractMesh:
    """Stand-in for the no-enclosing-shard_map context on old jax."""

    manual_axes: frozenset = frozenset()
    axis_names: tuple = ()

    def __bool__(self) -> bool:
        return False


def _compat_shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=True, axis_names=None, **kw):
    from jax.experimental.shard_map import shard_map as _sm

    auto = kw.pop("auto", frozenset())
    if axis_names is not None:
        auto = frozenset(getattr(mesh, "axis_names", ())) - frozenset(
            axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, auto=auto, **kw)


def install() -> None:
    """Idempotent; every patch is gated on the attribute being absent."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _compat_shard_map
    # jax.sharding uses a deprecation __getattr__ that RAISES for
    # unknown names, so hasattr is the correct probe here too.
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        ctx = _EmptyAbstractMesh()
        jax.sharding.get_abstract_mesh = lambda: ctx
