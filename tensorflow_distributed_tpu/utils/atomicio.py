"""Blessed writers for cross-process files — tmp + fsync + rename.

Several processes coordinate through files in this codebase: the
serve scheduler's `--observe.export-path` snapshot polled by
`fleet/router.py`, the fleet control-plane feed, replica inboxes and
request journals, Perfetto trace files, the resilience device-mask,
checkpoint manifests, calibration profiles, supervisor journals. A
raw ``open(path, "w")`` on any of those is a torn-read bug waiting
for a poller (or a post-SIGKILL supervisor) to hit it.

This module is the ONE place the tmp+fsync+rename idiom lives:

* :func:`atomic_write_json` / :func:`atomic_write_jsonl` — replace
  the whole file atomically. The reader always sees a complete
  payload, never a torn write; the fsync before the rename means a
  crash cannot leave an EMPTY renamed file either.
* :func:`durable_append` — one JSON line, flushed to the OS. Append
  streams (journals, inboxes, supervisor event logs) get process-kill
  durability; fsync-per-line is deliberately NOT done — it would only
  add OS-crash coverage these streams do not promise, at a latency
  cost on the serving hot path (see serve/journal.py).

``analysis/rules/durability.py`` enforces the split: a direct write
to a declared path family outside this module is a lint finding
(`raw-write-to-shared-path`), and an ``os.replace``/``os.rename``
onto one without an fsync in the same function is
`missing-fsync-on-durable-path`. Intentionally-raw writes carry a
``# graftcheck: disable=raw-write-to-shared-path -- <reason>``.

Pure stdlib — the supervisor and the lint tier import this without
jax.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable, Optional, Tuple

__all__ = ["PATH_FAMILIES", "atomic_write_json", "atomic_write_jsonl",
           "durable_append"]

#: Declared cross-process path families: (family, file_re, expr_re).
#: ``file_re`` scopes a family to one module ("" = any); ``expr_re``
#: matches the path EXPRESSION at the write site (source text, after
#: resolving one local assignment hop). The durability lint flags raw
#: writes whose path expression matches a family for its file.
PATH_FAMILIES: Tuple[Tuple[str, str, str], ...] = (
    ("export-path", "", r"export_path"),
    ("fleet-snapshot", "", r"snapshot_path"),
    ("inbox", "", r"inbox"),
    ("journal", "", r"journal_path"),
    ("metrics-jsonl", "", r"metrics_jsonl|jsonl_path"),
    ("trace-file", r"observe/trace\.py$", r"self\.path"),
    ("trace-file", r"observe/fleet_trace\.py$", r"out_path"),
    ("trace-file", "", r"trace_path"),
    ("device-mask", "", r"device_mask|mask_file|mask_path"),
    ("ckpt-manifest", "", r"manifest"),
    ("flight-bundle", "", r"bundle_path"),
    ("calibration-profile", r"analysis/planner/calibrate\.py$",
     r"\bpath\b"),
)


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of ``path``'s directory so the rename itself
    survives an OS crash (not just the file contents)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_json(path: str, obj: Any, *, indent: Optional[int] = None,
                      trailing_newline: bool = False,
                      default: Any = None) -> str:
    """Atomically replace ``path`` with ``obj`` as JSON.

    tmp file is ``<path>.tmp.<pid>`` (pid-suffixed so two writers
    racing on the same target never tear each other's staging file);
    contents are fsync'd before the rename. Returns ``path``.
    """
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent, default=default)
        if trailing_newline:
            f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def atomic_write_jsonl(path: str, records: Iterable[Any], *,
                       default: Any = None) -> str:
    """Atomically replace ``path`` with one JSON object per line."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, default=default) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def durable_append(path: str, record: Any) -> None:
    """Append one JSON line, flushed to the OS (single writer per
    file; readers tolerate a torn tail). Process-kill durable; NOT
    fsync'd — see the module docstring for why."""
    # The blessed appender IS the allowed raw-write site.
    # graftcheck: disable=raw-write-to-shared-path -- this helper is the blessed appender
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
