"""Deterministic fault injection: every recovery path, exercisable.

A fault plan is a comma-separated list of ``kind@step[:arg]`` events,
e.g. ``"nan_grad@40,ckpt_io_fail@80,data_stall@120:5s,sigterm@200"``.
The train loop binds one plan per run and consults it at the exact
points real faults strike:

- ``nan_grad@K`` — NaN-poison the float leaves of step K's HOST batch
  before it is sharded to devices. The loss and gradients of that step
  are then genuinely non-finite through the real math (not a spoofed
  metric), so the skip/rewind policies are tested against what an
  actual divergence produces.
- ``ckpt_io_fail@K[:N]`` — arm N (default 1) injected ``OSError``
  failures in the checkpoint writer the next time the cadence save at
  step K runs (train/checkpoint.py consumes them inside its retry
  loop, so a plan with N <= save_retries proves save-retry recovery).
- ``data_stall@K[:Ds]`` — sleep D seconds (default 5) inside the
  batch fetch for step K, on the consumer side of the prefetcher, so
  the data watchdog sees exactly the hang it guards against.
- ``sigterm@K`` / ``sigkill@K`` — self-signal when step K is
  dispatched: the graceful preemption notice, or the hard kill a
  supervisor must restart from. Signal events fire on the FIRST leg
  only (``bind(start_step=0)``): a resumed leg IS the recovery under
  test, and re-firing would kill a supervised run forever.
- ``device_loss@K[:N]`` — lose N devices (default 1) at step K: the
  drill writes the lost count to the device-mask file (under the
  checkpoint dir) and hard-kills the process — a chip preemption,
  which never says goodbye. An elastic supervisor
  (``supervisor --elastic``) reads the mask, picks the best mesh
  that fits the surviving devices, and restarts onto it (the restart
  masks the "dead" chips via ``TFD_DEVICE_MASK`` —
  parallel.mesh.alive_devices; real losses need no mask, the chips
  are simply gone from ``jax.devices()``). First-leg-only like the
  signals.

Under ``--mode serve`` the step key counts DECODE steps (the serving
engine's clock — serve/scheduler.py consults the plan between steps),
and three serve-phase kinds exist alongside ``sigterm``/``sigkill``:

- ``decode_stall@K[:Ds]`` — sleep D seconds (default 1) inside decode
  step K's device sync, exactly where a wedged device manifests; the
  decode watchdog (``--resilience.sync-timeout-s``) sees the hang and
  raises StallError instead of letting the engine freeze.
- ``slot_nan@K[:slot]`` — NaN-poison one slot's KV-cache row (default
  slot 0) before decode step K, so that slot's logits are genuinely
  non-finite through the real attention math; the engine's on-device
  per-slot finiteness check flags it and the scheduler quarantines +
  re-prefills ONLY that slot.
- ``reload@K`` — force a live weight swap before decode step K: params
  reload from the newest verifiable checkpoint between decode steps,
  without draining slots or recompiling.

Every injection emits an ``event="recovery", kind="fault_injected"``
record through the observe registry. Events are one-shot per plan
object, so an in-process rewind past an injected NaN does not re-poison
the replayed step.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensorflow_distributed_tpu.observe.registry import emit_event
from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json

KINDS = ("nan_grad", "ckpt_io_fail", "data_stall", "sigterm", "sigkill",
         "device_loss", "decode_stall", "slot_nan", "reload")
# Phase validity (config.validate rejects cross-phase plans at startup
# so a train-only fault never sits silently unfired in a serve run):
# signals fire in both phases, keyed on the phase's own step clock.
TRAIN_KINDS = ("nan_grad", "ckpt_io_fail", "data_stall", "sigterm",
               "sigkill", "device_loss")
SERVE_KINDS = ("decode_stall", "slot_nan", "reload", "sigterm",
               "sigkill")

# Where a device_loss drill records the masked-chip count for the
# supervisor's next leg (under the run's checkpoint dir — the one
# path both processes share; TFD_DEVICE_MASK_FILE overrides for
# tests/drills without a checkpoint dir).
DEVICE_MASK_FILENAME = "DEVICE_MASK"


def device_mask_path(ckpt_dir: str) -> str:
    return os.environ.get("TFD_DEVICE_MASK_FILE") or os.path.join(
        ckpt_dir, DEVICE_MASK_FILENAME)

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<step>\d+)(?::(?P<arg>[0-9.]+s?))?$")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    kind: str
    step: int
    arg: Optional[float] = None  # seconds for data_stall, count for
    #                              ckpt_io_fail/device_loss, slot for
    #                              slot_nan; None elsewhere


def parse_fault_plan(spec: str) -> "FaultPlan":
    """Parse ``kind@step[:arg]`` comma lists; raises ValueError with
    the offending token on any syntax problem (config.validate calls
    this, so a bad plan dies at startup, not at step K)."""
    events: List[FaultEvent] = []
    for token in filter(None, (t.strip() for t in spec.split(","))):
        m = _EVENT_RE.match(token)
        if not m:
            raise ValueError(
                f"bad fault-plan token {token!r}: want kind@step[:arg] "
                f"(e.g. nan_grad@40, data_stall@120:5s)")
        kind, step = m.group("kind"), int(m.group("step"))
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {token!r}; have "
                f"{KINDS}")
        if step < 1:
            raise ValueError(f"fault step must be >= 1 in {token!r}")
        arg_s = m.group("arg")
        arg: Optional[float] = None
        if arg_s is not None:
            if kind in ("data_stall", "decode_stall"):
                arg = float(arg_s[:-1] if arg_s.endswith("s") else arg_s)
                if arg <= 0:
                    raise ValueError(
                        f"{kind} duration must be > 0 in {token!r}")
            elif kind == "slot_nan":
                arg = float(arg_s)
                if arg != int(arg) or arg < 0:
                    raise ValueError(
                        f"slot_nan slot must be a non-negative int "
                        f"in {token!r}")
            elif kind in ("ckpt_io_fail", "device_loss"):
                arg = float(arg_s)
                if arg != int(arg) or arg < 1:
                    raise ValueError(
                        f"{kind} count must be a positive int "
                        f"in {token!r}")
            else:
                raise ValueError(
                    f"fault kind {kind!r} takes no :arg ({token!r})")
        events.append(FaultEvent(kind, step, arg))
    return FaultPlan(events)


class FaultPlan:
    """One run's bound fault schedule. Falsy when empty, so the loop
    can skip every hook at zero cost for production configs."""

    def __init__(self, events: List[FaultEvent] = ()):  # type: ignore[assignment]
        self._by_step: Dict[Tuple[str, int], FaultEvent] = {
            (e.kind, e.step): e for e in events}
        self._fired: set = set()
        self._start_step = 0

    def __bool__(self) -> bool:
        return bool(self._by_step)

    def kinds(self) -> set:
        """Distinct fault kinds in the plan (config.validate's phase
        check: a kind the run's mode never consults is rejected at
        startup, not silently unfired)."""
        return {kind for (kind, _step) in self._by_step}

    def bind(self, start_step: int) -> None:
        """Pin the leg's resume point: events at or before it are
        consumed (already happened on a previous leg), and signal
        events are suppressed entirely on a resumed leg — the restart
        being tested must terminate."""
        self._start_step = start_step
        for key, ev in self._by_step.items():
            if ev.step <= start_step:
                self._fired.add(key)

    def _take(self, kind: str, step: int) -> Optional[FaultEvent]:
        key = (kind, step)
        ev = self._by_step.get(key)
        if ev is None or key in self._fired:
            return None
        self._fired.add(key)
        return ev

    # -- injection points (the loop calls these; all no-op off-plan) ------
    def wrap_stream(self, stream, start_step: int):
        """Apply batch-level faults (nan_grad poisoning) to a task
        stream, aligned to absolute step ids: the k-th yielded batch
        feeds training step ``start_step + k``. Wrapping happens
        BEFORE prefetch/sharding so the poison flows through the real
        host->device path. Returns the stream unchanged for an empty
        plan."""
        if not self:
            return stream

        def gen():
            step = start_step
            for batch in stream:
                step += 1
                yield self.poison_batch(step, batch)

        return gen()

    def poison_batch(self, step: int, batch: Any) -> Any:
        """NaN-fill the float leaves of step ``step``'s host batch.
        Called on the raw task stream BEFORE sharding/prefetch, so the
        NaNs flow through the genuine device math."""
        if self._take("nan_grad", step) is None:
            return batch
        poisoned = [0]

        def one(x):
            if (isinstance(x, np.ndarray)
                    and np.issubdtype(x.dtype, np.floating)):
                poisoned[0] += 1
                return np.full_like(x, np.nan)
            return x

        import jax

        out = jax.tree_util.tree_map(one, batch)
        if not poisoned[0]:
            raise ValueError(
                f"fault nan_grad@{step}: batch has no float leaves to "
                f"poison (integer token streams can't produce a NaN "
                f"loss this way — use a float-input task)")
        emit_event("recovery", kind="fault_injected", fault="nan_grad",
                   step=step)
        return out

    def maybe_stall(self, step: int) -> None:
        """Sleep the injected stall inside the batch-fetch path (the
        watchdog wraps this call, so the timeout sees it)."""
        ev = self._take("data_stall", step)
        if ev is not None:
            emit_event("recovery", kind="fault_injected",
                       fault="data_stall", step=step,
                       seconds=ev.arg or 5.0)
            time.sleep(ev.arg if ev.arg is not None else 5.0)

    def arm_checkpoint_faults(self, step: int) -> None:
        """Arm N injected write failures in train.checkpoint just
        before the cadence save at ``step`` runs."""
        ev = self._take("ckpt_io_fail", step)
        if ev is not None:
            from tensorflow_distributed_tpu.train import checkpoint
            n = int(ev.arg) if ev.arg is not None else 1
            emit_event("recovery", kind="fault_injected",
                       fault="ckpt_io_fail", step=step, failures=n)
            checkpoint.arm_io_fault(n)

    def maybe_signal(self, step: int) -> None:
        """Self-SIGTERM/SIGKILL at dispatch of ``step`` — first leg
        only (see bind)."""
        if self._start_step > 0:
            return
        for kind, signum in (("sigterm", signal.SIGTERM),
                             ("sigkill", signal.SIGKILL)):
            if self._take(kind, step) is not None:
                emit_event("recovery", kind="fault_injected",
                           fault=kind, step=step)
                os.kill(os.getpid(), signum)

    def maybe_device_loss(self, step: int, ckpt_dir: str) -> None:
        """The chip-preemption drill at dispatch of ``step``: write the
        lost-device count to the mask file (flushed durable — the next
        line is a SIGKILL) and die without notice. First leg only,
        like the signals: the restarted-and-resized leg is the
        recovery under test."""
        if self._start_step > 0:
            return
        ev = self._take("device_loss", step)
        if ev is None:
            return
        lost = int(ev.arg) if ev.arg is not None else 1
        path = device_mask_path(ckpt_dir)
        emit_event("recovery", kind="fault_injected",
                   fault="device_loss", step=step, lost=lost,
                   mask_file=path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # fsync'd BEFORE the rename and the rename before the kill:
        # the supervisor that inherits this mask must never read a
        # torn or empty file.
        atomic_write_json(path, {"lost": lost, "step": step})
        os.kill(os.getpid(), signal.SIGKILL)

    # -- serve-phase injection points (step = the engine's decode step;
    #    serve/scheduler.py consults these between steps, the engine
    #    consumes decode_stall inside its watched device sync) ----------
    def decode_stall_sleep(self, step: int) -> None:
        """Sleep the injected stall inside the decode step's device
        sync (the engine runs this under the decode watchdog, so the
        deadline sees exactly the hang it guards against)."""
        ev = self._take("decode_stall", step)
        if ev is not None:
            emit_event("recovery", kind="fault_injected",
                       fault="decode_stall", step=step,
                       seconds=ev.arg or 1.0)
            time.sleep(ev.arg if ev.arg is not None else 1.0)

    def take_slot_nan(self, step: int) -> Optional[int]:
        """The slot to NaN-poison before decode step ``step`` (None
        off-plan). The engine poisons that slot's KV row on device, so
        the non-finite logits flow through the real attention math."""
        ev = self._take("slot_nan", step)
        if ev is None:
            return None
        slot = int(ev.arg) if ev.arg is not None else 0
        emit_event("recovery", kind="fault_injected", fault="slot_nan",
                   step=step, slot=slot)
        return slot

    def take_reload(self, step: int) -> bool:
        """True when a live weight swap (checkpoint reload under
        traffic) is due before decode step ``step``."""
        ev = self._take("reload", step)
        if ev is not None:
            emit_event("recovery", kind="fault_injected",
                       fault="reload", step=step)
            return True
        return False
