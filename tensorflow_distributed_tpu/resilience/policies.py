"""Bad-step policies: what to do when the loss goes wrong.

The loop's original behavior was binary — train on garbage, or
(``halt_on_nonfinite``) raise at the next log cadence. This module is
the configurable middle ground, checked per retired step on metrics
the loop has already paid to synchronize:

- ``halt`` — flush queued saves so the named resume point is the true
  latest, then raise.
- ``skip_batch`` — the jitted step already discarded that batch's
  update on device (train/step.py ``skip_nonfinite``): params,
  optimizer state, and EMA kept their pre-step values, only the step
  counter advanced. The host side here just charges the bounded skip
  budget and halts when it is exhausted — unbounded skipping would
  loop a truly-diverged run forever.
- ``rewind`` — the loop restores the newest verifiable checkpoint
  in-process and re-enters from there (bounded by ``max_rewinds``).
  Unlike skip, this also helps when the damage predates detection
  (loss spikes, silent corruption surfaced late).

Loss-SPIKE detection (:class:`LossSpikeDetector`) flags a finite loss
greater than ``factor`` x the rolling-window median. A spike differs
from a NaN in one crucial way: by the time the host sees it, the
update has already applied and cannot be skipped — so under the
``rewind`` policy a spike triggers a budgeted rewind, and under any
other policy it is emitted as a recovery event only.
"""

from __future__ import annotations

from typing import Optional

from tensorflow_distributed_tpu.observe.anomaly import RollingMedianSpike
from tensorflow_distributed_tpu.observe.registry import emit_event


class RecoveryBudgetExceeded(FloatingPointError):
    """A bounded recovery policy ran out of budget — the run halts
    with the full recovery history in the message."""


class NonFinitePolicy:
    """Budgeted per-step dispositions for non-finite losses (and, under
    ``rewind``, loss spikes). Returns one of ``"halt" | "skip" |
    "rewind"`` from :meth:`on_nonfinite`; the loop executes it."""

    def __init__(self, mode: str, max_skips: int = 3,
                 max_rewinds: int = 1):
        assert mode in ("halt", "skip_batch", "rewind"), mode
        self.mode = mode
        self.max_skips = max_skips
        self.max_rewinds = max_rewinds
        self.skips_used = 0
        self.rewinds_used = 0

    def on_nonfinite(self, step: int, loss: float) -> str:
        if self.mode == "halt":
            emit_event("recovery", kind="nonfinite", step=step,
                       loss=str(loss), action="halt")
            return "halt"
        if self.mode == "skip_batch":
            # Counters track EXECUTED recoveries; the attempt that
            # finds the budget empty halts without incrementing, so
            # the halt message reads "N/N", not "N+1/N".
            if self.skips_used >= self.max_skips:
                emit_event("recovery", kind="nonfinite", step=step,
                           loss=str(loss), action="halt",
                           reason="skip budget exhausted",
                           used=self.skips_used,
                           budget=self.max_skips)
                return "halt"
            self.skips_used += 1
            emit_event("recovery", kind="nonfinite", step=step,
                       loss=str(loss), action="skip",
                       used=self.skips_used, budget=self.max_skips)
            return "skip"
        return self._charge_rewind(step, loss=str(loss),
                                   trigger="nonfinite")

    def on_spike(self, step: int, loss: float,
                 median: float) -> Optional[str]:
        """A finite spike: rewind when that's the policy (the update
        already applied — skip can't help); otherwise event-only."""
        emit_event("recovery", kind="loss_spike", step=step,
                   loss=round(loss, 6), window_median=round(median, 6))
        if self.mode != "rewind":
            return None
        return self._charge_rewind(step, loss=round(loss, 6),
                                   trigger="loss_spike")

    def _charge_rewind(self, step: int, **fields) -> str:
        if self.rewinds_used >= self.max_rewinds:
            emit_event("recovery", kind="nonfinite", step=step,
                       action="halt", reason="rewind budget exhausted",
                       used=self.rewinds_used,
                       budget=self.max_rewinds, **fields)
            return "halt"
        self.rewinds_used += 1
        emit_event("recovery", kind="nonfinite", step=step,
                   action="rewind", used=self.rewinds_used,
                   budget=self.max_rewinds, **fields)
        return "rewind"

    def halt_message(self, step: int, loss: float,
                     last_checkpoint) -> str:
        return (
            f"non-finite loss {loss} at step {step} "
            f"(resilience.nonfinite={self.mode}; skips used "
            f"{self.skips_used}/{self.max_skips}, rewinds used "
            f"{self.rewinds_used}/{self.max_rewinds}); last durable "
            f"checkpoint: {last_checkpoint}")


class LossSpikeDetector(RollingMedianSpike):
    """Rolling-window divergence detector for FINITE losses — the
    loop-facing name for :class:`observe.anomaly.RollingMedianSpike`
    (ONE median-spike implementation in the repo; the anomaly hub's
    advisory loss-spike detector is the same class, so the acting
    policy and the incident telemetry cannot drift apart).

    ``observe(loss)`` returns the window median when ``loss >
    factor * median`` over a full window, else None. The spiking value
    is NOT added to the window (one outlier must not drag the baseline
    toward itself), but training-regime shifts still track because
    every non-spike value is; ``reset()`` clears the window after a
    rewind (the replayed steps re-approach the spike region
    legitimately — a stale window would re-flag them)."""
