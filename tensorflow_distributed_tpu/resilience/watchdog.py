"""Data/device watchdog: silent hangs become diagnosable errors.

The two places a training run can hang forever with no traceback are
the next-batch fetch (a wedged data source, a dead NFS mount) and the
device sync (a peer process gone without its collectives — the
XLA runtime can wait indefinitely). The watchdog runs each blocking
call on a worker thread with a deadline; a breach emits a recovery
event and raises :class:`StallError` naming what stalled and for how
long — which a restart supervisor can then act on.

Multi-host caveat (the important one): the watchdog RAISES, it never
unilaterally skips or retries the stalled work. Under
``jax.process_count() > 1`` every process runs the same SPMD program;
one process deciding on its own to drop a batch or abandon a
collective desyncs the others into exactly the silent hang this module
exists to prevent. Recovery from a stall is process-level (crash ->
supervisor restart -> --resume), never step-level.

The abandoned worker thread may still be blocked after the raise
(Python can't cancel a blocked call); that's fine — StallError is
fatal to the run by design, and the thread is a daemon.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

import jax

from tensorflow_distributed_tpu.observe import goodput as _goodput
from tensorflow_distributed_tpu.observe.registry import emit_event


class StallError(RuntimeError):
    """A watched blocking call exceeded its deadline."""


class Watchdog:
    # ONE persistent hand-rolled DAEMON worker, deliberately not
    # ThreadPoolExecutor: executor workers are non-daemon and joined
    # by an atexit handler, so a thread still wedged in the stalled
    # call would block interpreter shutdown forever — the process
    # would print the StallError and then hang at exit instead of
    # exiting code 3 for the supervisor to act on. A daemon dies with
    # the process. Persistent (vs thread-per-call) so the hot path
    # pays a queue handoff, not a thread spawn, per watched step; a
    # worker wedged by a timeout is abandoned and replaced on the
    # next call (which, timeouts being fatal by policy, is rare).

    def __init__(self, data_timeout_s: float = 0.0,
                 sync_timeout_s: float = 0.0):
        self.data_timeout_s = data_timeout_s
        self.sync_timeout_s = sync_timeout_s
        self._requests: Optional[queue.Queue] = None

    def _worker_loop(self, requests: queue.Queue) -> None:
        while True:
            fn, box, done = requests.get()
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

    def _watched(self, fn: Callable[[], Any], what: str, step: int,
                 timeout: float) -> Any:
        if timeout <= 0:
            return fn()
        if self._requests is None:
            self._requests = queue.Queue()
            threading.Thread(target=self._worker_loop,
                             args=(self._requests,), daemon=True,
                             name="tfd-watchdog").start()
        box: dict = {}
        done = threading.Event()
        self._requests.put((fn, box, done))
        if not done.wait(timeout):
            # Abandon the wedged worker (it still holds the stalled
            # call); a subsequent watched call gets a fresh one.
            self._requests = None
            emit_event("recovery", kind="stall", what=what, step=step,
                       timeout_s=timeout,
                       multihost=jax.process_count() > 1)
            _goodput.incr("stall")
            raise StallError(
                f"{what} for step {step} exceeded the "
                f"{timeout:g}s watchdog deadline"
                + (" (multi-host run: raising is the ONLY safe "
                   "disposition — an unilateral skip would desync the "
                   "peer processes' collectives; recover by restart + "
                   "--resume)" if jax.process_count() > 1 else
                   "; recover by restart + --resume (e.g. under "
                   "resilience.supervisor)"))
        if "error" in box:
            raise box["error"]
        return box["value"]

    def fetch(self, fn: Callable[[], Any], step: int) -> Any:
        """Run the next-batch fetch under the data deadline."""
        return self._watched(fn, "next-batch fetch", step,
                             self.data_timeout_s)

    def sync(self, value: Any, step: int) -> Any:
        """Block on a device value under the sync deadline."""
        return self._watched(lambda: jax.block_until_ready(value),
                             "device sync", step, self.sync_timeout_s)

    def decode(self, fn: Callable[[], Any], step: int) -> Any:
        """Run a serving engine's decode-step sync (token fetch) under
        the sync deadline — the serve-mode twin of :meth:`sync`, taking
        a callable so the engine can fold its injected decode_stall
        INSIDE the watched region (the watchdog must see exactly the
        hang a wedged device would produce). ``step`` is the decode
        step. Raises StallError instead of letting the engine freeze;
        the CLI maps it to exit 3 for the supervisor to restart."""
        return self._watched(fn, "decode step", step,
                             self.sync_timeout_s)

    def close(self) -> None:
        """Drop the worker reference; the daemon thread dies with the
        process (it blocks forever on a queue nobody feeds)."""
        self._requests = None
