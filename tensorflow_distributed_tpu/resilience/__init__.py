"""Fault tolerance: injection drills, recovery policies, watchdogs,
and the restart supervisor.

The reference's entire fault story was reactive — ``tf.train.Supervisor``
restarted a dead worker and restored the last periodic checkpoint
(mnist_python_m.py:245-253), losing everything since. This package is
the TPU-native, *proactive* layer on top of the durable checkpointing
train/checkpoint.py already provides:

- :mod:`faults` — a deterministic fault-injection plan
  (``--resilience.fault-plan "nan_grad@40,ckpt_io_fail@80,..."``) so
  every recovery path below is exercisable in CPU-only tests and
  production fire drills, not just believed.
- :mod:`policies` — non-finite-loss handling beyond halt: bounded
  ``skip_batch`` (the jitted step discards the update on device) and
  ``rewind`` (in-process restore of the newest verifiable checkpoint),
  plus rolling-window loss-spike detection.
- :mod:`watchdog` — timeouts on batch fetch and device sync that turn
  a silent hang into a diagnosable :class:`~watchdog.StallError`.
- :mod:`supervisor` — ``python -m
  tensorflow_distributed_tpu.resilience.supervisor -- <train cli
  args>``: restarts a crashed/preempted child with capped backoff and
  ``--resume`` — the reference Supervisor's restart loop, minus its
  lose-everything restore.

Checkpoint integrity (checksums, quarantine of corrupt step dirs,
fallback to the newest verifiable step, save-I/O retries) lives in
train/checkpoint.py itself; this package only injects its faults.

Every recovery event is emitted through the observe/ registry
(``observe.registry.emit_event``) as an ``event="recovery"`` record
and counted on the goodput ledger, so a run's metrics JSONL is also
its incident log.
"""
