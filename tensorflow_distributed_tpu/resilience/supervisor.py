"""Restart supervisor: the reference's ``tf.train.Supervisor``, TPU-shaped.

::

    python -m tensorflow_distributed_tpu.resilience.supervisor \\
        [--max-restarts N] [--backoff-base-s B] [--backoff-max-s M] \\
        -- <train cli args>

Runs ``python -m tensorflow_distributed_tpu.cli <args>`` as a child
and restarts it on any abnormal exit (crash, OOM kill, SIGKILL'd by
the scheduler) with capped exponential backoff, adding ``--resume
true`` from the second leg on so each restart continues from the
newest verifiable checkpoint — where the reference restored the last
periodic checkpoint and silently lost everything since
(mnist_python_m.py:245-253), this supervisor composes with the
preemption guard (SIGTERM legs exit 0 after a durable save and are
NOT restarts) and the checkpoint layer's integrity fallback.

Serve-aware: a ``--mode serve`` child restarts WITHOUT ``--resume``
(that flag is the train loop's checkpoint resume); its continuity
comes from the request journal instead — the identical restart
command finds the journal non-empty, skips finished requests, and
re-admits in-flight ones as continuations (serve/journal.py). Pass
``--serve.journal`` in the child args or restarts re-serve the whole
workload from scratch (warned at startup).

Exit-code semantics (cli.py), both phases:

- **0**: clean completion or graceful preemption drain — stop.
- **2** DIVERGED: train halted on a non-finite loss / exhausted
  recovery budget, or serve quarantined the SAME request past its
  slot-retry budget (SlotRetryExhausted). Deterministic inputs
  re-diverge identically, so restarting just burns the budget: NOT
  restarted unless ``--restart-on-diverge``; the supervisor exits 2.
- **3** STALLED: a watchdog deadline fired (train data/sync stall or
  serve decode stall). A restart is exactly the remedy — restarted
  like any crash, and rc 3 propagates out only when the restart
  budget is exhausted.
- anything else (crash, OOM, SIGKILL): restarted with capped backoff.

Stops on: clean child exit (rc 0), or restart-budget exhaustion
(exits with the child's last rc). SIGTERM/SIGINT to the supervisor is
forwarded to the child, so a preemption notice drains the whole tree
gracefully.

Each restart appends an ``event="recovery", kind="restart"`` JSON line
to the child's ``--observe.metrics-jsonl`` file (when one is
configured), so the run's metrics artifact records its own restart
history — the next leg appends to that same file because its
``--resume`` restore makes observe.hub open the sink in append mode.
"""

from __future__ import annotations

import argparse
import json
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def _child_flag_value(args: Sequence[str], flag: str) -> Optional[str]:
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            return args[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def build_leg_args(child_args: Sequence[str], restarts: int
                   ) -> List[str]:
    """The child argv for leg ``restarts``. Train children gain
    ``--resume true`` from the second leg on (never overriding an
    explicit user setting, either spelling); serve children restart
    with the UNCHANGED command — their continuity is the request
    journal, which the identical ``--serve.journal`` path makes a
    resume by construction."""
    args = list(child_args)
    serve = _child_flag_value(args, "--mode") == "serve"
    ckpt_dir = _child_flag_value(args, "--checkpoint-dir")
    if (restarts > 0 and not serve and ckpt_dir
            and _child_flag_value(args, "--resume") is None):
        args += ["--resume", "true"]
    return args


def _append_event(path: Optional[str], record: dict) -> None:
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass  # the event also went to stdout; never kill the
        #       supervisor over its own bookkeeping


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("usage: python -m tensorflow_distributed_tpu.resilience"
              ".supervisor [options] -- <train cli args>",
              file=sys.stderr)
        return 2
    split = argv.index("--")
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.resilience.supervisor",
        description="restart a crashed/killed training child with "
        "capped backoff and --resume")
    parser.add_argument("--max-restarts", type=int, default=5)
    parser.add_argument("--backoff-base-s", type=float, default=1.0)
    parser.add_argument("--backoff-max-s", type=float, default=60.0)
    # cli.py exits EXIT_DIVERGED (2) when training halts on a
    # non-finite loss / exhausted recovery budget — with a
    # deterministic data stream a resumed leg usually re-diverges at
    # the same step, so restarting just burns the budget. Off by
    # default; crashes and stalls (any other nonzero rc) do restart.
    parser.add_argument("--restart-on-diverge", action="store_true")
    opts = parser.parse_args(argv[:split])
    child_args = argv[split + 1:]

    ckpt_dir = _child_flag_value(child_args, "--checkpoint-dir")
    jsonl = _child_flag_value(child_args, "--observe.metrics-jsonl")
    serve = _child_flag_value(child_args, "--mode") == "serve"
    if serve and not _child_flag_value(child_args, "--serve.journal"):
        print("[supervisor] WARNING: serve child has no "
              "--serve.journal — restarts will re-serve the whole "
              "workload from scratch (in-flight and even finished "
              "requests replay)", flush=True)
    elif not serve and not ckpt_dir:
        print("[supervisor] WARNING: no --checkpoint-dir in child args"
              " — restarts will repeat from step 0 (the reference "
              "Supervisor's lose-everything behavior)", flush=True)

    restarts = 0
    rc = 1
    while True:
        args = build_leg_args(child_args, restarts)
        cmd = [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
               *args]
        print(f"[supervisor] leg {restarts}: {' '.join(cmd)}",
              flush=True)
        proc = subprocess.Popen(cmd)

        def forward(signum, frame, _p=proc):
            try:
                _p.send_signal(signum)
            except ProcessLookupError:
                pass

        prev = {s: signal.signal(s, forward)
                for s in (signal.SIGTERM, signal.SIGINT)}
        try:
            rc = proc.wait()
        finally:
            for s, h in prev.items():
                signal.signal(s, h)
        if rc == 0:
            print(f"[supervisor] clean exit after {restarts} "
                  f"restart(s)", flush=True)
            return 0
        if rc == 2 and not opts.restart_on_diverge:
            # EXIT_DIVERGED (see cli.py): the run halted on policy —
            # restarting replays the same divergence.
            print("[supervisor] child diverged (rc=2); not restarting"
                  " (pass --restart-on-diverge to override)",
                  flush=True)
            _append_event(jsonl, {
                "event": "recovery", "kind": "diverged_no_restart",
                "restarts": restarts, "rc": rc})
            return rc
        if restarts >= opts.max_restarts:
            print(f"[supervisor] restart budget exhausted "
                  f"({opts.max_restarts}); last rc={rc}", flush=True)
            _append_event(jsonl, {
                "event": "recovery", "kind": "restart_budget_exhausted",
                "restarts": restarts, "rc": rc})
            return 128 - rc if rc < 0 else rc
        restarts += 1
        delay = min(opts.backoff_base_s * 2 ** (restarts - 1),
                    opts.backoff_max_s)
        record = {"event": "recovery", "kind": "restart",
                  "leg": restarts, "rc": rc,
                  "backoff_s": round(delay, 3),
                  "resume": bool(_child_flag_value(
                      child_args, "--serve.journal")) if serve
                  else bool(ckpt_dir)}
        print(f"[supervisor] {json.dumps(record)}", flush=True)
        _append_event(jsonl, record)
        time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
