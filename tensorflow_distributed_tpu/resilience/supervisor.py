"""Restart supervisor: the reference's ``tf.train.Supervisor``, TPU-shaped.

::

    python -m tensorflow_distributed_tpu.resilience.supervisor \\
        [--max-restarts N] [--backoff-base-s B] [--backoff-max-s M] \\
        -- <train cli args>

Runs ``python -m tensorflow_distributed_tpu.cli <args>`` as a child
and restarts it on any abnormal exit (crash, OOM kill, SIGKILL'd by
the scheduler) with capped exponential backoff, adding ``--resume
true`` from the second leg on so each restart continues from the
newest verifiable checkpoint — where the reference restored the last
periodic checkpoint and silently lost everything since
(mnist_python_m.py:245-253), this supervisor composes with the
preemption guard (SIGTERM legs exit 0 after a durable save and are
NOT restarts) and the checkpoint layer's integrity fallback.

Serve-aware: a ``--mode serve`` child restarts WITHOUT ``--resume``
(that flag is the train loop's checkpoint resume); its continuity
comes from the request journal instead — the identical restart
command finds the journal non-empty, skips finished requests, and
re-admits in-flight ones as continuations (serve/journal.py). Pass
``--serve.journal`` in the child args or restarts re-serve the whole
workload from scratch (warned at startup).

Exit-code semantics (cli.py), both phases:

- **0**: clean completion or graceful preemption drain — stop.
- **2** DIVERGED: train halted on a non-finite loss / exhausted
  recovery budget, or serve quarantined the SAME request past its
  slot-retry budget (SlotRetryExhausted). Deterministic inputs
  re-diverge identically, so restarting just burns the budget: NOT
  restarted unless ``--restart-on-diverge``; the supervisor exits 2.
- **3** STALLED: a watchdog deadline fired (train data/sync stall or
  serve decode stall). A restart is exactly the remedy — restarted
  like any crash, and rc 3 propagates out only when the restart
  budget is exhausted.
- anything else (crash, OOM, SIGKILL): restarted with capped backoff.

Stops on: clean child exit (rc 0), or restart-budget exhaustion
(exits with the child's last rc). SIGTERM/SIGINT to the supervisor is
forwarded to the child, so a preemption notice drains the whole tree
gracefully.

Each restart appends an ``event="recovery", kind="restart"`` JSON line
to the child's ``--observe.metrics-jsonl`` file (when one is
configured), so the run's metrics artifact records its own restart
history — the next leg appends to that same file because its
``--resume`` restore makes observe.hub open the sink in append mode.

**Elastic restarts** (``--elastic``): instead of relaunching the
identical command, each leg first PROBES the live device count (a
subprocess ``jax.device_count()``, minus any chips the device-mask
file under the child's checkpoint dir declares lost — the
``device_loss`` drill writes it; a real preemption needs no mask, the
chips are simply gone) and picks the best mesh that fits: non-data
axes (model/seq/pipe/expert — semantic parallelism choices) are
preserved, and the data axis absorbs the resize — the largest width
whose product fits the surviving devices and divides the global batch,
so per-device batch re-derives from the SAME global batch and the loss
trajectory stays comparable. The relaunch args are rewritten to that
mesh, a ``kind="mesh_change"`` recovery event records old→new, and the
child's ``--resume`` restore goes through the checkpoint layer's
resharded path (train/checkpoint.py::restore_resharded) — so a
``device_loss`` fault degrades to a smaller mesh and CONTINUES instead
of crash-looping, and a capacity comeback (mask file removed, chips
back) grows the mesh again on the next restart. Without ``--elastic``
nothing changes: the identical-command relaunch stays as it was.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

# faults is import-light (stdlib + numpy + observe.registry — no jax,
# no backend init): sharing device_mask_path keeps the mask-file
# contract single-sourced between the drill that writes it and the
# supervisor that reads it.
from tensorflow_distributed_tpu.resilience.faults import device_mask_path
from tensorflow_distributed_tpu.utils.atomicio import durable_append
# config is pure stdlib (no jax, no backend init): child_flag is the
# argv contract — every flag the supervisor spells for a child is
# checked against the namespace config.py actually parses.
from tensorflow_distributed_tpu.config import child_flag

_MESH_AXES = ("data", "model", "seq", "pipe", "expert")


def _child_flag_value(args: Sequence[str], flag: str) -> Optional[str]:
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            return args[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def parse_mesh_args(args: Sequence[str]) -> Dict[str, int]:
    """The child's configured mesh axes (config.MeshConfig defaults
    where unset; ``data == -1`` = fill remaining devices). Pure —
    jax-free, unit-testable."""
    out = {a: (-1 if a == "data" else 1) for a in _MESH_AXES}
    for name in out:
        v = _child_flag_value(args, child_flag(f"mesh.{name}"))
        if v is not None:
            out[name] = int(v)
    return out


def pick_elastic_mesh(axes: Dict[str, int], alive: int,
                      batch: Optional[int] = None
                      ) -> Optional[Dict[str, int]]:
    """The best mesh for ``alive`` devices: the configured non-data
    axes preserved exactly (tensor/seq/pipe/expert degrees are
    semantic choices the checkpoint's layouts assume), the data axis
    re-sized to the largest width whose product fits ``alive`` and
    divides the global ``batch`` (per-device batch stays an integer
    share of the SAME global batch — the loss trajectory's
    comparability condition). None when even data=1 doesn't fit
    (fewer devices than the non-data product): there is no compatible
    mesh to degrade onto and the supervisor must stop rather than
    crash-loop. The width rule itself is parallel.mesh.pick_data_width
    — the ONE copy, shared with the auto-layout planner's candidate
    enumeration — imported lazily so this module stays importable (and
    its helpers unit-testable) with zero heavyweight machinery loaded;
    the import touches no jax backend."""
    from tensorflow_distributed_tpu.parallel.mesh import pick_data_width
    data = pick_data_width(axes, alive, batch)
    if data is None:
        return None
    out = {a: max(1, int(axes.get(a, 1))) for a in _MESH_AXES}
    out["data"] = data
    return out


def rewrite_mesh_args(args: Sequence[str], mesh: Dict[str, int]
                      ) -> List[str]:
    """Child argv with every ``--mesh.*`` flag pinned to ``mesh``
    (both ``--mesh.data N`` and ``--mesh.data=N`` spellings replaced
    in place; ``--mesh.data`` appended when absent so a default-``-1``
    child gets the EXPLICIT width the supervisor chose). Pure."""
    out = list(args)
    for name in _MESH_AXES:
        flag = child_flag(f"mesh.{name}")
        sval = str(int(mesh[name]))
        replaced = False
        i = 0
        while i < len(out):
            if out[i] == flag and i + 1 < len(out):
                out[i + 1] = sval
                replaced = True
                i += 2
                continue
            if out[i].startswith(flag + "="):
                out[i] = f"{flag}={sval}"
                replaced = True
            i += 1
        if not replaced and (name == "data" or int(mesh[name]) != 1):
            out += [flag, sval]
    return out


def plan_elastic(child_args: Sequence[str], total: int, masked: int
                 ) -> Optional[Tuple[Dict[str, int], int]]:
    """(mesh, child_mask) for a leg: the mesh to relaunch onto, and
    how many trailing devices the child must hide via
    ``TFD_DEVICE_MASK`` so its visible device set exactly equals the
    mesh product — the masked "dead" chips plus any remainder the
    mesh shape can't use. None = no compatible mesh. A child argv
    with no ``--batch-size`` flag runs with config.TrainConfig's
    default, so the divisibility constraint is held against THAT
    value — never dropped (a data width that doesn't divide the
    child's real global batch fails its startup validation and turns
    every leg into the crash loop --elastic exists to prevent)."""
    alive = total - masked
    batch = _child_flag_value(child_args, child_flag("batch_size"))
    mesh = pick_elastic_mesh(
        parse_mesh_args(child_args), alive,
        int(batch) if batch is not None else _default_batch_size())
    if mesh is None:
        return None
    used = mesh["data"]
    for name in ("model", "seq", "pipe", "expert"):
        used *= mesh[name]
    return mesh, total - used


def _default_batch_size() -> int:
    """config.TrainConfig's default global batch size — what a child
    argv with no ``--batch-size`` flag will actually run with. Lazy
    import so the pure helpers above stay unit-testable with zero
    package machinery loaded."""
    from tensorflow_distributed_tpu.config import TrainConfig
    return int(TrainConfig().batch_size)


def _read_mask(path: Optional[str]) -> int:
    """Lost-device count from the mask file (resilience/faults.py
    ``device_loss`` writes it; an operator deletes it when capacity
    comes back). 0 when absent/unreadable — absence means nothing is
    lost, never an error."""
    if not path:
        return 0
    try:
        with open(path) as f:
            return max(0, int(json.load(f).get("lost", 0)))
    except (OSError, ValueError, AttributeError, TypeError):
        return 0


def _probe_devices() -> Optional[int]:
    """Live device count, probed in a SUBPROCESS (the supervisor never
    INITIALIZES a jax backend in-process — a wedged runtime must not
    wedge the supervisor, and each leg must see the CURRENT count, not
    a stale cached backend; pick_elastic_mesh's lazy parallel.mesh
    import is module-load only and touches no backend). None on probe
    failure."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.device_count())"],
            capture_output=True, text=True, timeout=120)
        return int(out.stdout.strip()) if out.returncode == 0 else None
    except (subprocess.SubprocessError, ValueError, OSError):
        return None


def build_leg_args(child_args: Sequence[str], restarts: int
                   ) -> List[str]:
    """The child argv for leg ``restarts``. Train children gain
    ``--resume true`` from the second leg on (never overriding an
    explicit user setting, either spelling); serve children restart
    with the UNCHANGED command — their continuity is the request
    journal, which the identical ``--serve.journal`` path makes a
    resume by construction."""
    args = list(child_args)
    serve = _child_flag_value(args, child_flag("mode")) == "serve"
    ckpt_dir = _child_flag_value(args, child_flag("checkpoint_dir"))
    if (restarts > 0 and not serve and ckpt_dir
            and _child_flag_value(args, child_flag("resume")) is None):
        args += [child_flag("resume"), "true"]
    return args


def _leg_bundle(flight_dir: Optional[str], since: float
                ) -> Optional[str]:
    """The dead leg's flight-recorder bundle (observe/flightrec.py):
    newest postmortem (trapped death) or snapshot (SIGKILL — the last
    fsync'd ring survives where no handler could run) written since
    the leg launched. None without ``--observe.flightrec`` in the
    child args or when nothing qualifies; never raises — this runs on
    the restart path."""
    if not flight_dir:
        return None
    try:
        from tensorflow_distributed_tpu.observe.flightrec import (
            newest_bundle)
        return newest_bundle(flight_dir, since=since)
    except Exception:
        return None


def _append_event(jsonl_path: Optional[str], record: dict) -> None:
    if not jsonl_path:
        return
    try:
        durable_append(jsonl_path, record)
    except OSError:
        pass  # the event also went to stdout; never kill the
        #       supervisor over its own bookkeeping


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("usage: python -m tensorflow_distributed_tpu.resilience"
              ".supervisor [options] -- <train cli args>",
              file=sys.stderr)
        return 2
    split = argv.index("--")
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.resilience.supervisor",
        description="restart a crashed/killed training child with "
        "capped backoff and --resume")
    parser.add_argument("--max-restarts", type=int, default=5)
    parser.add_argument("--backoff-base-s", type=float, default=1.0)
    parser.add_argument("--backoff-max-s", type=float, default=60.0)
    # cli.py exits EXIT_DIVERGED (2) when training halts on a
    # non-finite loss / exhausted recovery budget — with a
    # deterministic data stream a resumed leg usually re-diverges at
    # the same step, so restarting just burns the budget. Off by
    # default; crashes and stalls (any other nonzero rc) do restart.
    parser.add_argument("--restart-on-diverge", action="store_true")
    # Elastic restarts: probe the live device count each leg and
    # rewrite the child's mesh args to the best compatible shape
    # (see the module docstring). Off by default — the identical-
    # command relaunch is unchanged without it.
    parser.add_argument("--elastic", action="store_true")
    opts = parser.parse_args(argv[:split])
    child_args = argv[split + 1:]

    if (opts.elastic
            and _child_flag_value(child_args, child_flag("plan")) == "auto"):
        # Two mesh owners: --elastic pins --mesh.* to the surviving
        # devices on EVERY leg, which the child's "--plan auto owns
        # the mesh" config guard rejects — the child would die at
        # validate on leg 0 and every restart after it, the exact
        # crash loop --elastic exists to prevent. Refuse up front;
        # --plan auto under the PLAIN supervisor is fine (each leg
        # re-plans on the same devices).
        print("[supervisor] --elastic does not compose with a child "
              "--plan auto (the elastic supervisor and the planner "
              "both own the mesh). Drop one: keep --elastic with an "
              "explicit --mesh.*, or keep --plan auto without "
              "--elastic.", file=sys.stderr)
        return 2

    ckpt_dir = _child_flag_value(child_args, child_flag("checkpoint_dir"))
    jsonl = _child_flag_value(child_args,
                              child_flag("observe.metrics_jsonl"))
    flight_dir = _child_flag_value(child_args,
                                   child_flag("observe.flightrec"))
    serve = _child_flag_value(child_args, child_flag("mode")) == "serve"
    if serve and not _child_flag_value(child_args,
                                       child_flag("serve.journal")):
        print("[supervisor] WARNING: serve child has no "
              "--serve.journal — restarts will re-serve the whole "
              "workload from scratch (in-flight and even finished "
              "requests replay)", flush=True)
    elif not serve and not ckpt_dir:
        print("[supervisor] WARNING: no --checkpoint-dir in child args"
              " — restarts will repeat from step 0 (the reference "
              "Supervisor's lose-everything behavior)", flush=True)

    # One path contract with the writer: resilience/faults.py's
    # device_loss drill writes where device_mask_path says.
    mask_file = (device_mask_path(ckpt_dir) if ckpt_dir
                 else os.environ.get("TFD_DEVICE_MASK_FILE"))
    prev_mesh: Optional[Dict[str, int]] = None
    prev_exit_t = 0.0   # previous leg's exit time: bundles older than
    #                     it belong to THAT leg, never this one

    restarts = 0
    rc = 1
    while True:
        args = build_leg_args(child_args, restarts)
        env = None
        if opts.elastic:
            total = _probe_devices()
            if total is None:
                print("[supervisor] WARNING: device probe failed — "
                      "launching this leg with the unchanged mesh",
                      flush=True)
            else:
                masked = _read_mask(mask_file)
                planned = plan_elastic(args, total, masked)
                if planned is None:
                    print(f"[supervisor] no compatible mesh for "
                          f"{total - masked} alive device(s) (of "
                          f"{total}; non-data axes "
                          f"{parse_mesh_args(args)}) — stopping",
                          flush=True)
                    _append_event(jsonl, {
                        "event": "recovery", "kind": "mesh_exhausted",
                        "leg": restarts, "alive": total - masked})
                    # Same signal normalization as budget exhaustion:
                    # the dead leg's rc is -signum after a SIGKILL and
                    # a raw negative return would alias to an
                    # unrelated 8-bit exit status.
                    return (128 - rc if rc < 0 else rc) \
                        if restarts else 1
                mesh, child_mask = planned
                args = rewrite_mesh_args(args, mesh)
                if child_mask:
                    env = dict(os.environ)
                    env["TFD_DEVICE_MASK"] = str(child_mask)
                # "from" is the previous leg's mesh, or the configured
                # one when a pre-existing mask resizes the FIRST leg.
                configured = parse_mesh_args(child_args)
                from_mesh = prev_mesh or (
                    configured if configured["data"] != -1 else None)
                if from_mesh is not None and mesh != from_mesh:
                    record = {"event": "recovery",
                              "kind": "mesh_change", "leg": restarts,
                              "from_mesh": from_mesh, "to_mesh": mesh,
                              "alive": total - masked,
                              "masked": masked}
                    print(f"[supervisor] {json.dumps(record)}",
                          flush=True)
                    _append_event(jsonl, record)
                prev_mesh = mesh
        cmd = [sys.executable, "-m", "tensorflow_distributed_tpu.cli",
               *args]
        print(f"[supervisor] leg {restarts}: {' '.join(cmd)}",
              flush=True)
        leg_t0 = time.time()
        proc = subprocess.Popen(cmd, env=env)

        def forward(signum, frame, _p=proc):
            try:
                _p.send_signal(signum)
            except ProcessLookupError:
                pass

        prev = {s: signal.signal(s, forward)
                for s in (signal.SIGTERM, signal.SIGINT)}
        try:
            rc = proc.wait()
        finally:
            for s, h in prev.items():
                signal.signal(s, h)
        if rc == 0:
            print(f"[supervisor] clean exit after {restarts} "
                  f"restart(s)", flush=True)
            return 0
        # The dead leg's postmortem bundle (flight recorder): name it
        # in whichever recovery event this exit produces, so the
        # incident's forensic state is one `observe.postmortem`
        # invocation away from the restart history. The 1s slack
        # absorbs coarse filesystem mtimes, but never reaches past
        # the PREVIOUS leg's exit — a leg that died before writing
        # anything must not be credited with its predecessor's bundle.
        bundle = _leg_bundle(flight_dir,
                             max(leg_t0 - 1.0, prev_exit_t))
        bundle_extra = {"bundle": bundle} if bundle else {}
        prev_exit_t = time.time()
        if rc == 2 and not opts.restart_on_diverge:
            # EXIT_DIVERGED (see cli.py): the run halted on policy —
            # restarting replays the same divergence.
            print("[supervisor] child diverged (rc=2); not restarting"
                  " (pass --restart-on-diverge to override)",
                  flush=True)
            _append_event(jsonl, {
                "event": "recovery", "kind": "diverged_no_restart",
                "restarts": restarts, "rc": rc, **bundle_extra})
            return rc
        if restarts >= opts.max_restarts:
            print(f"[supervisor] restart budget exhausted "
                  f"({opts.max_restarts}); last rc={rc}", flush=True)
            _append_event(jsonl, {
                "event": "recovery", "kind": "restart_budget_exhausted",
                "restarts": restarts, "rc": rc, **bundle_extra})
            return 128 - rc if rc < 0 else rc
        restarts += 1
        delay = min(opts.backoff_base_s * 2 ** (restarts - 1),
                    opts.backoff_max_s)
        record = {"event": "recovery", "kind": "restart",
                  "leg": restarts, "rc": rc,
                  "backoff_s": round(delay, 3),
                  "resume": bool(_child_flag_value(
                      child_args, child_flag("serve.journal"))) if serve
                  else bool(ckpt_dir),
                  **bundle_extra}
        print(f"[supervisor] {json.dumps(record)}", flush=True)
        _append_event(jsonl, record)
        time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
