"""Single configuration surface for the framework.

Replaces the reference's ``tf.app.flags`` block (mnist_python_m.py:49-87,
full surface in SURVEY.md Appendix A) and its role-by-editing-defaults
scheme (the only difference between mnist_python_m.py / _w1.py / _w2.py is
the default of ``job_name``/``task_index``). Here there are no roles:
every process runs the same program; multi-host identity comes from
``jax.distributed`` environment bootstrap, not from flags.

Flag mapping (reference -> here):
    data_dir                -> data_dir
    download_only           -> (dropped; zero-egress environments load
                               from disk or use --dataset=synthetic)
    task_index/job_name     -> (dropped; no ps/worker roles exist)
    ps_hosts/worker_hosts   -> coordinator/num_processes/process_id env
                               (see parallel.mesh.bootstrap)
    existing_servers        -> (dropped; no user-visible server object)
    num_gpus                -> (dropped; devices come from jax.devices())
    replicas_to_aggregate   -> mesh data-axis size (sync quorum == mesh,
                               by construction; mnist_python_m.py:62-65)
    hidden_units            -> (dead flag in the reference; dropped)
    train_steps             -> train_steps
    batch_size              -> batch_size (GLOBAL batch; the reference's
                               was per-worker, mnist_python_m.py:70,291)
    learning_rate           -> learning_rate
    sync_replicas           -> (sync is the only SPMD mode; async ps is a
                               documented non-goal, SURVEY.md N6. The
                               ps-style sync path survives only as the
                               benchmark baseline in parallel.collectives)
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
from typing import Optional, Sequence


@dataclasses.dataclass
class MeshConfig:
    """Logical device-mesh shape.

    ``data`` is the data-parallel axis (the reference's worker replicas,
    mnist_python_m.py:62-65); ``model`` is tensor parallelism; ``seq`` is
    sequence/context parallelism (ring attention); ``pipe`` is pipeline
    parallelism (GPipe microbatch schedule over stage-sharded layers);
    ``expert`` is a dedicated expert-parallel axis for MoE (experts
    alias the "model" axis when it is 1 — see models/moe.py).
    A value of -1 for ``data`` means "all remaining devices".
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def validate(self) -> None:
        for name in ("model", "seq", "pipe", "expert"):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"mesh.{name} must be >= 1, got {v}")
        if self.data == 0 or self.data < -1:
            raise ValueError(f"mesh.data must be -1 or >= 1, got {self.data}")


@dataclasses.dataclass
class ObserveConfig:
    """The observe/ subsystem's knobs (see observe package docs and the
    README "Observability" section). All off by default — the loop pays
    nothing unless a sink or trace path is configured."""

    # JSONL metrics sink: one JSON object per event (step records carry
    # the step-time breakdown and throughput/MFU fields). The durable
    # artifact format; summarize with
    # ``python -m tensorflow_distributed_tpu.observe.report <path>``.
    metrics_jsonl: str = ""
    # CSV sink: step records only, buffered and written on exit with a
    # union-of-keys header (late columns like mfu still get a column).
    # Convenience format — JSONL is the lossless, crash-durable one.
    metrics_csv: str = ""
    # Chrome-trace (Perfetto-compatible) JSON of HOST phases — data
    # wait, dispatch, device wait, eval, checkpoint, restore, drain.
    # Pure Python: works even when jax.profiler / the TPU tunnel is
    # down. Open at https://ui.perfetto.dev or chrome://tracing.
    trace: str = ""
    # Durable trace flushing (mode=serve): rewrite the trace file at
    # every request-lifecycle edge (admission/completion/eviction)
    # instead of only on the 5s cadence, so a SIGKILLed fleet replica
    # leaves its in-flight requests' spans on disk for the stitcher
    # (observe/fleet_trace.py). The controller sets this on replicas;
    # don't arm it for a high-rate standalone serve — each flush
    # rewrites the whole buffer.
    trace_durable: bool = False
    # Per-device peak TFLOP/s for MFU. 0 = auto-detect for known TPU
    # generations (observe.mfu.PEAK_BF16_FLOPS); unknown devices omit
    # MFU rather than invent a number.
    peak_tflops: float = 0.0
    # Rolling window (steps) for the p50/p95 step-time stats.
    window: int = 200
    # In-memory record ring-buffer cap (registry + MetricLogger) so
    # multi-million-step runs don't grow host memory unboundedly.
    max_records: int = 100_000
    # Compiled-program registry (observe/device.py): every jit call
    # site registers its program's cost_analysis/memory_analysis
    # (flops, bytes accessed, peak-HBM estimate, donated bytes) plus
    # lower/compile wall time, emitted as one "compile" record per
    # program. Default on, but armed only when a sink is configured
    # (the registration pass costs one extra trace + a persistent-
    # cache-absorbed compile per program).
    programs: bool = True
    # On-device model-health telemetry (observe/health.py): per-top-
    # level-module grad norm, update-to-param ratio, and param RMS
    # computed INSIDE the jitted step, cadence-gated on device so
    # off-cadence steps pay neither the norm reductions nor any extra
    # host transfer. Emitted as per-module "health" records on the
    # log cadence.
    health: bool = False
    # Health cadence in steps. 0 = ride log_every (the usual choice:
    # the scalars travel in the metrics fetch the logger already
    # does). A nonzero value must be a multiple of log_every — the
    # host only LOOKS on the log cadence.
    health_every: int = 0
    # Optional activation-RMS taps: each transformer block sows the
    # f32 RMS of its output (TransformerConfig.health_taps) into the
    # same per-layer health records. Transformer families except
    # pipelined_lm (its stages run inside a manual shard_map).
    health_taps: bool = False
    # --- serve observatory (mode=serve; README "Serve tracing & SLO
    # monitoring"). With mode=serve, --observe.trace writes the
    # PER-REQUEST Perfetto trace (observe/serve_trace.py: one async
    # span tree per request, recovery instants, counter tracks)
    # instead of the training host-phase trace. -------------------------
    # Declared SLO targets (observe/slo.py grammar):
    # "high:ttft_p95=100ms,tok_p50=30ms;standard:ttft_p95=500ms" —
    # ";"-separated class groups, an entry with no class prefix
    # applies to every request. Arms the live burn-rate monitor:
    # slo_alert/slo_ok JSONL events + error-budget accounting.
    slo: str = ""
    # Burn-rate windows in DECODE STEPS, "fast,slow" (the 1m/10m
    # multi-window shape at ~1 step/s, on the deterministic
    # decode-step clock).
    slo_windows: str = "60,600"
    # Burn-rate alert threshold: alert when BOTH windows burn error
    # budget faster than this multiple of the sustainable rate.
    slo_burn: float = 1.0
    # Periodic one-line live status print cadence in decode steps
    # (occupancy, queue, tokens/s, per-target window percentile +
    # budget burn). 0 = the fast window's length when slo is armed,
    # off otherwise.
    slo_status_every: int = 0
    # Rolling-metrics snapshot cadence in seconds (scheduler clock):
    # each snapshot is one "metrics_snapshot" JSONL record — the
    # payload a router/fleet supervisor polls. 0 = one final snapshot
    # only when export_path is set, nothing otherwise.
    export_every: float = 0.0
    # Atomic snapshot file (tmp+rename per dump): the single file a
    # poller reads. "" = snapshots ride the JSONL sink only.
    export_path: str = ""
    # --- incident observatory (observe/anomaly.py + observe/
    # flightrec.py; README "Incident observatory") -------------------
    # Online anomaly detection: streaming detectors over the values
    # the run already fetches on its log cadence (step-time /
    # grad-norm spikes, throughput-slope degradation, loss spike /
    # plateau / non-finite; serve: TTFT spike, decode-step-time
    # spike, queue growth, slot non-finite) emitting "anomaly" JSONL
    # records with severity + evidence window. Zero new host fetches.
    anomaly: bool = False
    # Rolling-window length (in the phase's step clock) for the spike
    # detectors; also the "active" horizon the exported incident
    # state uses.
    anomaly_window: int = 64
    # Crash flight recorder: a directory for the bounded in-memory
    # ring of recent records, periodically fsync'd as an atomic
    # snapshot bundle (flight-<pid>.jsonl — what a SIGKILL leaves
    # behind) and dumped in full (postmortem-<pid>.jsonl, with thread
    # stacks) on SIGTERM / fatal exceptions; faulthandler tracebacks
    # land beside them. Render with
    # ``python -m ...observe.postmortem <bundle>``. "" = off.
    flightrec: str = ""
    # Ring capacity (records) of the flight recorder.
    flightrec_ring: int = 256
    # Snapshot cadence in records (anomaly/recovery records always
    # snapshot immediately).
    flightrec_snapshot_every: int = 50
    # --- autopilot (observe/autopilot.py; README "Autopilot") -------
    # The online controller: closes the calibrate→plan→act loop on
    # the run's own telemetry (SLO burn → admission, page-pool
    # pressure → slot cap, rolling accept rate → speculation depth,
    # plan drift → calibration refit). Every decision is an auditable
    # "tune" record; every actuation rides the scheduler's control-
    # command path between decode steps (token-identical).
    autopilot: bool = False
    # Evaluation cadence in decode steps.
    autopilot_every: int = 50
    # Consecutive on-trigger evaluations before a knob moves (the
    # confirm half of the hysteresis; deadbands are built into each
    # loop's thresholds).
    autopilot_confirm: int = 3
    # Per-knob cooldown in decode steps after an actuation.
    autopilot_cooldown: int = 200
    # Relative plan-drift tolerance before a calibration refit
    # (|drift_ratio - 1| > tol triggers loop 1).
    autopilot_drift_tol: float = 0.25
    # Comma-separated knobs the autopilot must NEVER touch:
    # calibration,slot_cap,spec_k,decode_priority,num_pages,buckets.
    autopilot_pin: str = ""
    # Where loop 1 writes the refit calibration profile (atomic JSON,
    # planner-loadable). "" = refits become advisory tune records
    # only (applied=false).
    autopilot_calibration: str = ""

    def validate(self) -> None:
        if self.health_every < 0:
            raise ValueError(
                f"observe.health_every must be >= 0, "
                f"got {self.health_every}")
        if self.health_every and not self.health:
            raise ValueError(
                "observe.health_every has no effect without "
                "observe.health; add --observe.health true")
        if self.health_taps and not self.health:
            raise ValueError(
                "observe.health_taps has no effect without "
                "observe.health; add --observe.health true")
        if self.window < 1:
            raise ValueError(
                f"observe.window must be >= 1, got {self.window}")
        if self.max_records < 1:
            raise ValueError(
                f"observe.max_records must be >= 1, "
                f"got {self.max_records}")
        if self.peak_tflops < 0:
            raise ValueError(
                f"observe.peak_tflops must be >= 0, "
                f"got {self.peak_tflops}")
        if self.trace_durable and not self.trace:
            raise ValueError(
                "observe.trace_durable has no effect without "
                "observe.trace; set a trace path (--observe.trace)")
        if self.slo:
            from tensorflow_distributed_tpu.observe.slo import (
                parse_slo)
            parse_slo(self.slo)  # grammar at config time
        from tensorflow_distributed_tpu.observe.slo import parse_windows
        parse_windows(self.slo_windows)
        if self.slo_burn <= 0:
            raise ValueError(
                f"observe.slo_burn must be > 0, got {self.slo_burn}")
        if not self.slo:
            # The burn-rate shape knobs only matter once targets are
            # declared — accepting them alone would be a silent no-op.
            if self.slo_windows != "60,600":
                raise ValueError(
                    "observe.slo_windows has no effect without "
                    "observe.slo; declare targets (--observe.slo)")
            if self.slo_burn != 1.0:
                raise ValueError(
                    "observe.slo_burn has no effect without "
                    "observe.slo; declare targets (--observe.slo)")
        if self.slo_status_every < 0:
            raise ValueError(
                f"observe.slo_status_every must be >= 0, "
                f"got {self.slo_status_every}")
        if self.export_every < 0:
            raise ValueError(
                f"observe.export_every must be >= 0, "
                f"got {self.export_every}")
        if self.anomaly_window < 8:
            raise ValueError(
                f"observe.anomaly_window must be >= 8, "
                f"got {self.anomaly_window}")
        if self.anomaly_window != 64 and not self.anomaly:
            raise ValueError(
                "observe.anomaly_window has no effect without "
                "observe.anomaly; add --observe.anomaly true")
        if self.flightrec_ring < 8:
            raise ValueError(
                f"observe.flightrec_ring must be >= 8, "
                f"got {self.flightrec_ring}")
        if self.flightrec_snapshot_every < 1:
            raise ValueError(
                f"observe.flightrec_snapshot_every must be >= 1, "
                f"got {self.flightrec_snapshot_every}")
        if not self.flightrec and (
                self.flightrec_ring != 256
                or self.flightrec_snapshot_every != 50):
            raise ValueError(
                "observe.flightrec_ring/flightrec_snapshot_every have "
                "no effect without observe.flightrec; set a bundle "
                "directory (--observe.flightrec DIR)")
        if self.autopilot_every < 1:
            raise ValueError(
                f"observe.autopilot_every must be >= 1, "
                f"got {self.autopilot_every}")
        if self.autopilot_confirm < 1:
            raise ValueError(
                f"observe.autopilot_confirm must be >= 1, "
                f"got {self.autopilot_confirm}")
        if self.autopilot_cooldown < 0:
            raise ValueError(
                f"observe.autopilot_cooldown must be >= 0, "
                f"got {self.autopilot_cooldown}")
        if self.autopilot_drift_tol <= 0:
            raise ValueError(
                f"observe.autopilot_drift_tol must be > 0, "
                f"got {self.autopilot_drift_tol}")
        if self.autopilot_pin:
            from tensorflow_distributed_tpu.observe.autopilot import (
                KNOBS)
            bad = sorted(
                {p.strip() for p in self.autopilot_pin.split(",")
                 if p.strip()} - set(KNOBS))
            if bad:
                raise ValueError(
                    f"observe.autopilot_pin: unknown knob(s) "
                    f"{', '.join(bad)} (valid: {', '.join(KNOBS)})")
        if not self.autopilot and (
                self.autopilot_every != 50
                or self.autopilot_confirm != 3
                or self.autopilot_cooldown != 200
                or self.autopilot_drift_tol != 0.25
                or self.autopilot_pin
                or self.autopilot_calibration):
            raise ValueError(
                "observe.autopilot_* knobs have no effect without "
                "observe.autopilot; add --observe.autopilot true")


@dataclasses.dataclass
class ServeConfig:
    """The serve/ subsystem's knobs (continuous-batching inference —
    see the serve package docs and the README "Serving" section).
    Active only under ``mode=serve``."""

    # Decode batch width: concurrent requests in flight. The decode
    # step is ONE compiled program over [num_slots, max_len] for the
    # life of the process; requests join/leave between steps.
    num_slots: int = 8
    # Default per-request generation budget (a request file may
    # override per request).
    max_new_tokens: int = 64
    # Prefill bucket ladder, e.g. "32,64,128" (prompts pad up to the
    # next bucket; compiled prefill programs are bounded by the ladder
    # size). "" = power-of-two ladder covering the workload's longest
    # prompt (serve/buckets.py).
    buckets: str = ""
    # Starvation bound for the decode-priority interleave: a queued
    # request with a free slot is admitted after at most this many
    # decode steps.
    decode_priority: int = 4
    # EOS token id terminating a request early (-1 = run every request
    # to its full budget).
    eos_id: int = -1
    # Request file (JSONL: {"prompt": [ids...], "max_new_tokens": n,
    # "eos_id": e, "arrival_s": t} — "text" instead of "prompt" with
    # --dataset text). "" = synthetic workload below.
    requests: str = ""
    # Synthetic workload: request count, mixed prompt lengths
    # (uniform in [min, max], seeded by --seed), open-loop arrival
    # rate in req/s (0 = the whole batch queued at t=0).
    num_requests: int = 16
    prompt_len_min: int = 8
    prompt_len_max: int = 64
    arrival_rate: float = 0.0
    # Arrival-trace shape for the synthetic workload (serve/run.py):
    # "" = uniformly spaced at arrival_rate, "poisson" = exponential
    # interarrivals, "bursty" = whole bursts land at once, "diurnal" =
    # sinusoidally modulated rate, or a .jsonl file of per-request
    # {"arrival_s": t} offsets. Non-"" shapes (except a file) need
    # arrival_rate > 0.
    trace: str = ""
    # Request journal path (serve/journal.py): admits/tokens/
    # completions append here, flushed per decode step, so a killed
    # serving process resumes at token granularity — a non-empty
    # journal at startup means RESUME (finished requests skip,
    # in-flight ones re-admit as continuations). The supervisor's
    # serve-mode restart story; "" = off.
    journal: str = ""
    # Per-request slot-retry budget: how many times one request may be
    # quarantined (NaN logits -> free the slot, re-prefill prompt +
    # good tokens) before the run halts with SlotRetryExhausted (exit
    # 2 — serve's DIVERGED equivalent; the supervisor won't hot-loop).
    slot_retries: int = 2
    # Print each streamed token as it retires (chief only).
    stream: bool = False
    # --- speculative decoding (serve/speculate.py) -----------------
    # Tokens PROPOSED per decode step (0 = off). With speculation on,
    # each step runs ONE jitted verify program that scores all
    # spec_tokens proposals against the target model in a single
    # forward over the slot's KV cache and accepts the longest
    # greedy-consistent prefix — output stays token-identical to
    # non-speculative greedy decode; the win is (accepted + 1) tokens
    # per program dispatch instead of 1.
    spec_tokens: int = 0
    # Draft model spec, e.g. "tiny" or "size=tiny,n_layers=1" — a
    # smaller model of the same transformer family proposing the
    # spec_tokens. "" = k-gram SELF-draft: proposals come from the
    # request's own token history (prompt-lookup; no second model, no
    # extra device work), which is what repetitive greedy tails make
    # cheap to predict.
    draft_config: str = ""
    # Suffix length the k-gram self-draft matches on (history lookups
    # key on the last this-many tokens).
    spec_kgram: int = 3
    # --- KV-cache storage ------------------------------------------
    # "bf16": cache rows stored in the model's compute dtype (the
    # default). "int8": per-(token, head) absmax-quantized rows with
    # f32 scales stored beside the cache (models/transformer.py's
    # kv_cache_quant path) — roughly halves HBM per slot at real head
    # dims, so num_slots can grow at a fixed budget; greedy output may
    # diverge within the pinned servebench tolerance.
    kv_dtype: str = "bf16"  # bf16 | int8
    # --- paged KV cache + radix prefix reuse (serve/paging) --------
    # Replace the dense per-slot [max_len] KV rows with a refcounted
    # page pool + host page tables, and arm the radix prefix cache:
    # shared system prompts / few-shot headers / multi-turn sessions
    # attach cached pages instead of re-prefilling, and a slot holds
    # pages for its ACTUAL trajectory instead of reserving max_len.
    # Default OFF — the dense engine path is byte-identical to the
    # pre-paging tree (PAGEBENCH gates both the identity and the
    # >= 60% prefill-FLOPs saving on a shared-prefix trace).
    paged: bool = False
    # Tokens per page (must divide the cache length; serve/run.py
    # rounds an auto-sized --seq-len up to a multiple).
    page_size: int = 16
    # Physical pages in the pool (0 = auto: twice the dense worst
    # case — half serving, half prefix cache). Sizing it below
    # num_slots * max_len/page_size is how you trade cache headroom
    # for slots under a fixed HBM budget; admission defers under
    # pressure after LRU-evicting cached pages.
    num_pages: int = 0
    # Radix prefix cache + sessions (paged only). Off = pure paged
    # allocation with no reuse — an A/B diagnostic.
    radix: bool = True
    # Synthetic-workload multi-turn sessions: group consecutive
    # requests into conversations of this many turns — each turn's
    # prompt EXTENDS the previous turn's prompt (the client re-sends
    # the conversation so far), tagged with a shared session id.
    # Request files carry their own per-request "session" field.
    # Works on the dense engine too (turns just recompute).
    session_turns: int = 1
    # --- SLO-aware scheduling --------------------------------------
    # "fifo": arrival-order admission (the original policy). "slo":
    # class-priority admission (high > standard > batch), per-tenant
    # token quotas, and preempt-and-requeue of over-budget requests
    # (the PR-6 continuation machinery: prompt + tokens-so-far
    # re-admit, journal-compatible, token-identical by greedy
    # determinism).
    policy: str = "fifo"  # fifo | slo
    # Per-tenant decoded-token quota for policy=slo (0 = off): a
    # tenant at/over its quota is DEFERRED while an under-quota
    # request waits — requeued behind, never dropped, and still
    # served when nothing under-quota is waiting (work-conserving).
    tenant_quota: int = 0
    # Allow policy=slo to preempt a live lower-class (or over-quota)
    # request when a higher-class one has waited out the
    # decode-priority clock with no free slot.
    preempt: bool = True
    # Synthetic-workload SLO class mix, e.g. "high:0.25,batch:0.25"
    # (remainder "standard"); "" = all standard. Request files carry
    # their own per-request "slo" field instead.
    slo_mix: str = ""
    # Synthetic-workload tenant count (requests assigned round-robin);
    # request files carry their own "tenant" field.
    tenants: int = 1
    # --- tensor-parallel serving (README "Tensor-parallel serving") -
    # Shard the replica ITSELF over a model axis: the engine's
    # programs (prefill/insert/decode/verify) build over a
    # [data=1, model=N] mesh with tp_partitioning on — attention
    # heads and MLP width shard over the axis, the slot KV cache's
    # head dim shards with them (per-device cache bytes shrink by N),
    # and GSPMD inserts the block psums. Output stays token-identical
    # to the single-device engine (greedy determinism; SERVEBENCH's
    # tp phase gates it). Needs n_heads (and n_kv_heads under GQA)
    # divisible by N and N local devices — validated in serve/run.py
    # where both are known. 1 = the single-device engine, unchanged.
    # NOTE: this is deliberately NOT --mesh.model — the train mesh
    # flags keep their pure-data-mesh contract under mode=serve; the
    # serve mesh is the engine's own.
    mesh_model: int = 1
    # --- fleet serving (fleet/; README "Fleet serving") ------------
    # Inbox file this replica TAILS for requests and control commands
    # (fleet/replica.py line protocol): with an inbox the scheduler
    # serves an OPEN-ENDED stream — no synthetic workload, requests
    # appended by the fleet router, swap/drain/cancel commands from
    # the controller — until a drain lands and the engine runs dry.
    # Requires an explicit --seq-len (no workload to auto-size from)
    # and --serve.journal (the journal is the router's data plane).
    inbox: str = ""
    # HBM budget (GiB) the paged auto-sizing caps --serve.num-pages
    # against (0 = uncapped): pages = (budget - params - programs) /
    # page_bytes. Only meaningful with --serve.paged and num_pages=0.
    hbm_budget_gb: float = 0.0

    def validate(self) -> None:
        if self.num_slots < 1:
            raise ValueError(
                f"serve.num_slots must be >= 1, got {self.num_slots}")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"serve.max_new_tokens must be >= 1, "
                f"got {self.max_new_tokens}")
        if self.decode_priority < 1:
            raise ValueError(
                f"serve.decode_priority must be >= 1, "
                f"got {self.decode_priority}")
        if self.buckets:
            from tensorflow_distributed_tpu.serve.buckets import (
                parse_buckets)
            parse_buckets(self.buckets)  # syntax at config time
        if not self.requests:
            if self.num_requests < 1:
                raise ValueError(
                    f"serve.num_requests must be >= 1, "
                    f"got {self.num_requests}")
            if not 1 <= self.prompt_len_min <= self.prompt_len_max:
                raise ValueError(
                    f"serve prompt length range [{self.prompt_len_min},"
                    f" {self.prompt_len_max}] must satisfy 1 <= min "
                    f"<= max")
        if self.arrival_rate < 0:
            raise ValueError(
                f"serve.arrival_rate must be >= 0, "
                f"got {self.arrival_rate}")
        if self.slot_retries < 0:
            raise ValueError(
                f"serve.slot_retries must be >= 0, "
                f"got {self.slot_retries}")
        if self.trace and not self.trace.endswith(".jsonl"):
            if self.trace not in ("poisson", "bursty", "diurnal"):
                raise ValueError(
                    f"unknown serve.trace {self.trace!r}; have "
                    f"('poisson', 'bursty', 'diurnal') or a .jsonl "
                    f"file of arrival offsets")
            if not self.arrival_rate:
                raise ValueError(
                    f"serve.trace={self.trace!r} shapes the arrival "
                    f"process around serve.arrival_rate — set a rate "
                    f"> 0")
        if self.trace and self.requests:
            raise ValueError(
                "serve.trace shapes the SYNTHETIC workload's "
                "arrivals; a request file carries its own arrival_s "
                "— drop one of the flags")
        if self.spec_tokens < 0:
            raise ValueError(
                f"serve.spec_tokens must be >= 0, "
                f"got {self.spec_tokens}")
        if self.draft_config and not self.spec_tokens:
            raise ValueError(
                "serve.draft_config proposes serve.spec_tokens tokens "
                "per step; add --serve.spec-tokens > 0")
        if self.spec_kgram < 1:
            raise ValueError(
                f"serve.spec_kgram must be >= 1, "
                f"got {self.spec_kgram}")
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"unknown serve.kv_dtype {self.kv_dtype!r}; have "
                f"('bf16', 'int8')")
        if self.page_size < 1:
            raise ValueError(
                f"serve.page_size must be >= 1, got {self.page_size}")
        if self.num_pages < 0:
            raise ValueError(
                f"serve.num_pages must be >= 0, got {self.num_pages}")
        if not self.paged:
            # The paged knobs silently doing nothing would be a trap —
            # reject them without their parent (the repo-wide
            # no-effect-without-parent rule).
            if self.page_size != 16:
                raise ValueError(
                    "serve.page_size shapes the paged KV cache; add "
                    "--serve.paged")
            if self.num_pages:
                raise ValueError(
                    "serve.num_pages sizes the paged KV pool; add "
                    "--serve.paged")
            if not self.radix:
                raise ValueError(
                    "serve.radix toggles the paged engine's prefix "
                    "cache; add --serve.paged")
        if self.session_turns < 1:
            raise ValueError(
                f"serve.session_turns must be >= 1, "
                f"got {self.session_turns}")
        if self.session_turns > 1 and self.requests:
            raise ValueError(
                "serve.session_turns shapes the SYNTHETIC workload; a "
                "request file carries its own per-request session "
                "field — drop one of the flags")
        if self.policy not in ("fifo", "slo"):
            raise ValueError(
                f"unknown serve.policy {self.policy!r}; have "
                f"('fifo', 'slo')")
        if self.tenant_quota < 0:
            raise ValueError(
                f"serve.tenant_quota must be >= 0, "
                f"got {self.tenant_quota}")
        if self.tenant_quota and self.policy != "slo":
            raise ValueError(
                "serve.tenant_quota is enforced by the SLO scheduler; "
                "add --serve.policy slo")
        if (self.tenant_quota and not self.requests
                and self.tenants <= 1):
            raise ValueError(
                "serve.tenant_quota needs tenants to meter: the "
                "synthetic workload assigns tenants only when "
                "--serve.tenants > 1 (request files carry their own "
                "per-request tenant fields) — without them the quota "
                "silently never fires")
        if self.slo_mix:
            if self.policy != "slo":
                raise ValueError(
                    "serve.slo_mix assigns classes the SLO scheduler "
                    "acts on; add --serve.policy slo")
            if self.requests:
                raise ValueError(
                    "serve.slo_mix shapes the SYNTHETIC workload; a "
                    "request file carries its own per-request slo "
                    "field — drop one of the flags")
            from tensorflow_distributed_tpu.serve.scheduler import (
                parse_slo_mix)
            parse_slo_mix(self.slo_mix)  # syntax at config time
        if self.hbm_budget_gb < 0:
            raise ValueError(
                f"serve.hbm_budget_gb must be >= 0, "
                f"got {self.hbm_budget_gb}")
        if self.hbm_budget_gb and not self.paged:
            raise ValueError(
                "serve.hbm_budget_gb caps the paged KV pool's "
                "auto-sizing; add --serve.paged")
        if self.hbm_budget_gb and self.num_pages:
            raise ValueError(
                "serve.hbm_budget_gb sizes num_pages automatically; "
                "an explicit --serve.num-pages already pins the pool "
                "— drop one of the flags")
        if self.inbox:
            # Inbox mode replaces the workload entirely — knobs that
            # shape a synthetic/file workload would silently do
            # nothing (the repo-wide no-effect rule).
            if self.requests:
                raise ValueError(
                    "serve.inbox streams requests from the fleet "
                    "router; a request file is a fixed workload — "
                    "drop one of the flags")
            if self.trace or self.slo_mix or self.session_turns > 1:
                raise ValueError(
                    "serve.trace/slo_mix/session_turns shape the "
                    "SYNTHETIC workload; with serve.inbox the router "
                    "owns arrivals, classes, and sessions — drop "
                    "them")
            if not self.journal:
                raise ValueError(
                    "serve.inbox needs --serve.journal: the journal "
                    "is how the fleet router reads tokens back and "
                    "re-dispatches after a replica death")
        if self.mesh_model < 1:
            raise ValueError(
                f"serve.mesh_model must be >= 1, "
                f"got {self.mesh_model}")
        if self.tenants < 1:
            raise ValueError(
                f"serve.tenants must be >= 1, got {self.tenants}")


@dataclasses.dataclass
class ResilienceConfig:
    """The resilience/ subsystem's knobs (see resilience package docs
    and the README "Fault tolerance" section). All off by default —
    the loop's hot path pays nothing unless a policy, watchdog, or
    fault plan is configured. Checkpoint-save retries are the one
    always-on piece (they cost nothing until a save actually fails)."""

    # Deterministic fault-injection plan, e.g.
    # "nan_grad@40,ckpt_io_fail@80,data_stall@120:5s,sigterm@200" —
    # comma-separated kind@step[:arg] events (resilience/faults.py).
    # Kinds: nan_grad (NaN-poison that step's batch -> genuinely
    # non-finite loss AND gradients), ckpt_io_fail (:N failures,
    # default 1, injected into the next checkpoint save's write path),
    # data_stall (:duration, e.g. 5s, slept inside the batch fetch so
    # the watchdog sees it), sigterm / sigkill (self-signal when the
    # step is dispatched; first-leg only, so a supervised restart
    # terminates), device_loss (:N lost chips, default 1 — writes the
    # device-mask file under checkpoint_dir and hard-kills the
    # process; under resilience.supervisor --elastic the restart
    # degrades onto the best mesh that fits the survivors and
    # CONTINUES via the resharded restore). Under mode=serve the step
    # key counts DECODE steps
    # and the kinds are decode_stall (:duration, slept inside the
    # decode watchdog's window), slot_nan (:slot, NaN-poisons one
    # slot's KV row -> quarantine + re-prefill of only that slot),
    # reload (live weight swap from --checkpoint-dir), plus sigterm/
    # sigkill. Test/drill harness — empty in production runs.
    fault_plan: str = ""
    # Non-finite-loss policy, checked per step on the metrics the loop
    # already retires: "off" (legacy: train on, unless the separate
    # halt_on_nonfinite cadence check fires), "halt" (flush saves,
    # raise), "skip_batch" (the jitted step discards that batch's
    # update on device — params/opt state/EMA keep their pre-step
    # values, the step counter still advances — and the host charges
    # the skip budget), "rewind" (restore the newest verifiable
    # checkpoint in-process and re-enter the loop from there).
    nonfinite: str = "off"  # off | halt | skip_batch | rewind
    # Recovery budgets: exceeding either halts with a clear error —
    # unbounded skipping/rewinding would loop forever on a truly
    # diverged run.
    max_skips: int = 3
    max_rewinds: int = 1
    # Loss-spike detection over a rolling window: a FINITE loss >
    # spike_factor x the window median counts as a divergence event
    # (emitted always; under nonfinite=rewind it also triggers a
    # budgeted rewind — a skip can't help, the update already
    # applied). 0 = off.
    spike_window: int = 0
    spike_factor: float = 10.0
    # Watchdog timeouts (seconds; 0 = off): next-batch fetch and
    # device sync. A breach raises StallError — a diagnosable failure
    # instead of a silent hang. Multi-host caveat: always raise, never
    # unilaterally skip (an uncoordinated skip desyncs the SPMD
    # programs; resilience/watchdog.py).
    data_timeout_s: float = 0.0
    sync_timeout_s: float = 0.0
    # Capped-exponential-backoff retries around checkpoint save I/O
    # (train/checkpoint.py::set_io_policy): transient FS errors retry
    # instead of killing the run.
    save_retries: int = 2
    save_retry_backoff_s: float = 0.05

    def validate(self) -> None:
        if self.nonfinite not in ("off", "halt", "skip_batch",
                                  "rewind"):
            raise ValueError(
                f"unknown resilience.nonfinite {self.nonfinite!r}; "
                f"have ('off', 'halt', 'skip_batch', 'rewind')")
        if self.max_skips < 0 or self.max_rewinds < 0:
            raise ValueError(
                "resilience.max_skips/max_rewinds must be >= 0")
        if self.spike_window < 0:
            raise ValueError(
                f"resilience.spike_window must be >= 0, "
                f"got {self.spike_window}")
        if self.spike_window and self.spike_factor <= 1.0:
            raise ValueError(
                f"resilience.spike_factor must be > 1, "
                f"got {self.spike_factor}")
        if self.data_timeout_s < 0 or self.sync_timeout_s < 0:
            raise ValueError(
                "resilience timeouts must be >= 0 (0 disables)")
        if self.save_retries < 0 or self.save_retry_backoff_s < 0:
            raise ValueError(
                "resilience.save_retries/save_retry_backoff_s must "
                "be >= 0")
        if self.fault_plan:
            # Parse for syntax errors at config time, not mid-run.
            from tensorflow_distributed_tpu.resilience.faults import (
                parse_fault_plan)
            parse_fault_plan(self.fault_plan)


@dataclasses.dataclass
class TrainConfig:
    """Everything needed to run one training job, any model, any mesh."""

    # --- model -----------------------------------------------------------
    model: str = "mnist_cnn"  # mnist_cnn | resnet20 | resnet50 | bert_mlm
    # "reference" reproduces tf.random_normal stddev-1.0 init
    # (mnist_python_m.py:185-196); "improved" (default) uses He/Glorot and
    # is what reaches the >=99% target the reference never hits
    # (performance:6 tops out at 95.75%).
    init_scheme: str = "improved"  # improved | reference
    # Transformer-family size preset ("base"/"small"/"tiny"); empty =
    # the family's default. Ignored by models without presets.
    model_size: str = ""
    # Position encoding for the transformer families (pipelined_lm
    # included): "learned" (additive table, GPT-2/BERT) or "rope"
    # (rotary — relative positions, composes with flash/ring attention
    # and the pipeline schedules). Ignored by the vision models.
    pos_emb: str = "learned"  # learned | rope
    # RoPE base frequency; raising it (e.g. 500000, the Llama-3 value)
    # slows the rotation so longer contexts stay resolvable — the knob
    # context-window extension actually turns.
    rope_theta: float = 10000.0
    # Share the input embedding as the LM output projection (GPT-2
    # style weight tying). Transformer families only.
    tie_embeddings: bool = False
    # Grouped-query attention: K/V head count (0 = same as n_heads,
    # standard MHA; 1 = MQA). Shrinks the decode KV cache by
    # n_heads/n_kv_heads. Transformer families only.
    n_kv_heads: int = 0
    # Sliding-window attention (Mistral-style): attend to the last
    # W positions only (0 = full causal). Causal LM families; rides
    # the flash kernel's block-skip (O(L*W) compute) and masks the
    # decode cache to the window. Requires mesh.seq == 1 (the ring
    # schedule is not windowed; at W << L the window replaces it).
    attn_window: int = 0
    # Decode KV-cache storage: "none" or "int8" (per-(token, head)
    # absmax quantization, exact scale-adjusted int8 attend —
    # models/transformer.py). Generation/eval path only.
    kv_cache_quant: str = "none"
    # MLP nonlinearity for the transformer families: "gelu" (GPT-2/
    # BERT) or "swiglu" (gated, Llama-style).
    mlp_variant: str = "gelu"  # gelu | swiglu
    # Megatron vocab-parallel embedding: shard the token table's vocab
    # dim (and the tied logits) over mesh.model. Worth it at real
    # vocabs (50257 x 768 + Adam slots ~ 460 MB/replica); pointless at
    # mesh.model == 1. Not available for pipelined_lm (its shell params
    # carry no TP metadata).
    shard_vocab: bool = False
    # Fused (vocab-chunked) head+loss for the LM families: > 0 runs the
    # lm_head matmul INSIDE the training loss, ``ce_chunk`` vocab
    # columns at a time with online-softmax statistics, so the full
    # [B, L, V] logits (~825 MB bf16 at GPT-2-small train shapes) are
    # never materialized in forward or backward (ops/fused_ce.py).
    # 0 = dense path. Train-side only (eval keeps dense logits).
    # Composes with pipelined_lm (the 1F1B last stage runs the fused
    # loss inside its scheduled head vjp, train/pipeline_step.py) and
    # with tensor parallelism / shard_vocab (at mesh.model > 1 the
    # scan impl switches to the Megatron vocab-parallel form: each TP
    # rank scans its own head shard, stats combine with pmax/psum).
    # 8192 is a good first value at vocab 50257.
    ce_chunk: int = 0
    # Fused-loss formulation when ce_chunk > 0: "scan" (lax.scan over
    # vocab chunks — all shapes, SPMD-transparent) or "kernel" (the
    # Pallas flash-CE triple, ops/fused_ce_kernel.py — logits blocks
    # live only in VMEM; per-device token count and d_model must be
    # multiples of 8, tokens must divide the 256 block when above it —
    # kernel_supported() is the authority).
    ce_impl: str = "scan"  # scan | kernel
    # Block normalization: "layernorm" or "rmsnorm" (scale-only,
    # Llama-style). Transformer families only.
    norm: str = "layernorm"  # layernorm | rmsnorm
    dropout_rate: float = 0.25  # reference keep_prob 0.75 fed as literal
    # (mnist_python_m.py:292, mnist_single.py:112)

    # --- data ------------------------------------------------------------
    # mnist | synthetic | cifar10 | cifar10_synthetic | imagenet_synthetic
    # (see data.load_dataset dispatch). The LM families
    # (bert_mlm/gpt_lm/moe_lm/pipelined_lm) default to synthetic token
    # data regardless of this field, EXCEPT dataset="text": byte-level
    # causal LM over the local file named by --data-dir (vocab = the
    # 256 byte values; no tokenizer, no egress).
    dataset: str = "mnist"
    data_dir: str = "/tmp/mnist-data"  # reference default, mnist_python_m.py:50
    # Rows carved off the head of the real train split for validation
    # (the reference hardcodes 5000, mnist_python_m.py via
    # input_data.read_data_sets). Small local datasets (e.g. the
    # committed idx fixture) need a smaller split. mnist/cifar10 only.
    validation_size: int = 5000
    # Sequence length for the LM families: the data stream's window AND
    # the model's max_len. 0 = the family default (128). This is the
    # long-context knob: --seq-len 8192 --mesh.seq 8 trains with ring
    # attention over the seq axis (pair with --remat dots and
    # --pos-emb rope --rope-theta 500000 at real length). Ignored by
    # the vision models.
    seq_len: int = 0
    # Vocabulary of the SYNTHETIC LM token streams (and the model built
    # over them). 0 = the default (64). dataset="text" ignores it (the
    # byte corpus pins vocab to 256).
    synthetic_vocab: int = 0
    # dataset='text' tokenization: "byte" (vocab = the 256 byte
    # values, works on any file) or "bpe" (byte-level BPE trained ON
    # the corpus — no downloads; cached next to the file). The model
    # vocab follows the tokenizer (data/lm.py::text_clm).
    text_tokenizer: str = "byte"  # byte | bpe
    # Target merge count for text_tokenizer='bpe' (uint16 storage
    # caps it at 65536; tiny corpora may train fewer).
    bpe_vocab_size: int = 8192
    # Global batch. Reference: 128 per worker x 2 workers = 256 global
    # (mnist_python_m.py:70, replicas_to_aggregate :62-65).
    batch_size: int = 256
    shuffle_seed: int = 0
    # "u8_native": keep images as uint8 and gather batches with the C++
    # threaded gather (data/u8.py; falls back to numpy without a
    # toolchain). Same deterministic sample stream either way; "numpy"
    # stays the default so results don't depend on the host toolchain.
    data_backend: str = "numpy"  # numpy | u8_native

    # --- optimization ----------------------------------------------------
    # adam (reference: AdamOptimizer, mnist_python_m.py:208; becomes
    # adamw when weight_decay > 0) | sgd | adafactor (factored second
    # moments — O(rows+cols) state for the big-model families)
    optimizer: str = "adam"
    learning_rate: float = 1e-3
    lr_schedule: str = "constant"  # constant | cosine | warmup_cosine
    warmup_steps: int = 0
    weight_decay: float = 0.0
    grad_clip_norm: Optional[float] = None
    # Standard (1-eps) one-hot + eps/V uniform target mixture, applied
    # to every family's cross-entropy (including through the 1F1B
    # pipeline's loss head). 0 = off.
    label_smoothing: float = 0.0
    # Polyak/EMA weight averaging: eval (and mode=eval) runs on the
    # exponential moving average of the params, updated every step
    # with this decay. 0 = off. Costs one extra param-sized buffer
    # (sharded like the params — 1/data per device under FSDP).
    ema_decay: float = 0.0
    # > 1: split each global batch into this many microbatches and
    # accumulate the mean gradient before the (single) optimizer update
    # — 1/A the activation memory, same math (train.step).
    grad_accum_steps: int = 1
    train_steps: int = 500
    # bfloat16 matmuls keep the MXU fed; params/optimizer stay f32.
    compute_dtype: str = "bfloat16"  # bfloat16 | float32

    # --- MoE (transformer families only) ---------------------------------
    # > 0 overrides the family's expert count (moe_lm defaults to 4;
    # gpt_lm/bert_mlm/pipelined_lm default dense). Any transformer
    # family with experts trains with the MoE objective.
    moe_experts: int = 0
    # Switch-Transformer-style load-balancing coefficient.
    moe_aux_weight: float = 0.01
    # ST-MoE router z-loss coefficient (0 = off).
    moe_zloss_weight: float = 0.0
    # Experts each token routes to (1 = Switch-style, 2 = GShard-style).
    moe_top_k: int = 2
    # Per-expert buffer slack over the perfectly-balanced load; each
    # expert holds ceil(capacity_factor * top_k * tokens / experts)
    # slots (models/moe.py) and assignments past that are dropped (the
    # dropped fraction is a train metric).
    moe_capacity_factor: float = 1.25
    # Routing-group length for MoE layers: 0 routes the whole
    # sequence as one group; S' > 0 routes independent contiguous
    # chunks of S' tokens, bounding the dense dispatch tensors to
    # O(S'^2) per chunk (models/moe.py scale envelope).
    moe_group_len: int = 0
    # MoE token movement: "dense" one-hot dispatch/combine einsums
    # (GShard; the EP-proven layout) or "scatter" slot scatter/
    # gather (no one-hot tensors, no O(E*C)-per-token dispatch
    # FLOPs; models/moe.py).
    moe_dispatch: str = "dense"

    # --- mesh / parallelism ---------------------------------------------
    # "auto": run the cost-model auto-layout planner (analysis/planner)
    # before the mesh is built — every valid mesh factorization x
    # parallelism strategy for this model/device-count/batch is scored
    # by AOT-compiling the REAL train step (no execution), and the
    # winner's --mesh.* axes + --param-partition (+ pipelined
    # microbatches) replace the defaults. The choice is emitted as a
    # "plan" JSONL record through observe so it is auditable. "" =
    # the explicit mesh below (the default).
    plan: str = ""  # "" | auto
    # Per-device HBM budget (GB) the planner marks candidates
    # infeasible against. 0 = the device's own memory_stats limit
    # when it reports one (TPUs do; CPU hosts don't -> no budget).
    plan_hbm_budget_gb: float = 0.0
    # Calibration profile path (analysis/planner/calibrate.py writes
    # it; platform/device-kind tagged, git-sha stamped): its MEASURED
    # effective rates replace the GENERIC_HW/TPU-table peaks in the
    # planner roofline (--plan auto) and in the device-time
    # predicted-vs-measured join (--profile-dir). "" = table rates.
    plan_calibration: str = ""
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    # "fsdp": ZeRO-3-style sharding of params + optimizer slots over
    # the data axis (parallel.sharding.param_sharding) — memory per
    # device drops ~1/data for the large tensors; GSPMD inserts the
    # all-gather/reduce-scatter pair. "zero1": params stay replicated
    # (no per-use gathers), only the optimizer slots shard — the usual
    # best deal when params fit but Adam doubles don't. Both compose
    # with tensor/expert annotations (only still-unsharded dims are
    # taken). "replicated" (default) matches the reference's
    # every-worker-has-all-weights layout, minus its per-step ps
    # pull/push.
    param_partition: str = "replicated"  # replicated | zero1 | fsdp
    # Gradient-sync formulation (parallel/overlap.py; README
    # "Gradient-sync overlap"). "implicit" (default): GSPMD inserts
    # the allreduce — the serial psum tail. "overlap": the grad tree
    # is bucketed, each bucket reduce-scattered over the data axis as
    # its backward contribution completes, the ZeRO-1 sharded
    # optimizer update runs per bucket on each device's shard, and
    # updated params are all-gathered bucketed — XLA's latency-hiding
    # scheduler interleaves the explicit collectives with remaining
    # compute instead of paying them serially. Requires
    # param_partition=zero1 (the sharded update runs against zero1's
    # slot layout), a pure-data mesh with data > 1, an elementwise
    # optimizer (adam/sgd), and a non-pipelined family. "serial" is
    # the explicit monolithic-psum baseline the GRADSYNC A/B measures
    # overlap against (requires param_partition=replicated).
    grad_sync: str = "implicit"  # implicit | serial | overlap
    # Bucket bound (MiB) for grad_sync=overlap: leaves pack into
    # dtype-keyed buckets of at most this size, one fused
    # reduce-scatter + one fused all-gather per bucket. None = the
    # path's default (parallel.overlap.DEFAULT_BUCKET_BYTES, 4 MiB);
    # a sentinel rather than the literal so ANY explicit value without
    # --grad-sync overlap is rejected, not just non-default ones.
    grad_sync_bucket_mb: Optional[float] = None
    # Remat (jax.checkpoint) policy for big models: none | full | dots
    remat: str = "none"
    # Pipeline schedule for model=pipelined_lm: "1f1b" (default —
    # hand-scheduled backward interleaved with forward: per-stage
    # state O(S) AND lax.cond-skipped bubbles, measured 2.1x faster
    # than gpipe at S=4/M=4; train.pipeline_step) or "gpipe" (AD
    # through the forward schedule; per-stage residuals grow O(M);
    # composes with grad_accum_steps, which 1f1b subsumes).
    pipeline_schedule: str = "1f1b"
    # Microbatches per pipeline step (M): batch_size % M == 0 and
    # M >= mesh.pipe. More microbatches shrink the bubble,
    # (S-1)/(M+S-1) for gpipe (parallel.pipeline.bubble_fraction).
    pipeline_microbatches: int = 4
    # 1F1B backward strategy: "recompute" (stash stage inputs, re-run
    # the stage forward at the backward tick — minimal memory) or
    # "stash" (stash vjp residuals at the forward tick — no recompute,
    # ~4/3 fewer stage FLOPs; costs D=min(2*pipe, M) residual copies
    # per stage). parallel.pipeline.pipeline_value_and_grad.
    pipeline_backward: str = "recompute"
    # Interleaved (virtual-stage) layout, V > 1: each device owns V
    # depth chunks of n_layers/(pipe*V) layers (Megatron's interleaved
    # assignment, [S, V, lps] stacking). Correctness-complete for both
    # schedules (1f1b: the single-scan interleaved schedule; gpipe/
    # eval: V chained pipeline passes); the uniform-tick bubble math
    # is analyzed in parallel.pipeline.bubble_fraction. recompute
    # backward only.
    pipeline_virtual_stages: int = 1

    # The runnable async-family mode (reference: sync_replicas=False,
    # mnist_python_m.py:208,247-253; SURVEY N6): 1 = synchronous data
    # parallelism (default — psum every step). H > 1 = local SGD:
    # each data replica takes H optimizer steps on its own shard
    # with NO gradient sync, then replicas pmean their params — the
    # divergence-for-communication trade async-ps actually makes,
    # expressed SPMD-native (train/local_sgd.py; exact sync-DP
    # equivalence at H=1+SGD is a test). Pure-DP meshes, no EMA/
    # grad-accum/ZeRO, models without mutable extra state.
    param_sync_every: int = 1

    # --- eval / logging --------------------------------------------------
    eval_every: int = 100
    eval_batch_size: int = 1000  # reference validates 5x1000
    # (mnist_python_m.py:309-320)
    log_every: int = 10  # reference logs loss every 10 steps
    # (mnist_single.py:113-116)
    # Report the pre-clip global gradient norm as a per-step metric
    # (one fused on-device reduction; the standard divergence signal).
    log_grad_norm: bool = False
    # Raise at the next log point whose loss is NaN/inf instead of
    # silently training on garbage (checked host-side on the metrics
    # fetch the logger already does — zero extra device syncs).
    halt_on_nonfinite: bool = False

    # --- checkpoint ------------------------------------------------------
    # Unlike the reference, which checkpoints to a throwaway
    # tempfile.mkdtemp() making resume impossible (mnist_python_m.py:236),
    # this is a durable path; empty string disables checkpointing.
    checkpoint_dir: str = ""
    checkpoint_every: int = 200
    resume: bool = False
    keep_checkpoints: int = 3
    # Background-thread serialization/writes (the reference Supervisor's
    # background saver, mnist_python_m.py:245): the device->host
    # snapshot stays in-loop, the disk work overlaps training. The
    # loop flushes the writer (ckpt.wait) before returning.
    checkpoint_async: bool = False
    # "native" (flax msgpack, chief-only atomic writes after a
    # collective host fetch) or "orbax" (sharded OCDBT saves: every
    # process writes/reads ITS OWN shards, no allgather — the scale
    # path train/checkpoint.py's docstring documents). --resume
    # auto-detects the on-disk format either way.
    checkpoint_backend: str = "native"

    # --- profiling -------------------------------------------------------
    # Non-empty: the chief captures a jax.profiler trace of steps
    # [profile_start_step, profile_start_step + profile_num_steps) into
    # this dir (TensorBoard/Perfetto XPlane). The reference's only
    # "profiler" was wall-clock prints (SURVEY.md §5).
    profile_dir: str = ""
    profile_start_step: int = 10
    profile_num_steps: int = 5

    # --- observability ---------------------------------------------------
    # Structured metrics/trace/goodput (observe/ package). CLI flags:
    # --observe.metrics-jsonl, --observe.trace, --observe.peak-tflops...
    observe: ObserveConfig = dataclasses.field(
        default_factory=ObserveConfig)

    # --- resilience ------------------------------------------------------
    # Fault-tolerance policies and drills (resilience/ package). CLI
    # flags: --resilience.nonfinite, --resilience.fault-plan,
    # --resilience.data-timeout-s...
    resilience: ResilienceConfig = dataclasses.field(
        default_factory=ResilienceConfig)

    # --- serving ---------------------------------------------------------
    # Continuous-batching inference (serve/ package; active under
    # mode=serve). CLI flags: --serve.num-slots, --serve.buckets,
    # --serve.decode-priority, --serve.requests...
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)

    # --- static analysis / runtime checks --------------------------------
    # graftcheck's runtime mode (analysis/runtime.py): the inner train/
    # decode loops run under jax.transfer_guard("disallow") — any
    # IMPLICIT host<->device transfer raises at its source line instead
    # of silently serializing the pipeline every step — and the
    # sharding contract (layouts declared at state/cache creation vs
    # actual leaf shardings) is asserted after the first step. The
    # static layers are the CLI cousins:
    #   python -m tensorflow_distributed_tpu.analysis.lint
    #   python -m tensorflow_distributed_tpu.analysis.jaxprcheck
    # Costs nothing when off.
    check: bool = False

    # --- misc ------------------------------------------------------------
    seed: int = 0
    # "eval": restore the latest checkpoint from checkpoint_dir and run
    # only the validation pass (train.loop.evaluate_only) — the
    # reference's validation loop without its mandatory training
    # prelude; "generate" restores a checkpoint and continues a prompt
    # (causal LM families; train/loop.py::generate_only); "serve"
    # drives the continuous-batching inference engine over a request
    # workload (serve/run.py; checkpoint optional — fresh-init params
    # serve as a load-testing mode). "train" (default) is the full
    # loop.
    mode: str = "train"  # train | eval | generate | serve

    # --- mode=generate ---------------------------------------------------
    # The prompt: for dataset=text, a string run through the SAME
    # tokenizer as training (data/lm.py::text_codec); otherwise
    # comma-separated token ids (synthetic-stream models have no
    # text vocabulary).
    prompt: str = ""
    max_new_tokens: int = 64
    # 0 = greedy; > 0 samples (optionally truncated by gen_top_k /
    # nucleus gen_top_p — models/generate.py).
    gen_temperature: float = 0.0
    gen_top_k: int = 0
    gen_top_p: float = 1.0
    # > 1: beam search (deterministic; excludes gen_temperature > 0).
    num_beams: int = 1

    def _explicit_sync_knob_conflict(self) -> Optional[str]:
        """First training knob the explicit grad-sync step (serial or
        overlap; parallel/overlap.py) cannot compose with, as the
        message validate raises — None when compatible."""
        if self.grad_accum_steps > 1:
            return ("grad_sync != implicit has no microbatch scan; "
                    "drop grad_accum_steps or use the implicit step")
        if self.param_sync_every > 1:
            return ("grad_sync != implicit does not compose with "
                    "param_sync_every > 1 (local SGD has its own sync "
                    "protocol)")
        # grad_clip_norm COMPOSES: both explicit modes clip by the
        # SAME psum-reconstructed global norm (block sums-of-squares,
        # one scalar psum) before the elementwise update — the optax
        # chain clip is omitted for explicit runs (train/optim.py),
        # since inside the shard_map tx sees grad BLOCKS and a chain
        # clip would use each device's local norm. Serial+clip vs
        # overlap+clip bit-identity is pinned in tests/test_overlap.py.
        if self.ce_chunk:
            return ("ce_chunk's fused loss applies its own sharding "
                    "constraints, which cannot run inside the explicit "
                    "step's shard_map; drop one of the flags")
        if self.shard_vocab:
            return ("shard_vocab annotates params over the model axis; "
                    "the explicit grad-sync step needs plain pure-data "
                    "params — drop one of the flags")
        return None

    def overlap_grad_sync_conflict(self) -> Optional[str]:
        """Why grad_sync=overlap cannot run with this config's TRAINING
        knobs (mesh shape / partition / family aside) — None when
        compatible. The SAME checks validate raises for an explicit
        --grad-sync overlap; --plan auto consults this so the planner
        never picks an overlap layout the launch would then reject
        (analysis/planner/plan.apply_auto)."""
        if self.optimizer not in ("adam", "sgd"):
            return (f"grad_sync=overlap needs an ELEMENTWISE "
                    f"optimizer (adam/sgd; adamw via "
                    f"weight_decay): a device's block must compute "
                    f"exactly the full update's slice, which "
                    f"{self.optimizer!r}'s factored statistics "
                    f"break")
        return self._explicit_sync_knob_conflict()

    def validate(self) -> None:
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.train_steps < 0:
            raise ValueError(f"train_steps must be >= 0, got {self.train_steps}")
        if not 0.0 <= self.dropout_rate < 1.0:
            raise ValueError(f"dropout_rate must be in [0,1), got {self.dropout_rate}")
        if self.init_scheme not in ("improved", "reference"):
            raise ValueError(f"unknown init_scheme {self.init_scheme!r}")
        if self.compute_dtype not in ("bfloat16", "float32"):
            raise ValueError(f"unknown compute_dtype {self.compute_dtype!r}")
        if self.data_backend not in ("numpy", "u8_native"):
            raise ValueError(f"unknown data_backend {self.data_backend!r}")
        if self.remat not in ("none", "full", "dots"):
            raise ValueError(f"unknown remat {self.remat!r}")
        if self.checkpoint_backend not in ("native", "orbax"):
            raise ValueError(
                f"unknown checkpoint_backend "
                f"{self.checkpoint_backend!r}")
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r}")
        if self.pipeline_backward not in ("recompute", "stash"):
            raise ValueError(
                f"unknown pipeline_backward {self.pipeline_backward!r}")
        if (self.pipeline_backward != "recompute"
                and not (self.model == "pipelined_lm"
                         and self.pipeline_schedule == "1f1b")):
            # Same convention as the 1f1b/grad_accum exclusion below:
            # reject knobs that would be silently ignored. The backward
            # strategy only exists in the hand-scheduled 1F1B step;
            # GPipe's backward comes from AD and the other families
            # have no pipeline at all.
            raise ValueError(
                "pipeline_backward applies only to model=pipelined_lm "
                "with pipeline_schedule=1f1b")
        if (self.model == "pipelined_lm"
                and self.pipeline_schedule == "1f1b"
                and self.grad_accum_steps > 1):
            # Deliberate exclusion, not a gap: 1F1B's microbatch loop IS
            # gradient accumulation (per-microbatch grads accumulate in
            # the schedule's dp_acc before the single optimizer update,
            # with O(S) activation state). To cut activation memory
            # further, raise pipeline_microbatches — same math, smaller
            # microbatches — instead of wrapping a second accumulation
            # loop around the pipeline.
            raise ValueError(
                "pipeline_schedule=1f1b already accumulates per-"
                "microbatch gradients; raise pipeline_microbatches "
                "instead of grad_accum_steps")
        if self.param_partition not in ("replicated", "zero1", "fsdp"):
            raise ValueError(
                f"unknown param_partition {self.param_partition!r}")
        if (self.param_partition == "fsdp"
                and self.model == "pipelined_lm"):
            # FSDP only: pipelined stage PARAMS carry the "pipe" axis
            # and are consumed stage-sliced inside a manual shard_map —
            # a second data-axis shard would have to be gathered inside
            # the schedule by hand, not by GSPMD. ZeRO-1 composes:
            # optimizer slots are consumed in tx.update OUTSIDE the
            # pipe shard_map (train/pipeline_step.py), so sharding
            # them over "data" never touches the schedule — at
            # GPT-2-xl replicated Adam slots are ~19 GB f32, the first
            # OOM the size ladder hits (VERDICT r4 item 2).
            raise ValueError(
                "param_partition=fsdp does not compose with "
                "model=pipelined_lm (stage params are shard_map-"
                "managed); use param_partition=zero1 for optimizer-"
                "slot memory, mesh.pipe/mesh.model for param memory")
        if self.grad_sync not in ("implicit", "serial", "overlap"):
            raise ValueError(
                f"unknown grad_sync {self.grad_sync!r}; have "
                f"('implicit', 'serial', 'overlap')")
        if self.grad_sync_bucket_mb is not None:
            if self.grad_sync_bucket_mb <= 0:
                raise ValueError(
                    f"grad_sync_bucket_mb must be > 0, "
                    f"got {self.grad_sync_bucket_mb}")
            if self.grad_sync != "overlap":
                raise ValueError(
                    "grad_sync_bucket_mb sizes the overlap path's "
                    "collective buckets; it has no effect without "
                    "--grad-sync overlap — drop the flag")
        if self.grad_sync != "implicit":
            # The explicit-collective step (parallel/overlap.py) is a
            # shard_map over a pure data mesh; every exclusion below is
            # a knob the explicit formulation would silently ignore or
            # silently get wrong — rejected loudly, repo policy.
            if self.mode != "train":
                raise ValueError(
                    f"grad_sync={self.grad_sync!r} shapes the TRAIN "
                    f"step's gradient sync; it has no effect under "
                    f"mode={self.mode!r} — drop the flag")
            if self.model == "pipelined_lm":
                raise ValueError(
                    "grad_sync applies to the standard jitted step; "
                    "the hand-scheduled pipeline step owns its own "
                    "collective schedule (use mesh.pipe for that "
                    "family)")
            bad = [a for a in ("model", "seq", "pipe", "expert")
                   if getattr(self.mesh, a) > 1]
            if bad:
                raise ValueError(
                    f"grad_sync={self.grad_sync!r} needs a pure "
                    f"data-parallel mesh; axes {bad} > 1")
            if self.mesh.data == 1:
                raise ValueError(
                    "grad_sync with mesh.data=1 has nothing to "
                    "synchronize; use the implicit step")
            if self.grad_sync == "overlap":
                if self.param_partition != "zero1":
                    raise ValueError(
                        "grad_sync=overlap IS weight-update sharding: "
                        "the per-bucket update runs against zero1's "
                        "sharded optimizer slots — add "
                        "--param-partition zero1")
            elif self.param_partition != "replicated":
                raise ValueError(
                    "grad_sync=serial replicates the full-tree update "
                    "on every device; it requires "
                    "param_partition=replicated (overlap is the mode "
                    "that composes with zero1)")
            conflict = (self.overlap_grad_sync_conflict()
                        if self.grad_sync == "overlap"
                        else self._explicit_sync_knob_conflict())
            if conflict:
                raise ValueError(conflict)
        if self.pipeline_microbatches < 1:
            raise ValueError(
                f"pipeline_microbatches must be >= 1, "
                f"got {self.pipeline_microbatches}")
        if self.pipeline_virtual_stages < 1:
            raise ValueError(
                f"pipeline_virtual_stages must be >= 1, "
                f"got {self.pipeline_virtual_stages}")
        if self.pipeline_virtual_stages > 1:
            if self.model != "pipelined_lm":
                raise ValueError(
                    "pipeline_virtual_stages > 1 applies only to "
                    "model=pipelined_lm")
            if self.pipeline_backward != "recompute":
                raise ValueError(
                    "pipeline_virtual_stages > 1 supports "
                    "pipeline_backward='recompute' only (the stash "
                    "variant's per-chunk residual treedefs are a "
                    "follow-up; parallel.pipeline."
                    "interleaved_pipeline_value_and_grad)")
            if (self.pipeline_schedule == "1f1b"
                    and self.pipeline_microbatches
                    < self.mesh.pipe * self.pipeline_virtual_stages):
                raise ValueError(
                    f"pipeline_microbatches "
                    f"{self.pipeline_microbatches} < mesh.pipe x "
                    f"virtual stages ({self.mesh.pipe} x "
                    f"{self.pipeline_virtual_stages}): every virtual "
                    f"stage needs a microbatch in flight")
        if (self.model == "pipelined_lm"
                and self.batch_size % self.pipeline_microbatches):
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"pipeline_microbatches {self.pipeline_microbatches}")
        if (self.model == "pipelined_lm"
                and self.pipeline_microbatches < self.mesh.pipe):
            raise ValueError(
                f"pipeline_microbatches {self.pipeline_microbatches} "
                f"< mesh.pipe {self.mesh.pipe}: every stage needs at "
                f"least one microbatch in flight")
        if not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError(
                f"label_smoothing must be in [0, 1), "
                f"got {self.label_smoothing}")
        if self.param_sync_every < 1:
            raise ValueError(
                f"param_sync_every must be >= 1, "
                f"got {self.param_sync_every}")
        if self.param_sync_every > 1:
            bad = [a for a in ("model", "seq", "pipe", "expert")
                   if getattr(self.mesh, a) > 1]
            if bad:
                raise ValueError(
                    "param_sync_every > 1 (local SGD) needs a pure "
                    f"data-parallel mesh; axes {bad} > 1")
            if self.param_partition != "replicated":
                raise ValueError(
                    "param_sync_every > 1 needs "
                    "param_partition=replicated (each replica owns "
                    "its full diverged copy)")
            if self.grad_accum_steps > 1:
                raise ValueError(
                    "param_sync_every > 1 does not compose with "
                    "grad_accum_steps; raise batch_size instead")
            if self.ema_decay:
                raise ValueError(
                    "param_sync_every > 1 does not compose with "
                    "ema_decay (average-of-averages ambiguity)")
            from tensorflow_distributed_tpu.models import (
                MUTABLE_EXTRA_MODELS)
            if self.model in MUTABLE_EXTRA_MODELS:
                raise ValueError(
                    "param_sync_every > 1 needs models without "
                    "mutable extra state (BN statistics diverge "
                    "with no principled average); "
                    f"{self.model} carries them")
            if self.model == "pipelined_lm":
                raise ValueError(
                    "param_sync_every > 1 is a pure-DP mode; "
                    "pipelined_lm is not supported")
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1), got {self.ema_decay}")
        if self.grad_accum_steps < 1:
            raise ValueError(
                f"grad_accum_steps must be >= 1, got {self.grad_accum_steps}")
        if self.moe_experts < 0:
            raise ValueError(
                f"moe_experts must be >= 0, got {self.moe_experts}")
        if self.kv_cache_quant not in ("none", "int8"):
            raise ValueError(
                f"unknown kv_cache_quant {self.kv_cache_quant!r}")
        if self.attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0, got {self.attn_window}")
        if self.attn_window:
            if self.model not in ("gpt_lm", "moe_lm", "pipelined_lm"):
                raise ValueError(
                    "attn_window needs a causal LM family "
                    "(gpt_lm | moe_lm | pipelined_lm)")
            if self.mesh.seq > 1:
                raise ValueError(
                    "attn_window with mesh.seq > 1 is not "
                    "implemented; at W << L the window replaces "
                    "ring attention — use mesh.seq == 1")
        if self.moe_experts > 0 and self.model not in (
                "bert_mlm", "gpt_lm", "moe_lm", "pipelined_lm"):
            raise ValueError(
                f"moe_experts > 0 needs a transformer family, "
                f"got model={self.model!r}")
        if self.moe_aux_weight < 0 or self.moe_zloss_weight < 0:
            raise ValueError("moe_aux_weight/moe_zloss_weight must be >= 0")
        if self.moe_top_k < 1:
            raise ValueError(f"moe_top_k must be >= 1, got {self.moe_top_k}")
        if 0 < self.moe_experts < self.moe_top_k:
            # The router would argmax over an exhausted mask and route
            # the same token to expert 0 repeatedly — silent
            # degradation, not an error, so reject it here.
            raise ValueError(
                f"moe_top_k {self.moe_top_k} > moe_experts "
                f"{self.moe_experts}")
        if self.moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor must be > 0, "
                f"got {self.moe_capacity_factor}")
        if self.text_tokenizer not in ("byte", "bpe"):
            raise ValueError(
                f"unknown text_tokenizer {self.text_tokenizer!r}")
        if self.text_tokenizer == "bpe" and not (
                2 <= self.bpe_vocab_size <= 65536):
            raise ValueError(
                f"bpe_vocab_size must be in [2, 65536], "
                f"got {self.bpe_vocab_size}")
        if self.moe_dispatch not in ("dense", "scatter"):
            raise ValueError(
                f"unknown moe_dispatch {self.moe_dispatch!r}")
        if self.moe_group_len < 0:
            raise ValueError(
                f"moe_group_len must be >= 0, got {self.moe_group_len}")
        if (self.moe_group_len and self.seq_len > self.moe_group_len
                and self.seq_len % self.moe_group_len):
            # seq_len <= moe_group_len is fine: MoeMlp routes such
            # sequences as one group (the decode/short-prefill path).
            raise ValueError(
                f"seq_len {self.seq_len} not divisible by "
                f"moe_group_len {self.moe_group_len}")
        if self.batch_size % self.grad_accum_steps:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"grad_accum_steps {self.grad_accum_steps}")
        if self.resume and not self.checkpoint_dir:
            raise ValueError("resume=True requires checkpoint_dir")
        if self.mode not in ("train", "eval", "generate", "serve"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.mode == "serve":
            if self.model not in ("gpt_lm", "moe_lm"):
                raise ValueError(
                    f"mode=serve needs a causal LM with the decode "
                    f"cache (gpt_lm or moe_lm), got {self.model!r}")
            if (self.mesh.model > 1 or self.mesh.seq > 1
                    or self.mesh.pipe > 1 or self.mesh.expert > 1):
                raise ValueError(
                    "mode=serve requires a pure data --mesh.* (model/"
                    "seq/pipe/expert == 1): the serve engine builds "
                    "its OWN tensor-parallel mesh — use "
                    "--serve.mesh-model N to shard the replica")
        if self.resilience.fault_plan:
            # Phase check: a fault keyed to a phase that never consults
            # it would sit silently unfired — reject at startup
            # (resilience/faults.py TRAIN_KINDS/SERVE_KINDS).
            from tensorflow_distributed_tpu.resilience.faults import (
                SERVE_KINDS, TRAIN_KINDS, parse_fault_plan)
            kinds = parse_fault_plan(self.resilience.fault_plan).kinds()
            if self.mode == "serve":
                bad = sorted(kinds - set(SERVE_KINDS))
                if bad:
                    raise ValueError(
                        f"fault kinds {bad} are train-phase only; "
                        f"mode=serve consults {sorted(SERVE_KINDS)} "
                        f"on the decode-step clock")
                if "reload" in kinds and not self.checkpoint_dir:
                    raise ValueError(
                        "fault kind 'reload' performs a live weight "
                        "swap from --checkpoint-dir; set one (serve "
                        "needs a swap source)")
            elif self.mode == "train":
                bad = sorted(kinds - set(TRAIN_KINDS))
                if bad:
                    raise ValueError(
                        f"fault kinds {bad} are serve-phase only; "
                        f"mode=train consults {sorted(TRAIN_KINDS)} "
                        f"on the train-step clock")
                if "device_loss" in kinds and not self.checkpoint_dir:
                    raise ValueError(
                        "fault kind 'device_loss' writes the device-"
                        "mask file under --checkpoint-dir (and the "
                        "elastic restart resumes from there); set "
                        "one")
            else:
                raise ValueError(
                    f"resilience.fault_plan has no injection points "
                    f"under mode={self.mode!r}; drop the flag")
        if self.serve.journal and self.mode != "serve":
            raise ValueError(
                "serve.journal is written by the mode=serve "
                "scheduler; drop the flag")
        if self.serve.mesh_model > 1 and self.mode != "serve":
            raise ValueError(
                "serve.mesh_model shards the mode=serve engine's "
                "mesh; drop the flag or add --mode serve")
        if self.serve.inbox:
            if self.mode != "serve":
                raise ValueError(
                    "serve.inbox is the mode=serve fleet-replica "
                    "intake; drop the flag or add --mode serve")
            if not self.seq_len:
                raise ValueError(
                    "serve.inbox has no workload to auto-size the "
                    "cache from — set an explicit --seq-len (the "
                    "fleet's per-request bound)")
        if self.mode != "serve":
            if self.observe.slo:
                raise ValueError(
                    "observe.slo declares SERVING latency targets "
                    "(mode=serve's live burn-rate monitor); drop the "
                    "flag or add --mode serve")
            if self.observe.export_every or self.observe.export_path:
                raise ValueError(
                    "observe.export_every/export_path dump the "
                    "mode=serve scheduler's rolling-metrics "
                    "snapshots; drop the flags or add --mode serve")
            if self.observe.slo_status_every:
                raise ValueError(
                    "observe.slo_status_every prints the mode=serve "
                    "scheduler's live status line; drop the flag or "
                    "add --mode serve")
        elif self.observe.slo:
            # Class names in targets must be real scheduler classes —
            # a typo'd class would silently never match a request.
            from tensorflow_distributed_tpu.observe.slo import parse_slo
            from tensorflow_distributed_tpu.serve.scheduler import (
                SLO_CLASSES)
            for tgt in parse_slo(self.observe.slo):
                if tgt.cls and tgt.cls not in SLO_CLASSES:
                    raise ValueError(
                        f"observe.slo names unknown class "
                        f"{tgt.cls!r}; have {SLO_CLASSES} (or no "
                        f"prefix for all requests)")
        if self.mode == "generate":
            if self.model not in ("gpt_lm", "moe_lm"):
                raise ValueError(
                    f"mode=generate needs a causal LM with the decode "
                    f"cache (gpt_lm or moe_lm), got {self.model!r}")
            if not self.checkpoint_dir:
                raise ValueError("mode=generate requires checkpoint_dir")
            if not self.prompt:
                raise ValueError(
                    "mode=generate requires --prompt (text for "
                    "dataset=text, else comma-separated token ids)")
            if self.mesh.seq != 1:
                raise ValueError(
                    "mode=generate requires mesh.seq == 1 (single-"
                    "token decode steps can't be seq-sharded)")
            if self.num_beams > 1 and (
                    self.gen_temperature > 0 or self.gen_top_k
                    or self.gen_top_p != 1.0):
                raise ValueError(
                    "num_beams > 1 is deterministic beam search; it "
                    "excludes the sampling knobs (gen_temperature / "
                    "gen_top_k / gen_top_p) — pick one")
        if self.gen_temperature < 0:
            raise ValueError(
                f"gen_temperature must be >= 0, got "
                f"{self.gen_temperature} (negative would sample the "
                f"inverted distribution)")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.num_beams < 1:
            raise ValueError(
                f"num_beams must be >= 1, got {self.num_beams}")
        if self.pos_emb not in ("learned", "rope"):
            raise ValueError(f"unknown pos_emb {self.pos_emb!r}")
        if self.rope_theta <= 0:
            raise ValueError(
                f"rope_theta must be > 0, got {self.rope_theta}")
        if self.rope_theta != 10000.0 and self.pos_emb != "rope":
            raise ValueError(
                "rope_theta has no effect without pos_emb=rope; "
                "drop the flag or add --pos-emb rope")
        if self.n_kv_heads < 0:
            raise ValueError(
                f"n_kv_heads must be >= 0, got {self.n_kv_heads}")
        lm_families = ("bert_mlm", "gpt_lm", "moe_lm", "pipelined_lm")
        if self.shard_vocab and self.model not in lm_families:
            raise ValueError(
                f"shard_vocab has no effect on model={self.model!r} "
                f"(transformer families only); drop the flag")
        if self.shard_vocab and self.model == "pipelined_lm":
            raise ValueError(
                "shard_vocab is not available for pipelined_lm (the "
                "embedding shell carries no TP metadata; use mesh.pipe "
                "for memory)")
        if self.ce_chunk < 0:
            raise ValueError(
                f"ce_chunk must be >= 0, got {self.ce_chunk}")
        if self.ce_chunk and self.model not in lm_families:
            raise ValueError(
                f"ce_chunk has no effect on model={self.model!r} "
                f"(the fused head+loss exists for the LM families' "
                f"50k-row vocabs); drop the flag")
        if (self.ce_impl == "kernel" and self.model == "pipelined_lm"):
            raise ValueError(
                "ce_impl='kernel' is not available for pipelined_lm "
                "(the 1F1B schedule drives the fused loss through its "
                "own vjp at the last stage — the scan formulation "
                "composes there; the Mosaic kernel's shard_map wrap "
                "does not). Use the default ce_impl='scan'")
        if self.ce_impl == "kernel" and self.shard_vocab:
            raise ValueError(
                "ce_impl='kernel' does not compose with shard_vocab "
                "(the Mosaic kernel wants the whole head per device); "
                "the default ce_impl='scan' runs the vocab-parallel "
                "form instead")
        if self.ce_impl not in ("scan", "kernel"):
            raise ValueError(
                f"unknown ce_impl {self.ce_impl!r}; have "
                f"('scan', 'kernel')")
        if self.ce_impl != "scan" and not self.ce_chunk:
            raise ValueError(
                "ce_impl has no effect without ce_chunk > 0 (the fused "
                "head+loss master switch); add --ce-chunk")
        if self.ce_impl == "kernel" and self.mesh.model > 1:
            raise ValueError(
                "ce_impl='kernel' requires mesh.model == 1 (the "
                "Mosaic kernel wants the whole head per device); the "
                "default ce_impl='scan' runs the Megatron vocab-"
                "parallel form over the model axis instead")
        if self.seq_len < 0 or self.seq_len == 1:
            raise ValueError(
                f"seq_len must be 0 (family default) or >= 2, "
                f"got {self.seq_len}")
        if self.seq_len and self.model not in lm_families:
            raise ValueError(
                f"seq_len has no effect on model={self.model!r} "
                f"(LM families only); drop the flag")
        if self.seq_len and self.seq_len % self.mesh.seq:
            raise ValueError(
                f"seq_len {self.seq_len} not divisible by mesh.seq "
                f"{self.mesh.seq} (tokens shard the sequence dim over "
                f"the seq axis)")
        if self.synthetic_vocab < 0:
            raise ValueError(
                f"synthetic_vocab must be >= 0, got {self.synthetic_vocab}")
        if self.synthetic_vocab and self.model not in lm_families:
            raise ValueError(
                f"synthetic_vocab has no effect on model="
                f"{self.model!r} (LM families only); drop the flag")
        if self.synthetic_vocab and self.dataset == "text":
            raise ValueError(
                "synthetic_vocab has no effect with dataset='text' "
                "(the byte corpus pins vocab to 256); drop the flag")
        if self.mlp_variant not in ("gelu", "swiglu"):
            raise ValueError(f"unknown mlp_variant {self.mlp_variant!r}")
        if (self.mlp_variant != "gelu"
                and (self.moe_experts > 0 or self.model == "moe_lm")):
            raise ValueError(
                "mlp_variant has no effect with MoE (the block's MLP is "
                "replaced by MoeMlp, whose experts are gelu); drop the "
                "flag or use a dense family")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown norm {self.norm!r}")
        if self.plan not in ("", "auto"):
            raise ValueError(
                f"unknown plan {self.plan!r}; have ('', 'auto')")
        if self.plan_hbm_budget_gb < 0:
            raise ValueError(
                f"plan_hbm_budget_gb must be >= 0, "
                f"got {self.plan_hbm_budget_gb}")
        if self.plan_hbm_budget_gb and self.plan != "auto":
            raise ValueError(
                "plan_hbm_budget_gb has no effect without --plan auto; "
                "drop the flag")
        if (self.plan_calibration and self.plan != "auto"
                and not self.profile_dir):
            # The profile feeds exactly two consumers: the planner's
            # roofline and the profiled device-time comparison.
            raise ValueError(
                "plan_calibration has no effect without --plan auto "
                "or --profile-dir; drop the flag")
        if self.plan == "auto":
            if self.mode != "train":
                raise ValueError(
                    f"--plan auto chooses a TRAINING layout; it has "
                    f"no effect under mode={self.mode!r} — drop the "
                    f"flag")
            if self.model not in ("gpt_lm", "moe_lm", "pipelined_lm"):
                raise ValueError(
                    f"--plan auto plans the LM training families "
                    f"(gpt_lm | moe_lm | pipelined_lm), got "
                    f"model={self.model!r}")
            if self.mesh != MeshConfig():
                raise ValueError(
                    "--plan auto owns the mesh shape; drop the "
                    "explicit --mesh.* flags (or drop --plan auto and "
                    "keep them)")
            if self.param_partition != "replicated":
                raise ValueError(
                    "--plan auto owns the partition choice "
                    "(replicated/fsdp/zero1 is part of the strategy "
                    "it ranks); drop --param-partition")
            if self.grad_sync != "implicit":
                raise ValueError(
                    "--plan auto owns the grad-sync choice (the "
                    "overlap strategy is one of the candidates it "
                    "ranks); drop --grad-sync")
            if self.param_sync_every > 1:
                raise ValueError(
                    "--plan auto does not compose with "
                    "param_sync_every > 1 (local SGD is not a "
                    "planner strategy)")
            if self.moe_experts > 0 and self.model != "moe_lm":
                # The planner scores the FAMILY's own program; a
                # dense family turned MoE via --moe-experts would be
                # scored as dense (wrong flops, wrong HBM, no expert
                # axis enumerated) — reject rather than emit a plan
                # that misdescribes the run.
                raise ValueError(
                    "--plan auto with --moe-experts needs "
                    "model=moe_lm (the planner scores the family's "
                    "own expert layout; a dense family with experts "
                    "bolted on would be scored as dense)")
        if self.mode == "eval" and not self.checkpoint_dir:
            raise ValueError("mode=eval requires checkpoint_dir")
        if self.resilience.nonfinite == "rewind" and not self.checkpoint_dir:
            raise ValueError(
                "resilience.nonfinite=rewind restores the newest "
                "verifiable checkpoint in-process; it requires "
                "checkpoint_dir")
        if self.resilience.nonfinite == "skip_batch":
            if (self.model == "pipelined_lm"
                    and self.pipeline_schedule == "1f1b"):
                raise ValueError(
                    "resilience.nonfinite=skip_batch is implemented in "
                    "the standard jitted step (the update is discarded "
                    "on device); the hand-scheduled 1F1B step has no "
                    "skip path — use nonfinite=rewind or halt")
            if self.param_sync_every > 1:
                raise ValueError(
                    "resilience.nonfinite=skip_batch does not compose "
                    "with param_sync_every > 1 (the local-SGD step has "
                    "no skip path); use nonfinite=rewind or halt")
        if self.observe.health and self.mode != "train":
            # Same explicitness rule as the taps check below: health
            # vitals are computed inside the TRAIN step — an observed
            # serve/eval/generate run would silently produce zero
            # health records.
            raise ValueError(
                f"observe.health is train-side telemetry (per-module "
                f"grad/update vitals inside the train step); it has "
                f"no effect under mode={self.mode!r} — drop the flag")
        if self.observe.health and self.mode == "train":
            if not self.log_every:
                raise ValueError(
                    "observe.health needs log_every > 0: the health "
                    "scalars ride the log-cadence metrics fetch")
            if (self.observe.health_every
                    and self.observe.health_every % self.log_every):
                raise ValueError(
                    f"observe.health_every {self.observe.health_every} "
                    f"must be a multiple of log_every {self.log_every} "
                    f"(the host only looks on the log cadence)")
            if self.param_sync_every > 1:
                raise ValueError(
                    "observe.health is implemented in the standard and "
                    "1F1B steps; the local-SGD step (param_sync_every "
                    "> 1) has no health path")
        if self.observe.health_taps and self.model not in (
                "bert_mlm", "gpt_lm", "moe_lm"):
            # Same explicitness rule as every other no-op knob: the
            # vision families have no tapped blocks, and pipelined_lm's
            # stage forwards run inside a manual shard_map with no sow
            # path out — a silently tap-less run would look like a
            # telemetry bug.
            raise ValueError(
                f"observe.health_taps needs a non-pipelined "
                f"transformer family (bert_mlm | gpt_lm | moe_lm), "
                f"got model={self.model!r} — per-module health still "
                f"works there, drop the taps flag")
        if self.halt_on_nonfinite and self.resilience.nonfinite != "off":
            raise ValueError(
                "halt_on_nonfinite=true and resilience.nonfinite are "
                "two handlers for the same event — drop "
                "halt_on_nonfinite (resilience.nonfinite=halt is its "
                "per-step superset)")
        self.mesh.validate()
        self.observe.validate()
        self.resilience.validate()
        self.serve.validate()


def _add_dataclass_args(parser: argparse.ArgumentParser, cls, prefix: str = "") -> None:
    # ``from __future__ import annotations`` makes f.type a string, so
    # resolve real types via get_type_hints before testing for nesting.
    import typing
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        ftype = hints.get(f.name, str)
        if dataclasses.is_dataclass(ftype):
            _add_dataclass_args(parser, ftype, prefix=f"{f.name}.")
            continue
        name = f"--{prefix}{f.name}".replace("_", "-")
        default = f.default if f.default is not dataclasses.MISSING else None
        if ftype is bool or isinstance(default, bool):
            parser.add_argument(name, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=default)
        elif default is None:
            parser.add_argument(name, type=float, default=None)
        else:
            parser.add_argument(name, type=type(default), default=default)


@functools.lru_cache(maxsize=None)
def known_flags() -> frozenset:
    """Every ``--flag`` spelling the CLI parses — THE flag namespace
    of the parent->child argv protocol. The supervisor and the fleet
    controller spell child flags through :func:`child_flag`, and the
    argv lint (``analysis/rules/argvproto.py``) verifies every flag
    literal they construct is in this set."""
    parser = argparse.ArgumentParser(add_help=False)
    _add_dataclass_args(parser, TrainConfig)
    return frozenset(parser._option_string_actions)


def child_flag(path: str) -> str:
    """The blessed child-argv spelling for a config field: dotted
    dataclass path in, ``--flag`` out (``"mesh.data"`` ->
    ``"--mesh.data"``, ``"checkpoint_dir"`` -> ``"--checkpoint-dir"``).
    Raises KeyError for a field the CLI does not parse, so a typo'd
    parent flag fails at construction, not as a child crash loop."""
    flag = "--" + path.replace("_", "-")
    if flag not in known_flags():
        raise KeyError(
            f"{flag!r} (from {path!r}) is not parsed by config.py")
    return flag


def parse_args(argv: Optional[Sequence[str]] = None) -> TrainConfig:
    """Build a TrainConfig from CLI args (one CLI for every role/mesh)."""
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu",
        description="TPU-native distributed trainer (single entrypoint; "
        "mesh shape replaces the reference's ps/worker roles)",
    )
    _add_dataclass_args(parser, TrainConfig)
    ns = parser.parse_args(argv)
    import typing
    hints = typing.get_type_hints(TrainConfig)
    kwargs = {}
    for f in dataclasses.fields(TrainConfig):
        ftype = hints[f.name]
        if dataclasses.is_dataclass(ftype):
            sub = {g.name: getattr(ns, f"{f.name}.{g.name}")
                   for g in dataclasses.fields(ftype)}
            kwargs[f.name] = ftype(**sub)
            continue
        v = getattr(ns, f.name)
        if f.name == "grad_clip_norm" and v is not None:
            v = float(v)
        kwargs[f.name] = v
    cfg = TrainConfig(**kwargs)
    cfg.validate()
    return cfg
