"""graftcheck's runtime layer: the ``--check`` mode.

The lint pass reads source and the jaxpr pass reads traces; this
module checks the two contracts only a LIVE run can check, cheaply
enough to leave on in CI runs and drills:

- **transfer guard**: the inner train/decode loops run under
  ``jax.transfer_guard("disallow")`` — any IMPLICIT host↔device
  transfer (a numpy array silently fed to a jitted call, a tracer
  coerced on host) raises at its source line instead of quietly
  serializing the pipeline every step. Explicit transfers
  (``jax.device_put`` / ``jax.device_get`` — everything the loop does
  on purpose) stay allowed.
- **sharding contract**: after the first optimizer step, every state
  leaf's ACTUAL sharding must still be the layout declared at state
  creation. GSPMD is free to propagate shardings through the step —
  that is the mechanism by which a missing ``with_sharding_constraint``
  silently re-lays-out the params (the exact bug class train/step.py's
  ZeRO-1 ``params_out_shardings`` exists to stop) — so the contract is
  asserted where drift would first appear, not assumed.

Wired into ``train/loop.py`` and ``serve/engine.py`` behind the
``--check`` flag (config.TrainConfig.check); zero cost when off.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator

import jax


class ShardingContractError(AssertionError):
    """Actual leaf shardings drifted from the declared layout."""


def sharding_tree(tree: Any) -> Any:
    """The declared-layout snapshot: each leaf's live sharding."""
    return jax.tree_util.tree_map(
        lambda leaf: getattr(leaf, "sharding", None), tree)


def _describe(sharding: Any) -> str:
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else str(sharding)


def sharding_spec_strings(tree: Any) -> dict:
    """``{"/"-joined leaf path: str(PartitionSpec)}`` for every sharded
    leaf — the serializable layout record the checkpoint layer writes
    into its mesh manifest (train/checkpoint.py), kept here so the
    contract checker and the manifest agree on how a layout is
    described."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                           for k in path)
            out[key] = _describe(sharding)
    return out


def assert_sharding_contract(tree: Any, declared: Any,
                             what: str = "params") -> None:
    """Raise ShardingContractError listing every leaf whose actual
    sharding is not equivalent to the declared one.

    Equivalence, not equality: two shardings that place every element
    identically (``P()`` vs ``P(None)``) satisfy the contract.
    """
    mismatches = []

    def compare(path, leaf, want):
        have = getattr(leaf, "sharding", None)
        if want is None or have is None:
            return leaf
        ndim = getattr(leaf, "ndim", None)
        try:
            ok = (have.is_equivalent_to(want, ndim)
                  if ndim is not None else have == want)
        except (AttributeError, TypeError):
            ok = have == want
        if not ok:
            mismatches.append(
                f"  {jax.tree_util.keystr(path)}: declared "
                f"{_describe(want)}, actual {_describe(have)}")
        return leaf

    jax.tree_util.tree_map_with_path(compare, tree, declared)
    if mismatches:
        raise ShardingContractError(
            f"--check: {what} sharding drifted from the declared "
            f"layout after the first step ({len(mismatches)} "
            f"leaves):\n" + "\n".join(mismatches[:20])
            + ("\n  ..." if len(mismatches) > 20 else "")
            + "\n(a step function is missing a with_sharding_"
              "constraint, or an input reached it with the wrong "
              "placement)")


@contextlib.contextmanager
def transfer_guard(enabled: bool) -> Iterator[None]:
    """``jax.transfer_guard("disallow")`` when enabled; transparent
    otherwise — call sites wrap unconditionally and pass cfg.check."""
    if enabled:
        with jax.transfer_guard("disallow"):
            yield
    else:
        yield


@contextlib.contextmanager
def transfer_allowed(enabled: bool) -> Iterator[None]:
    """Re-allow transfers inside a guarded region; transparent when
    ``enabled`` is False (pass cfg.check: with --check off this must
    not override a user's own JAX_TRANSFER_GUARD setting). For the
    cold recovery paths only: a rewind's checkpoint restore
    legitimately performs implicit transfers (checkpoint._warm_runtime
    's probe, the buffer laundering) — the guard exists to police the
    STEADY-STATE loop, and a recovery that crashes on its own restore
    would turn --check from a diagnostic into an outage."""
    if enabled:
        with jax.transfer_guard("allow"):
            yield
    else:
        yield
