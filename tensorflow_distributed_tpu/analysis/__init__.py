"""graftcheck: the repo's self-hosting static-analysis toolchain.

Three layers, one contract — the classic pjit/shard_map footguns that
compile fine and only surface as perf cliffs or corruption at scale
must be caught in CI, not on TPU time:

- ``analysis.lint`` — a pure-Python (jax-free) AST lint engine with
  rules for hidden host↔device syncs in hot paths, PRNGKey reuse,
  jit-under-loop recompilation, use-after-donation, and Python side
  effects under trace. Runnable as
  ``python -m tensorflow_distributed_tpu.analysis.lint [paths]``;
  findings are suppressed inline with
  ``# graftcheck: disable=<rule> -- <reason>``.
- ``analysis.jaxprcheck`` — trace-level contract pass: the LM/MoE/
  pipelined train steps and the serve decode step are traced with
  ``jax.make_jaxpr`` and their collective counts (psum/all_gather/
  ppermute/...) and float-upcast counts (``convert_element_type``
  widening, e.g. a silent bf16→f32 in a bf16 path) are pinned against
  committed golden budgets (``analysis/goldens/census.json``).
- ``analysis.runtime`` — the ``--check`` runtime mode: a
  ``jax.transfer_guard`` around the hot loops plus a sharding-contract
  assertion (declared shardings vs actual leaf shardings after the
  first step) wired into ``train/loop.py`` and ``serve/engine.py``.

The toolchain is self-hosting: tier-1 lints the whole package, so a
finding in repo code must be fixed or explicitly suppressed with a
reason.
"""
